"""Migrated tier-1 hygiene guards (formerly flat AST checks in
tests/test_env_guard.py), re-expressed as rules over the shared project
model. Semantics are preserved check-for-check — same recognizers, same
allowlists — so the same offenders are detected; what changed is that
every rule now reads the one cached parse instead of re-reading the
package, and blindness floors are engine-enforced ``min_sites``
contracts instead of ad-hoc asserts."""

from __future__ import annotations

import ast

from kindel_tpu.analysis.engine import Finding, rule
from kindel_tpu.analysis.model import ProjectModel, dotted_parts


def _env_read_lines(fn) -> list:
    hits = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr == "environ":
            hits.append(n.lineno)
        elif isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr == "getenv") or (
                isinstance(f, ast.Name) and f.id == "getenv"
            ):
                hits.append(n.lineno)
    return hits


def _enclosing_functions(tree) -> dict:
    out = {}

    def visit(node, fname):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fname = node.name
        out[node] = fname
        for child in ast.iter_child_nodes(node):
            visit(child, fname)

    visit(tree, "<module>")
    return out


@rule("jit-env-read", min_sites=8)
def jit_env_read(model: ProjectModel):
    """No ``os.environ`` / ``os.getenv`` read inside a jit-decorated
    body: tuning knobs resolve at config-build time (kindel_tpu.tune),
    never at trace time — a traced env read only runs once and then the
    knob silently stops responding, while compiled behavior depends on
    ambient state the compile cache key does not capture."""
    findings, jitted = [], 0
    for fn in model.functions:
        if not fn.jit:
            continue
        jitted += 1
        for line in _env_read_lines(fn.node):
            findings.append(Finding(
                "jit-env-read", "error", fn.rel, line,
                f"os.environ read inside jitted `{fn.name}` — resolve "
                "the knob at config-build time (kindel_tpu.tune)",
            ))
    return findings, jitted


@rule("init-env-read", min_sites=10)
def init_env_read(model: ProjectModel):
    """No env read inside ``__init__`` either: instrumented classes
    (PhaseTimer, tracers, workers) must resolve env state where it is
    used, never cache it at construction — an env var exported between
    construction and use must win (the PhaseTimer trace-dir bug)."""
    findings, inits = [], 0
    for fn in model.functions:
        if fn.name != "__init__" or fn.cls is None:
            continue
        inits += 1
        for line in _env_read_lines(fn.node):
            findings.append(Finding(
                "init-env-read", "error", fn.rel, line,
                f"os.environ read cached in {fn.cls}.__init__ — resolve "
                "it where it is used instead",
            ))
    return findings, inits


#: wall-clock *timestamps* (not durations) where time.time() is the
#: point: the tune store's recorded_at field is read by humans
TIME_TIME_ALLOWLIST = {("tune.py", "record")}


@rule("time-time-duration", min_sites=1)
def time_time_duration(model: ProjectModel):
    """Durations come from ``time.perf_counter()`` — ``time.time()`` is
    a wall clock subject to NTP steps, and a negative "duration" in a
    span or latency histogram is a debugging rabbit hole. Timestamp
    uses must be allowlisted explicitly (TIME_TIME_ALLOWLIST)."""
    findings, sites = [], 0
    for rel, mod in model.modules.items():
        owners = _enclosing_functions(mod.tree)
        basename = rel.rsplit("/", 1)[-1]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr == "time"
                and isinstance(f.value, ast.Name)
                and f.value.id == "time"
            ):
                continue
            sites += 1
            owner = owners.get(node, "<module>")
            if (basename, owner) in TIME_TIME_ALLOWLIST:
                continue
            findings.append(Finding(
                "time-time-duration", "error", rel, node.lineno,
                f"time.time() in {owner} — use time.perf_counter() for "
                "durations, or allowlist a genuine timestamp",
            ))
    return findings, sites


@rule("metric-help-text", min_sites=15)
def metric_help_text(model: ProjectModel):
    """Every ``.counter/.gauge/.histogram/.info`` registration passes
    non-empty help text (second positional arg or ``help_text=``) — the
    exposition renders ``# HELP`` verbatim and a blank one is useless
    to whoever is staring at the dashboard. Also enforced at runtime by
    MetricsRegistry; the static rule catches sites tests never run."""
    findings, registrations = [], 0
    for rel, mod in model.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr in ("counter", "gauge", "histogram", "info")
            ):
                continue
            registrations += 1
            help_arg = None
            if len(node.args) >= 2:
                help_arg = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "help_text":
                        help_arg = kw.value
            if help_arg is None:
                findings.append(Finding(
                    "metric-help-text", "error", rel, node.lineno,
                    f".{f.attr}() registration without help text",
                ))
            elif isinstance(help_arg, ast.Constant) and not help_arg.value:
                findings.append(Finding(
                    "metric-help-text", "error", rel, node.lineno,
                    f".{f.attr}() registration with empty help text",
                ))
    return findings, registrations


@rule("zlib-confinement", min_sites=3)
def zlib_confinement(model: ProjectModel):
    """``import zlib`` (or direct ``zlib.decompress`` /
    ``zlib.decompressobj``) may only appear inside the io/ package —
    every inflate goes through the parallel-ingest chokepoint
    (io/inflate.py) and its ordering / bounded-window / metric
    invariants."""
    findings, io_sites = [], 0
    for rel, mod in model.modules.items():
        inside_io = rel.split("/")[1:2] == ["io"]
        for node in ast.walk(mod.tree):
            hit = None
            if isinstance(node, ast.Import):
                if any(a.name.split(".")[0] == "zlib" for a in node.names):
                    hit = "import zlib"
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "zlib":
                    hit = "from zlib import"
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("decompress", "decompressobj")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "zlib"
                ):
                    hit = f"zlib.{f.attr}"
            if hit is None:
                continue
            if inside_io:
                io_sites += 1
            else:
                findings.append(Finding(
                    "zlib-confinement", "error", rel, node.lineno,
                    f"{hit} outside {model.package}/io/ — route "
                    "inflation through the single chokepoint "
                    "(io/inflate.py)",
                ))
    return findings, io_sites


def _jax_free(model: ProjectModel, rule_id: str, subdir: str, why: str):
    findings, checked = [], 0
    prefix = f"{model.package}/{subdir}/"
    for rel, mod in model.modules.items():
        if not rel.startswith(prefix):
            continue
        checked += 1
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name == "jax" or name.startswith("jax."):
                    findings.append(Finding(
                        rule_id, "error", rel, node.lineno,
                        f"imports {name} inside {subdir}/ — {why}",
                    ))
    return findings, checked


@rule("io-jax-free", min_sites=8)
def io_jax_free(model: ProjectModel):
    """Nothing under io/ imports jax: inflate pool workers execute only
    io/ code on non-main threads, and a worker thread tripping lazy
    backend initialization mid-stream would deadlock or double-init the
    runtime. io/ stays L0 by construction."""
    return _jax_free(
        model, "io-jax-free", "io",
        "the ingest layer (and its worker threads) must stay jax-free",
    )


@rule("fleet-jax-free", min_sites=4)
def fleet_jax_free(model: ProjectModel):
    """The fleet tier (router/supervisor) never touches the device —
    only the ConsensusServices it assembles do. A jax import here would
    let the probe thread or the placement path trip backend init and
    couple eviction/drain decisions to device state."""
    return _jax_free(
        model, "fleet-jax-free", "fleet",
        "the fleet tier (router/supervisor) must never touch the device",
    )


_AOT_ATTRS = {
    "deserialize_and_load",
    "deserialize_executable",
    "serialize_executable",
    "runtime_executable",
}


@rule("aot-confinement", min_sites=3)
def aot_confinement(model: ProjectModel):
    """One AOT surface: ``.lower(...).compile(...)`` chains and PjRt
    executable (de)serialization may only appear in aot.py — a second
    lowering site would fork the store keying, the parity discipline,
    and the warn-once fallback. Dispatch sites consult the aot
    registry; they never compile or deserialize themselves."""
    findings, aot_sites = [], 0
    for rel, mod in model.modules.items():
        is_aot = rel == f"{model.package}/aot.py"
        for node in ast.walk(mod.tree):
            hit = None
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "compile"
                    and isinstance(f.value, ast.Call)
                    and isinstance(f.value.func, ast.Attribute)
                    and f.value.func.attr == "lower"
                ):
                    hit = ".lower().compile()"
                elif isinstance(f, ast.Attribute) and f.attr in _AOT_ATTRS:
                    hit = f".{f.attr}()"
            elif isinstance(node, ast.Import):
                if any("serialize_executable" in a.name for a in node.names):
                    hit = "import serialize_executable"
            elif isinstance(node, ast.ImportFrom):
                mod_name = node.module or ""
                if "serialize_executable" in mod_name or any(
                    a.name == "serialize_executable" for a in node.names
                ):
                    hit = "import serialize_executable"
            if hit is None:
                continue
            if is_aot:
                aot_sites += 1
            else:
                findings.append(Finding(
                    "aot-confinement", "error", rel, node.lineno,
                    f"{hit} outside aot.py — route it through the one "
                    "AOT surface",
                ))
    return findings, aot_sites


#: ragged/pack.py functions on the superbatch hot path — they run once
#: per dispatched flush, so per-request Python cost must stay O(1) array
#: bookkeeping, never an explicit loop hiding per-element work
RAGGED_HOT_FUNCTIONS = {"build_segment_table", "pack_superbatch"}


@rule("ragged-pack-vectorized", min_sites=2)
def ragged_pack_vectorized(model: ProjectModel):
    """Vectorized-only lint over the ragged packer: no ``for``/``while``
    anywhere inside the hot functions of ragged/pack.py — numpy does
    the per-element work; Python touches each request exactly once via
    comprehensions. A hot function going missing (renamed) is itself a
    finding, not a silent skip."""
    rel = f"{model.package}/ragged/pack.py"
    mod = model.modules.get(rel)
    if mod is None:
        return [], 0
    findings, found = [], set()
    for fn in model.by_module.get(rel, ()):
        if fn.name not in RAGGED_HOT_FUNCTIONS:
            continue
        found.add(fn.name)
        for n in ast.walk(fn.node):
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
                findings.append(Finding(
                    "ragged-pack-vectorized", "error", rel, n.lineno,
                    f"{type(n).__name__} loop inside hot `{fn.name}` — "
                    "keep the pack path vectorized (numpy concatenate/"
                    "cumsum over per-request comprehensions)",
                ))
    for missing in sorted(RAGGED_HOT_FUNCTIONS - found):
        findings.append(Finding(
            "ragged-pack-vectorized", "error", rel, 1,
            f"hot function `{missing}` missing from ragged/pack.py — "
            "renamed without updating the lint contract",
        ))
    return findings, len(found)


#: reviewed device→host download (and host-materialization) sites,
#: keyed (module-path-under-package, function). A download is where
#: transfer bytes get counted, where blocking on the device happens,
#: and — on a tunneled link — where a round trip is paid; every one of
#: these was reviewed when the rule landed (PR 13) and a NEW
#: `np.asarray` / `jax.device_get` / `.block_until_ready()` in a
#: jax-importing module must either live in one of these functions or
#: be added here with the same review (is it counted? is it bounded?).
DOWNLOAD_SITES = {
    # AOT export parity check blocks on both executables by design
    ("aot.py", "export_executable"),
    # compat.py reference-shape conversions run on host-resident numpy
    # Pileup fields — np.asarray there never touches a device buffer
    # (the module imports jax only for the version shims)
    ("compat.py", "pileup_to_alignment"),
    ("compat.py", "pileup_from_reference_arrays"),
    # cohort wire download + realign CDR window fetches (d2h counted)
    ("batch.py", "_assemble_outputs"),
    ("batch.py", "_fetch"),
    # the CDR fetchers' single-device fallback closures (PR 14): same
    # one-window dynamic-slice download the _fetch sites always were,
    # d2h counted by the enclosing fetcher
    ("batch.py", "classic"),
    ("ragged/unpack.py", "classic"),
    # the fused/compact/fast wire decoders + packed-arg host helpers
    ("call_jax.py", "unpack_wire"),
    ("call_jax.py", "unpack_depth_scalars"),
    ("call_jax.py", "masks_from_wire"),
    ("call_jax.py", "decode_fast"),
    ("call_jax.py", "decode_compact"),
    ("call_jax.py", "pack_kernel_args"),
    ("call_jax.py", "__init__"),  # CallUnit host-array normalization
    # tune's ragged geometry probe blocks on the launch deliberately
    ("cli.py", "ragged_pass"),
    # devingest downloads O(records) metadata planes (DESIGN.md §19)
    ("devingest/__init__.py", "_expand_chunk"),
    ("devingest/expand.py", "_np64"),
    ("devingest/expand.py", "fam"),
    ("devingest/expand.py", "cat"),
    ("devingest/fields.py", "<module>"),
    ("devingest/scan.py", "scan_records_device"),
    # mesh construction / sharded gather paths materialize by contract
    ("parallel/distributed.py", "make_global_mesh"),
    ("parallel/mesh.py", "make_mesh"),
    ("parallel/mesh.py", "sharded_call"),
    ("parallel/mesh.py", "batched_sharded_call"),
    ("parallel/product.py", "_host_global"),
    # meshexec (PR 14): owning-shard CDR-window fetches (d2h counted by
    # the calling fetcher; bounded to one window), mesh/device-list
    # construction, and host-side shard stacking ahead of placement
    ("parallel/meshexec.py", "fetch_window_rows"),
    ("parallel/meshexec.py", "fetch_window_flat"),
    ("parallel/meshexec.py", "_shard_block"),
    ("parallel/meshexec.py", "mesh_for"),
    ("parallel/meshexec.py", "place_stacked"),
    ("parallel/meshexec.py", "stack_shards"),
    # pod tier (DESIGN.md §27): put_sharded/replicated normalize HOST
    # numpy inputs ahead of placement (never a device read);
    # fetch_global is THE pod output download — the cross-process
    # allgather, bytes counted on kindel_pod_allgather_bytes_total
    ("parallel/meshexec.py", "put_sharded"),
    ("parallel/meshexec.py", "replicated"),
    ("parallel/meshexec.py", "fetch_global"),
    # pod-replicated paged admit/clear operands: np.asarray on
    # host-built offset/patch planes before replication (h2d counted
    # by the admit counter as always)
    ("paged/residency.py", "admit"),
    ("paged/residency.py", "clear"),
    # explicit *_host fetch helpers (named as downloads)
    ("pileup_jax.py", "fetch_counts_host"),
    ("stats_jax.py", "entropy_rows_host"),
    ("stats_jax.py", "jeffreys_interval_host"),
    ("pipeline.py", "_pipelined_consensus_impl"),
    # ragged launch counts upload bytes; unpack is THE superbatch
    # download site (whole-wire, emission prefix, per-segment windows)
    ("ragged/kernel.py", "launch_ragged"),
    ("ragged/unpack.py", "_fetch"),
    ("ragged/unpack.py", "unpack_rows"),
    ("ragged/unpack.py", "plane_for"),
    # streamed accumulation uploads/downloads at its reduce boundary
    ("streaming.py", "add_events"),
    ("streaming.py", "host"),
    ("workloads.py", "_jeffreys_ci"),
    ("workloads.py", "plot_clips"),
}


@rule("download-confinement", min_sites=20)
def download_confinement(model: ProjectModel):
    """Device→host downloads only inside declared download sites: in
    any jax-importing module, `np.asarray(...)`, `jax.device_get(...)`,
    and `.block_until_ready()` must sit in a DOWNLOAD_SITES function.
    An undeclared materialization is how transfer accounting goes
    silently wrong (bench's `transfers` object under-reports) and how a
    tunneled link grows an unbudgeted round trip — exactly the
    regression class the emit tier (kindel_tpu.emit, DESIGN.md §22)
    exists to eliminate."""
    findings, declared = [], 0
    for rel, mod in model.modules.items():
        imports_jax = False
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                if any(
                    a.name == "jax" or a.name.startswith("jax.")
                    for a in node.names
                ):
                    imports_jax = True
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    imports_jax = True
        if not imports_jax:
            continue
        sub_rel = "/".join(rel.split("/")[1:])
        owners = _enclosing_functions(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = None
            if isinstance(f, ast.Attribute):
                if f.attr == "device_get":
                    hit = "jax.device_get"
                elif f.attr == "asarray" and (
                    isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")
                ):
                    hit = "np.asarray"
                elif f.attr == "block_until_ready":
                    hit = ".block_until_ready()"
            if hit is None:
                continue
            owner = owners.get(node, "<module>")
            if (sub_rel, owner) in DOWNLOAD_SITES:
                declared += 1
                continue
            findings.append(Finding(
                "download-confinement", "error", rel, node.lineno,
                f"{hit} in {owner} is not a declared download site — "
                "route the materialization through one (transfer bytes "
                "counted, blocking bounded) or extend DOWNLOAD_SITES "
                "with a review",
            ))
    return findings, declared


@rule("jax-compat-confinement", min_sites=3)
def jax_compat_confinement(model: ProjectModel):
    """The version-sensitive jax multi-host surface — ``jax.shard_map``
    attribute access, any ``jax.distributed`` attribute access, and
    imports of ``shard_map``/``jax.distributed`` — may only appear in
    compat.py, the one version-spanning chokepoint. ``shard_map``
    graduated out of ``jax.experimental`` and ``jax.distributed`` grew
    ``is_initialized`` across releases: a raw spelling anywhere else is
    exactly how the seed's 9 shard_map tests broke on a jax pin. Call
    sites spell ``compat.shard_map`` / ``compat.distributed_*`` so a
    jax upgrade touches one file."""
    findings, compat_sites = [], 0
    for rel, mod in model.modules.items():
        is_compat = rel == f"{model.package}/compat.py"
        for node in ast.walk(mod.tree):
            hit = None
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ) and node.value.id == "jax":
                if node.attr == "shard_map":
                    hit = "jax.shard_map"
                elif node.attr == "distributed":
                    hit = "jax.distributed"
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in (
                        "jax.distributed", "jax.experimental.shard_map"
                    ):
                        hit = f"import {a.name}"
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if m == "jax.experimental.shard_map" or (
                    m in ("jax", "jax.experimental")
                    and any(
                        a.name in ("shard_map", "distributed")
                        for a in node.names
                    )
                ):
                    hit = f"from {m} import"
            if hit is None:
                continue
            if is_compat:
                compat_sites += 1
            else:
                findings.append(Finding(
                    "jax-compat-confinement", "error", rel, node.lineno,
                    f"{hit} outside compat.py — spell it compat.shard_map"
                    " / compat.distributed_* so the version-spanning "
                    "surface stays in one file",
                ))
    return findings, compat_sites


#: handler calls that count as "the failure was handled, not swallowed"
FAILURE_HANDLERS = {
    "_fail", "fail", "_settle", "set_exception", "record_failure",
    "_recover", "record_degrade", "record_probe_failure",
    # sessions (PR 16): PileupLease.settle / settle_future resolve an
    # append's ack future exactly once — a handler that routes the
    # exception there has NOT swallowed it
    "settle", "settle_future",
}

#: deliberately-swallowing sites, each with a local reason (see the
#: original guard's rationale comments, preserved in DESIGN.md §18)
SWALLOW_ALLOWLIST = {
    ("serve/service.py", "_warm"),
    ("serve/service.py", "consensus_post_response"),
    ("serve/service.py", "_aot_provenance"),
    ("fleet/service.py", "_replica_healthz"),
    # obs (PR 18): the runtime-introspection probes poll best-effort
    # backend internals (jit cache sizes, device memory stats) whose
    # APIs vary across jax versions — a probe failure must degrade to
    # "no sample", never to a serving failure
    ("obs/runtime.py", "install"),
    ("obs/runtime.py", "jit_cache_sizes"),
    ("obs/runtime.py", "device_memory_stats"),
    ("obs/runtime.py", "update_device_gauges"),
    ("obs/runtime.py", "runtime_snapshot"),
    # obs/perfgate (PR 18): provenance() decorates a bench result line
    # with the gate verdict — a history-read failure must surface as
    # {"error": ...} in the provenance object, never void the headline
    ("obs/perfgate.py", "provenance"),
}

#: packages whose broad except handlers must handle the failure —
#: serve/resilience/fleet (original scope) plus ragged/parallel (the
#: two other layers that sit on the admitted-request path), devingest
#: (its oracle-fallback discipline uses TYPED excepts only; a broad
#: swallow there would hide a device/host divergence), paged (the
#: continuous-superbatching tier holds admitted futures AND page
#: references — a swallowed failure leaks both), and emit (the
#: device-rendered emission decode sits on the same admitted-request
#: settle path as the classic wire decoders)
#: ... and durable (PR 15): the admission journal is the crash-recovery
#: source of truth — a swallowed journal write error silently converts
#: "durable" into "best effort", which is the one lie the subsystem
#: must never tell
#: ... and sessions (PR 16): a streaming lease holds append acks AND
#: SSE subscribers across minutes — a swallowed failure there strands
#: a client mid-stream with no typed error and no final emit
#: ... and obs (PR 18): the observability plane is how every other
#: failure becomes visible — a swallowed error in trace collection or
#: SLO accounting silently blinds the operator exactly when the data
#: mattered, so its handlers must record_failure or stay typed
SWALLOW_SCOPE = (
    "serve", "resilience", "fleet", "ragged", "parallel", "devingest",
    "paged", "emit", "durable", "sessions", "obs",
)


@rule("silent-swallow", min_sites=5)
def silent_swallow(model: ProjectModel):
    """Every ``except Exception`` / ``except BaseException`` in the
    serving, resilience, fleet, ragged, and parallel layers must
    re-raise, resolve a future, or record the failure — a handler that
    does none of those is exactly how an admitted request gets silently
    lost (the invariant the chaos suites enforce dynamically; this rule
    catches the sites tests never reach)."""

    def catches_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        return bool(
            dotted_parts(handler.type) & {"Exception", "BaseException"}
        )

    def handles_failure(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                f = n.func
                name = (
                    f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None
                )
                if name in FAILURE_HANDLERS:
                    return True
        return False

    findings, sites = [], 0
    for rel, mod in model.modules.items():
        parts = rel.split("/")
        if len(parts) < 2 or parts[1] not in SWALLOW_SCOPE:
            continue
        sub_rel = "/".join(parts[1:])
        owners = _enclosing_functions(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not catches_broad(node):
                continue
            sites += 1
            owner = owners.get(node, "<module>")
            if (sub_rel, owner) in SWALLOW_ALLOWLIST:
                continue
            if not handles_failure(node):
                findings.append(Finding(
                    "silent-swallow", "error", rel, node.lineno,
                    f"broad except in {owner} neither re-raises, "
                    "resolves a future, nor records the failure — add "
                    "handling or extend SWALLOW_ALLOWLIST with a "
                    "justification",
                ))
    return findings, sites
