"""Rule catalogue — importing this package registers every rule with
the engine. Two tiers: migrated tier-1 hygiene guards (hygiene), and
whole-program analyses the flat guards could not express (purity,
locks, futures, conformance). DESIGN.md §18 is the narrative index."""

from kindel_tpu.analysis.rules import (  # noqa: F401  (registration)
    conformance,
    futures,
    hygiene,
    locks,
    purity,
)
