"""Host decode of the device-rendered emission wire (see package doc).

jax-free on purpose: everything here runs on host threads after the
download already happened at a declared download site (call_jax.
unpack_wire, ragged.unpack, batch._assemble_outputs) — the
download-confinement lint (kindel_tpu.analysis) holds this module to
the same discipline as io/.
"""

from __future__ import annotations

import numpy as np

from kindel_tpu.call import CallMasks


def emit_plane_wire_bytes(length: int, i_pad: int) -> int:
    """Bytes one unit's emission wire carries (plane + packed insertion
    flags) — the per-request d2h cost the bench's `transfers` object
    compares against the wire-plane formats."""
    return int(length) + -(-int(i_pad) // 8)


def masks_from_emit_plane(plane: np.ndarray, ins_flag_bits: np.ndarray,
                          L: int, ins_pos: np.ndarray) -> CallMasks:
    """Rebuild assembler inputs from the device-rendered ASCII plane:
    `base_char` is the plane verbatim (the device already resolved
    argmax/tie/low-coverage to the final character), deletion skips are
    its zero bytes, and the insertion mask gathers from the bit-packed
    flags at the (host-known) sparse insertion positions — the same
    sparse-gather contract as `call_jax.decode_fast`. `n_mask` stays
    empty: the plane already carries N where the host path would have
    folded it in."""
    plane = np.asarray(plane)
    if plane.shape[0] < L:
        # a short plane must fail loudly, same contract as decode_fast —
        # silent truncation would emit a shorter consensus, not an error
        raise ValueError(
            f"emission plane too short for L={L}: {plane.shape[0]} bytes"
        )
    base_char = plane[:L]
    ins_flags = np.unpackbits(
        np.asarray(ins_flag_bits)
    )[: len(ins_pos)].astype(bool)
    ins_mask = np.zeros(L, dtype=bool)
    if len(ins_pos):
        ins_mask[ins_pos[(ins_pos < L) & ins_flags]] = True
    return CallMasks(
        base_char=base_char,
        del_mask=base_char == 0,
        n_mask=np.zeros(L, dtype=bool),
        ins_mask=ins_mask,
    )
