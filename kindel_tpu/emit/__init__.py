"""kindel_tpu.emit — device-rendered consensus emission (DESIGN.md §22).

Closing the wire at the output end: on the classic fast path the device
ships a 2-bit ACGT plane plus exception/deletion/insertion flag
bitmasks, and the host reconstructs per-position decisions
(`call_jax.decode_fast`) before splicing the final sequence. Under
``--emit-mode device`` the argmax/threshold decision code that already
runs on device *renders the final per-position ASCII base plane there*
— byte 0 for a deletion skip, ``N`` for low coverage and ties,
``A``/``T``/``G``/``C`` otherwise, exactly the characters
`call.assemble` would emit — and the wire carries only that plane plus
the sparse insertion flags. Host work shrinks to insertion-string
splicing and FASTA headers/line-wrap.

The decode here is deliberately thin: `masks_from_emit_plane` rebuilds
a `CallMasks` whose `base_char` IS the device plane (``del_mask`` is
the zero bytes, ``n_mask`` is already folded into the plane) and hands
it to the SAME `call.assemble` the host oracle runs — so byte-identity
with ``--emit-mode host`` follows from the device rendering the same
0..5 emission codes the masks wire packs (`call_jax._decide` shares the
code between both paths), not from a parallel reimplementation.

Why this is a transfer win where it matters: the emission plane is one
byte per *slot*, so a ragged superbatch downloads only its payload
prefix and a paged launch tick fetches only the retiring segments'
slices (`ragged.unpack`) — d2h per request becomes O(consensus length)
instead of O(page grid) wire planes. On the dense lanes/cohort path the
plane is larger than the packed 2-bit wire, which is exactly why the
knob resolves per host through `kindel_tpu.tune`
(``kindel tune --emit-mode-budget-s`` measures both) and defaults to
the host oracle.
"""

from kindel_tpu.emit.decode import (
    emit_plane_wire_bytes,
    masks_from_emit_plane,
)

__all__ = ["masks_from_emit_plane", "emit_plane_wire_bytes"]
