"""L5 — command-line interface.

Subcommand surface matches the reference CLI (consensus / weights /
features / plot / version, /root/reference/kindel/cli.py:9-70) plus the
`variants` subcommand its README promised (README.md:106). Every data
subcommand takes `--backend {numpy,jax}`. Flag names and defaults replicate
the reference — including the CLI default min_overlap=7 vs the Python API's 9
(/root/reference/kindel/cli.py:13 vs kindel.py:492; SURVEY §2.1).
"""

from __future__ import annotations

import argparse
import sys

from kindel_tpu import __version__, workloads


def _add_backend(p: argparse.ArgumentParser):
    p.add_argument(
        "--backend",
        choices=workloads.BACKENDS,
        default="numpy",
        help="compute backend: numpy (host oracle) or jax (TPU/jit)",
    )


def _consensus_parser(sub):
    p = sub.add_parser(
        "consensus", help="infer consensus sequence(s) from a SAM/BAM file"
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    p.add_argument(
        "-r", "--realign", action="store_true",
        help="attempt to reconstruct reference around soft-clip boundaries",
    )
    p.add_argument(
        "--min-depth", type=int, default=1,
        help="substitute Ns at coverage depths beneath this value",
    )
    p.add_argument(
        "--min-overlap", type=int, default=7,
        help="match length required to close soft-clipped gaps",
    )
    p.add_argument(
        "-c", "--clip-decay-threshold", type=float, default=0.1,
        help="read depth fraction at which to cease clip extension",
    )
    p.add_argument(
        "--mask-ends", type=int, default=50,
        help="ignore clip dominant positions within n positions of termini",
    )
    p.add_argument(
        "-t", "--trim-ends", action="store_true",
        help="trim ambiguous nucleotides (Ns) from sequence ends",
    )
    p.add_argument(
        "-u", "--uppercase", action="store_true",
        help="close gaps using uppercase alphabet",
    )
    p.add_argument(
        "--profile", action="store_true",
        help="print per-phase wall-time report to stderr "
             "(set KINDEL_TPU_TRACE_DIR for an XLA profiler trace)",
    )
    _add_backend(p)


def cmd_consensus(args) -> int:
    timer = None
    if args.profile:
        from kindel_tpu.utils.profiling import disable_profiling, enable_profiling

        timer = enable_profiling()
        timer.start_trace()
    try:
        res = workloads.bam_to_consensus(
            args.bam_path,
            realign=args.realign,
            min_depth=args.min_depth,
            min_overlap=args.min_overlap,
            clip_decay_threshold=args.clip_decay_threshold,
            mask_ends=args.mask_ends,
            trim_ends=args.trim_ends,
            uppercase=args.uppercase,
            backend=args.backend,
        )
    finally:
        if timer is not None:
            timer.stop_trace()
            timer.print_report()
            disable_profiling()
    print("\n".join(res.refs_reports.values()), file=sys.stderr)
    for record in res.consensuses:
        print(f">{record.name}")
        print(record.sequence)
    return 0


def cmd_weights(args) -> int:
    df = workloads.weights(
        args.bam_path,
        relative=args.relative,
        confidence=not args.no_confidence,
        confidence_alpha=args.confidence_alpha,
        backend=args.backend,
    )
    df.to_csv(sys.stdout, sep="\t", index=False)
    return 0


def cmd_features(args) -> int:
    df = workloads.features(args.bam_path, backend=args.backend)
    df.to_csv(sys.stdout, sep="\t", index=False)
    return 0


def cmd_variants(args) -> int:
    df = workloads.variants(
        args.bam_path,
        min_count=args.min_count,
        min_frequency=args.min_frequency,
        indels=not args.no_indels,
        backend=args.backend,
    )
    df.to_csv(sys.stdout, sep="\t", index=False)
    return 0


def cmd_plot(args) -> int:
    workloads.plot_clips(args.bam_path, backend=args.backend)
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kindel-tpu",
        description="TPU-native indel-aware consensus from aligned BAMs",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    _consensus_parser(sub)

    p = sub.add_parser(
        "weights", help="per-site nucleotide frequencies and coverage"
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    p.add_argument(
        "-r", "--relative", action="store_true",
        help="output relative nucleotide frequencies",
    )
    p.add_argument(
        "-n", "--no-confidence", action="store_true",
        help="skip consensus confidence intervals",
    )
    p.add_argument(
        "-c", "--confidence-alpha", type=float, default=0.01,
        help="confidence interval alpha",
    )
    _add_backend(p)

    p = sub.add_parser(
        "features",
        help="relative per-site nucleotide frequencies incl. indels",
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    _add_backend(p)

    p = sub.add_parser(
        "variants",
        help="variants exceeding absolute and relative frequency thresholds",
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    p.add_argument(
        "-a", "--min-count", type=int, default=1,
        help="minimum absolute observation count",
    )
    p.add_argument(
        "-f", "--min-frequency", type=float, default=0.0,
        help="minimum relative frequency",
    )
    p.add_argument(
        "--no-indels", action="store_true",
        help="exclude insertion/deletion variants",
    )
    _add_backend(p)

    p = sub.add_parser(
        "plot", help="sitewise depth/soft-clipping HTML dashboard"
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    _add_backend(p)

    sub.add_parser("version", help="show version")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        print(f"kindel-tpu {__version__}")
        return 0
    return {
        "consensus": cmd_consensus,
        "weights": cmd_weights,
        "features": cmd_features,
        "variants": cmd_variants,
        "plot": cmd_plot,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
