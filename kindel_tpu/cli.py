"""L5 — command-line interface.

Subcommand surface matches the reference CLI (consensus / weights /
features / plot / version, /root/reference/kindel/cli.py:9-70) plus the
`variants` subcommand its README promised (README.md:106). Every data
subcommand takes `--backend {numpy,jax}`. Flag names and defaults replicate
the reference — including the CLI default min_overlap=7 vs the Python API's 9
(/root/reference/kindel/cli.py:13 vs kindel.py:492; SURVEY §2.1).
"""

from __future__ import annotations

import argparse
import sys

from kindel_tpu import __version__, workloads


def _progress_parent() -> argparse.ArgumentParser:
    """--progress / --trace are accepted both before and after the
    subcommand (every other option lives on the subparser, so users will
    naturally type them there)."""
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument(
        # SUPPRESS: the subparser copies its parsed namespace over the
        # root's, so an ordinary default here would clobber a
        # root-position `--progress`; with SUPPRESS the attribute only
        # exists where the flag was actually given
        "--progress", action="store_true", default=argparse.SUPPRESS,
        help="report progress on stderr (chunks, contigs, cohort samples; "
             "also auto-enabled when stderr is a terminal — the reference's "
             "tqdm-bars equivalent)",
    )
    p.add_argument(
        "--trace", metavar="PATH", default=argparse.SUPPRESS,
        help="write a hierarchical span trace of this run (kindel_tpu.obs): "
             ".json -> Perfetto/chrome://tracing trace_event document, any "
             "other suffix -> JSONL (one span per line)",
    )
    p.add_argument(
        "--faults", metavar="SPEC", default=argparse.SUPPRESS,
        help="activate a seeded fault-injection plan for chaos testing "
             "(kindel_tpu.resilience), e.g. "
             "'seed=7,device.dispatch:oom:2,io.read_chunk:truncate'; "
             "overrides $KINDEL_TPU_FAULTS (see docs/usage.md)",
    )
    return p


def _nonneg_int(value: str) -> int:
    n = int(value)
    if n < 0:
        raise argparse.ArgumentTypeError("must be >= 0")
    return n


# argparse builds its "invalid ... value" message from type.__name__
_nonneg_int.__name__ = "non-negative int"


def _add_backend(p: argparse.ArgumentParser):
    p.add_argument(
        "--backend",
        choices=workloads.BACKENDS,
        default="numpy",
        help="compute backend: numpy (host oracle) or jax (TPU/jit)",
    )


def _consensus_parser(sub):
    p = sub.add_parser(
        "consensus", help="infer consensus sequence(s) from a SAM/BAM file"
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    p.add_argument(
        "-r", "--realign", action="store_true",
        help="attempt to reconstruct reference around soft-clip boundaries",
    )
    p.add_argument(
        "--min-depth", type=int, default=1,
        help="substitute Ns at coverage depths beneath this value",
    )
    p.add_argument(
        "--min-overlap", type=int, default=7,
        help="match length required to close soft-clipped gaps",
    )
    p.add_argument(
        "-c", "--clip-decay-threshold", type=float, default=0.1,
        help="read depth fraction at which to cease clip extension",
    )
    p.add_argument(
        "--mask-ends", type=int, default=50,
        help="ignore clip dominant positions within n positions of termini",
    )
    p.add_argument(
        "--cdr-gap", type=_nonneg_int, default=0, metavar="N",
        help="pair facing clip-dominant regions across up to N uncovered "
             "positions (beyond the reference, which requires overlapping "
             "spans and cannot close wide divergent segments — its own "
             "disabled gp120 case); the min-overlap merge gate still "
             "rejects false pairs. 0 (default) = reference-exact pairing",
    )
    p.add_argument(
        "--fix-clip-artifacts", action="store_true",
        help="fix two boundary artifacts the reference's own disabled "
             "issue23 test documents: insertions no longer emit where the "
             "min(depth, next-depth) threshold floor is zero (one stray "
             "read fabricated sequence), and a clip extension's first base "
             "that duplicates the unambiguous flank consensus is dropped. "
             "Off by default = reference-exact output",
    )
    p.add_argument(
        "-t", "--trim-ends", action="store_true",
        help="trim ambiguous nucleotides (Ns) from sequence ends",
    )
    p.add_argument(
        "-u", "--uppercase", action="store_true",
        help="close gaps using uppercase alphabet",
    )
    p.add_argument(
        "--profile", action="store_true",
        help="print per-phase wall-time report to stderr "
             "(set KINDEL_TPU_TRACE_DIR for an XLA profiler trace)",
    )
    p.add_argument(
        "--stream-chunk-mb", type=float, default=None, metavar="MB",
        help="stream the decode in chunks of this many (decompressed) MB, "
             "bounding host memory at O(chunk + reference length); files "
             "over $KINDEL_TPU_STREAM_THRESHOLD_MB (default 512) stream "
             "automatically",
    )
    p.add_argument(
        "--slabs", type=int, default=None, metavar="N",
        help="pin the slab-pipeline count explicitly (top of the "
             "explicit > $KINDEL_TPU_SLABS > tune store > default "
             "resolution order; `kindel tune` measures and persists a "
             "per-host winner)",
    )
    p.add_argument(
        "--ingest-workers", type=int, default=None, metavar="N",
        help="pin the parallel BGZF-inflate worker count (top of the "
             "explicit > $KINDEL_TPU_INGEST_WORKERS > tune store > "
             "per-core default order; 1 = the serial inflate path)",
    )
    p.add_argument(
        "--ingest-mode", choices=["host", "device"], default=None,
        help="where the streamed decode's record scan + CIGAR event "
             "expansion run: 'host' = numpy (the oracle), 'device' = "
             "the kindel_tpu.devingest kernels on the accelerator — "
             "byte-identical output (top of the explicit > "
             "$KINDEL_TPU_INGEST_MODE > tune store > host order; "
             "`kindel tune --ingest-mode-budget-s` measures a winner)",
    )
    p.add_argument(
        "--emit-mode", choices=["host", "device"], default=None,
        help="where the final per-position base plane renders: 'host' "
             "decodes the packed call wire (the oracle), 'device' "
             "renders the ASCII emission plane on the accelerator and "
             "ships only it + sparse insertion flags — byte-identical "
             "output (explicit > $KINDEL_TPU_EMIT_MODE > tune store > "
             "host; `kindel tune --emit-mode-budget-s` measures a "
             "winner). Applies to the fast (no-changes) path",
    )
    p.add_argument(
        "--mesh", type=str, default=None, metavar="SPEC",
        help="device-mesh spec: '<dp>' fans the call across up to dp "
             "local devices (1 pins single-device); 'pod' / 'pod:<dp>' "
             "spans every process of the JAX group (DESIGN.md §27). "
             "Top of the explicit > $KINDEL_TPU_MESH > tune store > "
             "all-local-devices order; `kindel tune --mesh-budget-s` "
             "measures a winner. Byte-identical output at every width",
    )
    _add_backend(p)


def cmd_consensus(args) -> int:
    timer = None
    if args.profile:
        from kindel_tpu.utils.profiling import disable_profiling, enable_profiling

        timer = enable_profiling()
        timer.start_trace()
    tuning = None
    if (
        args.slabs is not None
        or args.ingest_workers is not None
        or args.ingest_mode is not None
        or args.emit_mode is not None
        or args.mesh is not None
    ):
        from kindel_tpu.tune import TuningConfig

        tuning = TuningConfig(
            n_slabs=args.slabs, ingest_workers=args.ingest_workers,
            ingest_mode=args.ingest_mode, emit_mode=args.emit_mode,
            mesh=args.mesh,
        )
    try:
        res = workloads.bam_to_consensus(
            args.bam_path,
            realign=args.realign,
            min_depth=args.min_depth,
            min_overlap=args.min_overlap,
            clip_decay_threshold=args.clip_decay_threshold,
            mask_ends=args.mask_ends,
            trim_ends=args.trim_ends,
            uppercase=args.uppercase,
            backend=args.backend,
            stream_chunk_mb=args.stream_chunk_mb,
            cdr_gap=args.cdr_gap,
            fix_clip_artifacts=args.fix_clip_artifacts,
            tuning=tuning,
        )
    finally:
        if timer is not None:
            timer.stop_trace()
            timer.print_report()
            disable_profiling()
    print("\n".join(res.refs_reports.values()), file=sys.stderr)
    for record in res.consensuses:
        print(f">{record.name}")
        print(record.sequence)
    return 0


def _write_tsv(df, fh) -> None:
    """TSV out through pyarrow's C++ CSV writer when available — pandas'
    per-value float formatting dominates to_csv wall time on megabase
    tables (~20 s for a 6.1 Mb genome vs ~1 s via arrow). Falls back to
    pandas with identical column content; float rendering may differ in
    trailing-zero style between the two paths (values are pre-rounded in
    the workloads, so no information differs). NaN renders as the empty
    field either way."""
    try:
        import pyarrow as pa
        import pyarrow.csv as pacsv

        table = pa.Table.from_pandas(df, preserve_index=False)
        buf = pa.BufferOutputStream()
        pacsv.write_csv(
            table,
            buf,
            # header written by hand: arrow quotes header cells regardless
            # of the data quoting style
            pacsv.WriteOptions(
                delimiter="\t", quoting_style="none", include_header=False
            ),
        )
    except Exception:
        # pyarrow absent, or too old for quoting_style (<8) — the slow
        # path is always correct
        df.to_csv(fh, sep="\t", index=False)
        return
    data = (
        "\t".join(map(str, df.columns)).encode()
        + b"\n"
        + buf.getvalue().to_pybytes()
    )
    out = fh.buffer if hasattr(fh, "buffer") else fh
    try:
        out.write(data)
    except TypeError:  # text-mode StringIO and friends
        fh.write(data.decode())


def cmd_weights(args) -> int:
    df = workloads.weights(
        args.bam_path,
        relative=args.relative,
        confidence=not args.no_confidence,
        confidence_alpha=args.confidence_alpha,
        backend=args.backend,
    )
    _write_tsv(df, sys.stdout)
    return 0


def cmd_features(args) -> int:
    df = workloads.features(args.bam_path, backend=args.backend)
    _write_tsv(df, sys.stdout)
    return 0


def cmd_variants(args) -> int:
    df = workloads.variants(
        args.bam_path,
        min_count=args.min_count,
        min_frequency=args.min_frequency,
        indels=not args.no_indels,
        backend=args.backend,
    )
    _write_tsv(df, sys.stdout)
    return 0


def cmd_plot(args) -> int:
    workloads.plot_clips(args.bam_path, backend=args.backend)
    return 0


def cmd_batch(args) -> int:
    """Cohort consensus: one fused device program per chunk of samples,
    host decode of chunk k+1 overlapped with device compute of chunk k
    (kindel_tpu.batch; BASELINE.json config 5)."""
    import os

    from kindel_tpu.batch import stream_bam_to_results
    from kindel_tpu.io.fasta import format_fasta

    os.makedirs(args.out_dir, exist_ok=True)

    # the stream yields results keyed by path, so a path listed twice is
    # both redundant work and an output collision — process it once
    inputs: list = []
    seen: set = set()
    for p in args.bam_paths:
        if p in seen:
            print(f"warning: duplicate input {p} ignored", file=sys.stderr)
            continue
        seen.add(p)
        inputs.append(p)

    # map inputs to output names up front, disambiguating stem collisions
    # (a/s1.bam + b/s1.bam → s1.fa, s1-2.fa) so no sample is clobbered
    out_paths: dict = {}
    stems_used: dict[str, int] = {}
    for p in inputs:
        stem = os.path.splitext(os.path.basename(str(p)))[0]
        n = stems_used.get(stem, 0) + 1
        stems_used[stem] = n
        name = stem if n == 1 else f"{stem}-{n}"
        out_paths[p] = os.path.join(args.out_dir, name + ".fa")

    todo = inputs
    if args.resume:
        # existence is completeness: publication below is atomic (tmp +
        # os.replace), so even a 0-byte .fa (sample with no aligned reads)
        # is a finished result
        def complete(p) -> bool:
            if not os.path.exists(out_paths[p]):
                return False
            if args.reports:
                rep = os.path.splitext(out_paths[p])[0] + ".report.txt"
                # a 0-byte .fa (no aligned reads) legitimately has no report
                if os.path.getsize(out_paths[p]) and not os.path.exists(rep):
                    return False
            return True

        skip = {p for p in todo if complete(p)}
        todo = [p for p in todo if p not in skip]
        if skip:
            print(
                f"resume: skipping {len(skip)} already-written sample(s)",
                file=sys.stderr,
            )
    n_done = 0
    for path, res in stream_bam_to_results(
        todo,
        chunk_size=args.chunk_size,
        num_workers=args.workers,
        realign=args.realign,
        min_depth=args.min_depth,
        min_overlap=args.min_overlap,
        clip_decay_threshold=args.clip_decay_threshold,
        mask_ends=args.mask_ends,
        cdr_gap=args.cdr_gap,
        fix_clip_artifacts=args.fix_clip_artifacts,
        trim_ends=args.trim_ends,
        uppercase=args.uppercase,
        build_reports=args.reports,
    ):
        # atomic publish: a kill mid-write must not leave a truncated .fa
        # that --resume would later treat as complete
        dest = out_paths[path]
        tmp = dest + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(format_fasta(res.consensuses))
        os.replace(tmp, dest)
        if args.reports and res.refs_reports:
            rep = os.path.splitext(dest)[0] + ".report.txt"
            with open(rep + ".tmp", "w") as fh:
                fh.write("\n".join(res.refs_reports.values()))
            os.replace(rep + ".tmp", rep)
        n_done += 1
    print(f"wrote {n_done} consensus file(s) to {args.out_dir}",
          file=sys.stderr)
    return 0


def _serve_parser(sub):
    p = sub.add_parser(
        "serve",
        help="online consensus service: dynamic micro-batching over the "
             "cohort kernel, admission control, live /metrics",
    )
    p.add_argument(
        "--host", default="127.0.0.1", help="HTTP bind address"
    )
    p.add_argument(
        "--port", type=int, default=8765,
        help="HTTP port (POST /v1/consensus, GET /metrics, GET /healthz); "
             "0 binds an ephemeral port",
    )
    p.add_argument(
        "--max-batch-rows", type=int, default=64,
        help="flush a coalescing lane when it reaches this many cohort rows",
    )
    p.add_argument(
        "--max-wait-ms", type=float, default=20.0,
        help="flush a lane when its oldest request has waited this long — "
             "bounds added latency when traffic is sparse",
    )
    p.add_argument(
        "--max-depth", type=int, default=256,
        help="absolute queue bound",
    )
    p.add_argument(
        "--watermark", type=int, default=None,
        help="admission watermark: reject with Retry-After past this queue "
             "depth (default: --max-depth)",
    )
    p.add_argument(
        "--workers", type=int, default=4,
        help="host decode/assembly threads",
    )
    p.add_argument(
        "--min-depth", type=int, default=1,
        help="substitute Ns at coverage depths beneath this value",
    )
    p.add_argument(
        "-r", "--realign", action="store_true",
        help="attempt to reconstruct reference around soft-clip boundaries",
    )
    p.add_argument(
        "--min-overlap", type=int, default=7,
        help="match length required to close soft-clipped gaps",
    )
    p.add_argument(
        "-c", "--clip-decay-threshold", type=float, default=0.1,
        help="read depth fraction at which to cease clip extension",
    )
    p.add_argument(
        "--mask-ends", type=int, default=50,
        help="ignore clip dominant positions within n positions of termini",
    )
    p.add_argument(
        "--cdr-gap", type=_nonneg_int, default=0, metavar="N",
        help="pair facing clip-dominant regions across up to N uncovered "
             "positions (see the consensus subcommand's help)",
    )
    p.add_argument(
        "--fix-clip-artifacts", action="store_true",
        help="fix the reference's issue23 boundary artifacts "
             "(see the consensus subcommand's help)",
    )
    p.add_argument(
        "-t", "--trim-ends", action="store_true",
        help="trim ambiguous nucleotides (Ns) from sequence ends",
    )
    p.add_argument(
        "-u", "--uppercase", action="store_true",
        help="close gaps using uppercase alphabet",
    )
    p.add_argument(
        "--no-warmup", action="store_true",
        help="skip the startup AOT warmup (first request on each "
             "lane shape then pays its own load/compile)",
    )
    p.add_argument(
        "--warm", action="append", default=[], metavar="PATH",
        help="representative SAM/BAM payload(s) whose lane shapes are "
             "readied at startup (repeatable); the minimal synthetic "
             "lane is always warmed unless --no-warmup. With a warm AOT "
             "store the shapes LOAD instead of compiling — zero-compile "
             "startup (see `kindel tune --export-aot`)",
    )
    p.add_argument(
        "--lane-coalesce", type=int, default=None, metavar="N",
        help="merge up to N ready micro-batcher flushes of one lane "
             "into a single fat device launch (top of the explicit > "
             "$KINDEL_TPU_LANE_COALESCE > default-4 order; 1 disables). "
             "Byte-identical to per-flush launches — it only cuts "
             "per-dispatch upload/launch overhead",
    )
    p.add_argument(
        "--batch-mode", choices=["lanes", "ragged", "paged"], default=None,
        help="admission→dispatch batching: 'lanes' keys coalescing on "
             "padded lane shapes (one compiled kernel per shape), "
             "'ragged' packs variable-length requests into fixed "
             "page-class superbatches with a segment table (one "
             "compiled/AOT executable per page class serves ALL "
             "shapes — DESIGN.md §16), 'paged' keeps the pileup "
             "resident as a paged device state with per-segment "
             "admit/retire — no flush barrier, same kernel, same "
             "geometry-only signature (DESIGN.md §20). Top of the "
             "explicit > $KINDEL_TPU_BATCH_MODE > default-lanes order",
    )
    p.add_argument(
        "--ragged-classes", default=None, metavar="SPEC",
        help="page-class geometry under --batch-mode ragged, e.g. "
             "'small:64x2048,medium:32x16384,large:8x131072' "
             "(name:ROWSxLENGTH; explicit > $KINDEL_TPU_RAGGED_CLASSES "
             "> tune store > default)",
    )
    p.add_argument(
        "--ingest-mode", choices=["host", "device"], default=None,
        help="where request decode's record scan + CIGAR expansion "
             "run: 'host' numpy or the kindel_tpu.devingest device "
             "kernels — byte-identical output (explicit > "
             "$KINDEL_TPU_INGEST_MODE > tune store > host)",
    )
    p.add_argument(
        "--emit-mode", choices=["host", "device"], default=None,
        help="where the final per-position base plane renders: 'host' "
             "wire decode or the device-rendered ASCII plane "
             "(kindel_tpu.emit — byte-identical; ragged/paged "
             "extraction then downloads O(consensus length) per "
             "request). Explicit > $KINDEL_TPU_EMIT_MODE > tune store "
             "> host",
    )
    p.add_argument(
        "--mesh", type=str, default=None, metavar="SPEC",
        help="per-replica device-mesh spec: every dispatch tier "
             "(lanes, ragged, paged) fans one flush across up to "
             "'<dp>' local devices (kindel_tpu.parallel.meshexec, "
             "DESIGN.md §23); 'pod' / 'pod:<dp>' spans every process "
             "of the JAX group as ONE program (DESIGN.md §27). 1 pins "
             "single-device; top of the explicit > $KINDEL_TPU_MESH > "
             "tune store > all-local-devices order. Byte-identical "
             "output at every width",
    )
    p.add_argument(
        "--replicas", type=int, default=1, metavar="N",
        help="run N supervised in-process replicas behind a failover "
             "router (kindel_tpu.fleet): rendezvous-hash placement, "
             "health-scored eviction with replay onto survivors, "
             "zero-downtime drain + warm restart. 1 (default) = the "
             "single-service path",
    )
    p.add_argument(
        "--probe-interval-ms", type=float, default=100.0,
        help="fleet supervisor health-probe cadence (only with "
             "--replicas > 1)",
    )
    p.add_argument(
        "--hedge-ms", type=float, default=None, metavar="MS",
        help="deadline-aware hedging: a request not completed after "
             "this long gets one speculative duplicate on the next "
             "healthy replica; first result wins (consensus is pure, "
             "so duplicates are byte-identical). Off by default; only "
             "with --replicas > 1",
    )
    p.add_argument(
        "--fleet-watermark", type=int, default=None,
        help="fleet-wide admission bound: reject with Retry-After once "
             "total queued depth across replicas reaches this (default: "
             "sum of per-replica watermarks; only with --replicas > 1)",
    )
    p.add_argument(
        "--replica-mode", choices=["thread", "process"], default="thread",
        help="where fleet replicas live: 'thread' = supervised "
             "in-process services (PR 8), 'process' = each replica is "
             "its own OS process behind RPC "
             "(kindel_tpu.fleet.procreplica) — the supervisor survives "
             "process loss, SIGKILLed replicas are respawned warm from "
             "the shared AOT store. Only with --replicas > 1 or "
             "autoscaling",
    )
    p.add_argument(
        "--min-replicas", type=int, default=None, metavar="N",
        help="autoscaler floor: with --max-replicas, the fleet "
             "spawns/retires replicas between these bounds from the "
             "router's watermark-shed + occupancy signals (hysteresis "
             "prevents flapping; DESIGN.md §21). Unset = fixed "
             "--replicas roster",
    )
    p.add_argument(
        "--max-replicas", type=int, default=None, metavar="N",
        help="autoscaler ceiling (see --min-replicas)",
    )
    p.add_argument(
        "--rpc-timeout-ms", type=float, default=None, metavar="MS",
        help="per-call deadline of one fleet RPC exchange under "
             "--replica-mode process (explicit > "
             "$KINDEL_TPU_RPC_TIMEOUT_MS > default 30000)",
    )
    p.add_argument(
        "--max-body-mb", type=int, default=None, metavar="MB",
        help="largest POST body the HTTP front reads; oversized "
             "requests get 413 + Retry-After before any allocation "
             "(explicit > $KINDEL_TPU_MAX_BODY_MB > default 1024)",
    )
    p.add_argument(
        "--journal-dir", default=None, metavar="DIR",
        help="durable admission journal (DESIGN.md §24): WAL every "
             "admitted request under DIR so a SIGKILLed replica "
             "process replays its orphans at respawn instead of losing "
             "them; fleet modes give each replica slot its own "
             "subdirectory (explicit > $KINDEL_TPU_JOURNAL_DIR > off)",
    )
    p.add_argument(
        "--quarantine-after", type=int, default=None, metavar="K",
        help="quarantine a journal entry blamed for K process crashes "
             "instead of replaying it again — the poison request then "
             "fails typed (HTTP 422, no retry) while healthy traffic "
             "serves (explicit > $KINDEL_TPU_QUARANTINE_AFTER > 3)",
    )
    p.add_argument(
        "--session-idle-s", type=float, default=None, metavar="S",
        help="reap a /v1/stream session after S seconds without an "
             "append or close (DESIGN.md §25): its lease retires, "
             "outstanding acks settle typed, and its journal frames "
             "close so the WAL can GC (explicit > "
             "$KINDEL_TPU_SESSION_IDLE_S > 300)",
    )
    p.add_argument(
        "--emit-delta", type=int, default=None, metavar="N",
        help="depth-delta emission gate of the streaming lane: a "
             "session re-renders its consensus only once N pileup "
             "events have accumulated since the last emitted update "
             "(CLOSE always forces a final emit; explicit > "
             "$KINDEL_TPU_EMIT_DELTA > 64)",
    )
    p.add_argument(
        "--slo", default=None, metavar="SPEC",
        help="declarative SLOs over the request settle path "
             "(kindel_tpu.obs.slo, DESIGN.md §26): objectives separated "
             "by ';', each 'route=/v1/consensus p99_ms=500 "
             "err_budget=0.1%%' with optional window_s=/fast_window_s=/"
             "fast_burn= overrides; burn-rate gauges export as "
             "kindel_slo_* and a fast-burning route flips /readyz to "
             "503 (explicit > $KINDEL_TPU_SLO > off)",
    )
    p.add_argument(
        "--trace-collect", default=None, metavar="PATH",
        help="stitch every process's spans into ONE merged Perfetto/"
             "Chrome trace at PATH on drain/stop "
             "(kindel_tpu.obs.fleetview): replicas spool spans and "
             "serve GET /v1/trace; the fleet front joins them by trace "
             "id across process boundaries; ring capacity per process "
             "via $KINDEL_TPU_TRACE_BUFFER (explicit > "
             "$KINDEL_TPU_TRACE_COLLECT > off)",
    )
    p.add_argument(
        "--replica-addrs", default=None, metavar="HOST:PORT,...",
        help="static fleet roster: drive PRE-SPAWNED remote replicas "
             "(each running python -m kindel_tpu.fleet.procreplica, or "
             "any serve stack with the RPC adapter routes) at these "
             "addresses over RPC — spawn/respawn disabled, probe/"
             "evict/drain/failover unchanged; the multi-host leg "
             "(overrides --replicas/--replica-mode; incompatible with "
             "autoscaling)",
    )


def install_drain_handlers(stop_event) -> None:
    """SIGTERM/SIGINT → graceful drain (satellite of the fleet PR):
    the first signal only SETS `stop_event`, letting the serve loop
    drain — stop admitting, finish every in-flight request, flush the
    final metric state — instead of the old abrupt exit that lost
    whatever was queued. A second signal raises KeyboardInterrupt so an
    operator can still force a fast (drain=False-shaped) exit when the
    drain itself is wedged. Must run on the main thread (signal.signal
    constraint)."""
    import signal

    def _on_signal(signum, frame):
        if stop_event.is_set():
            raise KeyboardInterrupt  # second signal: stop waiting
        stop_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)


def cmd_serve(args) -> int:
    """Run the online consensus service until signaled, then drain."""
    import threading

    tuning = None
    if (
        args.lane_coalesce is not None
        or args.batch_mode is not None
        or args.ragged_classes is not None
        or args.ingest_mode is not None
        or args.emit_mode is not None
        or args.mesh is not None
    ):
        from kindel_tpu.tune import TuningConfig

        tuning = TuningConfig(
            lane_coalesce=args.lane_coalesce,
            batch_mode=args.batch_mode,
            ragged_classes=args.ragged_classes,
            ingest_mode=args.ingest_mode,
            emit_mode=args.emit_mode,
            mesh=args.mesh,
        )
    service_kwargs = dict(
        tuning=tuning,
        max_batch_rows=args.max_batch_rows,
        max_wait_s=args.max_wait_ms / 1e3,
        max_depth=args.max_depth,
        high_watermark=args.watermark,
        decode_workers=args.workers,
        realign=args.realign,
        min_depth=args.min_depth,
        min_overlap=args.min_overlap,
        clip_decay_threshold=args.clip_decay_threshold,
        mask_ends=args.mask_ends,
        cdr_gap=args.cdr_gap,
        fix_clip_artifacts=args.fix_clip_artifacts,
        trim_ends=args.trim_ends,
        uppercase=args.uppercase,
        warmup=not args.no_warmup,
        warm_payloads=args.warm,
        journal_dir=args.journal_dir,
        quarantine_after=args.quarantine_after,
        session_idle_s=args.session_idle_s,
        emit_delta=args.emit_delta,
    )
    autoscale = (
        args.min_replicas is not None and args.max_replicas is not None
    )
    fleet_wanted = (
        args.replicas > 1 or autoscale or args.replica_mode == "process"
    )
    if args.replica_addrs:
        # static roster (DESIGN.md §24 / ROADMAP multi-host leg b):
        # pre-spawned remote replicas join the fleet by address —
        # spawn/respawn disabled, probe/evict/drain/failover unchanged
        from kindel_tpu.fleet import static_fleet

        service = static_fleet(
            args.replica_addrs,
            rpc_timeout_ms=args.rpc_timeout_ms,
            http_host=args.host,
            http_port=args.port,
            probe_interval_s=args.probe_interval_ms / 1e3,
            hedge_s=(
                args.hedge_ms / 1e3 if args.hedge_ms is not None else None
            ),
            fleet_watermark=args.fleet_watermark,
            max_body_mb=args.max_body_mb,
            slo=args.slo,
            trace_collect=args.trace_collect,
        )
        posture = (
            f"static roster of {len(service.replicas)} remote "
            "replicas over RPC (spawn/respawn disabled)"
        )
    elif fleet_wanted:
        fleet_kwargs = dict(
            replicas=max(args.replicas, args.min_replicas or 1),
            http_host=args.host,
            http_port=args.port,
            probe_interval_s=args.probe_interval_ms / 1e3,
            hedge_s=(
                args.hedge_ms / 1e3 if args.hedge_ms is not None else None
            ),
            fleet_watermark=args.fleet_watermark,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            max_body_mb=args.max_body_mb,
            # fleet-front observability plane (DESIGN.md §26): the SLO
            # engine and the stitched-trace collector live on the
            # front, never in replica children
            slo=args.slo,
            trace_collect=args.trace_collect,
        )
        scale_note = (
            f", autoscaling {args.min_replicas}-{args.max_replicas}"
            if autoscale else ""
        )
        if args.replica_mode == "process":
            from kindel_tpu.fleet.procreplica import ProcessFleetService

            # children rebuild TuningConfig from a plain dict (the
            # config crosses a process boundary as JSON)
            config = {
                k: v for k, v in service_kwargs.items() if k != "tuning"
            }
            if tuning is not None:
                config["tuning"] = {
                    "lane_coalesce": args.lane_coalesce,
                    "batch_mode": args.batch_mode,
                    "ragged_classes": args.ragged_classes,
                    "ingest_mode": args.ingest_mode,
                    "emit_mode": args.emit_mode,
                    "mesh": args.mesh,
                }
            service = ProcessFleetService(
                service_config=config,
                rpc_timeout_ms=args.rpc_timeout_ms,
                **fleet_kwargs,
            )
            posture = (
                f"{fleet_kwargs['replicas']} replica processes over RPC "
                f"(kindel_tpu.fleet.procreplica{scale_note})"
            )
        else:
            from kindel_tpu.fleet import FleetService

            service = FleetService(**fleet_kwargs, **service_kwargs)
            posture = (
                f"{fleet_kwargs['replicas']} supervised replicas "
                f"(kindel_tpu.fleet{scale_note})"
            )
    else:
        from kindel_tpu.serve import ConsensusService

        service = ConsensusService(
            http_host=args.host, http_port=args.port,
            max_body_mb=args.max_body_mb, slo=args.slo,
            trace_collect=args.trace_collect, **service_kwargs
        )
        posture = "single replica"
    service.start()
    host, port = service.http_address
    print(
        f"kindel-tpu serving on http://{host}:{port} [{posture}] — "
        "POST /v1/consensus (SAM/BAM body -> FASTA), GET /metrics, "
        "GET /healthz, GET /readyz; SIGTERM/Ctrl-C to drain and stop"
        + ("" if args.no_warmup
           else " (AOT warmup running; /readyz flips 503 -> 200)"),
        file=sys.stderr,
    )
    stop_event = threading.Event()
    install_drain_handlers(stop_event)
    forced = False
    try:
        stop_event.wait()
        print(
            "draining: admission closed, finishing in-flight requests…",
            file=sys.stderr,
        )
    except KeyboardInterrupt:
        forced = True
        print("forced stop: failing pending requests…", file=sys.stderr)
    finally:
        if forced:
            service.stop(drain=False)
        else:
            # both shapes drain the same way: admission closed first,
            # everything already admitted served, then threads join
            service.drain()
        print("drained; bye", file=sys.stderr)
    return 0


def _tune_parser(sub):
    p = sub.add_parser(
        "tune",
        help="pre-tune this host offline: measure the slab-pipeline "
             "sweep on a representative BAM and persist the winner in "
             "the tune store (~/.cache/kindel_tpu/tune.json) so every "
             "later run starts hot",
    )
    p.add_argument(
        "bam_path",
        help="representative SAM/BAM file (the tuned value is keyed by "
             "this workload's contig-scale bucket)",
    )
    p.add_argument(
        "--budget-s", type=float, default=300.0,
        help="wall budget for the measurement loop; whatever configs are "
             "measured by then decide the pick",
    )
    p.add_argument(
        "--repeats", type=int, default=2,
        help="timed passes per config (best-of; single-pass walls are "
             "noisy on shared hosts)",
    )
    p.add_argument(
        "--ingest-budget-s", type=float, default=20.0,
        help="wall budget for the parallel-ingest worker sweep (streamed "
             "decode passes over the same BAM); 0 skips it",
    )
    p.add_argument(
        "--ingest-mode-budget-s", type=float, default=0.0,
        help="wall budget for the ingest-mode sweep (one streamed "
             "decode+expand pass per mode: host numpy vs the devingest "
             "device kernels); the winner persists host-keyed so every "
             "streamed entry point and serve decode start in the "
             "measured mode. 0 (default) skips it",
    )
    p.add_argument(
        "--ragged-budget-s", type=float, default=0.0,
        help="wall budget for the ragged page-class geometry sweep "
             "(packs this BAM's units into each candidate class set and "
             "times the segment kernel). Candidates derive from the "
             "traffic histogram the serve batcher records (host-keyed); "
             "the static ladder is the cold-start fallback. The winner "
             "persists host-keyed so `kindel serve --batch-mode "
             "ragged|paged` starts with measured geometry. 0 (default) "
             "skips it",
    )
    p.add_argument(
        "--emit-mode-budget-s", type=float, default=0.0,
        help="wall budget for the emission-mode sweep (one no-changes "
             "consensus pass per mode: host wire decode vs the "
             "device-rendered ASCII plane, kindel_tpu.emit); the winner "
             "persists host-keyed so every fast-path entry point starts "
             "in the measured mode. 0 (default) skips it",
    )
    p.add_argument(
        "--mesh-budget-s", type=float, default=0.0,
        help="wall budget for the device-mesh width sweep (one cohort "
             "pass per candidate dp over this BAM's units — the width "
             "every dispatch tier fans one flush across, "
             "kindel_tpu.parallel.meshexec); the winner persists "
             "host-keyed so `kindel serve`/`consensus` start on the "
             "measured mesh. 0 (default) skips it",
    )
    p.add_argument(
        "--dry-run", action="store_true",
        help="measure and report, but do not write the tune store",
    )
    p.add_argument(
        "--export-aot", action="store_true",
        help="also AOT-compile, parity-check, and serialize the device "
             "executables this host will serve — the batched cohort "
             "kernel for every startup-derivable lane shape (synthetic "
             "+ this BAM's), and the fused single-sample kernel for "
             "this BAM's upload geometry — into the tune store's aot/ "
             "directory, so a fresh `kindel serve` replica (or any "
             "host this cache is copied to) starts with ZERO compiles",
    )


def cmd_tune(args) -> int:
    """Offline host pre-tune: the bench's budget-bounded slab search
    plus the parallel-ingest worker sweep, run through the library
    (kindel_tpu.tune) and persisted."""
    import json
    import time as _time

    import jax

    from kindel_tpu import tune
    from kindel_tpu.call_jax import call_consensus_fused
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment

    ev = extract_events(load_alignment(args.bam_path))
    if not ev.present_ref_ids:
        print(f"{args.bam_path}: no aligned reads — nothing to tune",
              file=sys.stderr)
        return 1
    max_contig = max(int(ev.ref_lens[r]) for r in ev.present_ref_ids)
    clamp = tune.slab_clamp(max_contig)
    backend = jax.default_backend()
    key = tune.store_key(backend, max_contig)

    def one_pass(slabs: int) -> None:
        for rid in ev.present_ref_ids:
            res, _dmin, _dmax = call_consensus_fused(
                ev, rid, build_changes=False,
                tuning=tune.TuningConfig(n_slabs=slabs),
            )
            assert len(res.sequence) > 0

    t0 = _time.perf_counter()
    chosen, timings = tune.measured_slabs(
        one_pass, clamp, args.budget_s, repeats=args.repeats
    )
    wall = _time.perf_counter() - t0
    persisted = False
    if not args.dry_run:
        persisted = tune.record(
            key,
            {
                "n_slabs": chosen,
                "timings_s": {str(k): round(v, 4) for k, v in timings.items()},
                "tune_wall_s": round(wall, 3),
                "bam_path": str(args.bam_path),
            },
        )

    # parallel-ingest sweep: streamed decode passes with the worker
    # count explicit (same no-env-mutation contract as the slab search);
    # the winner persists host-keyed so every streamed entry point —
    # CLI, serve decode, bench — starts with a measured pool size
    ingest_chosen, ingest_timings, ingest_persisted = 1, {}, False
    if args.ingest_budget_s > 0:
        from kindel_tpu.io.stream import stream_alignment

        def ingest_pass(workers: int) -> float:
            t = _time.perf_counter()
            for _batch in stream_alignment(
                args.bam_path, 16 << 20, ingest_workers=workers
            ):
                pass
            return _time.perf_counter() - t

        ingest_chosen, ingest_timings = tune.search_ingest_workers(
            ingest_pass, budget_s=args.ingest_budget_s
        )
        if not args.dry_run and ingest_timings:
            ingest_persisted = tune.record(
                tune.ingest_store_key(),
                {
                    "ingest_workers": ingest_chosen,
                    "timings_s": {
                        str(k): round(v, 4)
                        for k, v in ingest_timings.items()
                    },
                    "bam_path": str(args.bam_path),
                },
            )
    # ingest-mode sweep (kindel_tpu.devingest): one streamed
    # decode+expand pass per mode, mode explicit (no env mutation); the
    # winner persists host-keyed next to the worker count so serve
    # decode and every streamed entry point start in the measured mode
    mode_chosen, mode_timings, mode_persisted = None, {}, False
    if args.ingest_mode_budget_s > 0:
        from kindel_tpu.events import extract_events as _exev
        from kindel_tpu.io.stream import stream_alignment as _stream

        def mode_pass(mode: str) -> float:
            t = _time.perf_counter()
            if mode == "device":
                from kindel_tpu import devingest

                for _ev in devingest.stream_device_events(
                    args.bam_path, 16 << 20
                ):
                    if hasattr(_ev, "to_host"):
                        _ev.to_host()  # force the async work (fair wall)
            else:
                for _batch in _stream(args.bam_path, 16 << 20):
                    _exev(_batch)
            return _time.perf_counter() - t

        mode_chosen, mode_timings = tune.search_ingest_mode(
            mode_pass, budget_s=args.ingest_mode_budget_s
        )
        if not args.dry_run and mode_timings:
            mode_persisted = tune.record(
                tune.ingest_store_key(),
                {
                    "ingest_mode": mode_chosen,
                    "mode_timings_s": {
                        k: round(v, 4) for k, v in mode_timings.items()
                        if v != float("inf")
                    },
                    "bam_path": str(args.bam_path),
                },
            )
    # emission-mode sweep (kindel_tpu.emit): one no-changes consensus
    # pass per mode, mode explicit (no env mutation); the winner
    # persists host-keyed so the serve fast path and the cohort API
    # start in the measured mode
    emit_chosen, emit_timings, emit_persisted = None, {}, False
    if args.emit_mode_budget_s > 0:
        def emit_pass(mode: str) -> float:
            t = _time.perf_counter()
            for rid in ev.present_ref_ids:
                res, _dmin, _dmax = call_consensus_fused(
                    ev, rid, build_changes=False,
                    tuning=tune.TuningConfig(emit_mode=mode),
                )
                assert len(res.sequence) > 0
            return _time.perf_counter() - t

        emit_chosen, emit_timings = tune.search_emit_mode(
            emit_pass, budget_s=args.emit_mode_budget_s
        )
        if not args.dry_run and emit_timings:
            emit_persisted = tune.record(
                tune.emit_store_key(),
                {
                    "emit_mode": emit_chosen,
                    "mode_timings_s": {
                        k: round(v, 4) for k, v in emit_timings.items()
                        if v != float("inf")
                    },
                    "bam_path": str(args.bam_path),
                },
            )
    # page-class geometry sweep (kindel_tpu.ragged): pack this BAM's
    # units into each candidate class set, time one superbatch launch,
    # persist the winning spec host-keyed
    ragged_chosen, ragged_timings, ragged_persisted = None, {}, False
    if args.ragged_budget_s > 0:
        import numpy as np

        from kindel_tpu.batch import BatchOptions
        from kindel_tpu.call_jax import CallUnit
        from kindel_tpu.ragged import (
            build_segment_table,
            classify_units,
            pack_superbatch,
            parse_classes,
        )
        from kindel_tpu.ragged.kernel import launch_ragged

        opts = BatchOptions()
        units = [
            CallUnit(ev, rid, with_ins_table=True)
            for rid in ev.present_ref_ids
        ]

        def ragged_pass(spec: str) -> float:
            classes = parse_classes(spec)
            idx = classify_units(units, classes)
            if idx is None:  # this BAM cannot superbatch under the spec
                return 1e9
            cls = classes[idx]
            table = build_segment_table(units, cls)
            arrays = pack_superbatch(units, table)
            np.asarray(launch_ragged(arrays, cls, opts))  # warm/compile
            t = _time.perf_counter()
            np.asarray(launch_ragged(arrays, cls, opts))
            return _time.perf_counter() - t

        # candidates come from the recorded traffic histogram when the
        # serve batcher has observed real arrivals on this host (the
        # static three-probe ladder is only the cold-start fallback)
        ragged_chosen, ragged_timings = tune.search_ragged_classes(
            ragged_pass, candidates=tune.ragged_class_candidates(),
            budget_s=args.ragged_budget_s,
        )
        measurable = {k: v for k, v in ragged_timings.items() if v < 1e9}
        if not args.dry_run and measurable:
            ragged_persisted = tune.record(
                tune.ragged_store_key(),
                {
                    "classes": ragged_chosen,
                    "timings_s": {
                        k: round(v, 4) for k, v in measurable.items()
                    },
                    "bam_path": str(args.bam_path),
                },
            )

    # device-mesh width sweep (kindel_tpu.parallel.meshexec): one
    # sharded cohort pass per candidate dp, width explicit (no env
    # mutation — the shared search contract); the winner persists
    # host-keyed so every dispatch tier starts on the measured mesh
    mesh_chosen, mesh_timings, mesh_persisted = None, {}, False
    if args.mesh_budget_s > 0:
        import numpy as _np

        from kindel_tpu.batch import (
            BatchOptions,
            launch_cohort_kernel,
            pack_cohort,
        )
        from kindel_tpu.call_jax import CallUnit
        from kindel_tpu.parallel import meshexec

        mesh_opts = BatchOptions()
        mesh_units = [
            CallUnit(ev, rid, with_ins_table=True)
            for rid in ev.present_ref_ids
        ]
        n_dev = meshexec.visible_devices()
        candidates = tuple(
            d for d in (1, 2, 4, 8, 16, 32) if d <= n_dev
        ) or (1,)

        def mesh_pass(dp: int) -> float:
            plan = meshexec.MeshPlan(dp=dp, source="probe")
            n_rows = plan.pad_rows(max(len(mesh_units), dp))
            sharding, eff = plan.row_sharding_for(n_rows)
            arrays, meta = pack_cohort(mesh_units, mesh_opts,
                                       n_rows=n_rows)
            # warm/compile, then one timed blocked pass
            _np.asarray(launch_cohort_kernel(
                arrays, meta, mesh_opts, sharding=sharding, mesh_dp=eff
            )[0])
            t = _time.perf_counter()
            _np.asarray(launch_cohort_kernel(
                arrays, meta, mesh_opts, sharding=sharding, mesh_dp=eff
            )[0])
            return _time.perf_counter() - t

        mesh_chosen, mesh_timings = tune.search_mesh_dp(
            mesh_pass, candidates=candidates,
            budget_s=args.mesh_budget_s,
        )
        if not args.dry_run and mesh_timings:
            mesh_persisted = tune.record(
                tune.mesh_store_key(),
                {
                    "mesh_dp": mesh_chosen,
                    "timings_s": {
                        str(k): round(v, 4)
                        for k, v in mesh_timings.items()
                        if v != float("inf")
                    },
                    "bam_path": str(args.bam_path),
                },
            )

    aot_report = None
    if args.export_aot:
        aot_report = _export_aot(args.bam_path, ev, dry_run=args.dry_run)

    doc = {
        "backend": backend,
        "key": key,
        "clamp": clamp,
        "n_slabs": chosen,
        "timings_s": {str(k): round(v, 4) for k, v in timings.items()},
        "tune_wall_s": round(wall, 3),
        "ingest_workers": ingest_chosen,
        "ingest_timings_s": {
            str(k): round(v, 4) for k, v in ingest_timings.items()
        },
        "ingest_persisted": ingest_persisted,
        "persisted": persisted,
        "store": str(tune.store_path()),
    }
    if mode_chosen is not None:
        doc["ingest_mode"] = mode_chosen
        doc["ingest_mode_timings_s"] = {
            k: round(v, 4) for k, v in mode_timings.items()
            if v != float("inf")
        }
        doc["ingest_mode_persisted"] = mode_persisted
    if emit_chosen is not None:
        doc["emit_mode"] = emit_chosen
        doc["emit_mode_timings_s"] = {
            k: round(v, 4) for k, v in emit_timings.items()
            if v != float("inf")
        }
        doc["emit_mode_persisted"] = emit_persisted
    if mesh_chosen is not None:
        doc["mesh_dp"] = mesh_chosen
        doc["mesh_timings_s"] = {
            str(k): round(v, 4) for k, v in mesh_timings.items()
            if v != float("inf")
        }
        doc["mesh_persisted"] = mesh_persisted
    if ragged_chosen is not None:
        doc["ragged_classes"] = ragged_chosen
        doc["ragged_timings_s"] = {
            k: round(v, 4) for k, v in ragged_timings.items() if v < 1e9
        }
        doc["ragged_persisted"] = ragged_persisted
    if aot_report is not None:
        doc["aot"] = aot_report
    print(json.dumps(doc))
    return 0


def _export_aot(bam_path: str, ev, dry_run: bool = False) -> dict:
    """Pre-bake this host's AOT executable store (kindel_tpu.aot): the
    cohort kernel for every lane shape `kindel serve --warm <bam>`
    would derive, plus the fused single-sample kernel for the BAM's
    exact upload geometry. Each export is parity-checked against the
    jit path before it persists; fleet cold-start then = copying
    ~/.cache/kindel_tpu/ to the target hosts."""
    from kindel_tpu import aot
    from kindel_tpu.batch import BatchOptions
    from kindel_tpu.call_jax import (
        CallUnit,
        _compact_bucket,
        _use_compact_wire,
        covered_index,
        pack_kernel_args,
    )
    from kindel_tpu.serve import warmup as serve_warmup

    if not aot.enabled():
        return {"enabled": False,
                "note": "tune store disabled (KINDEL_TPU_TUNE_CACHE=off)"}
    if dry_run:
        return {"enabled": True, "note": "skipped (--dry-run)"}
    # BOTH emission variants pre-bake (the emit keying dimension of
    # cohort_sig/fused_sig/ragged_sig): zero-compile replica startup
    # must cover --emit-mode host AND device, so flipping the knob on a
    # warm fleet never compiles. The bake runs under the host's
    # resolved mesh plan (DESIGN.md §23) so the SHARDED executables a
    # serving replica will actually dispatch are the ones persisted.
    from kindel_tpu.parallel import meshexec

    mesh_plan = meshexec.plan()
    shapes = serve_warmup.warm_shapes(
        BatchOptions(emit_mode="host"), payloads=[bam_path],
        mesh_plan=mesh_plan,
    )
    shapes.update({
        f"{label}:emit": t for label, t in serve_warmup.warm_shapes(
            BatchOptions(emit_mode="device"), payloads=[bam_path],
            mesh_plan=mesh_plan,
        ).items()
    })
    fused = 0
    for rid in ev.present_ref_ids:
        u = CallUnit(ev, rid)
        buf, pads = pack_kernel_args(u, 1)
        c_pad = None
        if _use_compact_wire():
            c_pad = _compact_bucket(
                len(covered_index(u.op_r_start, u.op_lens()))
            )
        if aot.export_fused(buf, pads, u.L, False, c_pad):
            fused += 1
        if aot.export_fused(buf, pads, u.L, False, None, emit=True):
            fused += 1
    # the ingest-mode dimension: under device ingest, pre-bake the
    # devingest record-scan executables for the chunk-buffer buckets a
    # streamed decode of this BAM would hit, so a device-ingest replica
    # starts zero-compile too (DESIGN.md §19)
    ingest_exported = 0
    from kindel_tpu import tune as _tune

    if _tune.resolve_ingest_mode()[0] == "device":
        import os as _os

        from kindel_tpu.devingest import _DATA_BUCKET_MIN, _bucket
        from kindel_tpu.io.stream import DEFAULT_CHUNK_BYTES

        size = _os.path.getsize(bam_path)
        pads = {
            _bucket(min(size * 4, DEFAULT_CHUNK_BYTES), _DATA_BUCKET_MIN),
            _bucket(DEFAULT_CHUNK_BYTES, _DATA_BUCKET_MIN),
        }
        for pad in sorted(pads):
            if aot.export_ingest_scan(pad):
                ingest_exported += 1
    ragged_shapes = {}
    if _tune.resolve_batch_mode()[0] in ("ragged", "paged"):
        from kindel_tpu.ragged import parse_classes

        spec, _src = _tune.resolve_ragged_classes()
        ragged_shapes = serve_warmup.warm_ragged(
            BatchOptions(), parse_classes(spec), mesh_plan=mesh_plan
        )
    return {
        "enabled": True,
        "cohort_shapes": {
            label: t.get("source") for label, t in shapes.items()
        },
        "ragged_shapes": {
            label: t.get("source") for label, t in ragged_shapes.items()
        },
        "fused_exported": fused,
        "ingest_scan_exported": ingest_exported,
        **aot.provenance(),
    }


def _lint_parser(sub):
    p = sub.add_parser(
        "lint",
        help="run the whole-program static analyzer "
             "(kindel_tpu.analysis): migrated tier-1 hygiene guards "
             "plus trace-purity closure, lock discipline, "
             "future-settlement, and knob/metric doc conformance",
    )
    p.add_argument(
        "--format", choices=["text", "json", "sarif"], default="text",
        help="output format (SARIF 2.1.0 for code-review UIs)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file of reviewed legacy findings "
             "(default tools/lint_baseline.json; 'none' disables)",
    )
    p.add_argument(
        "--strict", action="store_true",
        help="also fail on stale baseline entries (fixed findings whose "
             "ledger row was not deleted) — what tier-1 runs",
    )
    p.add_argument(
        "--rules", default=None, metavar="IDS",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="refreeze the baseline from the current findings (review "
             "the diff before committing) instead of checking",
    )


def cmd_lint(args) -> int:
    """Run the rule engine; exit 0 clean, 1 on new findings (or stale
    baseline entries under --strict), 2 on usage errors."""
    from kindel_tpu.analysis import engine as lint_engine
    from kindel_tpu.analysis import load_project

    rule_ids = None
    if args.rules:
        rule_ids = sorted(
            {r.strip() for r in args.rules.split(",") if r.strip()}
        )
        lint_engine._ensure_rules_loaded()
        unknown = [r for r in rule_ids if r not in lint_engine.RULES]
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(unknown)} — known: "
                + ", ".join(sorted(lint_engine.RULES)),
                file=sys.stderr,
            )
            return 2

    import time as _time

    t0 = _time.perf_counter()
    model = load_project()
    results = lint_engine.run(model, rule_ids=rule_ids)
    findings = lint_engine.all_findings(results)

    if args.write_baseline:
        path = lint_engine.default_baseline_path()
        lint_engine.write_baseline(path, findings)
        print(f"froze {len(findings)} finding(s) into {path}",
              file=sys.stderr)
        return 0

    if args.baseline == "none":
        baseline = {}
    else:
        baseline = lint_engine.load_baseline(
            args.baseline or lint_engine.default_baseline_path()
        )
    if rule_ids is not None:
        # a partial run must not report unrun rules' entries as stale
        baseline = {
            k: v for k, v in baseline.items() if k[0] in rule_ids
        }
    new, stale = lint_engine.diff_baseline(findings, baseline)
    wall = _time.perf_counter() - t0
    if args.format == "json":
        print(lint_engine.render_json(results, new, stale, wall_s=wall))
    elif args.format == "sarif":
        print(lint_engine.render_sarif(results, new, stale))
    else:
        print(lint_engine.render_text(results, new, stale))
    failed = bool(new) or (args.strict and bool(stale))
    return 1 if failed else 0


def _perf_parser(sub):
    p = sub.add_parser(
        "perf",
        help="the committed BENCH_*/MULTICHIP_* trajectory as a typed "
             "series store and a CI gate (kindel_tpu.obs.perfgate): "
             "list the history, or --gate to replay it (and optionally "
             "a fresh bench line) against noise-tolerant per-(backend, "
             "series) regression floors",
    )
    p.add_argument(
        "--gate", action="store_true",
        help="exit nonzero on regression: every committed sample is "
             "re-gated against its own predecessors in round order, "
             "plus the fresh --line if given",
    )
    p.add_argument(
        "--line", default=None, metavar="PATH",
        help="a fresh bench.py JSON result line to gate against the "
             "history ('-' reads stdin)",
    )
    p.add_argument(
        "--history", default=None, metavar="DIR",
        help="directory holding the BENCH_*/MULTICHIP_* JSON files "
             "(default: the repo root)",
    )
    p.add_argument(
        "--tolerance", type=float, default=None, metavar="F",
        help="allowed fractional drop below the best prior in a series "
             "before the gate fires (default 0.35 — CPU-fallback "
             "numbers swing with host load)",
    )
    p.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="output format",
    )


def cmd_perf(args) -> int:
    """Inspect/gate the committed perf trajectory; exit 0 when clean,
    1 on regression (--gate), 2 on usage errors."""
    import json as _json
    from pathlib import Path

    from kindel_tpu.obs import perfgate

    root = Path(
        args.history if args.history
        else Path(__file__).resolve().parent.parent
    )
    tolerance = (
        args.tolerance if args.tolerance is not None
        else perfgate.DEFAULT_TOLERANCE
    )
    store = perfgate.load_history(root)
    fresh_doc = None
    if args.line:
        try:
            raw = (
                sys.stdin.read() if args.line == "-"
                else open(args.line).read()
            )
            fresh_doc = _json.loads(raw)
        except (OSError, ValueError) as e:
            print(f"unreadable --line: {e}", file=sys.stderr)
            return 2
        if isinstance(fresh_doc, dict) and isinstance(
            fresh_doc.get("parsed"), dict
        ):
            fresh_doc = fresh_doc["parsed"]  # driver-wrapper shape
    if not args.gate:
        doc = {
            "series": {
                f"{backend}/{series}": [
                    {"round": s.round, "value": s.value, "unit": s.unit,
                     "source": s.source}
                    for s in samples
                ]
                for (backend, series), samples in store.series().items()
            },
            "skipped": [
                {"source": src, "reason": why}
                for src, why in store.skipped
            ],
        }
        if args.format == "json":
            print(_json.dumps(doc, indent=1))
        else:
            for key, rows in sorted(doc["series"].items()):
                values = " -> ".join(f"{r['value']:g}" for r in rows)
                print(f"{key}: {values} {rows[-1]['unit']}".rstrip())
            for row in doc["skipped"]:
                print(f"skipped {row['source']}: {row['reason']}")
        return 0
    result = perfgate.gate_history(store, tolerance=tolerance)
    if fresh_doc is not None:
        result.checks.extend(
            perfgate.gate_fresh(
                store, fresh_doc, tolerance=tolerance
            ).checks
        )
    if args.format == "json":
        print(_json.dumps(result.to_doc(), indent=1))
    else:
        for c in result.checks:
            mark = "ok " if c.ok else "REGRESSION"
            print(f"{mark} {c.backend}/{c.series}: {c.detail}")
        verdict = "clean" if result.ok else (
            f"{len(result.regressions)} regression(s)"
        )
        print(
            f"perf gate: {verdict} over {len(result.checks)} check(s), "
            f"{len(store.skipped)} record(s) skipped"
        )
    return 0 if result.ok else 1


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kindel-tpu",
        description="TPU-native indel-aware consensus from aligned BAMs",
        parents=[_progress_parent()],
    )
    sub = parser.add_subparsers(dest="command", required=True)
    # every subcommand also accepts --progress (argparse applies a
    # subparser default only when the root hasn't set the attribute, so
    # either position wins and neither clobbers the other)
    _orig_add_parser = sub.add_parser

    def _add_parser(*a, **k):
        k.setdefault("parents", []).append(_progress_parent())
        return _orig_add_parser(*a, **k)

    sub.add_parser = _add_parser

    _consensus_parser(sub)

    p = sub.add_parser(
        "weights", help="per-site nucleotide frequencies and coverage"
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    p.add_argument(
        "-r", "--relative", action="store_true",
        help="output relative nucleotide frequencies",
    )
    p.add_argument(
        "-n", "--no-confidence", action="store_true",
        help="skip consensus confidence intervals",
    )
    p.add_argument(
        "-c", "--confidence-alpha", type=float, default=0.01,
        help="confidence interval alpha",
    )
    _add_backend(p)

    p = sub.add_parser(
        "features",
        help="relative per-site nucleotide frequencies incl. indels",
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    _add_backend(p)

    p = sub.add_parser(
        "variants",
        help="variants exceeding absolute and relative frequency thresholds",
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    p.add_argument(
        "-a", "--min-count", type=int, default=1,
        help="minimum absolute observation count",
    )
    p.add_argument(
        "-f", "--min-frequency", type=float, default=0.0,
        help="minimum relative frequency",
    )
    p.add_argument(
        "--no-indels", action="store_true",
        help="exclude insertion/deletion variants",
    )
    _add_backend(p)

    p = sub.add_parser(
        "plot", help="sitewise depth/soft-clipping HTML dashboard"
    )
    p.add_argument("bam_path", help="path to SAM/BAM file")
    _add_backend(p)

    p = sub.add_parser(
        "batch",
        help="cohort consensus: many BAMs per fused device program, "
             "streamed with decode/compute overlap",
    )
    p.add_argument("bam_paths", nargs="+", help="SAM/BAM files")
    p.add_argument(
        "-o", "--out-dir", default=".",
        help="directory for per-sample <stem>.fa outputs",
    )
    p.add_argument(
        "--chunk-size", type=int, default=64,
        help="samples per device program",
    )
    p.add_argument(
        "--min-depth", type=int, default=1,
        help="substitute Ns at coverage depths beneath this value",
    )
    p.add_argument(
        "-t", "--trim-ends", action="store_true",
        help="trim ambiguous nucleotides (Ns) from sequence ends",
    )
    p.add_argument(
        "-u", "--uppercase", action="store_true",
        help="close gaps using uppercase alphabet",
    )
    p.add_argument(
        "-r", "--realign", action="store_true",
        help="attempt to reconstruct reference around soft-clip boundaries",
    )
    p.add_argument(
        "--min-overlap", type=int, default=7,
        help="match length required to close soft-clipped gaps",
    )
    p.add_argument(
        "-c", "--clip-decay-threshold", type=float, default=0.1,
        help="read depth fraction at which to cease clip extension",
    )
    p.add_argument(
        "--mask-ends", type=int, default=50,
        help="ignore clip dominant positions within n positions of termini",
    )
    p.add_argument(
        "--cdr-gap", type=_nonneg_int, default=0, metavar="N",
        help="pair facing clip-dominant regions across up to N uncovered "
             "positions (see the consensus subcommand's help)",
    )
    p.add_argument(
        "--fix-clip-artifacts", action="store_true",
        help="fix the reference's issue23 boundary artifacts "
             "(see the consensus subcommand's help)",
    )
    p.add_argument(
        "--reports", action="store_true",
        help="also write a per-sample <stem>.report.txt (the same text the "
             "consensus subcommand prints to stderr)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="skip samples whose output file already exists (checkpointed "
             "cohort runs survive interruption)",
    )
    p.add_argument(
        "--workers", type=int, default=8,
        help="host decode/assembly threads",
    )

    _serve_parser(sub)
    _tune_parser(sub)
    _lint_parser(sub)
    _perf_parser(sub)

    sub.add_parser("version", help="show version")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "progress", False):
        import os

        os.environ["KINDEL_TPU_PROGRESS"] = "1"
    # fault injection activates exactly once, at startup — the hot-path
    # hooks themselves never look at the environment
    faults_spec = getattr(args, "faults", None)
    if faults_spec is not None:
        from kindel_tpu.resilience import FaultPlan, activate

        activate(FaultPlan.parse(faults_spec))
    else:
        from kindel_tpu.resilience import activate_from_env

        activate_from_env()
    if args.command == "version":
        print(f"kindel-tpu {__version__}")
        return 0
    cmd = {
        "consensus": cmd_consensus,
        "weights": cmd_weights,
        "features": cmd_features,
        "variants": cmd_variants,
        "plot": cmd_plot,
        "batch": cmd_batch,
        "serve": cmd_serve,
        "tune": cmd_tune,
        "lint": cmd_lint,
        "perf": cmd_perf,
    }[args.command]
    trace_path = getattr(args, "trace", None)
    if trace_path is None:
        return cmd(args)
    # one root span per CLI run: every phase/workload/serve span below
    # parents into it, so the whole invocation renders as a single tree
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.obs import trace as obs_trace

    obs_trace.enable_tracing(trace_path)
    obs_runtime.install()
    try:
        with obs_trace.span(f"cli.{args.command}") as root:
            root.set_attribute(
                command=args.command,
                bam_path=str(getattr(args, "bam_path", "")) or None,
            )
            try:
                return cmd(args)
            finally:
                obs_runtime.attach_runtime(root)
    finally:
        obs_trace.disable_tracing()  # flush/close the exporter
        print(f"trace written to {trace_path}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
