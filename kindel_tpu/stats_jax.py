"""Device-side statistics kernels — the scipy replacement for pipelines
that keep stats resident on device.

The reference computes per-site Shannon entropy and Jeffreys binomial
confidence intervals with one scipy call per site
(/root/reference/kindel/kindel.py:614-624 — flagged HOT in SURVEY §3.2).
Here both are jitted whole-axis reductions.

NOTE: the weights/features TSV builders (kindel_tpu.workloads) now use
the exact host forms for BOTH backends — the f32 kernels here can print
one ulp-at-3dp off the scipy oracle on rounding-boundary values, and the
byte-identical-backends invariant outranks device residency for table
output (VERDICT r3 weakness 6). These kernels remain for device-resident
consumers and are accuracy-pinned by tests/test_stats.py:

  * entropy — plain jnp vector math over the [L, 4] relative-frequency
    block (scipy semantics: rows renormalized, 0·log0 = 0, all-zero → nan);
  * Jeffreys CI — beta.ppf(α/2 | c+½, n−c+½) has no closed form and no
    jax primitive, so it is inverted from jax.scipy.special.betainc by
    fixed-iteration bisection (60 rounds ⇒ ~1e-18 interval width, far
    below the 3-decimal rounding of the TSV output).
"""

from __future__ import annotations

from functools import partial

from kindel_tpu.utils.jax_cache import ensure_compilation_cache

ensure_compilation_cache()

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def entropy_rows(rel: jnp.ndarray) -> jnp.ndarray:
    """Shannon entropy per row with scipy.stats.entropy semantics."""
    totals = rel.sum(axis=1, keepdims=True)
    pk = rel / totals
    terms = jnp.where(pk > 0, -pk * jnp.log(pk), 0.0)
    out = terms.sum(axis=1)
    bad = jnp.isnan(rel).any(axis=1) | (totals[:, 0] == 0)
    return jnp.where(bad, jnp.nan, out)


@partial(jax.jit, static_argnames=("iters",))
def beta_ppf(q, a, b, iters: int = 60):
    """Inverse regularized incomplete beta by bisection on [0, 1]."""
    lo = jnp.zeros_like(q)
    hi = jnp.ones_like(q)

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        below = jax.scipy.special.betainc(a, b, mid) < q
        return jnp.where(below, mid, lo), jnp.where(below, hi, mid)

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return 0.5 * (lo + hi)


@jax.jit
def jeffreys_interval(count, nobs, alpha):
    """Jeffreys binomial proportion CI: beta.interval(1-alpha, c+0.5,
    n-c+0.5) (reference kindel.py:569-574), computed on device."""
    a = count + 0.5
    b = nobs - count + 0.5
    lower = beta_ppf(jnp.full_like(a, alpha / 2), a, b)
    upper = beta_ppf(jnp.full_like(a, 1 - alpha / 2), a, b)
    return lower, upper


def entropy_rows_host(rel: np.ndarray) -> np.ndarray:
    return np.asarray(entropy_rows(jnp.asarray(rel)))


def jeffreys_interval_host(count: np.ndarray, nobs: np.ndarray,
                           alpha: float):
    """float32 betainc bisection: agrees with scipy to ~1e-4; a value
    sitting exactly on a 3-decimal rounding boundary can therefore print
    one ulp-at-3dp away from the numpy oracle's table."""
    lower, upper = jeffreys_interval(
        jnp.asarray(count, jnp.float32),
        jnp.asarray(nobs, jnp.float32),
        jnp.float32(alpha),
    )
    return np.asarray(lower), np.asarray(upper)
