"""Zero-compile startup: load AOT executables, compile only on store miss.

The micro-batcher keys coalescing lanes on (call options,
`cohort_pad_shapes`) and pads row counts to a power-of-two bucket, so
the set of device programs a serving process runs is small and known.
Before PR 2 the FIRST request to open each lane paid the compile
(seconds to minutes on a tunneled accelerator) inside its own latency
budget; the warmup moved that wall to startup. This revision removes it
entirely on a warm host: for every startup-derivable lane shape,
`warm_shapes` first asks the AOT store (kindel_tpu.aot) for a
serialized executable and **loads** it — zero jit compiles, `/healthz`
flips to ok in however long a file read and one verification batch
take. Only on a store miss does it AOT-compile (parity-checked against
the jit path, then persisted), so the NEXT replica on this host — or
any host the store directory is copied to (`kindel tune --export-aot`)
— starts compile-free.

Shapes warmed:

  * a minimal synthetic cohort (the smallest bucket lane — every
    "tiny request" lands there), and
  * operator-supplied representative payloads (`kindel serve --warm
    sample.bam`), which warm the exact shapes production traffic hits.

Each shape's timing is split compile-wall vs execute via the
`jax.monitoring` listener (obs.runtime), so the warmup Info metric
attributes exactly what AOT saved — a conflated single wall would make
the zero-compile claim unverifiable from the exposition.
"""

from __future__ import annotations

import time

#: minimal synthetic cohort: two reads with matches, a deletion, an
#: insertion and a soft clip, so every sparse-event pad axis is
#: non-degenerate and the lane shapes equal the bucket minimums
_SYNTH_SAM = (
    b"@HD\tVN:1.6\n"
    b"@SQ\tSN:warmref\tLN:512\n"
    b"w0\t0\twarmref\t1\t60\t30M2D28M2S\t*\t0\t0\t" + b"ACGT" * 15 + b"\t*\n"
    b"w1\t0\twarmref\t5\t60\t28M4I28M\t*\t0\t0\t" + b"TGCA" * 15 + b"\t*\n"
)


def decode_payload(payload, opts, ingest_mode: str = "host") -> list:
    """Payload (path or SAM/BAM bytes) → CallUnits, through the same
    decode the worker's decode stage runs — warmed shapes must be
    derived exactly the way served shapes are. A device-ingest service
    passes its mode here, so warmup also loads-or-compiles the
    devingest kernels (zero-compile first request, ingest included)."""
    from kindel_tpu.serve.queue import ServeRequest
    from kindel_tpu.serve.worker import decode_request

    return decode_request(
        ServeRequest(payload=payload, opts=opts), ingest_mode=ingest_mode
    )


def shape_label(shapes: tuple, n_rows: int) -> str:
    return "r{}xL{}o{}b{}d{}i{}c{}".format(n_rows, *shapes)


def warm_shapes(opts, row_bucket: int = 8, payloads=(),
                include_synthetic: bool = True,
                ingest_mode: str = "host",
                mesh_plan=None) -> dict[str, dict]:
    """Ready the batched cohort kernel for every lane shape the given
    payloads (plus the minimal synthetic cohort) land in — by loading a
    stored AOT executable when the store is warm, by compiling (and
    then exporting) when it is not.

    Returns {shape_label: {"total_s", "compile_s", "execute_s",
    "source"}} — one entry per UNIQUE (pad shapes, row bucket) pair.
    `source` is "store" (loaded, zero compiles), "fresh" (compiled this
    startup, exported for the next), or "disabled" (AOT store off —
    plain jit warmup, exactly the pre-AOT behavior). A timing includes
    pack + load-or-compile + one executed batch (blocked on, because
    jax dispatch is async and a "warm" kernel that is still compiling
    would defeat the point); compile_s comes from the jax.monitoring
    compile-wall listener, so AOT savings are attributable."""
    import numpy as np

    from kindel_tpu import aot
    from kindel_tpu.batch import (
        cohort_pad_shapes,
        launch_cohort_kernel,
        pack_cohort,
    )
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.pileup_jax import _bucket
    from kindel_tpu.resilience import faults as rfaults

    # best-effort: without the listener compile_s reads 0 and the split
    # degrades to execute-only attribution, never to a failed warmup
    obs_runtime.install()

    cohorts: list = []
    if include_synthetic:
        cohorts.append(decode_payload(_SYNTH_SAM, opts))
    for p in payloads:
        cohorts.append(decode_payload(p, opts, ingest_mode=ingest_mode))

    timings: dict[str, dict] = {}
    for units in cohorts:
        if not units:
            continue
        shapes = cohort_pad_shapes(units, opts)
        n_rows = _bucket(len(units), row_bucket)
        sharding, mesh_dp = None, 1
        if mesh_plan is not None and getattr(mesh_plan, "active", False):
            # warm the SAME sharded layout the worker will dispatch
            # (DESIGN.md §23): a warm mesh must serve unseen traffic
            # with zero new compiles, so the warmed avals/shardings and
            # the served ones have to agree exactly
            n_rows = mesh_plan.pad_rows(n_rows)
            sharding, mesh_dp = mesh_plan.row_sharding_for(n_rows)
        label = shape_label(shapes, n_rows)
        if mesh_dp > 1:
            label += f":dp{mesh_dp}"
        if label in timings:
            continue
        rfaults.hook("device.compile")
        t0 = time.monotonic()
        _c0, compile_wall0 = obs_runtime.compile_totals()
        arrays, meta = pack_cohort(units, opts, n_rows=n_rows, shapes=shapes)
        if aot.enabled():
            loaded = aot.load_cohort(arrays, meta, opts, mesh=mesh_dp)
            if loaded is not None:
                source = "store"
            else:
                # miss (or undeserializable entry, already warned once):
                # AOT-compile + parity-verify + persist; the executable
                # registers either way, so dispatch below — and every
                # later flush of this lane — skips the jit cache
                source = "fresh"
                aot.export_cohort(arrays, meta, opts, sharding=sharding,
                                  mesh=mesh_dp)
        else:
            source = "disabled"
        out, _meta = launch_cohort_kernel(arrays, meta, opts,
                                          sharding=sharding,
                                          mesh_dp=mesh_dp)
        if mesh_dp > 1:
            from kindel_tpu.parallel import meshexec

            out = meshexec.fetch_global(out)  # pod results via allgather
        wire = out[0] if opts.realign else out
        np.asarray(wire)  # block: load/compile + execute must be done
        total = time.monotonic() - t0
        _c1, compile_wall1 = obs_runtime.compile_totals()
        compile_s = max(0.0, compile_wall1 - compile_wall0)
        timings[label] = {
            "total_s": total,
            "compile_s": compile_s,
            "execute_s": max(0.0, total - compile_s),
            "source": source,
        }
    return timings


def warm_ragged(opts, classes, mesh_plan=None) -> dict[str, dict]:
    """Ready the ragged superbatch kernel for every page class — the
    `--batch-mode ragged` counterpart of `warm_shapes`, with one
    decisive difference: a page class's geometry is fixed, so warming
    (or AOT-loading) it covers EVERY request shape the class will ever
    admit, not just startup-derivable ones. Both wire variants warm
    (the fast path and the masks path `build_changes`/`build_reports`
    requests switch to), so no traffic mix compiles post-startup.

    Returns {label: {"total_s", "compile_s", "execute_s", "source"}},
    labels `ragged:<class>:r<rows>xL<len>[:masks]`, sources as in
    warm_shapes ("store" / "fresh" / "disabled")."""
    from dataclasses import replace

    import numpy as np

    from kindel_tpu import aot
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.ragged import build_segment_table, pack_superbatch
    from kindel_tpu.ragged.kernel import launch_ragged
    from kindel_tpu.resilience import faults as rfaults

    obs_runtime.install()
    # every wire variant warms: the fast path, the masks path
    # (build_changes/build_reports requests), the realign variant —
    # since the segment kernel learned the clip channels, realign
    # traffic rides superbatches too and must not compile post-startup
    # — and BOTH emission modes (kindel_tpu.emit): a page class's
    # geometry is fixed, so pre-baking the emit-variant executables
    # here (and via `kindel tune --export-aot`) means flipping
    # --emit-mode never compiles on a warm host
    base = replace(opts, realign=False)
    variants = (
        ("", replace(base, build_changes=False, build_reports=False,
                     emit_mode="host")),
        (":masks", replace(base, build_changes=True)),
        (":realign", replace(base, realign=True, build_changes=False,
                             build_reports=False, emit_mode="host")),
        (":emit", replace(base, build_changes=False, build_reports=False,
                          emit_mode="device")),
        (":realign-emit", replace(base, realign=True, build_changes=False,
                                  build_reports=False,
                                  emit_mode="device")),
    )
    units = decode_payload(_SYNTH_SAM, base)
    realign_units = decode_payload(
        _SYNTH_SAM, replace(base, realign=True)
    )
    timings: dict[str, dict] = {}
    for cls in classes:
        table = build_segment_table(units, cls)
        realign_table = build_segment_table(realign_units, cls)
        for suffix, vopts in variants:
            label = f"ragged:{cls.label()}{suffix}"
            rfaults.hook("device.compile")
            t0 = time.monotonic()
            _c0, compile_wall0 = obs_runtime.compile_totals()
            vunits = realign_units if vopts.realign else units
            vtable = realign_table if vopts.realign else table
            arrays = pack_superbatch(vunits, vtable, realign=vopts.realign)
            if aot.enabled():
                if aot.load_ragged(cls, vopts) is not None:
                    source = "store"
                else:
                    source = "fresh"
                    aot.export_ragged(arrays, cls, vopts)
            else:
                source = "disabled"
            out = launch_ragged(arrays, cls, vopts)
            wire = out[0] if vopts.realign else out
            np.asarray(wire)  # block: load/compile + execute must be done
            total = time.monotonic() - t0
            _c1, compile_wall1 = obs_runtime.compile_totals()
            compile_s = max(0.0, compile_wall1 - compile_wall0)
            timings[label] = {
                "total_s": total,
                "compile_s": compile_s,
                "execute_s": max(0.0, total - compile_s),
                "source": source,
            }
        if mesh_plan is not None and getattr(mesh_plan, "active", False):
            timings.update(
                _warm_ragged_mesh(cls, variants, units, realign_units,
                                  mesh_plan)
            )
    return timings


def _warm_ragged_mesh(cls, variants, units, realign_units,
                      mesh_plan) -> dict[str, dict]:
    """Mesh-sharded counterpart of the per-class warm loop: one
    dp-replicated synthetic superbatch per wire variant readies the
    vmapped sharded executable (kindel_tpu.parallel.meshexec) — the
    sub-geometry is fixed per (class, dp), so arbitrary traffic on a
    warm mesh compiles nothing, exactly the page-class contract."""
    import numpy as np

    from kindel_tpu import aot
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.parallel import meshexec
    from kindel_tpu.resilience import faults as rfaults

    timings: dict[str, dict] = {}
    for suffix, vopts in variants:
        vunits = realign_units if vopts.realign else units
        d = meshexec.ragged_dp(cls, mesh_plan.dp, n_units=None,
                               procs=getattr(mesh_plan, "procs", 1))
        if d <= 1:
            continue
        # one unit per shard: the synthetic cohort replicated wide
        # enough that every shard packs something
        wide = (vunits * d)[: max(d, len(vunits))]
        ssb = meshexec.shard_superbatch(
            wide, cls, mesh_plan, realign=vopts.realign
        )
        if ssb is None:
            continue
        label = f"ragged:{cls.label()}{suffix}:dp{ssb.dp}"
        rfaults.hook("device.compile")
        t0 = time.monotonic()
        _c0, compile_wall0 = obs_runtime.compile_totals()
        if aot.enabled():
            if aot.load_sharded_ragged(cls, ssb.sub, vopts,
                                       ssb.dp) is not None:
                source = "store"
            else:
                source = "fresh"
                meshexec.export_sharded(ssb, vopts)
        else:
            source = "disabled"
        out = meshexec.launch_sharded_superbatch(ssb, vopts)
        out = meshexec.fetch_global(out)  # pod results land via allgather
        wire = out[0] if vopts.realign else out
        np.asarray(wire)  # block: load/compile + execute must be done
        total = time.monotonic() - t0
        _c1, compile_wall1 = obs_runtime.compile_totals()
        compile_s = max(0.0, compile_wall1 - compile_wall0)
        timings[label] = {
            "total_s": total,
            "compile_s": compile_s,
            "execute_s": max(0.0, total - compile_s),
            "source": source,
        }
    return timings
