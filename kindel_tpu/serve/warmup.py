"""AOT compile warmup: pay every lane shape's jit compile at startup.

The micro-batcher keys coalescing lanes on (call options,
`cohort_pad_shapes`) and pads row counts to a power-of-two bucket, so
the set of device programs a serving process runs is small and known —
but before this module the FIRST request to open each lane paid the
compile (seconds to minutes on a tunneled accelerator) inside its own
latency budget. `warm_shapes` walks exactly the dispatch path the
worker runs (`pack_cohort` → `launch_cohort_kernel` → block on the
wire) for every lane shape derivable at startup:

  * a minimal synthetic cohort (the smallest bucket lane — every
    "tiny request" lands there), and
  * operator-supplied representative payloads (`kindel serve --warm
    sample.bam`), which warm the exact shapes production traffic hits.

With the persistent XLA cache (utils/jax_cache.py) the warmup is
near-free on a host that has served before; on a cold host it moves the
compile wall from the first request's p99 to process startup, where
`/healthz` reports `warming` so load balancers hold traffic.
"""

from __future__ import annotations

import time

#: minimal synthetic cohort: two reads with matches, a deletion, an
#: insertion and a soft clip, so every sparse-event pad axis is
#: non-degenerate and the lane shapes equal the bucket minimums
_SYNTH_SAM = (
    b"@HD\tVN:1.6\n"
    b"@SQ\tSN:warmref\tLN:512\n"
    b"w0\t0\twarmref\t1\t60\t30M2D28M2S\t*\t0\t0\t" + b"ACGT" * 15 + b"\t*\n"
    b"w1\t0\twarmref\t5\t60\t28M4I28M\t*\t0\t0\t" + b"TGCA" * 15 + b"\t*\n"
)


def decode_payload(payload, opts) -> list:
    """Payload (path or SAM/BAM bytes) → CallUnits, through the same
    decode the worker's decode stage runs — warmed shapes must be
    derived exactly the way served shapes are."""
    from kindel_tpu.serve.queue import ServeRequest
    from kindel_tpu.serve.worker import decode_request

    return decode_request(ServeRequest(payload=payload, opts=opts))


def shape_label(shapes: tuple, n_rows: int) -> str:
    return "r{}xL{}o{}b{}d{}i{}c{}".format(n_rows, *shapes)


def warm_shapes(opts, row_bucket: int = 8, payloads=(),
                include_synthetic: bool = True) -> dict[str, float]:
    """Precompile the batched cohort kernel for every lane shape the
    given payloads (plus the minimal synthetic cohort) land in.

    Returns {shape_label: warmup_seconds} — one entry per UNIQUE
    (pad shapes, row bucket) pair; a timing includes pack + compile +
    one executed batch (blocked on, because jax dispatch is async and a
    "warm" kernel that is still compiling would defeat the point)."""
    import numpy as np

    from kindel_tpu.batch import (
        cohort_pad_shapes,
        launch_cohort_kernel,
        pack_cohort,
    )
    from kindel_tpu.pileup_jax import _bucket
    from kindel_tpu.resilience import faults as rfaults

    cohorts: list = []
    if include_synthetic:
        cohorts.append(decode_payload(_SYNTH_SAM, opts))
    for p in payloads:
        cohorts.append(decode_payload(p, opts))

    timings: dict[str, float] = {}
    for units in cohorts:
        if not units:
            continue
        shapes = cohort_pad_shapes(units, opts)
        n_rows = _bucket(len(units), row_bucket)
        label = shape_label(shapes, n_rows)
        if label in timings:
            continue
        rfaults.hook("device.compile")
        t0 = time.monotonic()
        arrays, meta = pack_cohort(units, opts, n_rows=n_rows, shapes=shapes)
        out, _meta = launch_cohort_kernel(arrays, meta, opts)
        wire = out[0] if opts.realign else out
        np.asarray(wire)  # block: compile + execute must have finished
        timings[label] = time.monotonic() - t0
    return timings
