"""Dynamic micro-batcher: coalesces decoded requests into device-shaped
cohorts.

Decoded requests land in *lanes* keyed by (call options, cohort pad
shapes) — the same power-of-two bucket shapes the offline cohort path
pads to (`kindel_tpu.batch.cohort_pad_shapes`), so every flush of a lane
re-dispatches one already-compiled kernel shape and the vmapped
`batched_call_kernel` runs hot under load. A lane flushes when its row
count reaches `max_batch_rows` (batch-full) or when its oldest entry has
waited `max_wait_s` (bounded idle latency: a single quiet request never
waits longer than the knob, it just rides a batch of one).

The batcher owns no threads — the worker's dispatch loop drives it via
`poll`, which blocks until a flush is due. That keeps flush timing in
exactly one place and makes the component deterministic to test: add N
requests, poll, observe one flush.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, fields

from kindel_tpu.batch import BatchOptions, cohort_pad_shapes
from kindel_tpu.obs import trace as obs_trace


def opts_key(opts: BatchOptions) -> tuple:
    """Hashable identity of the call options — requests may only share a
    device dispatch when every kernel/assembly knob matches."""
    return tuple(getattr(opts, f.name) for f in fields(BatchOptions))


@dataclass
class Flush:
    """One coalesced batch ready for the device."""

    opts: BatchOptions
    shapes: tuple  # cohort pad shapes every entry buckets to
    entries: list  # [(ServeRequest, [CallUnit, ...]), ...]
    opened_at: float  # when the lane's first entry arrived
    coalesced: int = 0  # extra sealed flushes merged in (fat dispatch)

    @property
    def n_rows(self) -> int:
        return sum(len(units) for _, units in self.entries)


class _Lane:
    __slots__ = ("opts", "shapes", "entries", "opened_at", "rows")

    def __init__(self, opts, shapes, now):
        self.opts = opts
        self.shapes = shapes
        self.entries: list = []
        self.opened_at = now
        self.rows = 0


class MicroBatcher:
    """Shape-keyed coalescing with batch-full / max-wait flush triggers."""

    def __init__(self, max_batch_rows: int = 64, max_wait_s: float = 0.02,
                 clock=time.monotonic):
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        self.max_batch_rows = max_batch_rows
        self.max_wait_s = max_wait_s
        self._clock = clock
        self._lanes: dict[tuple, _Lane] = {}
        self._ready: list[Flush] = []
        self._cond = threading.Condition()
        self._closed = False

    @property
    def pending_rows(self) -> int:
        with self._cond:
            return sum(lane.rows for lane in self._lanes.values()) + sum(
                f.n_rows for f in self._ready
            )

    def add(self, req, units) -> None:
        """Queue one decoded request (its CallUnits) for coalescing."""
        if not units:
            raise ValueError("a request with no units has nothing to batch")
        shapes = cohort_pad_shapes(units, req.opts)
        key = (opts_key(req.opts), shapes)
        with self._cond:
            now = self._clock()
            lane = self._lanes.get(key)
            if lane is None:
                lane = self._lanes[key] = _Lane(req.opts, shapes, now)
            lane.entries.append((req, units))
            lane.rows += len(units)
            sealed = lane.rows >= self.max_batch_rows
            if sealed:
                self._ready.append(self._seal_locked(key, lane))
            self._cond.notify_all()
        # trace-id propagation stage 2 of 4 (queue → BATCHER → worker →
        # device dispatch): mark the coalescing decision on the request's
        # own span tree (no-op span outside serve / with tracing off)
        span = getattr(req, "span", None)
        if span is not None and span is not obs_trace.NOOP_SPAN:
            span.add_event(
                "batcher.lane_add",
                rows=len(units), lane_rows=lane.rows, sealed=sealed,
                lane_shape="x".join(str(s) for s in shapes),
            )

    def _seal_locked(self, key, lane: _Lane) -> Flush:
        del self._lanes[key]
        return Flush(lane.opts, lane.shapes, lane.entries, lane.opened_at)

    def _due_locked(self, now: float) -> Flush | None:
        if self._ready:
            return self._ready.pop(0)
        oldest_key = None
        oldest = None
        for key, lane in self._lanes.items():
            if oldest is None or lane.opened_at < oldest.opened_at:
                oldest_key, oldest = key, lane
        if oldest is not None and now - oldest.opened_at >= self.max_wait_s:
            return self._seal_locked(oldest_key, oldest)
        return None

    # poll() drives these two hooks so a subclass with extra lane kinds
    # (kindel_tpu.ragged.RaggedBatcher) only overrides lane accounting,
    # never the wait/close logic itself

    def _has_open_locked(self) -> bool:
        """Any open (unsealed) lane left? Gates the closed-drain exit."""
        return bool(self._lanes)

    def _seal_open_locked(self) -> None:
        """Seal every open lane into the ready queue — the close-path
        accounting hook. Safe because close() is ordered after the last
        add (the worker shuts the decode pool down first), so an open
        lane can only shrink the drain: aging it toward max_wait would
        just stall shutdown by up to the knob per lane."""
        for key in list(self._lanes):
            self._ready.append(self._seal_locked(key, self._lanes[key]))

    def _oldest_open_locked(self) -> float | None:
        """opened_at of the oldest open lane (None when all are sealed)
        — what poll() sleeps against for the max-wait trigger."""
        if not self._lanes:
            return None
        return min(lane.opened_at for lane in self._lanes.values())

    def poll(self, timeout: float | None = None) -> Flush | None:
        """Block until a flush is due (full lane, or oldest lane aged past
        max_wait_s). Returns None on timeout, or when closed with nothing
        pending."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                now = self._clock()
                if self._closed:
                    # drain: seal whatever is still open instead of
                    # letting it age toward max_wait (nothing new can
                    # join a lane after close)
                    self._seal_open_locked()
                flush = self._due_locked(now)
                if flush is not None:
                    return flush
                if self._closed and not self._has_open_locked():
                    return None
                # sleep until the oldest lane matures or the caller's
                # deadline, whichever is sooner
                waits = []
                oldest = self._oldest_open_locked()
                if oldest is not None:
                    waits.append(oldest + self.max_wait_s - now)
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    waits.append(remaining)
                self._cond.wait(min(waits) if waits else None)

    def take_ready(self, like: Flush, limit: int) -> list[Flush]:
        """Pop up to `limit` ALREADY-SEALED flushes compatible with
        `like` (same call options, same lane pad shapes) — the fat-
        dispatch feeder: under load, full lanes seal faster than the
        dispatch loop drains them, and every compatible sealed flush
        merged into one launch is one device round trip saved. Only the
        `_ready` queue is consulted; open lanes keep aging toward their
        own max-wait flush (merging them here would re-order traffic
        and starve the age trigger)."""
        if limit <= 0:
            return []
        key = (opts_key(like.opts), like.shapes)
        out: list[Flush] = []
        with self._cond:
            keep: list[Flush] = []
            for f in self._ready:
                if len(out) < limit and (opts_key(f.opts), f.shapes) == key:
                    out.append(f)
                else:
                    keep.append(f)
            self._ready = keep
        return out

    def flush_all(self) -> list[Flush]:
        """Seal and return everything pending (drain path)."""
        with self._cond:
            out = list(self._ready)
            self._ready.clear()
            for key in list(self._lanes):
                out.append(self._seal_locked(key, self._lanes[key]))
            return out

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Wake poll()ers; poll returns None once drained."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
