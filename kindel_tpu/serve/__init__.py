"""L6 — online serving: the cohort kernel as a continuously-batched
service.

Every other entry point in this repo is a one-shot batch job; the
vmapped cohort kernel (kindel_tpu.batch) only amortizes host↔device
latency for callers who already hold a whole cohort. This package turns
it into an online service, the structure continuous-batching TPU
serving stacks converge on (PAPERS.md: ragged paged-attention serving,
arxiv 2604.15464; Gemma-on-TPU serving, 2605.25645):

  queue.py    bounded admission queue — reject-with-retry-after past a
              watermark, deadline-aware backpressure
  batcher.py  dynamic micro-batcher — coalesces independent requests
              into padded device cohorts keyed by the offline path's
              bucket shapes; flushes on batch-full or max-wait
  worker.py   intake/decode/dispatch executor — host-thread decode, one
              device program per flush, per-request error isolation
  warmup.py   AOT compile warmup — precompiles every startup-derivable
              lane shape so the first request never pays a jit compile
              (/healthz reports `warming` until done)
  metrics.py  thread-safe registry + /metrics + /healthz HTTP exposition
  service.py  ConsensusService facade, ConsensusClient, POST ingest

CLI: `python -m kindel_tpu serve` (see kindel_tpu.cli).
"""

from kindel_tpu.serve.batcher import Flush, MicroBatcher  # noqa: F401
from kindel_tpu.serve.metrics import (  # noqa: F401
    MetricsRegistry,
    ServeHTTPServer,
)
from kindel_tpu.serve.queue import (  # noqa: F401
    AdmissionError,
    DeadlineExceeded,
    RequestQueue,
    ServeRequest,
    ServiceDegraded,
)
from kindel_tpu.serve.service import (  # noqa: F401
    ConsensusClient,
    ConsensusService,
)
from kindel_tpu.serve.warmup import warm_shapes  # noqa: F401
from kindel_tpu.serve.worker import ServeWorker  # noqa: F401
