"""Serve metrics: HTTP exposition over the shared obs registry.

The thread-safe registry and metric types were lifted into
`kindel_tpu.obs.metrics` (so streaming/batch/tune and the JAX runtime
probes record into the same exposition the service renders); this
module keeps the serve-facing import surface — `MetricsRegistry` et al.
re-exported unchanged — and owns the transport: a stdlib
`ThreadingHTTPServer` rendering `/metrics` (Prometheus text format,
registry or MultiRegistry view) plus a JSON liveness document at
`/healthz`. The registry is also readable in-process (`snapshot()`),
which is what the deterministic serve tests and
`benchmarks/serve_load.py` consume — the HTTP layer is a view, never
the source of truth.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kindel_tpu.obs.metrics import (  # noqa: F401 — serve import surface
    Counter,
    Gauge,
    Histogram,
    Info,
    MetricsRegistry,
    MultiRegistry,
    _fmt,
    default_registry,
    escape_help,
    escape_label_value,
)


class ServeHTTPServer:
    """`/metrics` + `/healthz` (+ caller-supplied POST routes) on a
    stdlib ThreadingHTTPServer running on a daemon thread.

    `registry` is anything with a `render()` — a MetricsRegistry or a
    MultiRegistry union view; `health_fn` returns the /healthz JSON
    document; `post_routes` maps a path to `fn(body: bytes) -> (status,
    content_type, body_bytes, extra_headers)` — the consensus ingest
    endpoint plugs in here so the metrics module stays transport-only.
    A POST handler declared with two positional parameters instead
    receives `fn(body, headers)` — the fleet RPC adapter reads its
    idempotency-key / trace / deadline headers this way without every
    other route growing a parameter. `get_routes` maps extra GET paths
    to `fn() -> (status, content_type, body_bytes, extra_headers)` —
    the /readyz endpoint plugs in here (readiness must be able to
    answer 503, which the always-200 health_fn cannot). `sse_routes`
    maps a GET path to `fn(params: dict) -> iterator[str]` of
    SSE-framed text: the reply streams `text/event-stream` with no
    Content-Length (the connection closes when the iterator ends —
    1.1 keep-alive cannot frame an unbounded body, so these
    connections are never reused). A KeyError from the route fn maps
    to 404 — the sessions lane's unknown-session verdict.

    `max_body_bytes` bounds what one POST may make the server read
    (default MAX_BODY_BYTES; `kindel serve --max-body-mb` resolves the
    operator knob through kindel_tpu.tune): an oversized — or missing —
    Content-Length is refused with 413 + a jittered Retry-After BEFORE
    any allocation, the same "no allocation sized by untrusted input"
    rule the decode surface holds (docs/DESIGN.md §8), which matters
    exactly when the port stops being loopback-only (cross-host fleet).
    """

    #: refuse request bodies past this size before allocating (the serve
    #: ingest shares the decode surface's "no allocation sized by
    #: untrusted input" rule — docs/DESIGN.md §8)
    MAX_BODY_BYTES = 1 << 30

    #: on a 413, bodies up to this size are read-and-DISCARDED in fixed
    #: chunks (O(chunk) memory) so a well-behaved client mid-send gets
    #: the 413 + Retry-After instead of a broken pipe; anything larger
    #: gets the abrupt close (an attacker streaming gigabytes is owed
    #: nothing, least of all bandwidth)
    DISCARD_CAP_BYTES = 8 << 20

    def __init__(self, registry, host: str = "127.0.0.1",
                 port: int = 0, health_fn=None, post_routes: dict | None = None,
                 get_routes: dict | None = None,
                 sse_routes: dict | None = None,
                 max_body_bytes: int | None = None):
        import inspect

        self.registry = registry
        self._health_fn = health_fn or (lambda: {"status": "ok"})
        self._post_routes = {}
        for path, fn in (post_routes or {}).items():
            try:
                wants_headers = len(
                    inspect.signature(fn).parameters
                ) >= 2
            except (TypeError, ValueError):
                wants_headers = False
            self._post_routes[path] = (fn, wants_headers)
        self._get_routes = dict(get_routes or {})
        self._sse_routes = dict(sse_routes or {})
        self.max_body_bytes = (
            int(max_body_bytes) if max_body_bytes is not None
            else self.MAX_BODY_BYTES
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: the fleet RPC transport pools connections, and
            # HTTP/1.0's close-per-exchange would turn every probe and
            # every pooled call into a fresh dial (Content-Length is set
            # on every reply, so 1.1 framing is always valid here)
            protocol_version = "HTTP/1.1"

            # one serving process, many probes: keep the access log quiet
            def log_message(self, fmt, *args):
                pass

            def _reply(self, status: int, content_type: str, body: bytes,
                       extra_headers: dict | None = None):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(
                        200, "text/plain; version=0.0.4",
                        outer.registry.render().encode(),
                    )
                elif path == "/healthz":
                    self._reply(
                        200, "application/json",
                        json.dumps(outer._health_fn()).encode(),
                    )
                elif path in outer._get_routes:
                    status, ctype, payload, headers = outer._get_routes[
                        path
                    ]()
                    self._reply(status, ctype, payload, headers)
                elif path in outer._sse_routes:
                    self._stream_sse(path)
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _stream_sse(self, path: str) -> None:
                from urllib.parse import parse_qs

                raw = (
                    self.path.split("?", 1)[1]
                    if "?" in self.path else ""
                )
                params = {
                    k: v[0] for k, v in parse_qs(raw).items()
                }
                try:
                    events = outer._sse_routes[path](params)
                except KeyError as e:
                    self._reply(404, "text/plain", f"{e}\n".encode())
                    return
                except ValueError as e:
                    self._reply(400, "text/plain", f"{e}\n".encode())
                    return
                # unbounded body: no Content-Length, so this connection
                # cannot be kept alive — close when the stream ends
                self.close_connection = True
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    for chunk in events:
                        self.wfile.write(chunk.encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # subscriber hung up; the finally unsubscribes
                finally:
                    close = getattr(events, "close", None)
                    if close is not None:
                        close()

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                route = outer._post_routes.get(path)
                if route is None:
                    # request body unread: the connection cannot be
                    # reused for 1.1 keep-alive without desyncing
                    self.close_connection = True
                    self._reply(404, "text/plain", b"not found\n")
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    length = -1
                if not 0 <= length <= outer.max_body_bytes:
                    from kindel_tpu.serve.queue import jittered_retry_after

                    retry = jittered_retry_after(1.0)
                    if 0 <= length <= outer.DISCARD_CAP_BYTES:
                        # bounded discard (never buffered): the sender
                        # reads a clean 413 and the connection stays
                        # framed for keep-alive
                        remaining = length
                        while remaining > 0:
                            chunk = self.rfile.read(min(65536, remaining))
                            if not chunk:
                                break
                            remaining -= len(chunk)
                    else:
                        # too big to even drain: the unread body would
                        # desync a kept-alive connection, so close
                        self.close_connection = True
                    self._reply(
                        413, "text/plain",
                        f"body too large (limit {outer.max_body_bytes} "
                        "bytes)\n".encode(),
                        {"Retry-After": max(1, round(retry))},
                    )
                    return
                body = self.rfile.read(length)
                fn, wants_headers = route
                if wants_headers:
                    status, ctype, payload, headers = fn(body, self.headers)
                else:
                    status, ctype, payload, headers = fn(body)
                self._reply(status, ctype, payload, headers)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    def start(self) -> "ServeHTTPServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="kindel-serve-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
