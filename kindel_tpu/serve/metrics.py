"""Serve metrics: a small thread-safe registry + stdlib HTTP exposition.

First-party on purpose (no prometheus_client dependency): the serving
loop records a handful of counters, gauges, and histograms, and a
`ThreadingHTTPServer` renders them in the Prometheus text exposition
format at `/metrics` plus a JSON liveness document at `/healthz`. The
registry is also readable in-process (`snapshot()`), which is what the
deterministic serve tests and `benchmarks/serve_load.py` consume —
the HTTP layer is a view, never the source of truth.
"""

from __future__ import annotations

import bisect
import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def render(self) -> list[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} counter",
            f"{self.name} {self._value}",
        ]


class Gauge:
    """Instantaneous value (queue depth, pending rows)."""

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> list[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt(self._value)}",
        ]


class Histogram:
    """Cumulative-bucket histogram plus a bounded recent-observation
    window for exact quantiles (p50/p99 request latency).

    Prometheus histograms cannot express quantiles server-side, and the
    serve dashboard wants them live — so alongside the standard
    `_bucket`/`_sum`/`_count` series the renderer emits `<name>_p50` and
    `<name>_p99` gauges computed over the last `window` observations.
    """

    def __init__(self, name: str, help_text: str = "",
                 buckets: tuple = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0,
                                   2.5, 5.0, 10.0),
                 window: int = 4096):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf bucket last
        self._sum = 0.0
        self._count = 0
        self._max = 0.0
        self._recent: deque = deque(maxlen=window)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v
            self._recent.append(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        return self._max

    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Exact quantile over the recent window (0 when empty)."""
        with self._lock:
            window = sorted(self._recent)
        if not window:
            return 0.0
        idx = min(len(window) - 1, int(q * len(window)))
        return window[idx]

    def render(self) -> list[str]:
        with self._lock:
            counts = list(self._counts)
            total, total_sum, vmax = self._count, self._sum, self._max
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        cum = 0
        for bound, c in zip(self.buckets, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{self.name}_sum {_fmt(total_sum)}")
        lines.append(f"{self.name}_count {total}")
        lines.append(f"{self.name}_max {_fmt(vmax)}")
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            lines.append(f"{self.name}_{label} {_fmt(self.quantile(q))}")
        return lines


class Info:
    """Constant labeled marker (value always 1) — exports configuration
    facts (tune knob sources, warmed lane shapes) in the standard
    `name{label="..."} 1` idiom without pretending they are
    measurements. One sample per distinct label set; re-setting the
    same label set overwrites it."""

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._labels: dict[tuple, dict] = {}
        self._lock = threading.Lock()

    def set(self, **labels) -> None:
        with self._lock:
            self._labels[tuple(sorted(labels.items()))] = {
                k: str(v) for k, v in labels.items()
            }

    @property
    def value(self) -> list[dict]:
        with self._lock:
            return [dict(v) for v in self._labels.values()]

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
        ]
        with self._lock:
            for labels in self._labels.values():
                lab = ",".join(
                    f'{k}="{v}"' for k, v in sorted(labels.items())
                )
                lines.append(f"{self.name}{{{lab}}} 1")
        return lines


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class MetricsRegistry:
    """Get-or-create metric registry; render order is creation order."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, *args, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}"
                )
            return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "", **kw) -> Histogram:
        return self._get(Histogram, name, help_text, **kw)

    def info(self, name: str, help_text: str = "") -> Info:
        return self._get(Info, name, help_text)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        out: list[str] = []
        for m in metrics:
            out.extend(m.render())
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able view for in-process consumers (tests, load bench)."""
        with self._lock:
            metrics = dict(self._metrics)
        out: dict = {}
        for name, m in metrics.items():
            if isinstance(m, Histogram):
                out[name] = {
                    "count": m.count,
                    "sum": m.sum,
                    "max": m.max,
                    "mean": m.mean(),
                    "p50": m.quantile(0.5),
                    "p99": m.quantile(0.99),
                }
            else:
                out[name] = m.value
        return out


class ServeHTTPServer:
    """`/metrics` + `/healthz` (+ caller-supplied POST routes) on a
    stdlib ThreadingHTTPServer running on a daemon thread.

    `health_fn` returns the /healthz JSON document; `post_routes` maps
    a path to `fn(body: bytes) -> (status, content_type, body_bytes,
    extra_headers)` — the consensus ingest endpoint plugs in here so the
    metrics module stays transport-only.
    """

    #: refuse request bodies past this size before allocating (the serve
    #: ingest shares the decode surface's "no allocation sized by
    #: untrusted input" rule — docs/DESIGN.md §8)
    MAX_BODY_BYTES = 1 << 30

    def __init__(self, registry: MetricsRegistry, host: str = "127.0.0.1",
                 port: int = 0, health_fn=None, post_routes: dict | None = None):
        self.registry = registry
        self._health_fn = health_fn or (lambda: {"status": "ok"})
        self._post_routes = dict(post_routes or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # one serving process, many probes: keep the access log quiet
            def log_message(self, fmt, *args):
                pass

            def _reply(self, status: int, content_type: str, body: bytes,
                       extra_headers: dict | None = None):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(
                        200, "text/plain; version=0.0.4",
                        outer.registry.render().encode(),
                    )
                elif path == "/healthz":
                    self._reply(
                        200, "application/json",
                        json.dumps(outer._health_fn()).encode(),
                    )
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                fn = outer._post_routes.get(path)
                if fn is None:
                    self._reply(404, "text/plain", b"not found\n")
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    length = -1
                if not 0 <= length <= outer.MAX_BODY_BYTES:
                    self._reply(413, "text/plain", b"body too large\n")
                    return
                body = self.rfile.read(length)
                status, ctype, payload, headers = fn(body)
                self._reply(status, ctype, payload, headers)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    def start(self) -> "ServeHTTPServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="kindel-serve-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
