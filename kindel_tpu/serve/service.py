"""ConsensusService: the assembled L6 serving stack + in-process client.

    submit() ──► RequestQueue ──► intake ──► decode pool ──► MicroBatcher
    (admission control)                                          │ flush
                  futures  ◄── assemble ◄── device dispatch  ◄───┘

One service owns one device pipeline: requests from any number of
threads (or the HTTP ingest endpoint) coalesce into shared device
dispatches, which is where the vmapped cohort kernel's amortization
materializes under load. `ConsensusClient` is the synchronous wrapper
the tests and the load benchmark use.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from concurrent.futures import CancelledError, Future
from dataclasses import replace

from kindel_tpu.batch import BatchOptions, SampleResult

from kindel_tpu.durable.journal import (
    Journal,
    JournalWriteError,
    PoisonRequestError,
    journal_metrics,
    new_key as journal_new_key,
    payload_digest as journal_payload_digest,
)
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.serve.batcher import MicroBatcher
from kindel_tpu.serve.metrics import (
    MetricsRegistry,
    MultiRegistry,
    ServeHTTPServer,
    default_registry,
)
from kindel_tpu.resilience.breaker import CircuitBreaker
from kindel_tpu.serve.queue import (
    AdmissionError,
    DeadlineExceeded,
    PreDecoded,
    RequestQueue,
    ServeRequest,
    ServiceDegraded,
    jittered_retry_after,
)
from kindel_tpu.serve.worker import ServeWorker
from kindel_tpu.sessions.lease import LeaseRetired, settle_future


def consensus_post_response(request_fn, body: bytes):
    """POST /v1/consensus handler body, shared by the single service and
    the fleet front (kindel_tpu.fleet): SAM/BAM bytes in, FASTA text
    out. 429 + Retry-After under load shedding, 503 + Retry-After while
    degraded/draining, 400 on undecodable input, 504 on deadline
    expiry. `request_fn(body)` is the synchronous request entry point
    (ConsensusService.request or FleetService.request)."""
    from kindel_tpu.io.fasta import format_fasta

    try:
        res = request_fn(body)
    except ServiceDegraded as e:
        doc = {"error": str(e), "retry_after_s": e.retry_after_s}
        return (
            503, "application/json", json.dumps(doc).encode(),
            {"Retry-After": max(1, round(e.retry_after_s))},
        )
    except AdmissionError as e:
        doc = {"error": str(e), "retry_after_s": e.retry_after_s}
        return (
            429, "application/json", json.dumps(doc).encode(),
            {"Retry-After": max(1, round(e.retry_after_s))},
        )
    except DeadlineExceeded as e:
        return 504, "text/plain", f"{e}\n".encode(), {}
    except PoisonRequestError as e:
        # quarantined payload (DESIGN.md §24): a REQUEST-level verdict
        # with no retry-after — retrying it anywhere would crash a
        # replica; 422 = semantically unprocessable, unlike 400's
        # undecodable
        return 422, "text/plain", f"{e}\n".encode(), {}
    except ValueError as e:  # decode rejection — the client's fault
        return 400, "text/plain", f"{e}\n".encode(), {}
    except Exception as e:  # noqa: BLE001 — server-side failure
        return 500, "text/plain", f"{e}\n".encode(), {}
    return (
        200, "text/x-fasta",
        format_fasta(res.consensuses).encode(), {},
    )


def stream_post_response(fn):
    """Shared status mapping for the `/v1/stream` lane's POST handlers:
    `fn()` returns the JSON-able ack document. The taxonomy is the
    /v1/consensus one (503 degraded, 429 shed, 504 deadline, 400
    undecodable) plus 404 for an unknown/retired session — a reaped or
    re-homed lease is an address error, not a server fault."""
    try:
        doc = fn()
    except ServiceDegraded as e:
        body = {"error": str(e), "retry_after_s": e.retry_after_s}
        return (
            503, "application/json", json.dumps(body).encode(),
            {"Retry-After": max(1, round(e.retry_after_s))},
        )
    except AdmissionError as e:
        body = {"error": str(e), "retry_after_s": e.retry_after_s}
        return (
            429, "application/json", json.dumps(body).encode(),
            {"Retry-After": max(1, round(e.retry_after_s))},
        )
    except DeadlineExceeded as e:
        return 504, "text/plain", f"{e}\n".encode(), {}
    except (LeaseRetired, KeyError) as e:
        return 404, "text/plain", f"{e}\n".encode(), {}
    except PoisonRequestError as e:
        return 422, "text/plain", f"{e}\n".encode(), {}
    except ValueError as e:  # decode rejection — the client's fault
        return 400, "text/plain", f"{e}\n".encode(), {}
    except Exception as e:  # noqa: BLE001 — server-side failure
        from kindel_tpu.resilience.policy import record_degrade

        record_degrade("serve.stream", f"post_500:{type(e).__name__}", 1)
        return 500, "text/plain", f"{e}\n".encode(), {}
    return 200, "application/json", json.dumps(doc).encode(), {}


def readyz_response(readyz_fn):
    """GET /readyz handler body: 200 while ready, 503 during warmup,
    drain, and after death — the liveness/readiness split load balancers
    need (/healthz stays 200 + status text, unchanged)."""
    doc = readyz_fn()
    status = 200 if doc.get("ready") else 503
    return status, "application/json", json.dumps(doc).encode(), {}


def _journal_settle_callback(journal, key: str):
    """Done-callback tombstoning one journal entry: however the future
    resolves — result, error, cancellation — the entry's life ends with
    exactly one settle record (record_settle is idempotent, so a
    watchdog racing a late flush tombstones once)."""

    def _cb(fut):
        try:
            exc = fut.exception()
        except CancelledError:
            journal.record_settle(key, "cancelled")
            return
        journal.record_settle(
            key, "ok" if exc is None else f"error:{type(exc).__name__}"
        )

    return _cb


def _aot_provenance() -> dict:
    """kindel_tpu.aot.provenance(), tolerant of a broken AOT layer —
    /healthz must answer even when the store is unreadable."""
    try:
        from kindel_tpu import aot

        return aot.provenance()
    except Exception:  # noqa: BLE001 — health probe, never raise
        return {"loaded": 0, "compiled": 0, "source": "disabled"}


class ConsensusService:
    """Online consensus calling over the batched cohort kernel."""

    def __init__(
        self,
        *,
        max_batch_rows: int = 64,
        max_wait_s: float = 0.02,
        max_depth: int = 256,
        high_watermark: int | None = None,
        decode_workers: int = 4,
        row_bucket: int = 8,
        http_host: str = "127.0.0.1",
        http_port: int | None = None,
        max_body_mb: int | None = None,
        journal_dir: str | None = None,
        quarantine_after: int | None = None,
        session_idle_s: float | None = None,
        emit_delta: int | None = None,
        slo: str | None = None,
        trace_spool: str | None = None,
        trace_collect: str | None = None,
        trace_buffer: int | None = None,
        extra_post_routes: dict | None = None,
        extra_get_routes: dict | None = None,
        metrics: MetricsRegistry | None = None,
        warmup: bool = False,
        warm_payloads=(),
        tuning=None,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 5.0,
        watchdog_s: float | None = None,
        retry=None,
        numpy_fallback: bool = True,
        **consensus_opts,
    ):
        """consensus_opts are BatchOptions fields (min_depth, realign,
        trim_ends, ...) applied to every request unless overridden per
        submit(). http_port=None runs without the HTTP front end;
        http_port=0 binds an ephemeral port (tests).

        warmup=True (the `kindel serve` default) AOT-precompiles the
        cohort kernel for every startup-derivable lane shape on a
        background thread — the minimal synthetic lane plus the shapes
        of `warm_payloads` (representative SAM/BAM paths or bytes) —
        while `/healthz` reports "warming"; the first request after
        "ok" on a warmed lane triggers no compile. `tuning` is an
        optional kindel_tpu.tune.TuningConfig pinning performance knobs
        explicitly (its cohort budget feeds the dispatch grouping).

        Resilience knobs (kindel_tpu.resilience, DESIGN.md §13):
        `breaker_threshold` consecutive device failures flip the circuit
        breaker open — /healthz reports "degraded" and new submissions
        shed with ServiceDegraded (HTTP 503 + Retry-After) until a
        half-open probe succeeds after `breaker_reset_s`. `watchdog_s`
        (None = off) times out hung flushes, failing only the affected
        requests. `retry` is an optional
        kindel_tpu.resilience.RetryPolicy for flush dispatch;
        `numpy_fallback` enables the last-resort per-request host
        fallback when the device dispatch keeps failing."""
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        if (
            tuning is not None
            and getattr(tuning, "cohort_budget_mb", None) is not None
        ):
            consensus_opts.setdefault(
                "cohort_budget_mb", tuning.cohort_budget_mb
            )
        self.default_opts = BatchOptions(**consensus_opts)
        self._warm_payloads = tuple(warm_payloads)
        self._do_warmup = bool(warmup) or bool(self._warm_payloads)
        #: "off" | "pending" | "warming" | "ok"
        self._warm_state = "pending" if self._do_warmup else "off"
        self._warm_error: str | None = None
        self._warm_thread: threading.Thread | None = None
        self._m_warm_seconds = self.metrics.gauge(
            "kindel_serve_warmup_seconds",
            "wall time of the startup AOT compile warmup",
        )
        self._m_warm_shapes = self.metrics.counter(
            "kindel_serve_warmup_shapes_total",
            "distinct lane shapes precompiled at startup",
        )
        self._m_warm_shape_info = self.metrics.info(
            "kindel_serve_warmup_shape",
            "one marker per precompiled lane shape (label: shape)",
        )
        self._m_tune_source = self.metrics.info(
            "kindel_serve_tune_source",
            "where each tuning knob's value came from "
            "(explicit/env/cache/default)",
        )
        from kindel_tpu import tune

        _budget, src = tune.resolve_cohort_budget_mb(
            self.default_opts.cohort_budget_mb
        )
        self._m_tune_source.set(knob="cohort_budget_mb", source=src)
        lane_coalesce, lc_src = tune.resolve_lane_coalesce(
            getattr(tuning, "lane_coalesce", None)
        )
        self._m_tune_source.set(knob="lane_coalesce", source=lc_src)
        # batching mode (DESIGN.md §16): "lanes" = shape-keyed
        # micro-batcher, "ragged" = page-class superbatching — one
        # compiled (and AOT-exportable) executable per page class serves
        # every request shape the class admits
        self.batch_mode, bm_src = tune.resolve_batch_mode(
            getattr(tuning, "batch_mode", None)
        )
        self._m_tune_source.set(knob="batch_mode", source=bm_src)
        # ingest mode (DESIGN.md §19): where each request's record scan
        # + CIGAR expansion run — host numpy (oracle) or the devingest
        # device kernels; byte-identical either way
        self.ingest_mode, im_src = tune.resolve_ingest_mode(
            getattr(tuning, "ingest_mode", None)
        )
        self._m_tune_source.set(knob="ingest_mode", source=im_src)
        # emission mode (DESIGN.md §22): where the final per-position
        # base plane renders — host wire decode (the oracle) or the
        # device-rendered ASCII plane (kindel_tpu.emit); byte-identical
        # either way, stamped onto every request's options so lanes /
        # superbatch kernels and the warmup all key on the same variant
        em_explicit = self.default_opts.emit_mode
        if em_explicit is None:
            em_explicit = getattr(tuning, "emit_mode", None)
        emit_mode, em_src = tune.resolve_emit_mode(em_explicit)
        self.default_opts = replace(self.default_opts, emit_mode=emit_mode)
        self.emit_mode = emit_mode
        self._m_tune_source.set(knob="emit_mode", source=em_src)
        obs_runtime.emit_mode_info().set(mode=emit_mode, source=em_src)
        # HTTP body bound (413 + Retry-After past it — serve/metrics.py):
        # explicit arg > tuning pin > KINDEL_TPU_MAX_BODY_MB > default
        self.max_body_mb, mb_src = tune.resolve_max_body_mb(
            max_body_mb if max_body_mb is not None
            else getattr(tuning, "max_body_mb", None)
        )
        self._m_tune_source.set(knob="max_body_mb", source=mb_src)
        obs_runtime.ingest_counters().mode.set(
            mode=self.ingest_mode, source=im_src
        )
        # durable admission journal (DESIGN.md §24): a write-ahead log
        # under the queue — admit records before the queue accepts,
        # tombstones at settle, replay at the next start. Resolved like
        # every knob (explicit --journal-dir > KINDEL_TPU_JOURNAL_DIR >
        # off); the off path is one None check on every hot-path site
        # (allocation-free, PR 4 convention)
        jd_explicit = (
            journal_dir if journal_dir is not None
            else getattr(tuning, "journal_dir", None)
        )
        self.journal_dir, jd_src = tune.resolve_journal_dir(jd_explicit)
        self._m_tune_source.set(knob="journal_dir", source=jd_src)
        qa_explicit = (
            quarantine_after if quarantine_after is not None
            else getattr(tuning, "quarantine_after", None)
        )
        self.quarantine_after, qa_src = tune.resolve_quarantine_after(
            qa_explicit
        )
        self._m_tune_source.set(knob="quarantine_after", source=qa_src)
        #: the journal scans its directory synchronously here (the
        #: quarantined-digest gate must hold from the first request);
        #: the REPLAY of live entries runs on a background thread at
        #: start()
        self._journal = (
            Journal(self.journal_dir) if self.journal_dir else None
        )
        #: fleet RPC adapter's IdempotencyCache, set by the owner
        #: BEFORE start(): replay pre-claims its keys there so a wire
        #: resubmission coalesces with the local replay (at-most-once)
        self.recovery_claim = None
        self._recovery_thread: threading.Thread | None = None
        # per-replica device mesh (DESIGN.md §23): one flush fans
        # across every local device; resolved like every knob (explicit
        # > KINDEL_TPU_MESH > host-keyed store > all-local-devices) and
        # handed to the worker, the paged batcher, and the warmup so
        # all three dispatch tiers run the same plan
        from kindel_tpu.parallel import meshexec

        self.mesh_plan = meshexec.plan(getattr(tuning, "mesh", None))
        self._m_tune_source.set(knob="mesh", source=self.mesh_plan.source)
        self._ragged_classes: tuple = ()
        self.queue = RequestQueue(
            max_depth=max_depth, high_watermark=high_watermark,
            metrics=self.metrics,
        )
        if self.batch_mode in ("ragged", "paged"):
            from kindel_tpu.ragged import RaggedBatcher, parse_classes

            spec, rc_src = tune.resolve_ragged_classes(
                getattr(tuning, "ragged_classes", None)
            )
            self._m_tune_source.set(knob="ragged_classes", source=rc_src)
            self._ragged_classes = parse_classes(spec)
            if self.batch_mode == "paged":
                from kindel_tpu.paged import PagedBatcher

                self.batcher = PagedBatcher(
                    self._ragged_classes, max_batch_rows=max_batch_rows,
                    max_wait_s=max_wait_s, mesh_plan=self.mesh_plan,
                )
            else:
                self.batcher = RaggedBatcher(
                    self._ragged_classes, max_batch_rows=max_batch_rows,
                    max_wait_s=max_wait_s,
                )
        else:
            self.batcher = MicroBatcher(
                max_batch_rows=max_batch_rows, max_wait_s=max_wait_s
            )
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold, reset_s=breaker_reset_s,
            metrics=self.metrics,
        )
        self._m_shed = self.metrics.counter(
            "kindel_serve_degraded_rejects_total",
            "submissions shed because the device circuit breaker was open",
        )
        self.worker = ServeWorker(
            self.queue, self.batcher, metrics=self.metrics,
            decode_workers=decode_workers, row_bucket=row_bucket,
            breaker=self.breaker, retry=retry, watchdog_s=watchdog_s,
            numpy_fallback=numpy_fallback, lane_coalesce=lane_coalesce,
            ingest_mode=self.ingest_mode, mesh_plan=self.mesh_plan,
            journal=self._journal,
        )
        # streaming sessions lane (kindel_tpu.sessions, DESIGN.md §25):
        # the session registry owns every PileupLease on this replica;
        # its snapshots dispatch through the queue/batcher above, so
        # streaming and one-shot traffic share ticks and executables
        from kindel_tpu.sessions import SessionRegistry

        idle_s, si_src = tune.resolve_session_idle_s(
            session_idle_s if session_idle_s is not None
            else getattr(tuning, "session_idle_s", None)
        )
        self._m_tune_source.set(knob="session_idle_s", source=si_src)
        emit_delta_v, ed_src = tune.resolve_emit_delta(
            emit_delta if emit_delta is not None
            else getattr(tuning, "emit_delta", None)
        )
        self._m_tune_source.set(knob="emit_delta", source=ed_src)
        self.sessions = SessionRegistry(
            self, idle_s=idle_s, emit_delta=emit_delta_v,
            journal=self._journal,
        )
        # SLO engine (kindel_tpu.obs.slo, DESIGN.md §26): declarative
        # objectives over the request settle path; off unless a spec
        # resolves (explicit > KINDEL_TPU_SLO > off)
        slo_spec, slo_src = tune.resolve_slo(slo)
        self._m_tune_source.set(knob="slo", source=slo_src)
        self.slo_engine = None
        if slo_spec:
            from kindel_tpu.obs.slo import SloEngine, parse_slo

            self.slo_engine = SloEngine(parse_slo(slo_spec))
        # stitched-trace plumbing (kindel_tpu.obs.fleetview): a SpanTap
        # is installed at start() when either knob resolves — replicas
        # spool + serve /v1/trace, a single-process service can also
        # write its own merged file at stop()
        tc_path, tc_src = tune.resolve_trace_collect(trace_collect)
        self._m_tune_source.set(knob="trace_collect", source=tc_src)
        tb, tb_src = tune.resolve_trace_buffer(trace_buffer)
        self._m_tune_source.set(knob="trace_buffer", source=tb_src)
        self._trace_collect = tc_path
        self._trace_spool = trace_spool
        self._trace_buffer = tb
        self._trace_tap = None
        self._http: ServeHTTPServer | None = None
        self._http_host = http_host
        self._http_port = http_port
        #: caller-supplied POST routes merged OVER the defaults at
        #: start() — the fleet RPC adapter (fleet/rpc.py) replaces
        #: /v1/consensus with its idempotency-aware variant this way
        self._extra_post_routes = dict(extra_post_routes or {})
        #: caller-supplied GET routes merged over the defaults the same
        #: way (/v1/trace lands here when tracing collection is on)
        self._extra_get_routes = dict(extra_get_routes or {})
        self._started_at: float | None = None
        #: drain posture: /readyz answers 503 while True (admission is
        #: closed on the queue; in-flight work keeps finishing)
        self._draining = False

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ConsensusService":
        self._started_at = time.monotonic()
        # fold JAX compile wall-time into the default registry so the
        # /metrics exposition attributes cold-start cost (best-effort)
        obs_runtime.install()
        if (
            self._trace_spool or self._trace_collect
        ) and self._trace_tap is None:
            from kindel_tpu.obs import fleetview

            self._trace_tap = fleetview.install_replica_tracing(
                spool_path=self._trace_spool,
                capacity=self._trace_buffer,
            )
            self._extra_get_routes.setdefault(
                fleetview.TRACE_ROUTE,
                lambda: fleetview.trace_drain_response(self._trace_tap),
            )
        self.worker.start()
        self.sessions.start()
        if self._journal is not None and self._recovery_thread is None:
            # replay-on-respawn (DESIGN.md §24): live entries from the
            # previous process life re-enter through the normal
            # admission path under their original keys, off the start
            # path (a big orphan set must not delay readiness)
            self._recovery_thread = threading.Thread(
                target=self._recover_journal,
                name="kindel-serve-recovery", daemon=True,
            )
            self._recovery_thread.start()
        if self._do_warmup and self._warm_thread is None:
            self._warm_state = "warming"
            self._warm_thread = threading.Thread(
                target=self._warm, name="kindel-serve-warmup", daemon=True
            )
            self._warm_thread.start()
        if self._http_port is not None:
            # exposition = the service's own registry + the process-global
            # one (streaming/batch/tune/runtime metrics), device gauges
            # refreshed per scrape
            self._http = ServeHTTPServer(
                MultiRegistry(
                    self.metrics, default_registry(),
                    refresh=self._refresh_metrics,
                ),
                host=self._http_host, port=self._http_port,
                health_fn=self.healthz,
                post_routes={
                    "/v1/consensus": self._handle_consensus_post,
                    "/v1/stream": self._handle_stream_open,
                    "/v1/stream/append": self._handle_stream_append,
                    "/v1/stream/close": self._handle_stream_close,
                    **self._extra_post_routes,
                },
                get_routes={
                    "/readyz": self._handle_readyz,
                    **self._extra_get_routes,
                },
                sse_routes={"/v1/stream/events": self._handle_stream_events},
                max_body_bytes=self.max_body_mb * (1 << 20),
            ).start()
        return self

    def _refresh_metrics(self) -> None:
        """Per-scrape refresh hook: point-in-time device gauges plus
        the SLO burn gauges (both cheap; both must be current in the
        exposition a scrape renders)."""
        obs_runtime.update_device_gauges()
        if self.slo_engine is not None:
            self.slo_engine.refresh()

    def stop(self, drain: bool = True) -> None:
        if self._http is not None:
            self._http.stop()
            self._http = None
        # leases end typed BEFORE the worker drains: every queued append
        # future settles exactly once, and the journal keeps the open
        # sessions' frames for the next life to replay
        self.sessions.shutdown()
        self.worker.stop(drain=drain)
        self._flush_trace_tap()
        if self._journal is not None:
            self._journal.gc()
            self._journal.close()

    def _flush_trace_tap(self) -> None:
        """Final trace flush (stop/drain/SIGTERM path): write the
        single-process merged file when `trace_collect` asked for one,
        then close the tap so every span is durably spooled before the
        process exits."""
        tap = self._trace_tap
        if tap is None:
            return
        self._trace_tap = None
        from kindel_tpu.obs import fleetview

        if self._trace_collect:
            collector = fleetview.TraceCollector(self._trace_collect)
            collector.add_ndjson(
                fleetview.TraceCollector.FRONT, tap.drain_payload()
            )
            try:
                collector.write()
            except OSError as e:
                collector.record_failure("write", e)
        tap.close()
        from kindel_tpu.obs import trace as obs_trace

        active = obs_trace.active_tracer()
        if active is not None and active.exporter is tap:
            obs_trace.disable_tracing()

    def _recover_journal(self) -> None:
        """Background replay of the journal's live entries. A recovery
        failure never takes the service down — unreplayed entries stay
        live in the journal for the NEXT life to retry."""
        from kindel_tpu.durable import recovery

        try:
            report = recovery.replay(
                self, self._journal.scan, self._journal,
                quarantine_after=self.quarantine_after,
                claim_cache=self.recovery_claim,
            )
            n_sessions = recovery.replay_sessions(
                self.sessions, self._journal.scan
            )
            if n_sessions:
                report = dict(report, sessions=n_sessions)
            if any(report.values()):
                print(
                    f"kindel-serve journal recovery: {report}",
                    file=sys.stderr,
                )
        except Exception as e:  # noqa: BLE001 — recovery is best-effort per life
            from kindel_tpu.resilience.policy import record_degrade

            record_degrade("journal.replay", "recovery_failed", 1)
            print(
                f"kindel-serve journal recovery failed: {e!r}",
                file=sys.stderr,
            )

    def drain(self, handback: bool = False) -> list[ServeRequest]:
        """Graceful shutdown: stop admitting (new submits reject with a
        jittered retry-after, /readyz flips 503), finish every in-flight
        request, then stop. With handback=False (the single-replica
        SIGTERM path) queued-but-unstarted requests are SERVED before
        shutdown completes and the return value is empty; with
        handback=True (the fleet drain path) they are popped unresolved
        and returned, so the fleet router can re-queue them on a
        surviving replica while this one restarts."""
        self._draining = True
        handed = self.queue.handback() if handback else []
        # session snapshots never leave the replica through hand-back:
        # a PreDecoded payload has no wire form, and the session's
        # lease already settled the futures that were waiting on the
        # snapshot (hand-off/close); the inner future settles typed here
        stream = [r for r in handed if r.session is not None]
        handed = [r for r in handed if r.session is None]
        for req in stream:
            settle_future(
                req.future,
                exc=LeaseRetired(
                    f"session {req.session} snapshot dropped: replica "
                    "draining"
                ),
            )
        if not handback:
            self.queue.close_admission()
        jr = self._journal
        if jr is not None:
            # a handed-back request's future settles on ANOTHER replica
            # — this journal's entry would leak without its own
            # tombstone (the hand-back IS this replica's settle)
            for req in handed:
                if req.key is not None:
                    jr.record_settle(req.key, "handback")
        self.stop(drain=True)
        return handed

    def kill(self) -> None:
        """Chaos surface: abrupt replica death (see ServeWorker.kill) —
        admitted futures are abandoned unresolved, exactly what a
        SIGKILLed process leaves behind. The fleet supervisor's probe
        sees `live` go False, evicts, and replays."""
        if self._http is not None:
            self._http.stop()
            self._http = None
        self.worker.kill()

    @property
    def live(self) -> bool:
        """Liveness (vs readiness): can this service still make
        progress on admitted work? False once killed/stopped."""
        return self.worker.alive

    def __enter__(self) -> "ConsensusService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def http_address(self) -> tuple[str, int] | None:
        if self._http is None:
            return None
        return self._http.host, self._http.port

    def _warm(self) -> None:
        """Background AOT warmup (see serve/warmup.py). A warmup failure
        never takes the service down — the first request just pays its
        own compile, exactly the pre-warmup behavior."""
        from kindel_tpu.serve.warmup import warm_shapes

        t0 = time.monotonic()
        try:
            timings = warm_shapes(
                self.default_opts, row_bucket=self.worker.row_bucket,
                payloads=self._warm_payloads,
                ingest_mode=self.ingest_mode,
                mesh_plan=self.mesh_plan,
            )
            if (
                self.batch_mode in ("ragged", "paged")
                and self._ragged_classes
            ):
                # superbatch geometries are startup-known in FULL — with
                # a warm AOT store this is the zero-compile startup that
                # covers arbitrary traffic, not just derivable shapes.
                # Paged mode runs the SAME kernel over the same
                # geometries (its signature is geometry-only by design),
                # so one warmup covers both modes.
                from kindel_tpu.serve.warmup import warm_ragged

                timings.update(
                    warm_ragged(self.default_opts, self._ragged_classes,
                                mesh_plan=self.mesh_plan)
                )
            self._m_warm_shapes.inc(len(timings))
            for label, t in timings.items():
                if isinstance(t, dict):
                    # compile/execute split + AOT provenance per shape
                    # (plain floats still accepted: stand-in warmers)
                    self._m_warm_shape_info.set(
                        shape=label,
                        batch_mode=self.batch_mode,
                        seconds=round(t.get("total_s", 0.0), 3),
                        compile_s=round(t.get("compile_s", 0.0), 3),
                        execute_s=round(t.get("execute_s", 0.0), 3),
                        source=t.get("source", "fresh"),
                    )
                else:
                    self._m_warm_shape_info.set(
                        shape=label, batch_mode=self.batch_mode,
                        seconds=round(t, 3),
                    )
        except Exception as e:  # noqa: BLE001 — warmup is best-effort
            self._warm_error = repr(e)
            print(f"kindel-serve warmup failed: {e!r}", file=sys.stderr)
        finally:
            self._m_warm_seconds.set(round(time.monotonic() - t0, 3))
            self._warm_state = "ok"

    @property
    def warming(self) -> bool:
        return self._warm_state in ("pending", "warming")

    def wait_warm(self, timeout: float | None = None) -> bool:
        """Block until startup warmup finishes (True) or timeout (False).
        No-op True when warmup is disabled."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while self.warming:
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        return True

    def healthz(self) -> dict:
        if self.warming:
            status = "warming"
        elif self.breaker.state != "closed":
            # breaker open / half-open: load balancers should hold
            # traffic; submissions shed with 503 + Retry-After
            status = "degraded"
        else:
            status = "ok"
        doc = {
            "status": status,
            "breaker": self.breaker.snapshot(),
            "uptime_s": (
                round(time.monotonic() - self._started_at, 3)
                if self._started_at is not None else 0.0
            ),
            "queue_depth": self.queue.depth,
            "pending_rows": self.batcher.pending_rows,
            "watermark": self.queue.high_watermark,
            # EWMA time-to-service at the current depth: what a REMOTE
            # queue view (fleet/rpc.py) quotes for retry-after hints —
            # the wire carries the estimate so the router's admission
            # math works without a shared address space
            "est_wait_s": round(self.queue.estimated_wait_s(), 4),
            "warmup": self._warm_state,
            "warmup_s": self._m_warm_seconds.value,
            # AOT provenance, mirroring the tune_source convention: did
            # this replica's device programs load from the store or
            # compile fresh? (kindel_tpu.aot; "disabled" = store off)
            "aot": _aot_provenance(),
            # batching provenance, same convention: which admission →
            # dispatch path this replica runs, and (under ragged) the
            # page-class geometries its executables are warmed for
            "batch_mode": self.batch_mode,
            # emission provenance (DESIGN.md §22): host wire decode or
            # the device-rendered ASCII plane
            "emit_mode": self.emit_mode,
        }
        if self._ragged_classes:
            doc["ragged"] = {
                "classes": [c.label() for c in self._ragged_classes],
            }
        if self.batch_mode == "paged":
            # live residency per pool (pages in use, resident segments,
            # parked admissions) — the paged tier's capacity signal
            doc["paged"] = self.batcher.residency_snapshot()
        if self._journal is not None:
            # durability posture (DESIGN.md §24): live = entries a
            # respawn would replay, quarantined = poison digests barred
            # from admission
            doc["journal"] = self._journal.snapshot()
        # streaming lane posture (DESIGN.md §25): open sessions, idle
        # horizon, emission gate, per-session epoch watermarks
        doc["sessions"] = self.sessions.snapshot()
        if self._warm_error is not None:
            doc["warmup_error"] = self._warm_error
        return doc

    def readyz(self) -> dict:
        """Readiness (vs /healthz liveness): should a load balancer
        route NEW traffic here right now? Not ready during warmup (the
        first requests would pay compiles), during drain (admission is
        closed), and once dead. /healthz keeps its original semantics —
        always 200 with a status string — because existing probes and
        tests depend on them; /readyz is the 503-capable split."""
        if self.warming:
            ready, status = False, "warming"
        elif self._draining:
            ready, status = False, "draining"
        elif not self.live:
            ready, status = False, "dead"
        else:
            ready, status = True, "ok"
        doc = {
            "ready": ready,
            "status": status,
            "queue_depth": self.queue.depth,
        }
        if self.slo_engine is not None:
            # a fast-burning SLO degrades readiness: the balancer stops
            # routing NEW traffic here until the burn window drains
            slo_doc = self.slo_engine.evaluate()
            if ready and any(
                r["fast_burn_active"] for r in slo_doc.values()
            ):
                doc["ready"] = False
                doc["status"] = "slo_degraded"
            doc["slo"] = slo_doc
        return doc

    # ------------------------------------------------------------- requests

    def submit(self, payload, deadline_s: float | None = None,
               idempotency_key: str | None = None,
               **opt_overrides) -> Future:
        """Admit one request (path or SAM/BAM bytes). Returns a Future of
        SampleResult. Raises AdmissionError when load-shedding,
        PoisonRequestError (422 on the wire) when the payload's digest
        is quarantined. `idempotency_key` (the fleet RPC adapter passes
        the wire header's) keys the durable journal entry; with
        journaling on and no key supplied, one is generated — the
        journal and the wire share one key vocabulary."""
        if not self.breaker.allow_admission():
            self._m_shed.inc()
            # jittered so a cohort of synchronized shed clients does not
            # stampede the single half-open probe slot in lockstep
            raise ServiceDegraded(
                "service degraded: device circuit breaker is "
                f"{self.breaker.state}",
                jittered_retry_after(self.breaker.retry_after_s()),
            )
        opts = (
            replace(self.default_opts, **opt_overrides)
            if opt_overrides else self.default_opts
        )
        jr = self._journal
        if jr is None:
            req = ServeRequest(
                payload=payload, opts=opts,
                deadline=(
                    time.monotonic() + deadline_s
                    if deadline_s is not None else None
                ),
            )
            self.queue.submit(req)
            if self.slo_engine is not None:
                self.slo_engine.attach("/v1/consensus", req.future)
            return req.future
        digest = journal_payload_digest(payload)
        if jr.is_quarantined(digest):
            journal_metrics().poison_rejects.inc()
            raise PoisonRequestError(
                f"payload {digest[:16]} is quarantined: an identical "
                f"request crashed this replica {self.quarantine_after} "
                "times (DESIGN.md §24) — do not retry",
                digest=digest,
            )
        req = ServeRequest(
            payload=payload, opts=opts,
            deadline=(
                time.monotonic() + deadline_s
                if deadline_s is not None else None
            ),
            key=idempotency_key or journal_new_key(digest),
        )
        self._journal_admit(jr, req, opt_overrides, digest)
        if self.slo_engine is not None:
            self.slo_engine.attach("/v1/consensus", req.future)
        return req.future

    def _journal_admit(self, jr, req: ServeRequest, opt_overrides: dict,
                       digest: str, force: bool = False) -> None:
        """WAL-then-accept: the admit record is durable BEFORE the
        queue takes the request; a queue rejection tombstones the entry
        it just wrote (nothing to replay — the caller got the error)."""
        try:
            jr.record_admit(
                req.key, req.payload, opt_overrides, digest=digest
            )
        except JournalWriteError as e:
            # an admit the journal cannot protect is rejected, typed
            # and retryable — durability is the contract, not best
            # effort
            raise AdmissionError(
                f"admission journal unavailable: {e}",
                jittered_retry_after(0.5),
            ) from e
        req.future.add_done_callback(_journal_settle_callback(jr, req.key))
        try:
            self.queue.submit(req, force=force)
        except AdmissionError:
            jr.record_settle(req.key, "rejected")
            raise

    def _submit_replay(self, key: str, payload, opts: dict,
                       suspect: bool = False) -> Future:
        """Recovery-path admission (kindel_tpu.durable.recovery): the
        entry was already admitted in a previous process life, so
        re-admission is forced past the watermark; `suspect` entries
        (blamed for a crash) dispatch isolated. No deadline — the
        original one is a dead process's monotonic timestamp."""
        jr = self._journal
        req = ServeRequest(
            payload=payload,
            opts=(
                replace(self.default_opts, **opts) if opts
                else self.default_opts
            ),
            key=key,
            suspect=suspect,
        )
        self._journal_admit(
            jr, req, opts, journal_payload_digest(payload), force=True
        )
        return req.future

    def request(self, payload, timeout: float | None = None,
                idempotency_key: str | None = None,
                **opt_overrides) -> SampleResult:
        """Synchronous submit: blocks until served (or raises)."""
        return self.submit(
            payload, idempotency_key=idempotency_key, **opt_overrides
        ).result(timeout=timeout)

    def submit_stream_snapshot(self, units, opts, session: str) -> Future:
        """Session-snapshot admission (kindel_tpu.sessions): one
        consensus dispatch over the session's merged, pre-decoded units
        through the NORMAL queue — snapshots coalesce into the shared
        paged/ragged ticks and reuse the warmed executables. Forced past
        the watermark: backpressure was already applied at the append's
        admission, and shedding an internal launch would strand the
        triggering append's ack. key=None keeps the journal out — the
        session's APPEND frames are the durable record, and a PreDecoded
        payload has no digestable wire form."""
        req = ServeRequest(
            payload=PreDecoded(
                tuple(units), label=f"stream:{session}"
            ),
            opts=opts, session=session,
        )
        self.queue.submit(req, force=True)
        if self.slo_engine is not None:
            self.slo_engine.attach("/v1/stream", req.future)
        return req.future

    # ---------------------------------------------------------- HTTP ingest

    def _handle_consensus_post(self, body: bytes):
        """POST /v1/consensus (status mapping in consensus_post_response)."""
        return consensus_post_response(self.request, body)

    def _handle_stream_open(self, body: bytes):
        """POST /v1/stream: open a session (body = optional first read
        batch) → {"session": id}. Status mapping in stream_post_response."""
        return stream_post_response(
            lambda: {
                "session": self.sessions.open(
                    bytes(body) if body else None
                ),
            }
        )

    @staticmethod
    def _stream_sid(headers) -> str:
        sid = (headers.get("X-Kindel-Session") or "").strip()
        if not sid:
            raise ValueError("missing X-Kindel-Session header")
        return sid

    def _handle_stream_append(self, body: bytes, headers):
        """POST /v1/stream/append (X-Kindel-Session header): append one
        read batch; blocks until the append's ack settles — immediately
        for below-gate appends, at the emission decision for the
        gate-crossing one."""
        return stream_post_response(
            lambda: self.sessions.append(
                self._stream_sid(headers), bytes(body)
            ).result()
        )

    def _handle_stream_close(self, body: bytes, headers):
        """POST /v1/stream/close (X-Kindel-Session header): forced final
        snapshot + emit, lease retired; the ack carries the final FASTA."""
        return stream_post_response(
            lambda: self.sessions.close(
                self._stream_sid(headers)
            ).result()
        )

    def _handle_stream_events(self, params: dict):
        """GET /v1/stream/events?session=<id>: the SSE update stream
        (serve/metrics.py streams the returned generator)."""
        sid = (params.get("session") or "").strip()
        if not sid:
            raise ValueError("missing session query parameter")
        return self.sessions.subscribe(sid)

    def _handle_readyz(self):
        return readyz_response(self.readyz)


class ConsensusClient:
    """Synchronous in-process client over a running ConsensusService."""

    def __init__(self, service: ConsensusService):
        self._service = service

    def consensus(self, payload, timeout: float | None = None,
                  **opts) -> list:
        """[Sequence, ...] for one SAM/BAM path or bytes payload."""
        return self._service.request(payload, timeout=timeout,
                                     **opts).consensuses

    def result(self, payload, timeout: float | None = None, **opts):
        """Full workloads.result namedtuple (consensuses, changes,
        reports) — the bam_to_consensus-shaped view of a served request."""
        from kindel_tpu.workloads import consensus_result

        return consensus_result(
            self._service.request(
                payload, timeout=timeout, build_reports=True,
                build_changes=True, **opts,
            )
        )

    def fasta(self, payload, timeout: float | None = None, **opts) -> str:
        from kindel_tpu.io.fasta import format_fasta

        return format_fasta(self.consensus(payload, timeout=timeout, **opts))
