"""Bounded request queue with admission control and deadline-aware
backpressure.

The queue is the service's only unbounded-input surface, so it is where
load sheds: past the high watermark `submit` rejects immediately with a
`retry-after` hint instead of letting latency grow without bound
(clients see HTTP 429; in-process callers catch `AdmissionError`). The
hint is derived from an EWMA of observed per-request service time, the
same estimate used to reject deadline-infeasible requests up front —
a request that would certainly miss its deadline wastes a batch slot
some feasible request could have used.

Expired requests (deadline already passed while queued) are dropped at
`get` time: their futures fail with `DeadlineExceeded` and the worker
never spends a decode thread on them.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field

from kindel_tpu.obs import trace

#: bounded jitter applied to every retry-after hint: ±25% around the
#: estimate, so a cohort of synchronized clients spreads its retries
#: over half the window instead of stampeding the service (worst at the
#: breaker's half-open probe: one probe slot, N clients retrying on the
#: same fixed hint re-trips the breaker on arrival)
RETRY_JITTER_FRAC = 0.25

_jitter_rng = random.Random()


def jittered_retry_after(base_s: float, *, frac: float = RETRY_JITTER_FRAC,
                         floor: float = 0.05, rng=None) -> float:
    """`base_s` spread uniformly over [base*(1-frac), base*(1+frac)],
    floored — the retry-after de-synchronizer every admission-shed path
    (watermark, deadline, drain, breaker) runs its hint through."""
    r = rng if rng is not None else _jitter_rng
    return max(floor, base_s * (1.0 + frac * (2.0 * r.random() - 1.0)))


class AdmissionError(RuntimeError):
    """Request rejected at the door; retry after `retry_after_s`."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ServiceDegraded(AdmissionError):
    """Shed at the door because the device circuit breaker is open —
    the service is degraded, not overloaded (clients see HTTP 503 with
    Retry-After, vs the watermark's 429)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it could be served."""


@dataclass(frozen=True)
class PreDecoded:
    """An already-decoded payload: CallUnits ready for the batcher.

    The sessions lane (kindel_tpu.sessions) merges appended batches
    host-side and dispatches consensus SNAPSHOTS over the merged units
    — re-running the wire decode per snapshot would be pure waste, so
    the worker's decode stage passes these straight through. Never a
    wire payload (snapshots bypass the journal's digest/admit path:
    the session's APPEND frames are the durable record, queue.py keys
    these requests with key=None)."""

    units: tuple
    label: str = "<predecoded>"


@dataclass
class ServeRequest:
    """One in-flight consensus request.

    `payload` is a path (str/Path) or raw SAM/BAM bytes; `opts` is the
    cohort BatchOptions the worker will call with; `deadline` is an
    absolute monotonic timestamp or None. `span` is the request's root
    trace span (`serve.request`, opened at admission) — the handle every
    downstream stage parents its own span to, which is how one request's
    trace id propagates queue → batcher → worker → device dispatch
    across four threads; `wait_span` is the open `serve.queue_wait`
    child between enqueue and intake pop. Both default None and stay
    None when the request never passed through a queue (direct
    component tests) or when tracing is disabled (the no-op span).
    """

    payload: object
    opts: object
    future: Future = field(default_factory=Future)
    enqueued_at: float = 0.0
    deadline: float | None = None
    span: object = None
    wait_span: object = None
    #: durable-journal identity (kindel_tpu.durable, DESIGN.md §24):
    #: the idempotency key the admission journal WAL'd this request
    #: under (None with journaling off — no allocation on that path)
    key: str | None = None
    #: quarantine suspect: this entry was in flight when a previous
    #: process life crashed (blamed at least once on replay). The
    #: worker dispatches suspects ISOLATED — a flush of one — so a
    #: poison request cannot take co-batched survivors down again
    suspect: bool = False
    #: owning streaming session id (kindel_tpu.sessions), or None for
    #: one-shot traffic. A session snapshot must never leave its
    #: replica through the fleet hand-back path — its PreDecoded
    #: payload has no wire form and its session's lease settles it at
    #: hand-off — so the drain path filters on this field
    session: str | None = None


class RequestQueue:
    """FIFO of ServeRequests, bounded by an admission watermark."""

    #: service-time estimate before any request has completed (seconds)
    DEFAULT_SERVICE_S = 0.25
    #: EWMA smoothing for observed service times
    _ALPHA = 0.2

    def __init__(self, max_depth: int = 256,
                 high_watermark: int | None = None,
                 metrics=None, clock=time.monotonic):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        self.max_depth = max_depth
        self.high_watermark = (
            max_depth if high_watermark is None
            else min(high_watermark, max_depth)
        )
        if self.high_watermark < 1:
            raise ValueError("high_watermark must be >= 1")
        self._clock = clock
        self._q: deque[ServeRequest] = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._ewma_service_s = self.DEFAULT_SERVICE_S
        self._closed = False
        #: drain posture: submit rejects, get keeps serving what's queued
        self._admission_closed = False
        if metrics is not None:
            self._depth_gauge = metrics.gauge(
                "kindel_serve_queue_depth", "requests waiting for decode"
            )
            self._rejects = metrics.counter(
                "kindel_serve_admission_rejects_total",
                "requests rejected at admission (watermark or deadline)",
            )
            self._expired = metrics.counter(
                "kindel_serve_deadline_expired_total",
                "queued requests dropped because their deadline passed",
            )
        else:
            self._depth_gauge = self._rejects = self._expired = None

    @property
    def depth(self) -> int:
        return len(self._q)

    def estimated_wait_s(self, depth: int | None = None) -> float:
        """Rough time-to-service for a request entering at `depth`."""
        d = len(self._q) if depth is None else depth
        return self._ewma_service_s * max(d, 1)

    def observe_service_time(self, seconds: float) -> None:
        """Worker feedback: one request's enqueue→complete wall time."""
        with self._lock:
            self._ewma_service_s = (
                (1 - self._ALPHA) * self._ewma_service_s
                + self._ALPHA * max(seconds, 1e-4)
            )

    def submit(self, req: ServeRequest, force: bool = False) -> None:
        """Admit or reject. Raises AdmissionError past the watermark or
        when the request's deadline is already infeasible. Opens the
        request's root trace span plus its admission / queue-wait
        children (all shared no-op spans when tracing is disabled).

        `force` skips the watermark and deadline-feasibility checks
        (closed/draining still reject): the journal replay path — these
        requests were already admitted once, in a previous process
        life, and re-admission must not be sheddable or the entry would
        leak until some later respawn finds headroom."""
        now = self._clock()
        if req.span is None:
            req.span = trace.start_span("serve.request")
            if req.span is not trace.NOOP_SPAN:
                payload = req.payload
                if isinstance(payload, (bytes, bytearray)):
                    req.span.set_attribute(
                        payload="<bytes>", payload_bytes=len(payload)
                    )
                else:
                    req.span.set_attribute(payload=str(payload))
        traced = req.span is not None and req.span is not trace.NOOP_SPAN
        adm = trace.start_span("serve.admission", parent=req.span)
        try:
            with self._not_empty:
                if self._closed:
                    raise AdmissionError(
                        "service is shutting down", jittered_retry_after(1.0)
                    )
                if self._admission_closed:
                    raise AdmissionError(
                        "service is draining: admission closed",
                        jittered_retry_after(1.0),
                    )
                depth = len(self._q)
                if traced:
                    adm.set_attribute(depth=depth)
                if not force and depth >= self.high_watermark:
                    if self._rejects is not None:
                        self._rejects.inc()
                    retry = self.estimated_wait_s(
                        depth - self.high_watermark + 1
                    )
                    raise AdmissionError(
                        f"queue depth {depth} at/over watermark "
                        f"{self.high_watermark}", jittered_retry_after(retry),
                    )
                if not force and req.deadline is not None:
                    budget = req.deadline - now
                    est = self.estimated_wait_s(depth + 1)
                    if budget <= 0 or est > budget:
                        if self._rejects is not None:
                            self._rejects.inc()
                        raise AdmissionError(
                            f"deadline budget {budget:.3f}s < estimated wait "
                            f"{est:.3f}s",
                            jittered_retry_after(max(est - max(budget, 0), 0.05)),
                        )
                req.enqueued_at = now
                self._q.append(req)
                if self._depth_gauge is not None:
                    self._depth_gauge.set(len(self._q))
                req.wait_span = trace.start_span(
                    "serve.queue_wait", parent=req.span
                )
                self._not_empty.notify()
        except AdmissionError as e:
            if traced:
                adm.set_attribute(outcome="rejected")
                adm.finish()
                req.span.set_attribute(outcome="rejected", error=str(e))
                req.span.finish()
            raise
        if traced:
            adm.set_attribute(outcome="admitted")
        adm.finish()

    def get(self, timeout: float | None = None) -> ServeRequest | None:
        """Pop the oldest live request; None on timeout or close.

        Requests whose deadline passed while queued are failed with
        DeadlineExceeded here and never returned."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._not_empty:
            while True:
                while self._q:
                    req = self._q.popleft()
                    if self._depth_gauge is not None:
                        self._depth_gauge.set(len(self._q))
                    if (
                        req.deadline is not None
                        and self._clock() >= req.deadline
                    ):
                        if self._expired is not None:
                            self._expired.inc()
                        try:
                            # a caller may have cancelled the future while
                            # it sat queued; the expiry settle must not
                            # take the popping worker thread down with an
                            # InvalidStateError
                            if req.future.set_running_or_notify_cancel():
                                req.future.set_exception(
                                    DeadlineExceeded(
                                        "deadline passed while queued "
                                        f"({self._clock() - req.enqueued_at:.3f}s)"
                                    )
                                )
                        except (InvalidStateError, RuntimeError):
                            pass  # cancelled/settled while queued
                        if req.wait_span is not None:
                            req.wait_span.set_attribute(outcome="expired")
                            req.wait_span.finish()
                        if req.span is not None:
                            req.span.set_attribute(outcome="expired")
                            req.span.finish()
                        continue
                    if req.wait_span is not None:
                        req.wait_span.finish()
                    return req
                if self._closed:
                    return None
                if deadline is None:
                    self._not_empty.wait()
                else:
                    remaining = deadline - self._clock()
                    if remaining <= 0 or not self._not_empty.wait(remaining):
                        return None

    def close_admission(self) -> None:
        """Drain posture: reject every new submit (AdmissionError with a
        jittered retry-after) while `get` keeps serving what is already
        queued — the single-replica graceful-shutdown half of the drain
        path (everything admitted still completes on this service)."""
        with self._not_empty:
            self._admission_closed = True

    @property
    def admitting(self) -> bool:
        return not (self._closed or self._admission_closed)

    def handback(self) -> list[ServeRequest]:
        """Drain hand-back: stop admission AND pop every queued-but-
        unstarted request, returning them with futures untouched — the
        fleet router re-queues them on a surviving replica (consensus is
        pure, so replay is idempotent; the Future is the exactly-once
        settle point). Requests already popped by intake are unaffected
        and finish here. Every admitted request is therefore in exactly
        one place afterwards: this service's in-flight set, or the
        returned list."""
        with self._not_empty:
            self._admission_closed = True
            out = list(self._q)
            self._q.clear()
            if self._depth_gauge is not None:
                self._depth_gauge.set(0)
            self._not_empty.notify_all()
        for req in out:
            if req.wait_span is not None:
                req.wait_span.set_attribute(outcome="handback")
                req.wait_span.finish()
                req.wait_span = None
        return out

    def close(self) -> list[ServeRequest]:
        """Stop admitting; wake blocked getters; return drained leftovers
        (callers fail or hand them off)."""
        with self._not_empty:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            if self._depth_gauge is not None:
                self._depth_gauge.set(0)
            self._not_empty.notify_all()
        return leftovers
