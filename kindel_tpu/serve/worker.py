"""Serve executor: decode on host threads, one device dispatch per flush.

Three stages, mirroring the offline cohort pipeline's overlap structure
(kindel_tpu.batch.stream_bam_to_results) but driven by arrival instead
of by a file list:

  intake    one thread pops admitted requests off the RequestQueue and
            fans decode/event-extraction out to a host thread pool
  decode    per-request: payload → ReadBatch → EventSet → CallUnits,
            then into the micro-batcher. A malformed payload fails ONLY
            its own future here — the batch a request would have joined
            never sees it. BGZF payloads inflate through the ONE
            process-wide worker pool (kindel_tpu.io.inflate.shared_pool,
            pre-sized in start()), so concurrent decodes queue members
            on a bounded pool instead of oversubscribing the host.
  dispatch  one thread drives MicroBatcher.poll; each flush packs into
            the lane's pinned pad shapes (kindel_tpu.batch.pack_cohort),
            launches ONE batched device program, assembles every
            request's FASTA on the host pool, and completes futures.

Failure handling is layered (kindel_tpu.resilience — DESIGN.md §13):
a transient device error retries the flush with backoff; a device OOM
that survives the retries bisects the flush and re-dispatches the
halves; any other batch-level failure re-runs one request at a time so
only the culpable request fails; a singleton that still dies on a
transient device error is served by the per-request numpy fallback.
A supervisor thread auto-restarts a dead intake/dispatch loop and
watchdogs hung flushes — failing only the affected requests' futures,
so every admitted request resolves exactly once no matter what the
device does. Dispatch outcomes feed the service's circuit breaker.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import InvalidStateError, ThreadPoolExecutor

from kindel_tpu.batch import (
    SampleResult,
    _assemble_outputs,
    _fold_results,
    cohort_pad_shapes,
    launch_cohort_kernel,
    pack_cohort,
)
from kindel_tpu.durable.journal import mark_if_active
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs import trace
from kindel_tpu.pileup_jax import _bucket
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience import policy as rpolicy
from kindel_tpu.resilience.breaker import FlushTimeout
from kindel_tpu.utils.profiling import maybe_phase

from kindel_tpu.serve.batcher import Flush, MicroBatcher
from kindel_tpu.serve.queue import PreDecoded, RequestQueue, ServeRequest


_COALESCE_COUNTERS: tuple | None = None
_PADDING_COUNTERS: tuple | None = None
_RAGGED_METRICS: tuple | None = None


def _padding_counters() -> tuple:
    """(payload bases, padded bases) counters on the PROCESS-GLOBAL
    registry, fed by EVERY serve dispatch — lanes and ragged alike — so
    bench's shape-diverse scenario can compare the two paths' pad waste
    from one place."""
    global _PADDING_COUNTERS
    if _PADDING_COUNTERS is None:
        from kindel_tpu.obs.metrics import default_registry

        reg = default_registry()
        _PADDING_COUNTERS = (
            reg.counter(
                "kindel_dispatch_payload_bases_total",
                "true reference positions carried by serve device "
                "dispatches (the useful fraction of the padded grid)",
            ),
            reg.counter(
                "kindel_dispatch_padded_bases_total",
                "padded grid positions serve device dispatches "
                "scattered over (payload / padded = occupancy)",
            ),
        )
    return _PADDING_COUNTERS


def _ragged_metrics() -> tuple:
    """Superbatch occupancy/shape metrics on the process-global registry
    (kindel_tpu.ragged; DESIGN.md §16)."""
    global _RAGGED_METRICS
    if _RAGGED_METRICS is None:
        from kindel_tpu.obs.metrics import default_registry

        reg = default_registry()
        _RAGGED_METRICS = (
            reg.histogram(
                "kindel_ragged_occupancy",
                "payload slots / page-class slots per dispatched "
                "superbatch",
                buckets=(0.05, 0.1, 0.2, 0.3, 0.5, 0.7, 0.85, 0.95, 1.0),
            ),
            reg.histogram(
                "kindel_ragged_segments",
                "segments (request units) per dispatched superbatch",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            ),
            reg.counter(
                "kindel_ragged_superbatches_total",
                "superbatches dispatched through the segment kernel, "
                "labeled by page class",
            ),
        )
    return _RAGGED_METRICS


def _coalesce_counters() -> tuple:
    """(flushes-merged, fat-launches) counters on the PROCESS-GLOBAL
    registry — the serve /metrics exposition includes it via
    MultiRegistry, and bench.py's JSON line reports dispatch coalescing
    from the same place it reports transfer bytes."""
    global _COALESCE_COUNTERS
    if _COALESCE_COUNTERS is None:
        from kindel_tpu.obs.metrics import default_registry

        reg = default_registry()
        _COALESCE_COUNTERS = (
            reg.counter(
                "kindel_dispatch_coalesced_flushes_total",
                "ready micro-batcher flushes merged into a fat device "
                "launch instead of dispatching alone",
            ),
            reg.counter(
                "kindel_dispatch_coalesced_launches_total",
                "device launches that carried more than one coalesced "
                "flush",
            ),
        )
    return _COALESCE_COUNTERS


def _payload_label(payload) -> str:
    if isinstance(payload, PreDecoded):
        return payload.label
    return "<bytes>" if isinstance(payload, (bytes, bytearray)) else str(
        payload
    )


def _flush_note(entries) -> str:
    """Request-identity string for `match=`-scoped fault specs: the
    member idempotency keys (payload labels for unjournaled requests).
    Built ONLY when a fault plan is active — the disabled hot path
    stays allocation-free."""
    return "|".join(
        req.key if req.key is not None else _payload_label(req.payload)
        for req, _units in entries
    )


def _shape_label(shapes: tuple) -> str:
    """Lane pad shapes as one metric-label-safe token ("1024x64x...")."""
    return "x".join(str(s) for s in shapes)


def decode_events(payload, ingest_mode: str = "host"):
    """The decode stage's event half: payload (path or SAM/BAM bytes) →
    EventSet. Split out of decode_request for the sessions lane
    (kindel_tpu.sessions), whose appends merge at the EventSet level —
    one decode path, whatever consumes the events. Under
    ingest_mode="device" the record scan + CIGAR expansion run as
    kindel_tpu.devingest kernels on the accelerator (byte-identical;
    SAM-text payloads and any anomaly fall back to the host oracle)."""
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment, load_alignment_bytes

    ev = None
    if ingest_mode == "device":
        ev = _decode_device(payload)
    if ev is None:
        if isinstance(payload, (bytes, bytearray)):
            batch = load_alignment_bytes(bytes(payload))
        else:
            batch = load_alignment(str(payload))
        ev = extract_events(batch)
    return ev


def decode_request(req: ServeRequest, ingest_mode: str = "host") -> list:
    """Host stage: payload → CallUnits (empty list = no aligned reads).
    PreDecoded payloads (session snapshots — the registry already
    merged and unit-built them) pass straight through; everything else
    decodes via decode_events."""
    from kindel_tpu.call_jax import CallUnit

    payload = req.payload
    if isinstance(payload, PreDecoded):
        return list(payload.units)
    with maybe_phase("serve decode"):
        ev = decode_events(payload, ingest_mode)
    units = []
    for rid in ev.present_ref_ids:
        u = CallUnit(ev, rid, with_ins_table=True, realign=req.opts.realign)
        units.append(u)
    return units


def _decode_device(payload):
    """Device-ingest decode of one payload, or None to fall back to the
    host path: SAM text (which the device tier does not frame) and any
    decode error both return None, and the host decoder then accepts or
    raises canonically — so device mode never changes the service's
    error surface."""
    from kindel_tpu import devingest

    try:
        if isinstance(payload, (bytes, bytearray)):
            return devingest.extract_events_device(bytes(payload))
        from kindel_tpu.io.stream import sniff_alignment

        path = str(payload)
        if sniff_alignment(path) != "bam":
            return None
        with open(path, "rb") as fh:
            return devingest.extract_events_device(fh.read())
    except ValueError:
        return None  # not BAM / corrupt: the host decoder owns the verdict


def numpy_request_result(req: ServeRequest) -> SampleResult:
    """Last-resort per-request fallback: the whole request recomputed on
    the host numpy oracle, no device involved — the same decode→pileup→
    call path `bam_to_consensus(backend="numpy")` runs. Slow, but a
    wedged accelerator then degrades throughput instead of availability."""
    from kindel_tpu.call import call_consensus
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment, load_alignment_bytes
    from kindel_tpu.io.fasta import Sequence
    from kindel_tpu.pileup import build_pileup
    from kindel_tpu.realign import cdrp_consensuses, merge_cdrps
    from kindel_tpu.workloads import build_report

    opts = req.opts
    payload = req.payload
    if isinstance(payload, (bytes, bytearray)):
        batch = load_alignment_bytes(bytes(payload))
    else:
        batch = load_alignment(str(payload))
    ev = extract_events(batch)
    res = SampleResult()
    for rid in ev.present_ref_ids:
        ref_id = ev.ref_names[rid]
        pileup = build_pileup(ev, rid)
        cdr_patches = None
        if opts.realign:
            cdr_patches = merge_cdrps(
                cdrp_consensuses(
                    pileup,
                    clip_decay_threshold=opts.clip_decay_threshold,
                    mask_ends=opts.mask_ends,
                    max_gap=opts.cdr_gap,
                    flank_dedup=opts.fix_clip_artifacts,
                    min_depth=opts.min_depth,
                ),
                opts.min_overlap,
            )
        out = call_consensus(
            pileup, cdr_patches=cdr_patches, trim_ends=opts.trim_ends,
            min_depth=opts.min_depth, uppercase=opts.uppercase,
            strict_ins=opts.fix_clip_artifacts,
        )
        res.consensuses.append(
            Sequence(name=f"{ref_id}_cns", sequence=out.sequence)
        )
        if opts.build_changes:
            res.refs_changes[ref_id] = out.changes
        if opts.build_reports:
            acgt = pileup.acgt_depth
            dmin = int(acgt.min()) if len(acgt) else 0
            dmax = int(acgt.max()) if len(acgt) else 0
            res.refs_reports[ref_id] = build_report(
                ref_id, dmin, dmax, out.changes, cdr_patches,
                _payload_label(payload), opts.realign, opts.min_depth,
                opts.min_overlap, opts.clip_decay_threshold,
                opts.trim_ends, opts.uppercase,
            )
    return res


def _settle(req: ServeRequest, *, result=None, exc=None) -> bool:
    """Resolve one request's future exactly once. Returns False when it
    was already settled (watchdog raced the dispatcher, or the caller
    cancelled) — the loser of the race records nothing."""
    fut = req.future
    try:
        if not fut.set_running_or_notify_cancel():
            return False
    except (InvalidStateError, RuntimeError):
        # set_running_or_notify_cancel raises a bare RuntimeError (not
        # InvalidStateError) on a FINISHED future — the watchdog or a
        # cancelling caller beat us; the loser records nothing
        return False
    if exc is not None:
        fut.set_exception(exc)
    else:
        fut.set_result(result)
    return True


class ServeWorker:
    """Owns the intake/decode/dispatch machinery for one service."""

    def __init__(self, queue: RequestQueue, batcher: MicroBatcher,
                 metrics=None, decode_workers: int = 4,
                 row_bucket: int = 8, clock=time.monotonic,
                 breaker=None, retry: rpolicy.RetryPolicy | None = None,
                 watchdog_s: float | None = None,
                 numpy_fallback: bool = True, supervise: bool = True,
                 supervise_interval_s: float = 0.1,
                 lane_coalesce: int = 1, ingest_mode: str = "host",
                 mesh_plan=None, journal=None):
        self.queue = queue
        self.batcher = batcher
        self._clock = clock
        #: durable admission journal (kindel_tpu.durable, DESIGN.md
        #: §24), or None. The worker's only journal duty is the
        #: in-flight MARK at each dispatch site — one None check when
        #: off (allocation-free, PR 4 convention)
        self.journal = journal
        #: per-replica device mesh plan (kindel_tpu.parallel.meshexec,
        #: DESIGN.md §23): one flush fans across every local device.
        #: None = single-device dispatch, the exact pre-mesh behavior
        self.mesh_plan = mesh_plan
        #: where request decode's scan/expand run (resolved once by the
        #: service through kindel_tpu.tune): "device" routes payloads
        #: through kindel_tpu.devingest, byte-identically
        self.ingest_mode = ingest_mode
        #: rows pad to this power-of-two bucket so repeat flushes of a
        #: lane reuse one compiled kernel shape even as occupancy varies
        self.row_bucket = row_bucket
        #: fat dispatch: up to this many ready flushes of one lane merge
        #: into a single device launch (kindel_tpu.tune resolves the
        #: knob; 1 = off). Rows are independent under vmap, so merged
        #: output is byte-identical — the launch just pays pack + upload
        #: + dispatch once instead of per flush.
        self.lane_coalesce = max(1, int(lane_coalesce))
        #: resilience wiring (DESIGN.md §13): dispatch retry policy,
        #: device circuit breaker fed flush outcomes, hung-flush watchdog
        #: deadline, and the last-resort host fallback switch
        self.breaker = breaker
        self.retry = retry if retry is not None else rpolicy.RetryPolicy()
        self.watchdog_s = watchdog_s
        self.numpy_fallback = numpy_fallback
        self.supervise = supervise
        self.supervise_interval_s = supervise_interval_s
        self._decode_pool = ThreadPoolExecutor(
            max_workers=decode_workers,
            thread_name_prefix="kindel-serve-decode",
        )
        self._assemble_pool = ThreadPoolExecutor(
            max_workers=decode_workers,
            thread_name_prefix="kindel-serve-assemble",
        )
        #: paged-mode launch executor (lazy — only --batch-mode paged
        #: creates it): each launch tick runs on its own slot so a
        #: stalled or slow launch never blocks the next tick, which is
        #: the paged tier's straggler-isolation property
        self._paged_pool: ThreadPoolExecutor | None = None
        self._paged_pool_lock = threading.Lock()
        self._intake_thread: threading.Thread | None = None
        self._dispatch_thread: threading.Thread | None = None
        self._supervisor_thread: threading.Thread | None = None
        self._stop_event = threading.Event()
        self._draining = False
        self._stopped = False
        self._killed = False
        self._flush_seq = 0
        #: in-flight flush registry for the watchdog: key → (deadline,
        #: entries); registered around every device dispatch attempt
        self._inflight: dict[int, tuple] = {}
        self._inflight_lock = threading.Lock()
        self._inflight_seq = 0
        #: lane-shape label chokepoint for the dispatch histogram: under
        #: shape-diverse traffic raw pad-shape labels are unbounded; the
        #: capper admits the first DEFAULT_LABEL_CAP distinct shapes and
        #: collapses the tail into "other" (ragged page classes are
        #: bounded by construction and pass through)
        from kindel_tpu.obs.metrics import LabelCapper

        self._shape_labels = LabelCapper()
        if metrics is not None:
            self._m_requests = metrics.counter(
                "kindel_serve_requests_total", "requests accepted"
            )
            self._m_failed = metrics.counter(
                "kindel_serve_requests_failed_total",
                "requests completed with an error",
            )
            self._m_dispatches = metrics.counter(
                "kindel_serve_device_dispatches_total",
                "batched device programs launched",
            )
            self._m_batch_retries = metrics.counter(
                "kindel_serve_batch_isolation_retries_total",
                "flushes re-run split or one request at a time after a "
                "batch failure",
            )
            self._m_occupancy = metrics.histogram(
                "kindel_serve_batch_occupancy",
                "requests coalesced per device dispatch",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            )
            self._m_latency = metrics.histogram(
                "kindel_serve_request_latency_seconds",
                "enqueue-to-complete request latency",
            )
            self._m_pending_rows = metrics.gauge(
                "kindel_serve_batcher_pending_rows",
                "decoded rows waiting to coalesce",
            )
            self._m_outcomes = metrics.counter(
                "kindel_serve_requests_outcome_total",
                "completed requests by outcome label (ok/error)",
            )
            self._m_dispatch_s = metrics.histogram(
                "kindel_serve_dispatch_seconds",
                "wall time of one batched dispatch (pack + launch + "
                "assemble), labeled by coalescing-lane shape",
            )
            self._m_watchdog = metrics.counter(
                "kindel_serve_flush_watchdog_total",
                "hung flushes timed out by the watchdog (only the "
                "affected requests fail)",
            )
            self._m_restarts = metrics.counter(
                "kindel_serve_worker_restarts_total",
                "worker loop threads auto-restarted by the supervisor",
            )
            self._m_fallbacks = metrics.counter(
                "kindel_serve_numpy_fallback_total",
                "requests served by the per-request numpy fallback after "
                "the device dispatch failed",
            )
        else:
            self._m_requests = self._m_failed = self._m_dispatches = None
            self._m_batch_retries = None
            self._m_occupancy = self._m_latency = self._m_pending_rows = None
            self._m_outcomes = self._m_dispatch_s = None
            self._m_watchdog = self._m_restarts = self._m_fallbacks = None

    # ------------------------------------------------------------ lifecycle

    def _start_loop(self, which: str) -> None:
        if which == "intake":
            t = threading.Thread(
                target=self._intake_loop, name="kindel-serve-intake",
                daemon=True,
            )
            self._intake_thread = t
        else:
            t = threading.Thread(
                target=self._dispatch_loop, name="kindel-serve-dispatch",
                daemon=True,
            )
            self._dispatch_thread = t
        t.start()

    def start(self) -> "ServeWorker":
        # pre-register the fat-dispatch counters so the /metrics series
        # exist (at 0) from boot, not from the first merge
        _coalesce_counters()
        # pre-size the shared inflate pool (resolved here, not in
        # __init__ — env pins exported before start must win) so the
        # first request's decode never pays pool construction
        from kindel_tpu import tune
        from kindel_tpu.io import inflate

        workers, _src = tune.resolve_ingest_workers()
        if workers > 1:
            inflate.shared_pool(workers)
        self._start_loop("intake")
        self._start_loop("dispatch")
        if self.supervise:
            self._supervisor_thread = threading.Thread(
                target=self._supervise_loop, name="kindel-serve-supervisor",
                daemon=True,
            )
            self._supervisor_thread.start()
        return self

    @property
    def alive(self) -> bool:
        """Can this worker still make progress? False once stopped or
        killed, or when a loop thread died with no supervisor to
        resurrect it — the fleet probe's liveness signal."""
        if self._stopped or self._killed:
            return False
        t_i, t_d = self._intake_thread, self._dispatch_thread
        if t_i is None or t_d is None:
            return False  # never started
        if (
            self.supervise
            and self._supervisor_thread is not None
            and self._supervisor_thread.is_alive()
        ):
            return True  # a dead loop will be resurrected
        return t_i.is_alive() and t_d.is_alive()

    def kill(self) -> None:
        """Chaos surface: emulate abrupt replica death (a SIGKILLed
        process). Every loop stops at its next iteration WITHOUT
        resolving admitted futures — queued and batched requests are
        simply abandoned, exactly what a killed process leaves behind
        and exactly what the fleet supervisor (kindel_tpu.fleet) must
        detect, evict, and replay onto survivors. Never part of any
        graceful path; stop()/drain() settle every future instead."""
        self._killed = True
        self._stopped = True
        self._stop_event.set()
        self.queue.close()  # leftovers dropped UNRESOLVED — fleet replays
        self.batcher.close()

    def reap(self) -> None:
        """Post-eviction cleanup of a killed worker: shut the host
        thread pools down without waiting (running decodes finish and
        lose their settle races harmlessly). Called by the fleet
        supervisor after replay, never on a live worker."""
        self._decode_pool.shutdown(wait=False)
        self._assemble_pool.shutdown(wait=False)
        with self._paged_pool_lock:
            paged_pool = self._paged_pool
        if paged_pool is not None:
            paged_pool.shutdown(wait=False)

    def stop(self, drain: bool = True) -> None:
        """Shut down. drain=True serves everything already admitted;
        drain=False fails pending requests with RuntimeError."""
        if self._stopped:
            return
        self._stopped = True
        # the supervisor must stand down before the joins below, or it
        # could resurrect a loop the shutdown is waiting on
        self._stop_event.set()
        if self._supervisor_thread is not None:
            self._supervisor_thread.join()
        if not drain:
            for req in self.queue.close():
                _fail(req, RuntimeError("service stopped"))
        self._draining = True
        if self._intake_thread is not None:
            self._intake_thread.join()
        # everything popped from the queue is now in the decode pool;
        # wait for those to land in the batcher (or fail their futures)
        self._decode_pool.shutdown(wait=True)
        if drain:
            for req in self.queue.close():  # raced past the intake exit
                _fail(req, RuntimeError("service stopped mid-drain"))
        self.batcher.close()
        if self._dispatch_thread is not None:
            self._dispatch_thread.join()
        with self._paged_pool_lock:
            paged_pool = self._paged_pool
        if paged_pool is not None:
            # in-flight launch ticks finish (and settle their futures)
            # before the assemble pool they extract on goes away
            paged_pool.shutdown(wait=True)
        self._assemble_pool.shutdown(wait=True)

    # ----------------------------------------------------------- supervisor

    def _supervise_loop(self) -> None:
        """Self-healing: restart a dead intake/dispatch loop (a crashed
        or fault-killed thread must not wedge the queue) and fail the
        futures of watchdog-overdue flushes."""
        while not self._stop_event.wait(self.supervise_interval_s):
            if self._stopped or self._draining:
                return
            for which, t in (
                ("intake", self._intake_thread),
                ("dispatch", self._dispatch_thread),
            ):
                if t is not None and not t.is_alive():
                    if self._m_restarts is not None:
                        self._m_restarts.labels(loop=which).inc()
                    sp = trace.span("serve.worker_restart")
                    with sp:
                        if sp is not trace.NOOP_SPAN:
                            sp.set_attribute(loop=which)
                    self._start_loop(which)
            self._check_watchdog()

    def _check_watchdog(self) -> None:
        """Fail the futures of flushes past their deadline. The hung
        dispatch thread itself cannot be unblocked — but its requests
        resolve NOW with a typed FlushTimeout, and when (if) the thread
        eventually finishes, _settle loses the race quietly."""
        if self.watchdog_s is None:
            return
        now = time.perf_counter()
        with self._inflight_lock:
            overdue = [
                (key, entries)
                for key, (deadline, entries) in self._inflight.items()
                if now >= deadline
            ]
            for key, _entries in overdue:
                del self._inflight[key]
        for _key, entries in overdue:
            if self._m_watchdog is not None:
                self._m_watchdog.inc()
            if self.breaker is not None:
                self.breaker.record_failure()
            for req, _units in entries:
                self._fail(
                    req,
                    FlushTimeout(
                        f"flush exceeded the {self.watchdog_s}s watchdog "
                        "deadline (device dispatch hung)"
                    ),
                )

    def _watch(self, entries):
        """Register `entries` with the watchdog for the duration of one
        dispatch attempt; returns the registry key (None when off)."""
        if self.watchdog_s is None:
            return None
        with self._inflight_lock:
            self._inflight_seq += 1
            key = self._inflight_seq
            self._inflight[key] = (
                time.perf_counter() + self.watchdog_s, entries
            )
        return key

    def _unwatch(self, key) -> None:
        if key is None:
            return
        with self._inflight_lock:
            self._inflight.pop(key, None)

    # --------------------------------------------------------------- intake

    def _intake_loop(self) -> None:
        while True:
            if self._killed:
                return  # abrupt death: abandon, do not settle
            rfaults.hook("serve.worker")
            req = self.queue.get(timeout=0.05)
            if req is None:
                if self._killed or (
                    self._draining and self.queue.depth == 0
                ):
                    return
                continue
            if self._m_requests is not None:
                self._m_requests.inc()
            self._decode_pool.submit(self._decode_one, req)

    def _decode_one(self, req: ServeRequest) -> None:
        sp = trace.span("serve.decode", parent=req.span)
        traced = sp is not trace.NOOP_SPAN
        with sp:
            try:
                units = decode_request(req, ingest_mode=self.ingest_mode)
            except BaseException as e:  # noqa: BLE001 — isolation boundary
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    # shutdown is not a per-request failure: resolve the
                    # future with a shutdown error and let the interrupt
                    # propagate to the executor
                    self._fail(
                        req,
                        RuntimeError(
                            f"service interrupted ({type(e).__name__})"
                        ),
                    )
                    raise
                if traced:
                    sp.set_attribute(outcome="error", error=repr(e))
                self._fail(req, e)
                return
            if traced:
                sp.set_attribute(units=len(units))
        if not units:
            # no aligned reads: a legitimate empty result, same as
            # bam_to_consensus on a read-less file
            self._complete(req, SampleResult())
            return
        if req.suspect:
            # quarantine suspect (DESIGN.md §24): this entry was in
            # flight when a previous process life crashed. Dispatch it
            # ISOLATED — a flush of one, bypassing every batcher — so
            # if it crashes again it takes no co-batched survivors
            # with it (the §13 bisection, applied preemptively).
            self._solo_dispatch(req, units)
            return
        self.batcher.add(req, units)
        if self._m_pending_rows is not None:
            self._m_pending_rows.set(self.batcher.pending_rows)

    def _solo_dispatch(self, req: ServeRequest, units) -> None:
        """One-request dispatch for quarantine suspects, on the decode
        thread (a suspect may crash the process — it must never share a
        launching tick). The classic shape-derived path: byte-identical
        to any batched mode by vmap-row independence."""
        shapes = cohort_pad_shapes(units, req.opts)
        flush = Flush(req.opts, shapes, [(req, units)], self._clock())
        self._flush_seq += 1
        try:
            self._dispatch_entries(
                flush.entries, flush, self._flush_seq, flush.shapes,
                depth=0,
            )
        except BaseException as e:  # noqa: BLE001 — decode-pool isolation boundary
            self._fail(
                req, RuntimeError(f"suspect dispatch aborted: {e!r}")
            )
            raise

    # ------------------------------------------------------------- dispatch

    def _dispatch_loop(self) -> None:
        while True:
            if self._killed:
                return  # abrupt death: abandon, do not settle
            rfaults.hook("serve.worker")
            flush = self.batcher.poll(timeout=0.25)
            if self._killed:
                return  # a flush popped mid-kill stays unresolved
            if flush is None:
                # poll yields None on a timeout OR once the batcher is
                # closed and drained — only the latter ends the loop
                # (decode threads may still be filling lanes mid-drain)
                if self.batcher.closed and self.batcher.pending_rows == 0:
                    return
                continue
            from kindel_tpu.paged.batcher import PagedFlush

            if isinstance(flush, PagedFlush):
                # continuous path: the tick's launch + extraction run
                # on the paged executor, never on this loop — the loop
                # immediately polls for the next tick
                self._paged_dispatch(flush)
                continue
            flush = self._coalesce(flush)
            try:
                self._execute(flush)
            except BaseException as e:  # noqa: BLE001
                # the loop must never die holding unresolved futures:
                # settle what remains, then re-raise so the thread dies
                # visibly and the supervisor restarts it
                for req, _units in flush.entries:
                    self._fail(
                        req, RuntimeError(f"serve dispatch aborted: {e!r}")
                    )
                raise
            if self._m_pending_rows is not None:
                self._m_pending_rows.set(self.batcher.pending_rows)

    def _coalesce(self, flush: Flush) -> Flush:
        """Fat dispatch: merge compatible ready flushes into this one
        (entries concatenate; row padding re-buckets at pack time).
        Byte-identity with per-flush launches is pinned by tests — vmap
        rows are independent and lane shapes are shared by construction."""
        if self.lane_coalesce <= 1:
            return flush
        extra = self.batcher.take_ready(flush, self.lane_coalesce - 1)
        if not extra:
            return flush
        entries = list(flush.entries)
        for f in extra:
            entries.extend(f.entries)
        merged = Flush(
            flush.opts, flush.shapes, entries,
            min(f.opened_at for f in (flush, *extra)),
            coalesced=len(extra),
        )
        c_flushes, c_launches = _coalesce_counters()
        c_flushes.inc(len(extra))
        c_launches.inc()
        return merged

    def _execute(self, flush: Flush) -> None:
        self._flush_seq += 1
        self._dispatch_entries(
            flush.entries, flush, self._flush_seq, flush.shapes, depth=0
        )

    # ------------------------------------------------- paged (continuous)

    def _paged_executor(self) -> ThreadPoolExecutor:
        with self._paged_pool_lock:
            if self._paged_pool is None:
                self._paged_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="kindel-serve-paged",
                )
            return self._paged_pool

    def _paged_dispatch(self, flush) -> None:
        """Hand one launch tick to the paged executor (DESIGN.md §20):
        the dispatch loop never blocks on a launch, so a straggler tick
        stalls only its own requests while later ticks launch and
        retire around it."""
        self._flush_seq += 1
        self._paged_executor().submit(
            self._paged_execute, flush, self._flush_seq
        )

    def _paged_execute(self, flush, flush_id: int) -> None:
        """One tick end to end: snapshot → launch → extract → settle →
        retire. Failures release the tick's page references and walk
        the requests down the classic §13 ladder (retry already
        exhausted here), so no admitted future is lost and no pages
        leak."""
        entries = flush.entries
        t0 = time.perf_counter()
        wkey = self._watch(entries)
        try:
            results = self.retry.run(
                "serve.flush", lambda: self._paged_run(flush)
            )
        except BaseException as e:  # noqa: BLE001 — isolation boundary
            self._unwatch(wkey)
            try:
                self.batcher.release_flush(flush)
            except Exception:  # noqa: BLE001 — pages may leak; the
                # futures below still settle through the ladder
                rpolicy.record_degrade("serve.flush", "release_failed", 1)
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                for req, _units in entries:
                    self._fail(
                        req,
                        RuntimeError(
                            f"service interrupted ({type(e).__name__})"
                        ),
                    )
                raise
            try:
                self._recover(entries, flush, flush_id, 0, e)
            except BaseException as e2:  # noqa: BLE001
                # the executor swallows raises — settle what remains so
                # no admitted future dies with the tick
                for req, _units in entries:
                    self._fail(
                        req, RuntimeError(f"paged recovery aborted: {e2!r}")
                    )
                raise
            return
        self._unwatch(wkey)
        if self.breaker is not None:
            self.breaker.record_success()
        t1 = time.perf_counter()
        if self._m_dispatches is not None:
            self._m_dispatches.inc()
            self._m_occupancy.observe(len(entries))
            self._m_dispatch_s.labels(
                shape=f"paged:{flush.page_class.name}"
            ).observe(t1 - t0)
        for req, result in results:
            self._complete(req, result)
        # retire AFTER settle: a segment's read completes when its
        # request has its bytes — the admit→retire histogram then bounds
        # end-to-end residency, not just device wall
        self.batcher.retire_flush(flush)
        if self._m_pending_rows is not None:
            self._m_pending_rows.set(self.batcher.pending_rows)

    def _paged_run(self, flush):
        """Launch + extract one tick (retried as a unit by the §13
        retry policy; references release only on the final outcome, so
        a retry re-reads a consistent resident set). The launch itself
        runs through the batcher (PagedBatcher.dispatch_tick): zero
        per-tick upload over the persistent donated arrays when device
        residency is active, classic snapshot+re-upload otherwise."""
        from kindel_tpu.paged.retire import extract_flush

        # in-flight marker BEFORE the fault hook: a crash fired at this
        # site must already be attributable to the tick's member keys
        mark_if_active(self.journal, flush.entries)
        if rfaults.active_plan() is None:
            rfaults.hook("serve.flush")
        else:
            rfaults.hook("serve.flush", _flush_note(flush.entries))
        cls = flush.page_class
        with trace.span("paged.launch") as sp:
            out, table, row_of = self.batcher.dispatch_tick(flush)
            if sp is not trace.NOOP_SPAN:
                delta = getattr(flush.lane.pool, "residency", None)
                sp.set_attribute(
                    page_class=cls.label(), resident=table.n_segments,
                    tick_entries=len(flush.entries),
                    delta_resident=bool(delta is not None and delta.active),
                )
        payload, padded = _padding_counters()
        payload.inc(sum(u.L for _r, units in flush.entries for u in units))
        # paged occupancy denominator = the pages the tick's segments
        # actually hold (free pages serve other traffic — that is the
        # point of paging), unlike ragged's whole-grid denominator
        from kindel_tpu.paged.state import PAGE_SLOTS

        padded.inc(sum(
            seg.n_pages * PAGE_SLOTS
            for _req, segs in flush.bindings
            for seg, _u in segs
        ))
        return extract_flush(out, table, row_of, flush, flush.opts)

    def _dispatch_entries(self, entries, flush: Flush, flush_id: int,
                          shapes, depth: int) -> None:
        """Dispatch one (possibly split) entry set: retry transients,
        then hand failures to _recover. Every request in `entries` is
        settled by the time this returns."""
        t0 = time.perf_counter()
        launch_window: dict = {}
        # the superbatch geometry rides only the FIRST (whole-flush)
        # attempt: recovery re-dispatches (shapes=None) run the classic
        # shape-derived path, which the degrade ladder already knows how
        # to bisect/isolate — byte-identical either way
        page_class = (
            getattr(flush, "page_class", None) if shapes is not None
            else None
        )
        wkey = self._watch(entries)
        try:
            with maybe_phase("serve dispatch+assemble"):
                outputs, units = self.retry.run(
                    "serve.flush",
                    lambda: self._run_entries(
                        entries, flush.opts, shapes, launch_window,
                        page_class,
                    ),
                )
        except Exception as e:
            self._unwatch(wkey)
            self._recover(entries, flush, flush_id, depth, e)
            return
        self._unwatch(wkey)
        if self.breaker is not None:
            self.breaker.record_success()
        t1 = time.perf_counter()
        if self._m_dispatches is not None:
            self._m_dispatches.inc()
            self._m_occupancy.observe(len(entries))
            # page-class labels are bounded by construction; lane-shape
            # labels go through the cardinality chokepoint
            label = (
                f"ragged:{page_class.name}" if page_class is not None
                else self._shape_labels.see(_shape_label(flush.shapes))
            )
            self._m_dispatch_s.labels(shape=label).observe(t1 - t0)
        self._record_flush_spans(
            entries, flush, flush_id, t0, t1, launch_window,
            occupancy=len(entries), isolated=depth > 0,
        )
        self._complete_entries(entries, units, outputs, flush.opts)

    def _recover(self, entries, flush: Flush, flush_id: int, depth: int,
                 exc: BaseException) -> None:
        """Degrade ladder for a failed dispatch (retry already
        exhausted): bisect on OOM, isolate per-request otherwise, numpy
        fallback at the singleton — every future resolves."""
        transient = rpolicy.is_transient(exc)
        if self.breaker is not None and transient:
            # only device-level failures feed the breaker: one request's
            # corrupt input is its own problem, not the device's
            self.breaker.record_failure()
        if len(entries) > 1 and depth < 6:
            if self._m_batch_retries is not None:
                self._m_batch_retries.inc()
            if rpolicy.is_oom(exc):
                # the batch's padded footprint no longer fits: halves
                # re-derive their own (smaller) pad shapes
                rpolicy.record_degrade("serve.flush", "bisect", depth + 1)
                mid = len(entries) // 2
                parts = [entries[:mid], entries[mid:]]
            else:
                # batch-level failure of unknown blame: one request at a
                # time, so only the culpable request(s) fail
                parts = [[e] for e in entries]
            for part in parts:
                self._dispatch_entries(
                    part, flush, flush_id, None, depth + 1
                )
            return
        req, _units = entries[0]
        if self.numpy_fallback and transient:
            rpolicy.record_degrade(
                "serve.flush", "numpy_fallback", depth + 1
            )
            if self._m_fallbacks is not None:
                self._m_fallbacks.inc()
            try:
                result = numpy_request_result(req)
            except Exception as fe:  # fallback failed too
                fe.__cause__ = exc
                self._fail(req, fe)
                return
            self._complete(req, result)
            return
        self._fail(req, exc)

    def _record_flush_spans(self, entries, flush, flush_id, t0, t1,
                            launch_window, occupancy,
                            isolated: bool = False) -> None:
        """Record the shared flush as a `serve.batch_dispatch` +
        `serve.device_launch` pair in EVERY member request's span tree —
        the shared micro-batch launch is part of each request's story,
        so each tree carries a copy stamped with the common flush_id."""
        if trace.active_tracer() is None:
            return
        shape = _shape_label(flush.shapes)
        for req, _req_units in entries:
            dsp = trace.record_span(
                "serve.batch_dispatch", req.span, t0, t1,
                flush_id=flush_id, occupancy=occupancy,
                rows=flush.n_rows, lane_shape=shape, isolated=isolated,
                coalesced=flush.coalesced,
            )
            trace.record_span(
                "serve.device_launch", dsp,
                launch_window.get("t0", t0), launch_window.get("t1", t1),
                flush_id=flush_id, lane_shape=shape,
                compiled_new=launch_window.get("compiled_new", 0),
                h2d_bytes=launch_window.get("h2d_bytes", 0),
            )

    def _run_entries(self, entries, opts, shapes, launch_window=None,
                     page_class=None):
        """Pack + launch + assemble one coalesced batch. Returns
        (per-unit outputs, flat unit list in row order); `launch_window`
        (when given) receives the pack+launch interval, the jit
        cache-entry delta, and the upload byte count for the dispatch
        span. With `page_class` set (a RaggedFlush's first attempt) the
        batch packs into that class's fixed-geometry superbatch and runs
        the segment kernel (kindel_tpu.ragged) — byte-identical output,
        one compiled executable per page class instead of one per lane
        shape."""
        # in-flight marker BEFORE the fault hook: a crash fired at this
        # site must already be attributable to the batch's member keys
        mark_if_active(self.journal, entries)
        if rfaults.active_plan() is None:
            rfaults.hook("serve.flush")
        else:
            rfaults.hook("serve.flush", _flush_note(entries))
        units = []
        paths = []
        for idx, (req, req_units) in enumerate(entries):
            for u in req_units:
                u.sample_idx = idx
                units.append(u)
            paths.append(_payload_label(req.payload))
        probing = launch_window is not None and trace.active_tracer() is not None
        if probing:
            cache_before = obs_runtime.jit_cache_entries()
            launch_window["t0"] = time.perf_counter()
        plan = self.mesh_plan
        if page_class is not None:
            from kindel_tpu.ragged import build_segment_table, pack_superbatch
            from kindel_tpu.ragged.kernel import launch_ragged
            from kindel_tpu.ragged.unpack import unpack_superbatch

            # mesh-sharded superbatch (DESIGN.md §23): the flush splits
            # into dp page-aligned sub-superbatches launched as ONE
            # vmapped program over the dp axis — byte-identical FASTA;
            # a flush that does not shard (one unit, shard overflow)
            # falls through to the classic single-device launch
            ssb = None
            if plan is not None and plan.active:
                from kindel_tpu.parallel import meshexec

                ssb = meshexec.shard_superbatch(
                    units, page_class, plan, realign=opts.realign
                )
            if ssb is not None:
                out = meshexec.launch_sharded_superbatch(ssb, opts)
                if probing:
                    launch_window["t1"] = time.perf_counter()
                    launch_window["compiled_new"] = (
                        obs_runtime.jit_cache_entries() - cache_before
                    )
                payload_slots = ssb.payload_slots
                occupancy = ssb.occupancy
                n_segments = ssb.n_segments
            else:
                table = build_segment_table(units, page_class)
                arrays = pack_superbatch(units, table, realign=opts.realign)
                out = launch_ragged(arrays, page_class, opts)
                if probing:
                    launch_window["t1"] = time.perf_counter()
                    launch_window["compiled_new"] = (
                        obs_runtime.jit_cache_entries() - cache_before
                    )
                    launch_window["h2d_bytes"] = sum(
                        a.nbytes for a in arrays
                    )
                payload_slots = table.payload_slots
                occupancy = table.occupancy
                n_segments = table.n_segments
            payload, padded = _padding_counters()
            payload.inc(payload_slots)
            padded.inc(page_class.n_slots)
            m_occ, m_segs, m_super = _ragged_metrics()
            m_occ.observe(occupancy)
            m_segs.observe(n_segments)
            m_super.labels(page_class=page_class.name).inc()
            if ssb is not None:
                outputs = meshexec.unpack_sharded_superbatch(
                    out, ssb, opts, self._assemble_pool, paths
                )
            else:
                outputs = unpack_superbatch(
                    out, table, units, opts, self._assemble_pool, paths
                )
            return outputs, units
        n_rows = _bucket(len(units), self.row_bucket)
        sharding, mesh_dp = None, 1
        if plan is not None and plan.active:
            n_rows = plan.pad_rows(n_rows)
            sharding, mesh_dp = plan.row_sharding_for(n_rows)
        arrays, meta = pack_cohort(units, opts, n_rows=n_rows, shapes=shapes)
        device_out = launch_cohort_kernel(
            arrays, meta, opts, sharding=sharding, mesh_dp=mesh_dp
        )
        if probing:
            launch_window["t1"] = time.perf_counter()
            launch_window["compiled_new"] = (
                obs_runtime.jit_cache_entries() - cache_before
            )
            launch_window["h2d_bytes"] = sum(a.nbytes for a in arrays)
        payload, padded = _padding_counters()
        payload.inc(sum(u.L for u in units))
        padded.inc(int(arrays[0].shape[0]) * int(meta[0]))
        outputs = _assemble_outputs(
            units, device_out, opts, self._assemble_pool, paths
        )
        return outputs, units

    def _complete_entries(self, entries, units, outputs, opts) -> None:
        grouped = _fold_results(units, outputs, len(entries))
        for idx, (req, _req_units) in enumerate(entries):
            self._complete(req, grouped[idx])

    def _complete(self, req: ServeRequest, result: SampleResult) -> None:
        latency = self._clock() - req.enqueued_at
        if not _settle(req, result=result):
            return  # cancelled while queued, or the watchdog beat us
        if self._m_latency is not None:
            self._m_latency.observe(latency)
            self._m_outcomes.labels(outcome="ok").inc()
        self.queue.observe_service_time(latency)
        sp = req.span
        if sp is not None and sp is not trace.NOOP_SPAN:
            sp.set_attribute(outcome="ok", latency_s=round(latency, 6))
            sp.finish()

    def _fail(self, req: ServeRequest, exc: BaseException) -> None:
        """Fail one request's future, counting and closing its trace."""
        if not _fail(req, exc):
            return  # already settled — count nothing twice
        if self._m_failed is not None:
            self._m_failed.inc()
            self._m_outcomes.labels(outcome="error").inc()


def _fail(req: ServeRequest, exc: BaseException) -> bool:
    if not _settle(req, exc=exc):
        return False
    sp = req.span
    if sp is not None and sp is not trace.NOOP_SPAN:
        sp.set_attribute(outcome="error", error=repr(exc))
        sp.finish()
    return True
