"""Serve executor: decode on host threads, one device dispatch per flush.

Three stages, mirroring the offline cohort pipeline's overlap structure
(kindel_tpu.batch.stream_bam_to_results) but driven by arrival instead
of by a file list:

  intake    one thread pops admitted requests off the RequestQueue and
            fans decode/event-extraction out to a host thread pool
  decode    per-request: payload → ReadBatch → EventSet → CallUnits,
            then into the micro-batcher. A malformed payload fails ONLY
            its own future here — the batch a request would have joined
            never sees it.
  dispatch  one thread drives MicroBatcher.poll; each flush packs into
            the lane's pinned pad shapes (kindel_tpu.batch.pack_cohort),
            launches ONE batched device program, assembles every
            request's FASTA on the host pool, and completes futures.

Dispatch-stage failures are isolated by re-running the flush one request
at a time, so a request that only breaks in the batched path still fails
alone while its batch-mates complete.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from kindel_tpu.batch import (
    SampleResult,
    _assemble_outputs,
    _fold_results,
    launch_cohort_kernel,
    pack_cohort,
)
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs import trace
from kindel_tpu.pileup_jax import _bucket
from kindel_tpu.utils.profiling import maybe_phase

from kindel_tpu.serve.batcher import Flush, MicroBatcher
from kindel_tpu.serve.queue import RequestQueue, ServeRequest


def _payload_label(payload) -> str:
    return "<bytes>" if isinstance(payload, (bytes, bytearray)) else str(
        payload
    )


def _shape_label(shapes: tuple) -> str:
    """Lane pad shapes as one metric-label-safe token ("1024x64x...")."""
    return "x".join(str(s) for s in shapes)


def decode_request(req: ServeRequest) -> list:
    """Host stage: payload → CallUnits (empty list = no aligned reads)."""
    from kindel_tpu.call_jax import CallUnit
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment, load_alignment_bytes

    payload = req.payload
    with maybe_phase("serve decode"):
        if isinstance(payload, (bytes, bytearray)):
            batch = load_alignment_bytes(bytes(payload))
        else:
            batch = load_alignment(str(payload))
        ev = extract_events(batch)
    units = []
    for rid in ev.present_ref_ids:
        u = CallUnit(ev, rid, with_ins_table=True, realign=req.opts.realign)
        units.append(u)
    return units


class ServeWorker:
    """Owns the intake/decode/dispatch machinery for one service."""

    def __init__(self, queue: RequestQueue, batcher: MicroBatcher,
                 metrics=None, decode_workers: int = 4,
                 row_bucket: int = 8, clock=time.monotonic):
        self.queue = queue
        self.batcher = batcher
        self._clock = clock
        #: rows pad to this power-of-two bucket so repeat flushes of a
        #: lane reuse one compiled kernel shape even as occupancy varies
        self.row_bucket = row_bucket
        self._decode_pool = ThreadPoolExecutor(
            max_workers=decode_workers,
            thread_name_prefix="kindel-serve-decode",
        )
        self._assemble_pool = ThreadPoolExecutor(
            max_workers=decode_workers,
            thread_name_prefix="kindel-serve-assemble",
        )
        self._intake_thread: threading.Thread | None = None
        self._dispatch_thread: threading.Thread | None = None
        self._draining = False
        self._stopped = False
        self._flush_seq = 0
        if metrics is not None:
            self._m_requests = metrics.counter(
                "kindel_serve_requests_total", "requests accepted"
            )
            self._m_failed = metrics.counter(
                "kindel_serve_requests_failed_total",
                "requests completed with an error",
            )
            self._m_dispatches = metrics.counter(
                "kindel_serve_device_dispatches_total",
                "batched device programs launched",
            )
            self._m_batch_retries = metrics.counter(
                "kindel_serve_batch_isolation_retries_total",
                "flushes re-run one request at a time after a batch failure",
            )
            self._m_occupancy = metrics.histogram(
                "kindel_serve_batch_occupancy",
                "requests coalesced per device dispatch",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            )
            self._m_latency = metrics.histogram(
                "kindel_serve_request_latency_seconds",
                "enqueue-to-complete request latency",
            )
            self._m_pending_rows = metrics.gauge(
                "kindel_serve_batcher_pending_rows",
                "decoded rows waiting to coalesce",
            )
            self._m_outcomes = metrics.counter(
                "kindel_serve_requests_outcome_total",
                "completed requests by outcome label (ok/error)",
            )
            self._m_dispatch_s = metrics.histogram(
                "kindel_serve_dispatch_seconds",
                "wall time of one batched dispatch (pack + launch + "
                "assemble), labeled by coalescing-lane shape",
            )
        else:
            self._m_requests = self._m_failed = self._m_dispatches = None
            self._m_batch_retries = None
            self._m_occupancy = self._m_latency = self._m_pending_rows = None
            self._m_outcomes = self._m_dispatch_s = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ServeWorker":
        self._intake_thread = threading.Thread(
            target=self._intake_loop, name="kindel-serve-intake", daemon=True
        )
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="kindel-serve-dispatch",
            daemon=True,
        )
        self._intake_thread.start()
        self._dispatch_thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Shut down. drain=True serves everything already admitted;
        drain=False fails pending requests with RuntimeError."""
        if self._stopped:
            return
        self._stopped = True
        if not drain:
            for req in self.queue.close():
                _fail(req, RuntimeError("service stopped"))
        self._draining = True
        if self._intake_thread is not None:
            self._intake_thread.join()
        # everything popped from the queue is now in the decode pool;
        # wait for those to land in the batcher (or fail their futures)
        self._decode_pool.shutdown(wait=True)
        if drain:
            for req in self.queue.close():  # raced past the intake exit
                _fail(req, RuntimeError("service stopped mid-drain"))
        self.batcher.close()
        if self._dispatch_thread is not None:
            self._dispatch_thread.join()
        self._assemble_pool.shutdown(wait=True)

    # --------------------------------------------------------------- intake

    def _intake_loop(self) -> None:
        while True:
            req = self.queue.get(timeout=0.05)
            if req is None:
                if self._draining and self.queue.depth == 0:
                    return
                continue
            if self._m_requests is not None:
                self._m_requests.inc()
            self._decode_pool.submit(self._decode_one, req)

    def _decode_one(self, req: ServeRequest) -> None:
        sp = trace.span("serve.decode", parent=req.span)
        traced = sp is not trace.NOOP_SPAN
        with sp:
            try:
                units = decode_request(req)
            except BaseException as e:  # noqa: BLE001 — isolation boundary
                if traced:
                    sp.set_attribute(outcome="error", error=repr(e))
                self._fail(req, e)
                return
            if traced:
                sp.set_attribute(units=len(units))
        if not units:
            # no aligned reads: a legitimate empty result, same as
            # bam_to_consensus on a read-less file
            self._complete(req, SampleResult())
            return
        self.batcher.add(req, units)
        if self._m_pending_rows is not None:
            self._m_pending_rows.set(self.batcher.pending_rows)

    # ------------------------------------------------------------- dispatch

    def _dispatch_loop(self) -> None:
        while True:
            flush = self.batcher.poll(timeout=0.25)
            if flush is None:
                # poll yields None on a timeout OR once the batcher is
                # closed and drained — only the latter ends the loop
                # (decode threads may still be filling lanes mid-drain)
                if self.batcher.closed and self.batcher.pending_rows == 0:
                    return
                continue
            self._execute(flush)
            if self._m_pending_rows is not None:
                self._m_pending_rows.set(self.batcher.pending_rows)

    def _execute(self, flush: Flush) -> None:
        self._flush_seq += 1
        flush_id = self._flush_seq
        t0 = time.perf_counter()
        launch_window: dict = {}
        try:
            with maybe_phase("serve dispatch+assemble"):
                outputs, units = self._run_entries(
                    flush.entries, flush.opts, flush.shapes, launch_window
                )
        except Exception:
            # batch-level failure: isolate by re-running one request at a
            # time so only the culpable request(s) fail
            if self._m_batch_retries is not None:
                self._m_batch_retries.inc()
            for entry in flush.entries:
                if self._m_dispatches is not None:
                    self._m_dispatches.inc()
                    self._m_occupancy.observe(1)
                e_t0 = time.perf_counter()
                e_launch: dict = {}
                try:
                    outputs, units = self._run_entries(
                        [entry], flush.opts, None, e_launch
                    )
                except BaseException as e:  # noqa: BLE001
                    self._fail(entry[0], e)
                    continue
                self._record_flush_spans(
                    [entry], flush, flush_id, e_t0, time.perf_counter(),
                    e_launch, occupancy=1, isolated=True,
                )
                self._complete_entries([entry], units, outputs, flush.opts)
            return
        t1 = time.perf_counter()
        if self._m_dispatches is not None:
            self._m_dispatches.inc()
            self._m_occupancy.observe(len(flush.entries))
            self._m_dispatch_s.labels(
                shape=_shape_label(flush.shapes)
            ).observe(t1 - t0)
        self._record_flush_spans(
            flush.entries, flush, flush_id, t0, t1, launch_window,
            occupancy=len(flush.entries),
        )
        self._complete_entries(flush.entries, units, outputs, flush.opts)

    def _record_flush_spans(self, entries, flush, flush_id, t0, t1,
                            launch_window, occupancy,
                            isolated: bool = False) -> None:
        """Record the shared flush as a `serve.batch_dispatch` +
        `serve.device_launch` pair in EVERY member request's span tree —
        the shared micro-batch launch is part of each request's story,
        so each tree carries a copy stamped with the common flush_id."""
        if trace.active_tracer() is None:
            return
        shape = _shape_label(flush.shapes)
        for req, _req_units in entries:
            dsp = trace.record_span(
                "serve.batch_dispatch", req.span, t0, t1,
                flush_id=flush_id, occupancy=occupancy,
                rows=flush.n_rows, lane_shape=shape, isolated=isolated,
            )
            trace.record_span(
                "serve.device_launch", dsp,
                launch_window.get("t0", t0), launch_window.get("t1", t1),
                flush_id=flush_id, lane_shape=shape,
                compiled_new=launch_window.get("compiled_new", 0),
                h2d_bytes=launch_window.get("h2d_bytes", 0),
            )

    def _run_entries(self, entries, opts, shapes, launch_window=None):
        """Pack + launch + assemble one coalesced batch. Returns
        (per-unit outputs, flat unit list in row order); `launch_window`
        (when given) receives the pack+launch interval, the jit
        cache-entry delta, and the upload byte count for the dispatch
        span."""
        units = []
        paths = []
        for idx, (req, req_units) in enumerate(entries):
            for u in req_units:
                u.sample_idx = idx
                units.append(u)
            paths.append(_payload_label(req.payload))
        n_rows = _bucket(len(units), self.row_bucket)
        probing = launch_window is not None and trace.active_tracer() is not None
        if probing:
            cache_before = obs_runtime.jit_cache_entries()
            launch_window["t0"] = time.perf_counter()
        arrays, meta = pack_cohort(units, opts, n_rows=n_rows, shapes=shapes)
        device_out = launch_cohort_kernel(arrays, meta, opts)
        if probing:
            launch_window["t1"] = time.perf_counter()
            launch_window["compiled_new"] = (
                obs_runtime.jit_cache_entries() - cache_before
            )
            launch_window["h2d_bytes"] = sum(a.nbytes for a in arrays)
        outputs = _assemble_outputs(
            units, device_out, opts, self._assemble_pool, paths
        )
        return outputs, units

    def _complete_entries(self, entries, units, outputs, opts) -> None:
        grouped = _fold_results(units, outputs, len(entries))
        for idx, (req, _req_units) in enumerate(entries):
            self._complete(req, grouped[idx])

    def _complete(self, req: ServeRequest, result: SampleResult) -> None:
        latency = self._clock() - req.enqueued_at
        if self._m_latency is not None:
            self._m_latency.observe(latency)
            self._m_outcomes.labels(outcome="ok").inc()
        self.queue.observe_service_time(latency)
        sp = req.span
        if sp is not None and sp is not trace.NOOP_SPAN:
            sp.set_attribute(outcome="ok", latency_s=round(latency, 6))
            sp.finish()
        if not req.future.set_running_or_notify_cancel():
            return  # caller cancelled while queued
        req.future.set_result(result)

    def _fail(self, req: ServeRequest, exc: BaseException) -> None:
        """Fail one request's future, counting and closing its trace."""
        if self._m_failed is not None:
            self._m_failed.inc()
            self._m_outcomes.labels(outcome="error").inc()
        _fail(req, exc)


def _fail(req: ServeRequest, exc: BaseException) -> None:
    sp = req.span
    if sp is not None and sp is not trace.NOOP_SPAN:
        sp.set_attribute(outcome="error", error=repr(exc))
        sp.finish()
    if req.future.set_running_or_notify_cancel():
        req.future.set_exception(exc)
