import sys

from kindel_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
