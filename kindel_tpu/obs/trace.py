"""Thread-safe hierarchical span tracer with pluggable exporters.

A *span* is one named, timed piece of work with a parent link, free-form
attributes, and point-in-time events. Spans form trees: a CLI run is one
root (`cli.consensus`) whose children are the decode / extract / call
phases; a serve request is one root (`serve.request`) whose children are
admission, queue wait, decode, and the shared micro-batch dispatch —
every span of a request carries the request's trace id, so one request
renders as a single tree even though its stages execute on four
different threads.

Two propagation modes, because the two callers need different ones:

  * **stacked** (`span(name)`): the common context-manager form. Each
    thread keeps its own span stack; a nested `span()` parents to the
    enclosing one automatically. Used by the phase instrumentation in
    workloads/streaming/batch/pipeline.
  * **detached** (`start_span(name, parent=...)` / `record_span`): the
    caller owns the lifetime and threads the parent explicitly. Used by
    serve, where a request's spans open on one thread and close on
    another (submit thread → intake thread → dispatch thread).

Disabled-tracer overhead is the design constraint (the span sites sit
on hot paths): `span()`/`start_span()` are a single module-global check
returning one shared immutable no-op span — no string formatting, no
allocation beyond the context-manager protocol itself. Pinned by
tests/test_obs.py with tracemalloc.

Exporters: `JsonlExporter` (one JSON object per finished span —
machine-diffable, what the deterministic tests consume) and
`ChromeTraceExporter` (Perfetto/chrome://tracing `trace_event` JSON).
`enable_tracing(path)` picks by suffix: `.json` → Chrome, else JSONL.

Durations come from `time.perf_counter()`; wall-clock anchoring uses a
single `time.time_ns()` offset captured at import (the tier-1 lint
forbids `time.time()` deltas for duration measurement).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

#: perf_counter → epoch-seconds anchor (captured once; durations never
#: touch the wall clock)
_ANCHOR_EPOCH_S = time.time_ns() / 1e9
_ANCHOR_PERF_S = time.perf_counter()


def _epoch_s(perf_t: float) -> float:
    return _ANCHOR_EPOCH_S + (perf_t - _ANCHOR_PERF_S)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One live span. Not created directly — via Tracer/module helpers."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attrs", "events", "thread", "_tracer", "_stacked",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, stacked: bool):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.end: float | None = None
        self.attrs: dict = {}
        self.events: list = []
        self.thread = threading.current_thread().name
        self._tracer = tracer
        self._stacked = stacked

    def set_attribute(self, **attrs) -> None:
        self.attrs.update(attrs)

    def add_event(self, name: str, **attrs) -> None:
        self.events.append((time.perf_counter(), name, attrs))

    def finish(self) -> None:
        """End a detached span (idempotent)."""
        if self.end is None:
            self._tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None and "error" not in self.attrs:
            self.attrs["error"] = repr(exc)
        if self._stacked:
            self._tracer._pop(self)
        self.finish()
        return False


class _NoopSpan:
    """The shared disabled-tracing span: every method a no-op, one
    instance for the whole process (identity-pinned by test — a fresh
    object per call site would be an allocation per span)."""

    __slots__ = ()

    name = None
    trace_id = None
    span_id = None
    parent_id = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set_attribute(self, **attrs):
        pass

    def add_event(self, name, **attrs):
        pass

    def finish(self):
        pass


NOOP_SPAN = _NoopSpan()


# ------------------------------------------------------------- exporters


class JsonlExporter:
    """One JSON object per finished span, written (and flushed) as spans
    finish — a crash loses at most the in-flight spans, and tests read
    the file without a close handshake."""

    def __init__(self, path):
        self.path = str(path)
        self._fh = open(self.path, "w")
        self._lock = threading.Lock()

    def export(self, record: dict) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()


class ChromeTraceExporter:
    """Perfetto / chrome://tracing `trace_event` JSON: complete ("X")
    events buffered in memory, one document written at close (the format
    is a single JSON object, so it cannot stream)."""

    def __init__(self, path):
        self.path = str(path)
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._tids: dict[str, int] = {}

    def _tid(self, thread_name: str) -> int:
        tid = self._tids.get(thread_name)
        if tid is None:
            tid = self._tids[thread_name] = len(self._tids) + 1
        return tid

    def export(self, record: dict) -> None:
        args = dict(record.get("attrs") or {})
        args["trace_id"] = record["trace_id"]
        args["span_id"] = record["span_id"]
        if record.get("parent_id"):
            args["parent_id"] = record["parent_id"]
        with self._lock:
            self._events.append(
                {
                    "name": record["name"],
                    "ph": "X",
                    "ts": round(record["start_s"] * 1e6, 3),
                    "dur": round(record["duration_s"] * 1e6, 3),
                    "pid": self._pid,
                    "tid": self._tid(record.get("thread", "main")),
                    "args": args,
                }
            )
            for ev in record.get("events") or []:
                self._events.append(
                    {
                        "name": ev["name"],
                        "ph": "i",
                        "ts": round(ev["t_s"] * 1e6, 3),
                        "pid": self._pid,
                        "tid": self._tid(record.get("thread", "main")),
                        "s": "t",
                        "args": dict(ev.get("attrs") or {}),
                    }
                )

    def close(self) -> None:
        with self._lock:
            doc = {
                "traceEvents": self._events,
                "displayTimeUnit": "ms",
                "otherData": {"producer": "kindel-tpu obs.trace"},
            }
            with open(self.path, "w") as fh:
                json.dump(doc, fh)
            self._events = []


class ListExporter:
    """In-memory exporter (bench span summaries, tests)."""

    def __init__(self):
        self.records: list[dict] = []
        self._lock = threading.Lock()

    def export(self, record: dict) -> None:
        with self._lock:
            self.records.append(record)

    def close(self) -> None:
        pass


# --------------------------------------------------------------- tracer


class Tracer:
    """Owns per-thread span stacks and one exporter. Thread-safe: spans
    may start and finish on different threads (detached mode); stacked
    spans are per-thread by construction."""

    def __init__(self, exporter):
        self.exporter = exporter
        self._local = threading.local()

    # -- stacks ------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Span | None:
        st = self._stack()
        return st[-1] if st else None

    def _pop(self, sp: Span) -> None:
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        elif sp in st:  # tolerate out-of-order exit; never corrupt others
            st.remove(sp)

    # -- span lifecycle ----------------------------------------------

    def _make(self, name: str, parent, stacked: bool) -> Span:
        if parent is None or parent is NOOP_SPAN or isinstance(
            parent, _NoopSpan
        ):
            ambient = self.current()
            if ambient is not None:
                parent = ambient
            else:
                parent = None
        if parent is None:
            return Span(self, name, _new_id(), None, stacked)
        return Span(self, name, parent.trace_id, parent.span_id, stacked)

    def span(self, name: str, parent=None) -> Span:
        """Context-manager span, parented to the thread's enclosing span
        unless `parent` is given explicitly."""
        sp = self._make(name, parent, stacked=True)
        self._stack().append(sp)
        return sp

    def start_span(self, name: str, parent=None) -> Span:
        """Detached span: the caller finishes it (possibly on another
        thread) via `.finish()` or by using it as a context manager."""
        return self._make(name, parent, stacked=False)

    def record_span(self, name: str, parent, start: float, end: float,
                    **attrs):
        """Record an already-timed interval as a finished span (the
        serve dispatcher times a shared flush once and records it into
        every member request's tree). Returns the finished Span."""
        sp = self._make(name, parent, stacked=False)
        sp.start = start
        sp.attrs.update(attrs)
        sp.end = end
        self.exporter.export(self._record(sp))
        return sp

    def _finish(self, sp: Span) -> None:
        sp.end = time.perf_counter()
        self.exporter.export(self._record(sp))

    @staticmethod
    def _record(sp: Span) -> dict:
        return {
            "name": sp.name,
            "trace_id": sp.trace_id,
            "span_id": sp.span_id,
            "parent_id": sp.parent_id,
            "start_s": round(_epoch_s(sp.start), 6),
            "duration_s": round(sp.end - sp.start, 6),
            "thread": sp.thread,
            "attrs": sp.attrs,
            "events": [
                {
                    "name": name,
                    "t_s": round(_epoch_s(t), 6),
                    "attrs": attrs,
                }
                for t, name, attrs in sp.events
            ],
        }

    def close(self) -> None:
        self.exporter.close()


# ------------------------------------------------------------ module API

_ACTIVE: Tracer | None = None


def active_tracer() -> Tracer | None:
    return _ACTIVE


def open_exporter(path):
    """Exporter for `path` by suffix: `.json` → Chrome trace_event
    (Perfetto-loadable), anything else → JSONL."""
    if str(path).endswith(".json"):
        return ChromeTraceExporter(path)
    return JsonlExporter(path)


def enable_tracing(path=None, exporter=None) -> Tracer:
    """Install the process tracer (replacing any active one — the
    previous exporter is closed/flushed)."""
    global _ACTIVE
    if exporter is None:
        if path is None:
            raise ValueError("enable_tracing needs a path or an exporter")
        exporter = open_exporter(path)
    if _ACTIVE is not None:
        _ACTIVE.close()
    _ACTIVE = Tracer(exporter)
    return _ACTIVE


def disable_tracing() -> None:
    """Uninstall and flush/close the active tracer (no-op when off)."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
        _ACTIVE = None


def span(name: str, parent=None):
    """Context-manager span against the active tracer; the shared no-op
    span when tracing is disabled (no allocation — hot paths call this
    unconditionally)."""
    tracer = _ACTIVE
    if tracer is None:
        return NOOP_SPAN
    return tracer.span(name, parent=parent)


def start_span(name: str, parent=None):
    """Detached span (caller calls .finish(), any thread); the shared
    no-op span when tracing is disabled."""
    tracer = _ACTIVE
    if tracer is None:
        return NOOP_SPAN
    return tracer.start_span(name, parent=parent)


def record_span(name: str, parent, start: float, end: float, **attrs):
    """Record a pre-timed interval (perf_counter timestamps) as a
    finished span; returns it (the no-op span when disabled)."""
    tracer = _ACTIVE
    if tracer is None:
        return NOOP_SPAN
    return tracer.record_span(name, parent, start, end, **attrs)
