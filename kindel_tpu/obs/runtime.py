"""JAX runtime probes: compile time, jit cache entries, transfers, memory.

The span tracer and metric registry see *our* code; this module makes
the JAX runtime underneath visible in the same telemetry:

  * **compile wall-time per launch** — a `jax.monitoring` event-duration
    listener folds every XLA compilation into
    `kindel_jax_compiles_total` / `kindel_jax_compile_seconds` on the
    default registry (install once via `install()`; tolerant of jax
    versions without the hook).
  * **jit cache-entry deltas** — `jit_cache_entries()` sums the
    `_cache_size()` of the hot kernels (batched/realign/counts/slab), so
    a dispatch site can attach `compiled_new=...` to its span by
    differencing before/after (that is exactly how the serve warmup test
    pins "first request compiles nothing").
  * **host↔device transfer bytes** — `transfer_counters()` returns the
    (h2d, d2h) byte counters the launch/download sites feed
    (`kindel_device_h2d_bytes_total` / `kindel_device_d2h_bytes_total`).
  * **live device memory** — `update_device_gauges()` refreshes
    `kindel_jax_device_bytes_in_use` (TPU/GPU `memory_stats()`; absent
    on CPU backends) and `kindel_jax_live_arrays`; wired as the
    `MultiRegistry` refresh hook of the serve exposition.

Everything is best-effort: a missing jax API degrades to "no data",
never to a failed pipeline. Nothing here imports jax at module import
time (bench.py's hermetic parent must stay jax-free).
"""

from __future__ import annotations

import threading

from kindel_tpu.obs.metrics import default_registry

_COMPILE_BUCKETS = (0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0)

#: names of the jit-wrapped hot kernels whose cache sizes we track
_TRACKED_KERNELS = (
    ("kindel_tpu.call_jax", "batched_call_kernel"),
    ("kindel_tpu.call_jax", "batched_realign_call_kernel"),
    ("kindel_tpu.call_jax", "counts_call_kernel"),
    ("kindel_tpu.call_jax", "fused_call_kernel_slab"),
    ("kindel_tpu.ragged.kernel", "ragged_call_kernel"),
    ("kindel_tpu.parallel.meshexec", "sharded_ragged_kernel"),
)

_install_lock = threading.Lock()
_installed = False


def install(registry=None) -> bool:
    """Register the jax.monitoring compile-time listener (idempotent).
    Returns True when the listener is active."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        reg = registry if registry is not None else default_registry()
        compiles = reg.counter(
            "kindel_jax_compiles_total",
            "XLA compilations observed via jax.monitoring",
        )
        compile_s = reg.histogram(
            "kindel_jax_compile_seconds",
            "wall time of each observed XLA compilation",
            buckets=_COMPILE_BUCKETS,
        )
        try:
            from jax import monitoring

            def _on_event(event, duration, **_kw):
                # jax names its backend-compile duration events
                # '/jax/core/compile' / '.../backend_compile' across
                # versions — match the family, not one spelling
                if "compile" in event:
                    compiles.inc()
                    compile_s.observe(float(duration))

            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception:
            return False
        _installed = True
        return True


def compile_totals(registry=None) -> tuple[int, float]:
    """(count, total wall seconds) of compilations observed so far."""
    reg = registry if registry is not None else default_registry()
    compiles = reg.counter(
        "kindel_jax_compiles_total",
        "XLA compilations observed via jax.monitoring",
    )
    compile_s = reg.histogram(
        "kindel_jax_compile_seconds",
        "wall time of each observed XLA compilation",
        buckets=_COMPILE_BUCKETS,
    )
    return int(compiles.value), float(compile_s.sum)


def jit_cache_sizes() -> dict[str, int]:
    """Per-kernel jit cache-entry counts of the tracked hot kernels
    (empty when jax or the _cache_size API is unavailable)."""
    import sys

    out: dict[str, int] = {}
    for mod_name, fn_name in _TRACKED_KERNELS:
        mod = sys.modules.get(mod_name)
        if mod is None:
            continue  # never force a jax import from a probe
        try:
            fn = getattr(mod, fn_name, None)
            cache_size = getattr(fn, "_cache_size", None)
            if cache_size is not None:
                out[fn_name] = int(cache_size())
        except Exception:
            continue
    return out


def jit_cache_entries() -> int:
    """Total tracked jit cache entries (0 when unavailable)."""
    return sum(jit_cache_sizes().values())


_TRANSFER: tuple | None = None


def transfer_counters(registry=None):
    """(h2d, d2h) byte counters the dispatch/download sites feed. The
    default-registry pair is cached — the download site sits on the
    per-slab hot path and must not pay a registry lookup per call."""
    global _TRANSFER
    if registry is None:
        if _TRANSFER is None:
            _TRANSFER = transfer_counters(default_registry())
        return _TRANSFER
    return (
        registry.counter(
            "kindel_device_h2d_bytes_total",
            "host-to-device bytes uploaded by kernel dispatch sites",
        ),
        registry.counter(
            "kindel_device_d2h_bytes_total",
            "device-to-host bytes downloaded by wire/decode sites",
        ),
    )


_EMIT_INFO = None


def emit_mode_info(registry=None):
    """The resolved emission-mode Info metric (kindel_tpu.emit,
    DESIGN.md §22) — cached on the default registry like the transfer
    counters; the serve service and bench both stamp it."""
    global _EMIT_INFO
    if registry is None:
        if _EMIT_INFO is None:
            _EMIT_INFO = emit_mode_info(default_registry())
        return _EMIT_INFO
    return registry.info(
        "kindel_emit_mode",
        "resolved emission mode (host|device) and where it came from",
    )


_INGEST: "_IngestCounters | None" = None


class _IngestCounters:
    """The host-ingest counter family fed by kindel_tpu.io.inflate —
    cached like the transfer counters (the inflate path flushes per
    stream/slurp call and must not pay registry lookups there)."""

    __slots__ = (
        "members", "bytes_in", "bytes_out", "inflate_s", "scan_s",
        "stall_s", "read_s", "expand_s", "workers", "upload_bytes",
        "scan_device_s", "expand_device_s", "mode",
    )

    def __init__(self, registry):
        self.members = registry.counter(
            "kindel_ingest_members_total",
            "BGZF members inflated by the parallel ingest path",
        )
        self.bytes_in = registry.counter(
            "kindel_ingest_bytes_in_total",
            "compressed bytes consumed by the inflate chokepoint",
        )
        self.bytes_out = registry.counter(
            "kindel_ingest_bytes_out_total",
            "decompressed bytes produced by the inflate chokepoint",
        )
        self.inflate_s = registry.counter(
            "kindel_ingest_inflate_seconds_total",
            "summed zlib inflate wall across pool workers and inline "
            "members (exceeds elapsed wall when workers overlap)",
        )
        self.scan_s = registry.counter(
            "kindel_ingest_scan_seconds_total",
            "serial member-boundary scan + reassembly wall on the "
            "consumer thread",
        )
        self.stall_s = registry.counter(
            "kindel_ingest_stall_seconds_total",
            "consumer wall spent blocked on the head-of-line inflate "
            "future (0 when the pool keeps ahead of the decoder)",
        )
        self.read_s = registry.counter(
            "kindel_ingest_read_seconds_total",
            "wall spent in compressed-side file reads on the ingest path",
        )
        self.expand_s = registry.counter(
            "kindel_ingest_expand_seconds_total",
            "wall spent expanding CIGAR ops into event streams "
            "(events.extract_events)",
        )
        self.workers = registry.gauge(
            "kindel_ingest_pool_workers",
            "resolved inflate worker count of the most recent ingest run",
        )
        self.upload_bytes = registry.counter(
            "kindel_ingest_upload_bytes_total",
            "decompressed chunk bytes uploaded to the accelerator by "
            "the device ingest path (kindel_tpu.devingest)",
        )
        self.scan_device_s = registry.counter(
            "kindel_ingest_scan_device_seconds_total",
            "wall spent in the device record-boundary scan (upload-side "
            "sync included; 0 under host ingest mode)",
        )
        self.expand_device_s = registry.counter(
            "kindel_ingest_expand_device_seconds_total",
            "wall spent in the device field-extraction + CIGAR event "
            "expansion kernels (0 under host ingest mode)",
        )
        self.mode = registry.info(
            "kindel_ingest_mode",
            "resolved ingest mode (host|device) and where it came from",
        )


def ingest_counters(registry=None) -> _IngestCounters:
    """The ingest counter family (host-side counterpart of
    transfer_counters); the default-registry instance is cached."""
    global _INGEST
    if registry is None:
        if _INGEST is None:
            _INGEST = _IngestCounters(default_registry())
        return _INGEST
    return _IngestCounters(registry)


def device_memory_stats() -> dict | None:
    """First device's memory_stats() (None on backends without it —
    CPU — or before jax initialized)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def update_device_gauges(registry=None) -> None:
    """Refresh the point-in-time device gauges (MultiRegistry refresh
    hook: runs on every /metrics render)."""
    import sys

    reg = registry if registry is not None else default_registry()
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        live = len(jax.live_arrays())
    except Exception:
        live = None
    if live is not None:
        reg.gauge(
            "kindel_jax_live_arrays",
            "live jax arrays held by this process",
        ).set(live)
    stats = device_memory_stats()
    if stats and "bytes_in_use" in stats:
        reg.gauge(
            "kindel_jax_device_bytes_in_use",
            "bytes in use on device 0 (absent on CPU backends)",
        ).set(int(stats["bytes_in_use"]))


def runtime_snapshot() -> dict:
    """One JSON-able dict of every probe (span attributes, bench)."""
    snap: dict = {"jit_cache": jit_cache_sizes()}
    count, wall = compile_totals()
    snap["compiles"] = count
    snap["compile_wall_s"] = round(wall, 3)
    try:
        from kindel_tpu import aot

        snap["aot"] = aot.provenance()
    except Exception:
        pass  # probe stays best-effort: no AOT data beats no snapshot
    mem = device_memory_stats()
    if mem is not None:
        snap["device_memory"] = {
            k: mem[k] for k in ("bytes_in_use", "peak_bytes_in_use")
            if k in mem
        }
    return snap


def attach_runtime(span) -> None:
    """Attach the runtime snapshot to a span (no-op span safe)."""
    snap = runtime_snapshot()
    span.set_attribute(
        jit_cache_entries=sum(snap["jit_cache"].values()),
        compiles=snap["compiles"],
        compile_wall_s=snap["compile_wall_s"],
    )
