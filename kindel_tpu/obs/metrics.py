"""Process-wide metric registry + Prometheus text-format rendering.

Lifted out of `kindel_tpu/serve/metrics.py` (which now re-exports from
here) so every layer — streaming, batch, tune, the JAX runtime probes —
records into the same exposition the serve HTTP endpoint renders.
First-party on purpose (no prometheus_client dependency): the serving
loop records a handful of counters, gauges, and histograms; the
registry is equally readable in-process (`snapshot()`), which is what
the deterministic tests and `benchmarks/serve_load.py` consume — any
HTTP layer is a view, never the source of truth.

Beyond the serve-era registry this adds:

  * **labels**: every Counter/Gauge/Histogram is also a family —
    `.labels(outcome="ok")` returns a get-or-create child rendered as
    `name{outcome="ok"} v`. Label sets are expected to be small and
    bounded (outcomes, lane shapes) — there is no eviction.
  * **escaping per the exposition format spec**: HELP text escapes
    `\\` and newline; label values escape `\\`, `"` and newline
    (previously rendered raw — a help string or label value containing
    a quote produced an unparseable exposition).
  * **a process-global default registry** (`default_registry()`), and
    `MultiRegistry` so serve's `/metrics` can render its own registry
    plus the global one in a single exposition.

Registration through a registry requires non-empty help text (also
enforced statically by the tier-1 AST guard in tests/test_env_guard.py).
"""

from __future__ import annotations

import bisect
import re
import threading
from collections import deque

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: log-spaced latency buckets for wire-era histograms (RPC exchanges,
#: stream update latency): the serve-era default bucket ladder tops out
#: too coarsely for paths whose p99 lands seconds deep on the CPU bench
#: — this ladder keeps the 1-2.5-5 per-decade pattern from 1 ms through
#: 10 s so a slow p99 resolves into a real bucket instead of +Inf
WIRE_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def escape_help(text: str) -> str:
    """HELP-line escaping per the text exposition format: backslash and
    newline (quotes are legal raw in help text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value) -> str:
    """Label-value escaping: backslash, double-quote, newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_suffix(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _check_labels(labels: dict) -> dict:
    for k in labels:
        if not _LABEL_RE.match(k) or k.startswith("__"):
            raise ValueError(f"invalid label name {k!r}")
    return labels


class _Metric:
    """Shared family machinery: a metric is its own unlabeled series
    plus (optionally) labeled children of the same class."""

    def __init__(self, name: str, help_text: str = "",
                 label_values: dict | None = None):
        self.name = name
        self.help = help_text
        self._label_values = dict(label_values or {})
        self._children: dict[tuple, "_Metric"] = {}
        self._lock = threading.Lock()

    def _new_child(self, labels: dict):
        raise NotImplementedError

    def labels(self, **labels):
        """Get-or-create the child series for this label set."""
        _check_labels(labels)
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child(dict(key))
                self._children[key] = child
            return child

    def _suffix(self) -> str:
        return _label_suffix(self._label_values)

    def _header(self, type_name: str) -> list[str]:
        return [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {type_name}",
        ]

    def _series(self) -> list["_Metric"]:
        """Self plus labeled children; the bare series is omitted when
        children exist and it was never touched (a family used only via
        labels must not emit a spurious `name 0` sample)."""
        with self._lock:
            children = list(self._children.values())
        if children and not self._touched():
            return children
        return [self] + children

    def _touched(self) -> bool:
        return True

    def snapshot_value(self):
        raise NotImplementedError

    def snapshot_into(self, out: dict) -> None:
        out[self.name + self._suffix()] = self.snapshot_value()
        with self._lock:
            children = list(self._children.values())
        for c in children:
            out[c.name + c._suffix()] = c.snapshot_value()


class Counter(_Metric):
    """Monotonic counter (family: `.labels(outcome="ok").inc()`)."""

    def __init__(self, name: str, help_text: str = "",
                 label_values: dict | None = None):
        super().__init__(name, help_text, label_values)
        self._value = 0

    def _new_child(self, labels: dict) -> "Counter":
        return Counter(self.name, self.help, labels)

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _touched(self) -> bool:
        return self._value != 0

    def snapshot_value(self):
        return self._value

    def render(self) -> list[str]:
        lines = self._header("counter")
        for s in self._series():
            lines.append(f"{s.name}{s._suffix()} {s._value}")
        return lines


class Gauge(_Metric):
    """Instantaneous value (queue depth, pending rows, bytes in use)."""

    def __init__(self, name: str, help_text: str = "",
                 label_values: dict | None = None):
        super().__init__(name, help_text, label_values)
        self._value = 0.0
        self._set_ever = False

    def _new_child(self, labels: dict) -> "Gauge":
        return Gauge(self.name, self.help, labels)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            self._set_ever = True

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n
            self._set_ever = True

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n
            self._set_ever = True

    @property
    def value(self) -> float:
        return self._value

    def _touched(self) -> bool:
        return self._set_ever

    def snapshot_value(self):
        return self._value

    def render(self) -> list[str]:
        lines = self._header("gauge")
        for s in self._series():
            lines.append(f"{s.name}{s._suffix()} {_fmt(s._value)}")
        return lines


class Histogram(_Metric):
    """Cumulative-bucket histogram plus a bounded recent-observation
    window for exact quantiles (p50/p99 request latency).

    Prometheus histograms cannot express quantiles server-side, and the
    serve dashboard wants them live — so alongside the standard
    `_bucket`/`_sum`/`_count` series the renderer emits `<name>_p50` and
    `<name>_p99` gauges computed over the last `window` observations.
    """

    def __init__(self, name: str, help_text: str = "",
                 buckets: tuple = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0,
                                   2.5, 5.0, 10.0),
                 window: int = 4096, label_values: dict | None = None):
        super().__init__(name, help_text, label_values)
        self.buckets = tuple(sorted(buckets))
        self._window = window
        self._counts = [0] * (len(self.buckets) + 1)  # +inf bucket last
        self._sum = 0.0
        self._count = 0
        self._max = 0.0
        self._recent: deque = deque(maxlen=window)

    def _new_child(self, labels: dict) -> "Histogram":
        return Histogram(self.name, self.help, buckets=self.buckets,
                         window=self._window, label_values=labels)

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v
            self._recent.append(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        return self._max

    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Exact quantile over the recent window (0 when empty)."""
        with self._lock:
            window = sorted(self._recent)
        if not window:
            return 0.0
        idx = min(len(window) - 1, int(q * len(window)))
        return window[idx]

    def _touched(self) -> bool:
        return self._count != 0

    def snapshot_value(self):
        return {
            "count": self.count,
            "sum": self.sum,
            "max": self.max,
            "mean": self.mean(),
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
        }

    def _render_series(self) -> list[str]:
        with self._lock:
            counts = list(self._counts)
            total, total_sum, vmax = self._count, self._sum, self._max
        base = dict(self._label_values)
        lines = []
        cum = 0
        for bound, c in zip(self.buckets, counts):
            cum += c
            lines.append(
                f"{self.name}_bucket"
                f"{_label_suffix({**base, 'le': _fmt(bound)})} {cum}"
            )
        lines.append(
            f"{self.name}_bucket{_label_suffix({**base, 'le': '+Inf'})} "
            f"{total}"
        )
        suffix = self._suffix()
        lines.append(f"{self.name}_sum{suffix} {_fmt(total_sum)}")
        lines.append(f"{self.name}_count{suffix} {total}")
        lines.append(f"{self.name}_max{suffix} {_fmt(vmax)}")
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            lines.append(
                f"{self.name}_{label}{suffix} {_fmt(self.quantile(q))}"
            )
        return lines

    def render(self) -> list[str]:
        lines = self._header("histogram")
        for s in self._series():
            lines.extend(s._render_series())
        return lines


class Info(_Metric):
    """Constant labeled marker (value always 1) — exports configuration
    facts (tune knob sources, warmed lane shapes) in the standard
    `name{label="..."} 1` idiom without pretending they are
    measurements. One sample per distinct label set; re-setting the
    same label set overwrites it."""

    def __init__(self, name: str, help_text: str = "",
                 label_values: dict | None = None):
        super().__init__(name, help_text, label_values)
        self._labels: dict[tuple, dict] = {}

    def set(self, **labels) -> None:
        _check_labels(labels)
        with self._lock:
            self._labels[tuple(sorted(labels.items()))] = {
                k: str(v) for k, v in labels.items()
            }

    @property
    def value(self) -> list[dict]:
        with self._lock:
            return [dict(v) for v in self._labels.values()]

    def snapshot_value(self):
        return self.value

    def snapshot_into(self, out: dict) -> None:
        out[self.name] = self.value

    def render(self) -> list[str]:
        lines = self._header("gauge")
        with self._lock:
            for labels in self._labels.values():
                lines.append(f"{self.name}{_label_suffix(labels)} 1")
        return lines


#: default bound on the distinct values one label family may carry —
#: room for every page class and a healthy set of warmed lane shapes,
#: far below what a scrape pipeline starts choking on
DEFAULT_LABEL_CAP = 24

#: the overflow value every post-cap label collapses into
LABEL_OTHER = "other"


class LabelCapper:
    """Bound one label family's cardinality: the first `cap` distinct
    values pass through verbatim, every later NEW value maps to
    `other`. Metric label sets must be small and bounded (the families
    here have no eviction), but a label derived from traffic — a lane
    pad shape under shape-diverse load — is unbounded by nature; this
    is the chokepoint that keeps such a family scrapeable. Values
    already admitted keep reporting under their own name forever, so
    dashboards stay stable; only the long tail collapses."""

    def __init__(self, cap: int = DEFAULT_LABEL_CAP, other: str = LABEL_OTHER):
        if cap < 1:
            raise ValueError("label cap must be >= 1")
        self.cap = cap
        self.other = other
        self._seen: set = set()
        self._lock = threading.Lock()

    def see(self, value) -> str:
        v = str(value)
        with self._lock:
            if v in self._seen:
                return v
            if len(self._seen) < self.cap:
                self._seen.add(v)
                return v
        return self.other


class MetricsRegistry:
    """Get-or-create metric registry; render order is creation order.
    Names must match the exposition grammar and carry non-empty help."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help_text: str, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        if not help_text:
            raise ValueError(
                f"metric {name!r} registered without help text"
            )
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_text, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}"
                )
            return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "", **kw) -> Histogram:
        return self._get(Histogram, name, help_text, **kw)

    def info(self, name: str, help_text: str = "") -> Info:
        return self._get(Info, name, help_text)

    def _render_into(self, out: list[str], seen: set) -> None:
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.name in seen:
                continue  # first registry wins on a name collision
            seen.add(m.name)
            out.extend(m.render())

    def render(self) -> str:
        out: list[str] = []
        self._render_into(out, set())
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able view for in-process consumers (tests, load bench).
        Labeled children appear under `name{label="v"}` keys; unlabeled
        series keep their bare name."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict = {}
        for m in metrics:
            m.snapshot_into(out)
        return out


class LabeledRegistry:
    """Render-time view of one registry with a constant label injected
    into every sample (the fleet `/metrics` union tags each replica's
    series `replica="<slot>"` while front-process series stay bare).

    Inside a MultiRegistry, a plain registry claims its metric names in
    `seen` and later same-named families are skipped entirely — correct
    for the serve-plus-global pair, silently wrong for N replicas whose
    same-named histograms would all collapse into whichever rendered
    first.  A labeled view dedupes only the HELP/TYPE comments by name;
    its samples always render, distinguished by the injected label."""

    def __init__(self, registry: MetricsRegistry, label: str, value):
        if not _LABEL_RE.match(label):
            raise ValueError(f"invalid label name {label!r}")
        self._registry = registry
        self._label = label
        self._value = escape_label_value(value)

    def _inject(self, line: str) -> str:
        """Add the constant label to one rendered sample line."""
        brace = line.find("{")
        if brace >= 0:
            end = line.rfind("}")
            inner = line[brace + 1:end]
            pair = f'{self._label}="{self._value}"'
            inner = pair + ("," + inner if inner else "")
            return f"{line[:brace]}{{{inner}}}{line[end + 1:]}"
        sp = line.find(" ")
        return (
            f'{line[:sp]}{{{self._label}="{self._value}"}}{line[sp:]}'
        )

    def _render_into(self, out: list[str], seen: set) -> None:
        with self._registry._lock:
            metrics = list(self._registry._metrics.values())
        for m in metrics:
            emit_comments = m.name not in seen
            seen.add(m.name)
            for line in m.render():
                if line.startswith("#"):
                    if emit_comments:
                        out.append(line)
                else:
                    out.append(self._inject(line))

    def render(self) -> str:
        out: list[str] = []
        self._render_into(out, set())
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """Underlying snapshot with the label injected into every key
        so replica snapshots merge without clobbering each other."""
        out: dict = {}
        pair = f'{self._label}="{self._value}"'
        for key, v in self._registry.snapshot().items():
            brace = key.find("{")
            if brace >= 0:
                out[f"{key[:brace]}{{{pair},{key[brace + 1:]}"] = v
            else:
                out[f"{key}{{{pair}}}"] = v
        return out


class MultiRegistry:
    """Read-only union view over several registries (serve renders its
    own registry plus the process-global one in a single exposition).
    `refresh` is an optional callable run before each render/snapshot —
    the hook that updates point-in-time gauges (device memory)."""

    def __init__(self, *registries: MetricsRegistry, refresh=None):
        self._registries = registries
        self._refresh = refresh

    def render(self) -> str:
        if self._refresh is not None:
            self._refresh()
        out: list[str] = []
        seen: set = set()
        for reg in self._registries:
            reg._render_into(out, seen)
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        if self._refresh is not None:
            self._refresh()
        out: dict = {}
        for reg in reversed(self._registries):
            out.update(reg.snapshot())
        return out


#: the process-global registry: streaming/batch/tune/runtime metrics
#: land here so the serve exposition (and bench snapshots) see them
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


#: kindel_fleet_replica_state gauge encoding (kindel_tpu.fleet)
FLEET_STATE_CODES = {
    "starting": 0,
    "ok": 1,
    "degraded": 2,
    "draining": 3,
    "dead": 4,
    "restarting": 5,
}

_FLEET_METRICS = None
_fleet_lock = threading.Lock()


def fleet_metrics():
    """The process-global `kindel_fleet_*` family (kindel_tpu.fleet,
    DESIGN.md §17), cached so the supervisor's probe loop and the
    router's placement path never pay a registry lock per decision:

      replica_state  per-replica state gauge (labels: replica), coded
                     per FLEET_STATE_CODES
      evictions      replicas evicted after consecutive failed probes
      failovers      placements moved to the next healthy replica after
                     a shed/typed failure on the ranked-first choice
      hedges         duplicate speculative dispatches raced against a
                     straggling primary (first settle wins)
      drained        admitted requests handed back by a draining
                     replica and re-queued on a survivor
      replays        admitted requests replayed from a DEAD replica
                     onto survivors (the no-request-lost path)
      restarts       replica warm restarts (eviction or drain)
      watermark_sheds  fleet-level admission rejections (total queued
                     depth at/over the fleet watermark) — the
                     autoscaler's scale-up pressure signal
      scale_events   autoscaler actions by direction label (up = a
                     replica spawned, down = one drained and retired)
      spawns         replicas added to a live fleet (autoscale-up)
      respawns       replica PROCESSES respawned after host/process
                     loss (the cross-host sibling of restarts: counted
                     when a process-backed replica's restart spawns a
                     fresh OS process)
      respawn_seconds  spawn→ready wall time of one replica process
                     (first spawns and respawns alike) — recovery cost
                     as a tracked number; serve_load's rpc report
                     renders the p50/p99
    """
    global _FLEET_METRICS
    if _FLEET_METRICS is None:
        with _fleet_lock:
            if _FLEET_METRICS is None:
                from types import SimpleNamespace

                reg = default_registry()
                _FLEET_METRICS = SimpleNamespace(
                    replica_state=reg.gauge(
                        "kindel_fleet_replica_state",
                        "fleet replica state by replica label (0=starting,"
                        " 1=ok, 2=degraded, 3=draining, 4=dead,"
                        " 5=restarting)",
                    ),
                    evictions=reg.counter(
                        "kindel_fleet_evictions_total",
                        "replicas evicted by the fleet supervisor after "
                        "consecutive failed health probes",
                    ),
                    failovers=reg.counter(
                        "kindel_fleet_failovers_total",
                        "request placements failed over to the next "
                        "healthy replica (shed or typed replica failure "
                        "on the preferred one)",
                    ),
                    hedges=reg.counter(
                        "kindel_fleet_hedges_total",
                        "speculative duplicate dispatches raced against "
                        "a straggling primary replica",
                    ),
                    drained=reg.counter(
                        "kindel_fleet_drained_requests_total",
                        "admitted requests handed back by a draining "
                        "replica and re-queued on a survivor",
                    ),
                    replays=reg.counter(
                        "kindel_fleet_replayed_requests_total",
                        "admitted requests replayed from a dead replica "
                        "onto surviving replicas",
                    ),
                    restarts=reg.counter(
                        "kindel_fleet_restarts_total",
                        "replica warm restarts performed by the fleet "
                        "(post-eviction and post-drain)",
                    ),
                    watermark_sheds=reg.counter(
                        "kindel_fleet_watermark_sheds_total",
                        "requests rejected at the fleet watermark "
                        "(total queued depth across admitting replicas "
                        "at/over the bound) — the autoscaler's "
                        "scale-up pressure signal",
                    ),
                    scale_events=reg.counter(
                        "kindel_fleet_scale_events_total",
                        "fleet autoscaler actions by direction "
                        "(up = replica spawned, down = lowest-occupancy "
                        "replica drained and retired)",
                    ),
                    spawns=reg.counter(
                        "kindel_fleet_spawns_total",
                        "replicas added to a live fleet by the "
                        "autoscaler (scale-up spawns)",
                    ),
                    respawns=reg.counter(
                        "kindel_fleet_respawns_total",
                        "replica OS processes respawned after "
                        "host/process loss (cross-host sibling of the "
                        "warm-restart counter)",
                    ),
                    respawn_seconds=reg.histogram(
                        "kindel_fleet_respawn_seconds",
                        "spawn-to-ready wall time of one replica "
                        "process (first spawns and respawns alike) — "
                        "what a recovery-from-host-loss costs",
                    ),
                )
    return _FLEET_METRICS
