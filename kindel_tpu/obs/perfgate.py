"""kindel_tpu.obs.perfgate — the BENCH history as a CI gate.

The repo carries its own performance trajectory as committed JSON:
``BENCH_r01..r05.json`` (driver wrappers around a ``bench.py`` line),
``BENCH_tpu_live.json`` (one bare line from real hardware), and
``MULTICHIP_r01..r06.json`` (mesh rounds — failures, then the PR 14
sweep).  Until now that trajectory was loose files; this module types
it into a series store and turns it into a gate:

  * **Ingestion** — each file becomes :class:`PerfSample` rows keyed by
    ``(backend, series)``.  Records that carry no number (rc != 0
    wrappers, ``parsed: null``, mesh timeout rounds) are *skipped with
    a reason*, never silently dropped — ``kindel perf`` prints them.
  * **Noise-tolerant thresholds** — CPU-fallback numbers swing with
    host load (the committed history spans 13.3 → 27.9 Mbases/s on the
    same code path), so the gate compares a fresh value against the
    best prior in its series and fails only below
    ``best * (1 - tolerance)`` (default tolerance 0.35).  Higher is
    better for every ingested series (throughput, occupancy).
  * **History replay** (``kindel perf --gate``) — every committed
    sample is re-gated against its own predecessors in round order, so
    the committed trajectory itself proves the gate's polarity: the
    real r01→r06 history passes, a deliberately-regressed fixture line
    (tools/perfgate_regressed_fixture.json) fails.

Backends are normalised (``cpu-fallback``/``cpu`` collapse to ``cpu``)
so a fresh CPU line gates against the CPU history, never the TPU line.
Stdlib-only on purpose: bench.py's parent process imports this without
pulling jax.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field

#: regression threshold: fail when fresh < best_prior * (1 - tolerance)
DEFAULT_TOLERANCE = 0.35

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


@dataclass(frozen=True)
class PerfSample:
    """One typed point on the committed performance trajectory."""

    series: str        # e.g. consensus_throughput_bacterial
    backend: str       # normalised: cpu | tpu | ...
    value: float
    unit: str
    source: str        # file name the sample came from
    round: int         # ordering key within the series (r01 -> 1)

    @property
    def key(self) -> tuple:
        return (self.backend, self.series)


@dataclass
class HistoryStore:
    """Every ingested sample plus every skip, with its reason."""

    samples: list = field(default_factory=list)
    skipped: list = field(default_factory=list)  # (source, reason)

    def series(self) -> dict:
        """``(backend, series) -> [PerfSample]`` sorted by round."""
        out: dict = {}
        for s in self.samples:
            out.setdefault(s.key, []).append(s)
        for key in out:
            out[key].sort(key=lambda s: (s.round, s.source))
        return out


def normalize_backend(backend) -> str:
    b = str(backend or "unknown").split()[0].strip().lower()
    if b.startswith("cpu"):
        return "cpu"  # cpu-fallback and forced-cpu gate against cpu
    return b


def _round_of(source: str, default: int = 0) -> int:
    m = _ROUND_RE.search(source)
    return int(m.group(1)) if m else default


def _headline_sample(doc: dict, source: str, round_no: int):
    """A bare bench.py result line -> PerfSample (None if numberless)."""
    value = doc.get("value")
    metric = doc.get("metric")
    if not isinstance(value, (int, float)) or not metric:
        return None
    return PerfSample(
        series=str(metric),
        backend=normalize_backend(doc.get("backend")),
        value=float(value),
        unit=str(doc.get("unit", "")),
        source=source,
        round=round_no,
    )


def ingest_doc(store: HistoryStore, doc, source: str) -> None:
    """Type one committed JSON document into the store.  Recognises the
    three shapes in the repo root (driver wrapper, bare bench line,
    mesh sweep) and records a skip reason for anything numberless."""
    name = os.path.basename(source)
    round_no = _round_of(name)
    if not isinstance(doc, dict):
        store.skipped.append((name, "not a JSON object"))
        return
    if "parsed" in doc or ("rc" in doc and "cmd" in doc):
        # driver wrapper around a bench.py run
        if doc.get("rc") not in (0, None):
            store.skipped.append((name, f"bench rc={doc.get('rc')}"))
            return
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            store.skipped.append((name, "no parsed bench line"))
            return
        sample = _headline_sample(parsed, name, round_no)
        if sample is None:
            store.skipped.append((name, "parsed line carries no value"))
            return
        store.samples.append(sample)
        return
    if isinstance(doc.get("pod"), dict):
        # MULTICHIP pod sweep (DESIGN.md §27): the 2-process legs'
        # sweep throughput as (backend, pod_dp<dp>) series — each
        # config runs the fixed pod cohort in a fresh process, so
        # 1/wall is proportional to end-to-end throughput incl. the
        # cross-process allgather tax; the 0.35 default tolerance
        # absorbs the CPU-fallback noise like every other cpu series
        backend = normalize_backend(doc.get("backend"))
        added = False
        for row in (doc["pod"].get("configs") or []):
            wall = (row or {}).get("wall_s")
            if row.get("procs") != 2 or \
                    not isinstance(wall, (int, float)) or wall <= 0:
                continue
            store.samples.append(
                PerfSample(
                    series=f"pod_dp{row.get('dp')}",
                    backend=backend,
                    value=round(1.0 / float(wall), 4),
                    unit="sweeps_per_s",
                    source=name,
                    round=round_no,
                )
            )
            added = True
        if not added:
            store.skipped.append(
                (name, "pod sweep without a 2-process wall")
            )
        return
    if "ragged" in doc or "paged" in doc:
        # MULTICHIP mesh sweep: occupancy per lane width as SLI series
        backend = normalize_backend(doc.get("backend"))
        added = False
        for section in ("ragged", "paged"):
            widths = (doc.get(section) or {}).get("widths") or {}
            for width, row in sorted(widths.items()):
                occ = (row or {}).get("occupancy")
                if not isinstance(occ, (int, float)):
                    continue
                store.samples.append(
                    PerfSample(
                        series=f"mesh_{section}_occupancy_w{width}",
                        backend=backend,
                        value=float(occ),
                        unit="fraction",
                        source=name,
                        round=round_no,
                    )
                )
                added = True
        if not added:
            store.skipped.append((name, "mesh sweep without occupancy"))
        return
    if "n_devices" in doc and "ok" in doc:
        store.skipped.append(
            (name, f"multichip failure record (rc={doc.get('rc')})")
        )
        return
    sample = _headline_sample(doc, name, round_no)
    if sample is None:
        store.skipped.append((name, "unrecognised shape"))
        return
    store.samples.append(sample)


def load_history(root) -> HistoryStore:
    """Ingest every BENCH_*/MULTICHIP_* JSON under ``root``."""
    store = HistoryStore()
    patterns = ("BENCH_r*.json", "BENCH_tpu_live.json",
                "MULTICHIP_r*.json")
    paths: list[str] = []
    for pat in patterns:
        paths.extend(glob.glob(os.path.join(str(root), pat)))
    for path in sorted(paths):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            store.skipped.append((os.path.basename(path), f"unreadable: {e}"))
            continue
        ingest_doc(store, doc, path)
    return store


@dataclass(frozen=True)
class Check:
    """One gate comparison (fresh-vs-history or replayed history)."""

    series: str
    backend: str
    value: float
    best_prior: float | None
    floor: float | None
    ok: bool
    detail: str


@dataclass
class GateResult:
    checks: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    @property
    def regressions(self) -> list:
        return [c for c in self.checks if not c.ok]

    def to_doc(self) -> dict:
        return {
            "ok": self.ok,
            "checks": [
                {
                    "series": c.series,
                    "backend": c.backend,
                    "value": c.value,
                    "best_prior": c.best_prior,
                    "floor": c.floor,
                    "ok": c.ok,
                    "detail": c.detail,
                }
                for c in self.checks
            ],
        }


def _check_sample(sample: PerfSample, priors,
                  tolerance: float) -> Check:
    values = [p.value for p in priors]
    if not values:
        return Check(
            series=sample.series, backend=sample.backend,
            value=sample.value, best_prior=None, floor=None, ok=True,
            detail="no prior history — recorded, not gated",
        )
    best = max(values)
    floor = best * (1.0 - tolerance)
    ok = sample.value >= floor
    detail = (
        f"{sample.value:g} vs best prior {best:g} "
        f"(floor {floor:g}, tolerance {tolerance:.0%})"
    )
    return Check(
        series=sample.series, backend=sample.backend,
        value=sample.value, best_prior=best, floor=floor, ok=ok,
        detail=detail,
    )


def gate_fresh(store: HistoryStore, fresh_doc: dict,
               tolerance: float = DEFAULT_TOLERANCE,
               source: str = "fresh") -> GateResult:
    """Gate one fresh bench.py line against the committed history."""
    result = GateResult()
    sample = _headline_sample(dict(fresh_doc or {}), source, 10**9)
    if sample is None:
        result.checks.append(
            Check(
                series=str((fresh_doc or {}).get("metric", "?")),
                backend=normalize_backend(
                    (fresh_doc or {}).get("backend")
                ),
                value=float("nan"), best_prior=None, floor=None,
                ok=False, detail="fresh line carries no numeric value",
            )
        )
        return result
    priors = store.series().get(sample.key, [])
    result.checks.append(_check_sample(sample, priors, tolerance))
    return result


def gate_history(store: HistoryStore,
                 tolerance: float = DEFAULT_TOLERANCE) -> GateResult:
    """Replay the committed trajectory in round order: each sample is
    gated against its own predecessors.  The real history must pass;
    a regressed line spliced into it must fail."""
    result = GateResult()
    for _key, samples in sorted(store.series().items()):
        for i, sample in enumerate(samples):
            result.checks.append(
                _check_sample(sample, samples[:i], tolerance)
            )
    return result


def provenance(root, fresh_doc: dict,
               tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Compact verdict embedded in the bench.py result line: how this
    run compares to the committed history (never raises — bench output
    must survive a broken history dir)."""
    try:
        store = load_history(root)
        gated = gate_fresh(store, fresh_doc, tolerance=tolerance)
        check = gated.checks[0]
        if check.best_prior is None:
            verdict = "no_history"
        else:
            verdict = "pass" if check.ok else "regression"
        return {
            "verdict": verdict,
            "series": check.series,
            "backend": check.backend,
            "best_prior": check.best_prior,
            "floor": check.floor,
            "tolerance": tolerance,
            "history_samples": len(store.samples),
            "history_skipped": len(store.skipped),
        }
    except Exception as e:  # pragma: no cover - defensive
        return {"verdict": "error", "error": repr(e)}
