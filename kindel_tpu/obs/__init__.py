"""kindel_tpu.obs — the observability spine: spans, metrics, runtime probes.

Three pieces, shared by every layer (CLI, workloads, streaming, batch,
pipeline, tune, serve) so one run produces one coherent telemetry view:

  trace.py    thread-safe hierarchical span tracer (span ids, parent
              links, attributes, events) with pluggable exporters —
              JSONL (one span per line) and Perfetto/Chrome
              `trace_event` JSON. `--trace PATH` on every CLI
              subcommand; per-request trace ids in serve propagate
              queue → batcher → worker → device dispatch. Disabled
              tracing is a single global check returning a shared
              no-op span: no string formatting, no allocation.
  metrics.py  the thread-safe metric registry (Counter/Gauge/Histogram/
              Info), lifted out of serve/metrics.py and extended with
              label support and Prometheus text-format escaping, plus a
              process-global default registry so streaming/batch/tune
              record into the same exposition as serve.
  runtime.py  JAX runtime probes — compile wall-time via
              jax.monitoring, jit cache-entry counts of the hot
              kernels, host↔device transfer byte counters, live
              device-memory gauges — attached as span attributes and
              default-registry metrics.

`utils/profiling.py` (the `--profile` phase table) is a thin
compatibility shim over spans; `serve/metrics.py` re-exports from here.
"""

from kindel_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Info,
    MetricsRegistry,
    MultiRegistry,
    default_registry,
)
from kindel_tpu.obs.trace import (  # noqa: F401
    Tracer,
    active_tracer,
    disable_tracing,
    enable_tracing,
    record_span,
    span,
    start_span,
)
