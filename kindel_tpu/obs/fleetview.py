"""kindel_tpu.obs.fleetview — stitched cross-process fleet traces.

The span tracer (obs/trace.py) and its RPC propagation stop at the
process boundary: a request served by a 3-process fleet leaves N
disjoint span files.  This module stitches them back into ONE
Perfetto/Chrome trace where a request's tree spans front router → RPC
hop → replica queue/batcher/worker → device dispatch:

  * **SpanTap** — a trace exporter each replica installs: a bounded
    in-memory ring (drop-oldest, counted) drained over the wire via
    ``GET /v1/trace`` (ndjson), plus an optional write-through spool
    file flushed per span (JsonlExporter-style) so a SIGKILLed replica
    still leaves everything up to its last completed span on disk.
  * **Journal-style reads** — ``parse_ndjson``/``read_spool`` truncate
    at the first torn or corrupt line (the PR 15 durability rule: a
    torn tail is data loss already paid for; propagating it would turn
    one bad line into a corrupt merged file).
  * **TraceCollector** — the fleet front's merge point: deduplicates
    records by ``(trace_id, span_id)`` (a span drained over HTTP and
    later re-read from the spool counts once), assigns each source a
    stable pseudo-pid with a ``process_name`` metadata event, and
    writes a single ``traceEvents`` document.  Spans from different
    processes join by the trace id that already crossed the wire in
    ``X-Kindel-Trace``.

Collection must never take serving down: every wire/file failure lands
in ``TraceCollector.record_failure`` (counted, remembered, swallowed).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

from kindel_tpu.obs import trace
from kindel_tpu.obs.metrics import default_registry

#: the drain route every traced replica (and the front) exposes
TRACE_ROUTE = "/v1/trace"

#: drain payload content type: one JSON span record per ``\n`` line
TRACE_CONTENT_TYPE = "application/x-ndjson"

#: default SpanTap ring capacity (spans) — KINDEL_TPU_TRACE_BUFFER
DEFAULT_BUFFER = 4096

_FLEETVIEW_METRICS = None
_fv_lock = threading.Lock()


def fleetview_metrics():
    """The process-global ``kindel_fleetview_*`` family (cached, same
    pattern as ``rpc_metrics``/``fleet_metrics``)."""
    global _FLEETVIEW_METRICS
    if _FLEETVIEW_METRICS is None:
        with _fv_lock:
            if _FLEETVIEW_METRICS is None:
                from types import SimpleNamespace

                reg = default_registry()
                _FLEETVIEW_METRICS = SimpleNamespace(
                    collected=reg.counter(
                        "kindel_fleetview_spans_collected_total",
                        "span records merged into the stitched fleet "
                        "trace by source (front or replica slot)",
                    ),
                    dropped=reg.counter(
                        "kindel_fleetview_spans_dropped_total",
                        "span records dropped from a full SpanTap ring "
                        "before any drain could ship them",
                    ),
                    truncated=reg.counter(
                        "kindel_fleetview_truncated_tails_total",
                        "torn/corrupt trailing lines truncated from "
                        "replica span streams during collection "
                        "(journal-style: cut at the last complete span)",
                    ),
                    collections=reg.counter(
                        "kindel_fleetview_collections_total",
                        "fleet-wide trace collection sweeps (drains of "
                        "every reachable replica plus spool reads)",
                    ),
                    collect_errors=reg.counter(
                        "kindel_fleetview_collect_errors_total",
                        "per-source trace collection failures "
                        "(unreachable replica, unreadable spool) — "
                        "the merged trace is still written without them",
                    ),
                )
    return _FLEETVIEW_METRICS


class SpanTap:
    """Trace exporter with a drainable ring and a crash-tolerant spool.

    ``export`` is called by the tracer for every finished span: the
    record is appended to a bounded ring (oldest dropped, counted) and,
    when a spool path is configured, written+flushed as one JSON line —
    so a SIGKILL tears at most the line in flight.  ``drain()`` empties
    the ring; the /v1/trace route serves it over the wire.
    """

    def __init__(self, spool_path=None, capacity: int = DEFAULT_BUFFER):
        self.spool_path = str(spool_path) if spool_path else None
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._spool = None
        self._dropped = 0
        self._closed = False
        if self.spool_path:
            self._spool = open(self.spool_path, "w")

    def export(self, record: dict) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            if self._closed:
                return
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                fleetview_metrics().dropped.inc()
            self._ring.append(line)
            if self._spool is not None:
                self._spool.write(line + "\n")
                self._spool.flush()

    @property
    def dropped(self) -> int:
        return self._dropped

    def drain_lines(self) -> list[str]:
        """Return-and-clear the ring (each element one JSON record)."""
        with self._lock:
            lines = list(self._ring)
            self._ring.clear()
        return lines

    def drain_payload(self) -> bytes:
        """The ring as an ndjson wire payload (and clear it)."""
        lines = self.drain_lines()
        if not lines:
            return b""
        return ("\n".join(lines) + "\n").encode()

    def close(self) -> None:
        """Final flush (SIGTERM/drain path): close the spool so every
        exported span is durably on disk before the process exits."""
        with self._lock:
            self._closed = True
            if self._spool is not None:
                try:
                    self._spool.flush()
                    self._spool.close()
                finally:
                    self._spool = None


def trace_drain_response(tap: SpanTap):
    """``GET /v1/trace`` handler body: drain the tap as ndjson."""
    return 200, TRACE_CONTENT_TYPE, tap.drain_payload(), {}


def parse_ndjson(data: bytes) -> tuple[list[dict], int]:
    """Parse an ndjson span stream journal-style.

    Returns ``(records, truncated)``: parsing stops at the first line
    that is torn (no trailing newline) or fails to parse as a JSON
    object with the span-record keys — everything before the tear is
    kept, everything after discarded, and ``truncated`` counts the
    cut lines.  Never raises on payload content.
    """
    records: list[dict] = []
    if not data:
        return records, 0
    text = data.decode("utf-8", errors="replace")
    lines = text.split("\n")
    # a well-formed stream ends with "\n" → last element is ""; any
    # trailing non-empty element is a torn line (write cut mid-record)
    complete, tail = lines[:-1], lines[-1]
    truncated = 1 if tail.strip() else 0
    for i, line in enumerate(complete):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            rec = None
        if (
            not isinstance(rec, dict)
            or "trace_id" not in rec
            or "span_id" not in rec
            or "name" not in rec
        ):
            # corrupt line: journal rule — cut here, count the rest
            truncated += sum(
                1 for rest in complete[i:] if rest.strip()
            )
            break
        records.append(rec)
    return records, truncated


def read_spool(path) -> tuple[list[dict], int]:
    """Read a replica spool file journal-style (see parse_ndjson).
    A missing file is simply an empty stream."""
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return [], 0
    return parse_ndjson(data)


class TraceCollector:
    """Merge span streams from many processes into one Perfetto file.

    Sources are named (``front``, ``r0`` …); each gets a stable
    pseudo-pid plus a ``process_name`` metadata event so Perfetto
    renders the fleet as named process lanes.  Records are deduplicated
    by ``(trace_id, span_id)`` — a span seen both over the wire and in
    a spool counts once (first sighting wins).
    """

    FRONT = "front"

    def __init__(self, path=None):
        self.path = str(path) if path else None
        self._lock = threading.Lock()
        self._spans: dict[tuple, tuple] = {}  # (trace,span) -> (src, rec)
        self._pids: dict[str, int] = {}
        self._truncated: dict[str, int] = {}
        self._errors: list[tuple[str, str]] = []
        self._m = fleetview_metrics()

    def _pid(self, source: str) -> int:
        pid = self._pids.get(source)
        if pid is None:
            pid = self._pids[source] = len(self._pids) + 1
        return pid

    def record_failure(self, source: str, exc: BaseException) -> None:
        """One source failed to yield its stream (unreachable replica,
        unreadable spool).  Count it, remember it, keep collecting —
        a merged trace minus one source beats no trace."""
        self._m.collect_errors.inc()
        with self._lock:
            self._errors.append((source, repr(exc)))

    @property
    def errors(self) -> list[tuple[str, str]]:
        with self._lock:
            return list(self._errors)

    def add_records(self, source: str, records) -> int:
        """Merge parsed span records from one source; returns how many
        were new (not already seen under their (trace_id, span_id))."""
        added = 0
        with self._lock:
            self._pid(source)
            for rec in records:
                key = (rec.get("trace_id"), rec.get("span_id"))
                if key in self._spans:
                    continue
                self._spans[key] = (source, rec)
                added += 1
        if added:
            self._m.collected.labels(source=source).inc(added)
        return added

    def add_ndjson(self, source: str, data: bytes) -> int:
        """Merge a wire/spool ndjson stream (journal-truncated)."""
        records, truncated = parse_ndjson(data)
        if truncated:
            self._m.truncated.inc(truncated)
            with self._lock:
                self._truncated[source] = (
                    self._truncated.get(source, 0) + truncated
                )
        return self.add_records(source, records)

    def add_spool(self, source: str, path) -> int:
        """Merge a replica's on-disk spool (crashed-replica path)."""
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as e:
            self.record_failure(source, e)
            return 0
        return self.add_ndjson(source, data)

    def collect_spool_dir(self, trace_dir) -> int:
        """Merge every ``<rid>.<pid>.trace.jsonl`` spool in a directory
        (each process writes its own generation-unique spool, so a
        respawned slot never overwrites its predecessor's spans)."""
        added = 0
        try:
            names = sorted(os.listdir(str(trace_dir)))
        except OSError as e:
            self.record_failure("spool-dir", e)
            return 0
        for name in names:
            if not name.endswith(".trace.jsonl"):
                continue
            source = name.split(".", 1)[0]
            added += self.add_spool(
                source, os.path.join(str(trace_dir), name)
            )
        return added

    def span_count(self) -> int:
        with self._lock:
            return len(self._spans)

    def sources(self) -> list[str]:
        with self._lock:
            return sorted(self._pids)

    def merge(self) -> dict:
        """Build the single Perfetto/Chrome ``traceEvents`` document:
        pseudo-pid per source, per-source thread lanes, span args
        carrying trace/span/parent ids so cross-process trees stay
        joinable in the UI and in tests."""
        events: list[dict] = []
        with self._lock:
            pids = dict(self._pids)
            spans = list(self._spans.values())
            truncated = dict(self._truncated)
        tids: dict[tuple, int] = {}
        for source, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": f"kindel:{source}"},
                }
            )
        for source, rec in spans:
            pid = pids[source]
            tkey = (source, rec.get("thread", "main"))
            tid = tids.get(tkey)
            if tid is None:
                tid = tids[tkey] = (
                    len([1 for k in tids if k[0] == source]) + 1
                )
            args = dict(rec.get("attrs") or {})
            args["trace_id"] = rec["trace_id"]
            args["span_id"] = rec["span_id"]
            if rec.get("parent_id"):
                args["parent_id"] = rec["parent_id"]
            args["source"] = source
            events.append(
                {
                    "name": rec["name"],
                    "ph": "X",
                    "ts": round(float(rec.get("start_s", 0.0)) * 1e6, 3),
                    "dur": round(
                        float(rec.get("duration_s", 0.0)) * 1e6, 3
                    ),
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                }
            )
            for ev in rec.get("events") or []:
                events.append(
                    {
                        "name": ev.get("name", "event"),
                        "ph": "i",
                        "ts": round(float(ev.get("t_s", 0.0)) * 1e6, 3),
                        "pid": pid,
                        "tid": tid,
                        "s": "t",
                        "args": dict(ev.get("attrs") or {}),
                    }
                )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "kindel-tpu obs.fleetview",
                "sources": sorted(pids),
                "truncated_tails": truncated,
                "collect_errors": len(self._errors),
            },
        }

    def write(self, path=None) -> str:
        """Write the merged document atomically (tmp + rename) so a
        crash mid-write never leaves a half-merged file at the final
        path."""
        out = str(path or self.path)
        if not out:
            raise ValueError("TraceCollector.write: no output path")
        self._m.collections.inc()
        doc = self.merge()
        tmp = out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, out)
        return out


def install_replica_tracing(
    spool_path=None, capacity: int = DEFAULT_BUFFER
) -> SpanTap:
    """Install a SpanTap as the process tracer exporter (replica boot
    path).  Returns the tap; the caller wires ``/v1/trace`` to it and
    closes it on drain/SIGTERM."""
    tap = SpanTap(spool_path=spool_path, capacity=capacity)
    trace.enable_tracing(exporter=tap)
    return tap
