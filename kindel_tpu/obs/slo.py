"""kindel_tpu.obs.slo — declarative SLOs with multi-window burn-rate alerts.

The resilience stack (hedging, failover, replay, durable admission)
exists to protect service-level objectives, but until now nothing in
the process *watched* them: operators got raw histograms and had to do
the burn math in their heads.  This module closes the loop:

  * **Declarative objectives** — `--slo 'route=/v1/consensus p99_ms=500
    err_budget=0.1%'` (explicit > ``KINDEL_TPU_SLO`` > off, resolved
    like every knob via tune.py).  Several objectives separated by
    ``;``.  A request counts against the budget when it errors OR when
    it exceeds the route's latency target — the standard "slow is the
    new down" accounting: latency violations spend error budget.
  * **Ring-buffer observations** — per-route bounded deques of
    ``(t, latency_s, ok)`` fed from the existing request settle path
    (serve worker completion / fleet front futures).  No new
    synchronisation on the hot path beyond one deque append under a
    lock.
  * **Multi-window burn rate** — the classic fast/slow pair: the burn
    rate is ``bad_fraction / err_budget`` over a window; an alert needs
    BOTH the fast window (is it burning *now*?) and the slow window
    (is it more than a blip?) over threshold.  On fast-burn the engine
    flips ``degraded()`` true — serve/fleet ``/readyz`` turns 503 — and
    drops a detached ``slo.fast_burn`` span so a burn incident carries
    its own annotation inside the active trace window.  Recovery is
    automatic when the fast window drains below threshold.

Gauges exported per route (process-global registry):

  kindel_slo_burn_rate          bad_fraction/err_budget, fast window
  kindel_slo_budget_remaining   1 - slow-window burn (negative = blown)
  kindel_slo_fast_burn_active   1 while the multi-window alert is firing
  kindel_slo_fast_burn_total    alert activations (counter)
  kindel_slo_observations_total settled requests by route/outcome

The engine is deliberately self-contained: parse errors in a spec fall
through to "off" (an unparseable knob must never take a replica down at
boot — tune.py's standing rule), and evaluation is O(window) on a
bounded deque, cheap enough to run inline from ``/readyz``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent import futures
from dataclasses import dataclass

from kindel_tpu.obs import trace
from kindel_tpu.obs.metrics import default_registry

#: default budget/burn windows (seconds).  Production SLOs use long
#: windows (hours); the defaults here are short enough that a serving
#: process sees signal within a bench run while still giving the
#: fast/slow pair distinct roles.  Both are per-spec overridable
#: (``window_s=`` / ``fast_window_s=``) so tests can compress time.
DEFAULT_WINDOW_S = 300.0
DEFAULT_FAST_WINDOW_S = 60.0

#: default multi-window alert threshold: the fast window must burn at
#: this multiple of the budget rate (and the slow window at >= 1x)
#: before the engine degrades readiness.  14.4 is the canonical
#: "2% of a 30-day budget in one hour" page threshold scaled to our
#: fast window; per-spec overridable (``fast_burn=``).
DEFAULT_FAST_BURN = 14.4

#: per-route observation ring size — bounds memory under sustained load
#: (old observations age out by window anyway; the cap is a backstop)
DEFAULT_RING = 4096


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective for one route."""

    route: str
    p99_ms: float | None = None      # latency target; None = errors only
    err_budget: float = 0.001        # allowed bad fraction (0.1% default)
    window_s: float = DEFAULT_WINDOW_S
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    fast_burn: float = DEFAULT_FAST_BURN


class SloParseError(ValueError):
    """A spec string that does not follow the grammar."""


def _parse_fraction(tok: str) -> float:
    """``0.1%`` -> 0.001; ``0.001`` -> 0.001."""
    tok = tok.strip()
    if tok.endswith("%"):
        v = float(tok[:-1]) / 100.0
    else:
        v = float(tok)
    if not (0.0 < v <= 1.0):
        raise SloParseError(f"err_budget out of (0, 1]: {tok!r}")
    return v


def parse_slo(spec: str) -> list[SloSpec]:
    """Parse an ``--slo`` string into specs.

    Grammar: objectives separated by ``;``; each objective is
    whitespace-separated ``key=value`` tokens.  ``route=`` is required;
    ``p99_ms=``, ``err_budget=`` (percent or fraction), ``window_s=``,
    ``fast_window_s=`` and ``fast_burn=`` are optional.  Raises
    :class:`SloParseError` on malformed input — callers resolving the
    knob from the environment catch it and fall through to off.
    """
    specs: list[SloSpec] = []
    for entry in str(spec).split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields: dict = {}
        for tok in entry.split():
            if "=" not in tok:
                raise SloParseError(f"token without '=': {tok!r}")
            key, _, val = tok.partition("=")
            key = key.strip()
            try:
                if key == "route":
                    fields["route"] = val.strip()
                elif key == "p99_ms":
                    fields["p99_ms"] = float(val)
                elif key == "err_budget":
                    fields["err_budget"] = _parse_fraction(val)
                elif key == "window_s":
                    fields["window_s"] = float(val)
                elif key == "fast_window_s":
                    fields["fast_window_s"] = float(val)
                elif key == "fast_burn":
                    fields["fast_burn"] = float(val)
                else:
                    raise SloParseError(f"unknown SLO key {key!r}")
            except SloParseError:
                raise
            except (TypeError, ValueError) as e:
                raise SloParseError(f"bad value for {key!r}: {val!r}") from e
        if "route" not in fields or not fields["route"]:
            raise SloParseError(f"objective without route=: {entry!r}")
        for fkey in ("p99_ms", "window_s", "fast_window_s", "fast_burn"):
            if fkey in fields and fields[fkey] <= 0:
                raise SloParseError(f"{fkey} must be positive: {entry!r}")
        specs.append(SloSpec(**fields))
    return specs


_SLO_METRICS = None
_slo_lock = threading.Lock()


def slo_metrics():
    """The process-global ``kindel_slo_*`` family (cached, same pattern
    as ``rpc_metrics``/``fleet_metrics``)."""
    global _SLO_METRICS
    if _SLO_METRICS is None:
        with _slo_lock:
            if _SLO_METRICS is None:
                from types import SimpleNamespace

                reg = default_registry()
                _SLO_METRICS = SimpleNamespace(
                    burn_rate=reg.gauge(
                        "kindel_slo_burn_rate",
                        "SLO burn rate over the fast window by route "
                        "(bad_fraction / err_budget; > 1 means the "
                        "budget is being spent faster than allowed)",
                    ),
                    budget_remaining=reg.gauge(
                        "kindel_slo_budget_remaining",
                        "fraction of the route's error budget left over "
                        "the slow window (1 = untouched, 0 = exactly "
                        "spent, negative = blown)",
                    ),
                    fast_burn_active=reg.gauge(
                        "kindel_slo_fast_burn_active",
                        "1 while the multi-window fast-burn alert is "
                        "firing for the route (readiness is degraded)",
                    ),
                    fast_burn_total=reg.counter(
                        "kindel_slo_fast_burn_total",
                        "fast-burn alert activations by route "
                        "(transitions into the burning state)",
                    ),
                    observations=reg.counter(
                        "kindel_slo_observations_total",
                        "settled requests observed by the SLO engine "
                        "by route and outcome (good/bad)",
                    ),
                )
    return _SLO_METRICS


class _RouteState:
    __slots__ = ("spec", "ring", "burning")

    def __init__(self, spec: SloSpec, ring: int):
        self.spec = spec
        self.ring: deque = deque(maxlen=ring)  # (t, latency_s, ok)
        self.burning = False


class SloEngine:
    """Evaluate declarative SLOs over ring-buffered observations.

    Thread-safe; ``observe()`` is the hot-path entry (one append under
    a lock), ``evaluate()``/``degraded()`` are the read side, called
    from ``/readyz`` and the metrics refresh hook.
    """

    def __init__(self, specs, ring: int = DEFAULT_RING, clock=None):
        self._routes = {s.route: _RouteState(s, ring) for s in specs}
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._m = slo_metrics()

    @property
    def specs(self) -> list[SloSpec]:
        return [st.spec for st in self._routes.values()]

    def observe(self, route: str, latency_s: float, ok: bool) -> None:
        """Record one settled request.  Routes without an objective are
        ignored — the engine only buffers what it will evaluate."""
        st = self._routes.get(route)
        if st is None:
            return
        bad = (not ok) or (
            st.spec.p99_ms is not None
            and latency_s * 1000.0 > st.spec.p99_ms
        )
        with self._lock:
            st.ring.append((self._clock(), latency_s, not bad))
        self._m.observations.labels(
            route=route, outcome="bad" if bad else "good"
        ).inc()

    def attach(self, route: str, fut, start_s: float | None = None) -> None:
        """Feed a Future's settlement into the engine: latency measured
        from ``start_s`` (engine clock) to the done callback; any
        exception (or cancellation) counts as bad."""
        if route not in self._routes:
            return
        t0 = self._clock() if start_s is None else start_s

        def _settled(f) -> None:
            try:
                ok = f.exception() is None
            except futures.CancelledError:
                ok = False  # a cancelled request spent budget too
            self.observe(route, self._clock() - t0, ok)

        fut.add_done_callback(_settled)

    def _burn(self, st: _RouteState, now: float, horizon_s: float) -> tuple:
        """(burn_rate, good, bad) over the window ending now."""
        cutoff = now - horizon_s
        good = bad = 0
        for t, _lat, ok in st.ring:
            if t < cutoff:
                continue
            if ok:
                good += 1
            else:
                bad += 1
        total = good + bad
        if total == 0:
            return 0.0, 0, 0
        return (bad / total) / st.spec.err_budget, good, bad

    def evaluate(self) -> dict:
        """Recompute burn rates for every route, update the gauges, and
        manage fast-burn state transitions.  Returns a per-route doc
        (also embedded in readyz responses)."""
        now = self._clock()
        out: dict = {}
        with self._lock:
            states = list(self._routes.values())
        for st in states:
            spec = st.spec
            with self._lock:
                # trim aged-out observations so the ring stays small
                cutoff = now - max(spec.window_s, spec.fast_window_s)
                while st.ring and st.ring[0][0] < cutoff:
                    st.ring.popleft()
                fast_burn, fgood, fbad = self._burn(
                    st, now, spec.fast_window_s
                )
                slow_burn, sgood, sbad = self._burn(st, now, spec.window_s)
            firing = fast_burn >= spec.fast_burn and slow_burn >= 1.0
            if firing and not st.burning:
                st.burning = True
                self._m.fast_burn_total.labels(route=spec.route).inc()
                # annotate the active trace window: a burn incident
                # carries its own marker span with the numbers attached
                sp = trace.start_span("slo.fast_burn")
                sp.set_attribute(
                    route=spec.route,
                    burn_rate=round(fast_burn, 3),
                    fast_window_s=spec.fast_window_s,
                    err_budget=spec.err_budget,
                )
                sp.finish()
            elif not firing and st.burning:
                st.burning = False
            budget_remaining = 1.0 - slow_burn
            route_labels = {"route": spec.route}
            self._m.burn_rate.labels(**route_labels).set(fast_burn)
            self._m.budget_remaining.labels(**route_labels).set(
                budget_remaining
            )
            self._m.fast_burn_active.labels(**route_labels).set(
                1.0 if st.burning else 0.0
            )
            out[spec.route] = {
                "burn_rate": round(fast_burn, 4),
                "slow_burn_rate": round(slow_burn, 4),
                "budget_remaining": round(budget_remaining, 4),
                "fast_burn_active": st.burning,
                "window": {"good": sgood, "bad": sbad},
                "fast_window": {"good": fgood, "bad": fbad},
            }
        return out

    def refresh(self) -> None:
        """Metrics-refresh hook (MultiRegistry ``refresh=``): recompute
        gauges before a scrape renders them."""
        self.evaluate()

    def degraded(self) -> bool:
        """True while any route's fast-burn alert is firing.  Evaluates
        inline — readyz always sees current-window truth."""
        doc = self.evaluate()
        return any(r["fast_burn_active"] for r in doc.values())
