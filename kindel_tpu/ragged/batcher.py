"""RaggedBatcher — the MicroBatcher flush contract, superbatched.

Drop-in replacement for the serve tier's shape-keyed micro-batcher
(`--batch-mode ragged`): instead of keying coalescing lanes on per-flush
pad shapes, requests accumulate into **page-class lanes** keyed only by
(call options, page class). A lane seals when its next admission would
overflow any of the class's fixed capacities, when it reaches the
segment bound, or when its oldest entry ages past max-wait — the same
batch-full / max-wait / drain semantics the worker's dispatch loop
already drives through `poll`, so the worker, watchdog, supervisor, and
admission watermarks are untouched.

One request kind cannot ride a superbatch and falls through to the
inherited shape-keyed lanes (still one batcher, one poll loop, one
dispatch thread): oversize requests no page class admits. Realign
traffic used to fall back too, until the segment kernel learned the
flat clip-channel scatter and segment-windowed CDR fetches — the
`reason="realign"` label of the fallback counter is now a regression
tripwire pinned at zero, and only `reason="oversize"` is a live route.

Fat-dispatch coalescing (`take_ready`) degrades to "already one batch"
for superbatch flushes: merging two sealed superbatches would overflow
the class geometry, and a superbatch is already the fattest dispatch
the class allows. Sealed shape-keyed flushes keep the inherited
behavior.
"""

from __future__ import annotations

from dataclasses import dataclass

from kindel_tpu.obs import trace as obs_trace
from kindel_tpu.ragged import pack as rpack
from kindel_tpu.serve.batcher import Flush, MicroBatcher, opts_key

_FALLBACK_COUNTER = None


def _fallback_counter():
    """Requests routed to the shape-keyed lanes path instead of a
    superbatch, labeled by reason (process-global registry)."""
    global _FALLBACK_COUNTER
    if _FALLBACK_COUNTER is None:
        from kindel_tpu.obs.metrics import default_registry

        _FALLBACK_COUNTER = default_registry().counter(
            "kindel_ragged_fallback_total",
            "requests routed to the shape-keyed lanes path instead of a "
            "superbatch (reason label: oversize is the only live route; "
            "realign is a regression tripwire pinned at zero)",
        )
    return _FALLBACK_COUNTER


@dataclass
class RaggedFlush(Flush):
    """One sealed superbatch. `shapes` carries the page-class geometry
    key (so span/metric labels and flush identity stay well-defined);
    `page_class` is what the worker's ragged dispatch packs against."""

    page_class: object = None


class _RaggedLane:
    __slots__ = ("opts", "cls_idx", "entries", "opened_at", "segments",
                 "slots", "spans", "events", "dels", "inss", "clips")

    def __init__(self, opts, cls_idx, now):
        self.opts = opts
        self.cls_idx = cls_idx
        self.entries: list = []
        self.opened_at = now
        self.segments = 0
        self.slots = 0
        self.spans = 0
        self.events = 0
        self.dels = 0
        self.inss = 0
        self.clips = 0

    def admits(self, need: rpack.Consumption, cls: rpack.PageClass,
               seg_cap: int) -> bool:
        return (
            self.segments + need.segments <= seg_cap
            and self.slots + need.slots <= cls.n_slots
            and self.spans + need.spans <= cls.o_cap
            and self.events + need.events <= cls.e_cap
            and self.dels + need.dels <= cls.d_cap
            and self.inss + need.inss <= cls.i_cap
            and self.clips + need.clips <= cls.c_cap
        )

    def take(self, req, units, need: rpack.Consumption) -> None:
        self.entries.append((req, units))
        self.segments += need.segments
        self.slots += need.slots
        self.spans += need.spans
        self.events += need.events
        self.dels += need.dels
        self.inss += need.inss
        self.clips += need.clips


class RaggedBatcher(MicroBatcher):
    """Page-class superbatching with the MicroBatcher flush contract."""

    def __init__(self, classes, max_batch_rows: int = 64,
                 max_wait_s: float = 0.02, clock=None):
        import time

        super().__init__(
            max_batch_rows=max_batch_rows, max_wait_s=max_wait_s,
            clock=clock if clock is not None else time.monotonic,
        )
        self.classes = tuple(classes)
        if not self.classes:
            raise ValueError("RaggedBatcher needs at least one page class")
        self._rlanes: dict[tuple, _RaggedLane] = {}

    # ------------------------------------------------------------ admission

    def _seg_cap(self, cls: rpack.PageClass) -> int:
        """Segments one superbatch may hold: the class's row bound,
        further capped by the operator's max_batch_rows knob (segments
        are the ragged tier's 'rows')."""
        return min(cls.rows, self.max_batch_rows)

    def add(self, req, units) -> None:
        if not units:
            raise ValueError("a request with no units has nothing to batch")
        # realign rides a superbatch like everything else since the
        # segment kernel learned the clip-channel scatter + windowed CDR
        # fetches — reason="realign" is a regression tripwire pinned at
        # zero by tests/test_ragged.py, never a live route
        cls_idx = rpack.classify_units(units, self.classes)
        if cls_idx is None:
            # oversize: the inherited shape-keyed lane path
            _fallback_counter().labels(reason="oversize").inc()
            super().add(req, units)
            return
        need = rpack.consumption(units)
        okey = opts_key(req.opts)
        with self._cond:
            now = self._clock()
            # occupancy-first placement: join the smallest OPEN lane (of
            # this class or any larger one) that still admits the
            # request, before opening a new lane — small traffic fills
            # an already-committed bigger grid instead of paying for its
            # own. Dispatch output is per-unit, so which class carries a
            # unit never changes its bytes.
            lane = None
            key = None
            for c in range(cls_idx, len(self.classes)):
                cand_key = (okey, c)
                cand = self._rlanes.get(cand_key)
                if cand is not None and cand.admits(
                    need, self.classes[c], self._seg_cap(self.classes[c])
                ):
                    lane, key = cand, cand_key
                    break
            if lane is None:
                key = (okey, cls_idx)
                full = self._rlanes.get(key)
                if full is not None:
                    # capacity-full home lane: seal it, open a fresh one
                    self._ready.append(self._seal_ragged(key, full))
                lane = self._rlanes[key] = _RaggedLane(req.opts, cls_idx, now)
            cls = self.classes[lane.cls_idx]
            lane.take(req, units, need)
            sealed = lane.segments >= self._seg_cap(cls)
            if sealed:
                self._ready.append(self._seal_ragged(key, lane))
            self._cond.notify_all()
        span = getattr(req, "span", None)
        if span is not None and span is not obs_trace.NOOP_SPAN:
            span.add_event(
                "batcher.ragged_add",
                segments=need.segments, slots=need.slots, sealed=sealed,
                page_class=cls.label(),
            )

    def _seal_ragged(self, key, lane: _RaggedLane) -> RaggedFlush:
        del self._rlanes[key]
        cls = self.classes[lane.cls_idx]
        return RaggedFlush(
            lane.opts, cls.key(), lane.entries, lane.opened_at,
            page_class=cls,
        )

    # ----------------------------------------------------------- poll hooks

    def _due_locked(self, now: float):
        flush = super()._due_locked(now)
        if flush is not None:
            return flush
        oldest_key = None
        oldest = None
        for key, lane in self._rlanes.items():
            if oldest is None or lane.opened_at < oldest.opened_at:
                oldest_key, oldest = key, lane
        if oldest is not None and now - oldest.opened_at >= self.max_wait_s:
            return self._seal_ragged(oldest_key, oldest)
        return None

    def _has_open_locked(self) -> bool:
        return super()._has_open_locked() or bool(self._rlanes)

    def _oldest_open_locked(self) -> float | None:
        candidates = [
            t for t in (super()._oldest_open_locked(),) if t is not None
        ] + [lane.opened_at for lane in self._rlanes.values()]
        return min(candidates) if candidates else None

    def _seal_open_locked(self) -> None:
        for key in list(self._rlanes):
            self._ready.append(self._seal_ragged(key, self._rlanes[key]))
        super()._seal_open_locked()

    # -------------------------------------------------------- flush contract

    @property
    def pending_rows(self) -> int:
        with self._cond:
            classic = sum(lane.rows for lane in self._lanes.values())
            ragged = sum(lane.segments for lane in self._rlanes.values())
            ready = sum(f.n_rows for f in self._ready)
            return classic + ragged + ready

    def take_ready(self, like, limit: int) -> list:
        # a superbatch is already the fattest launch its class allows —
        # fat-dispatch coalescing degrades to "already one batch"
        if isinstance(like, RaggedFlush):
            return []
        return super().take_ready(like, limit)

    def flush_all(self) -> list:
        with self._cond:
            out = [
                self._seal_ragged(key, self._rlanes[key])
                for key in list(self._rlanes)
            ]
        return out + super().flush_all()
