"""Superbatch packing: variable-length units → one fixed-geometry slab.

A **page class** is a fixed superbatch geometry — `rows` segments ×
`length` slots plus derived event/span/indel capacities. Every array the
ragged kernel consumes is padded to the class's capacities, so the jit
(and AOT-export) signature of a page class never changes no matter what
traffic packs into it. The serving process runs a small tuned set of
classes (small/medium/large by default; `kindel_tpu.tune` resolves the
spec), so the whole shape-diverse serve tier compiles at most
#classes × #wire-variants kernels — versus one per lane shape before.

Units pack end-to-end on a single flat slot axis. Each unit's segment is
aligned to an 8-slot granule with at least one empty slot after it:

  * byte alignment — every per-position wire plane (2-bit bases, 4-bit
    emits, 1-bit masks) slices per-unit on byte boundaries, so unpacking
    is a couple of numpy slices per request;
  * the guaranteed zero-depth gap slot reproduces the per-row padding
    semantics of the lanes kernel exactly (`depth_next` past a unit's
    last position reads 0), which is what makes ragged output
    byte-identical to the shape-keyed path.

The **segment table** carries per-segment slot offsets/lengths, flat
event/deletion/insertion stream offsets, and request back-pointers
(entry index per segment). It is built with vectorized numpy — the
tier-1 AST guard (tests/test_env_guard.py) pins `build_segment_table`
and `pack_superbatch` loop-free: per-request Python work is O(1) array
bookkeeping (comprehensions feeding concatenate/cumsum), never
per-event.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kindel_tpu.pileup_jax import PAD_POS, check_pad_safe_block

#: slot-alignment granule: 8 keeps every bit-packed wire plane sliceable
#: per segment on byte boundaries (and ≥1 zero gap slot per segment)
GRANULE = 8

#: derived-capacity model: events per slot the event buffers budget for,
#: and the slot fraction reserved for sparse deletion/insertion events —
#: a superbatch that would exceed any capacity simply closes early
#: (capacity never affects correctness, only occupancy)
EVENTS_PER_SLOT = 4
SPANS_PER_ROW = 256
INDEL_SLOT_FRACTION = 16


class RaggedCapacityError(ValueError):
    """Units exceed the page class's fixed capacities — the caller must
    split the batch or route it to a larger class / the lanes path."""


def stride_for(length: int) -> int:
    """Slots one unit of reference length L consumes: L rounded up to the
    granule with at least one empty gap slot after the last position."""
    return ((int(length) // GRANULE) + 1) * GRANULE


@dataclass(frozen=True)
class PageClass:
    """One fixed superbatch geometry (see module docstring)."""

    name: str
    rows: int  # max segments per superbatch
    length: int  # max slot stride a single admitted unit may have

    def __post_init__(self):
        if self.rows < 1:
            raise ValueError(f"page class {self.name!r}: rows must be >= 1")
        if self.length < 1024 or self.length % 1024:
            raise ValueError(
                f"page class {self.name!r}: length must be a positive "
                "multiple of 1024"
            )
        check_pad_safe_block(self.n_slots, f"page class {self.name!r}")

    @property
    def n_slots(self) -> int:
        return self.rows * self.length

    @property
    def s_pad(self) -> int:
        return self.rows

    @property
    def o_cap(self) -> int:
        """Match op-span capacity (flat, all segments)."""
        return self.rows * SPANS_PER_ROW

    @property
    def e_cap(self) -> int:
        """Match event capacity (flat); always even (4-bit pairing)."""
        return EVENTS_PER_SLOT * self.n_slots

    @property
    def b_cap(self) -> int:
        """Packed base-code bytes (2 events per byte)."""
        return self.e_cap // 2

    @property
    def d_cap(self) -> int:
        return max(64, self.n_slots // INDEL_SLOT_FRACTION)

    @property
    def i_cap(self) -> int:
        return max(64, self.n_slots // INDEL_SLOT_FRACTION)

    @property
    def c_cap(self) -> int:
        """Clip-projection event capacity (realign traffic): soft-clip
        projections are bounded by read bases, so one event per slot is
        a generous budget that still keeps the upload O(n_slots)."""
        return max(128, self.n_slots)

    def key(self) -> tuple:
        """Static geometry identity — the jit/AOT signature component
        (the leading marker keeps it disjoint from every shape-keyed
        lane tuple, so flush identities never collide)."""
        return ("ragged", self.name, self.rows, self.length, self.o_cap,
                self.b_cap, self.d_cap, self.i_cap, self.c_cap)

    def label(self) -> str:
        return f"{self.name}:r{self.rows}xL{self.length}"


def parse_classes(spec: str) -> tuple[PageClass, ...]:
    """Parse a page-class spec string — ``"small:64x2048,medium:32x16384"``
    (name:ROWSxLENGTH, comma-separated) — into classes sorted ascending
    by length (classification picks the first class a unit fits)."""
    classes = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            name, geom = part.split(":")
            rows_s, length_s = geom.lower().split("x")
            classes.append(PageClass(name.strip(), int(rows_s), int(length_s)))
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad page-class spec segment {part!r} "
                "(expected name:ROWSxLENGTH)"
            ) from e
    if not classes:
        raise ValueError(f"page-class spec {spec!r} defines no classes")
    out = tuple(sorted(classes, key=lambda c: (c.length, c.rows)))
    if len({c.name for c in out}) != len(out):
        raise ValueError(f"page-class spec {spec!r} repeats a class name")
    return out


@dataclass(frozen=True)
class Consumption:
    """What one set of units costs a page class, in capacity units."""

    segments: int
    slots: int
    max_stride: int
    spans: int
    events: int
    dels: int
    inss: int
    clips: int = 0  # soft-clip projection events (realign traffic)


def _n_clips(u) -> int:
    csw = getattr(u, "csw_pos", None)
    cew = getattr(u, "cew_pos", None)
    return (0 if csw is None else len(csw)) + (0 if cew is None else len(cew))


def consumption(units) -> Consumption:
    strides = [stride_for(u.L) for u in units]
    return Consumption(
        segments=len(units),
        slots=sum(strides),
        max_stride=max(strides, default=0),
        spans=sum(len(u.op_r_start) for u in units),
        events=sum(u.n_events for u in units),
        dels=sum(len(u.del_pos) for u in units),
        inss=sum(len(u.ins_pos) for u in units),
        clips=sum(_n_clips(u) for u in units),
    )


def fits(need: Consumption, cls: PageClass,
         max_segments: int | None = None) -> bool:
    """Does `need` fit an EMPTY superbatch of `cls`? (The batcher adds
    lane-occupancy on top before asking.)"""
    seg_cap = cls.rows if max_segments is None else min(cls.rows, max_segments)
    return (
        need.segments <= seg_cap
        and need.slots <= cls.n_slots
        and need.max_stride <= cls.length
        and need.spans <= cls.o_cap
        and need.events <= cls.e_cap
        and need.dels <= cls.d_cap
        and need.inss <= cls.i_cap
        and need.clips <= cls.c_cap
    )


def classify_units(units, classes) -> int | None:
    """Index of the smallest page class one request's units fit, or None
    when no class admits them (oversize → the shape-keyed lanes path).
    A request is atomic: all its units ride one superbatch, so routing is
    by the largest unit's stride plus total capacity."""
    need = consumption(units)
    for i, cls in enumerate(classes):
        if need.max_stride <= cls.length and fits(need, cls):
            return i
    return None


@dataclass
class SegmentTable:
    """Per-segment layout of one packed superbatch (numpy int32 arrays,
    all length S = number of real segments): slot offsets/lengths, flat
    stream offsets for events/deletions/insertions, and the request
    back-pointer every result routes home through."""

    page_class: PageClass
    entry_idx: np.ndarray  # request (flush-entry) back-pointer per segment
    seg_start: np.ndarray  # slot offset (GRANULE-aligned)
    seg_len: np.ndarray  # true reference length
    ev_off: np.ndarray  # flat match-event stream offset
    ev_len: np.ndarray
    del_off: np.ndarray  # flat deletion stream offset
    del_len: np.ndarray
    ins_off: np.ndarray  # flat insertion stream offset
    ins_len: np.ndarray

    @property
    def n_segments(self) -> int:
        return len(self.seg_start)

    @property
    def payload_slots(self) -> int:
        return int(self.seg_len.sum())

    @property
    def occupancy(self) -> float:
        """Payload positions / total superbatch slots — the pad-waste
        number the obs metrics and bench's ragged object report."""
        return self.payload_slots / float(self.page_class.n_slots)


def build_segment_table(units, page_class: PageClass) -> SegmentTable:
    """Lay `units` out on the flat slot axis (vectorized; loop-free by
    tier-1 AST guard). Unit order is segment order; `u.sample_idx` is the
    request back-pointer the serve worker assigned at flatten time."""
    n = len(units)
    if n == 0:
        raise ValueError("an empty superbatch has nothing to pack")
    lens = np.fromiter((u.L for u in units), np.int64, count=n)
    strides = (lens // GRANULE + 1) * GRANULE
    seg_start = np.concatenate(([0], np.cumsum(strides)[:-1]))
    ev_len = np.fromiter((u.n_events for u in units), np.int64, count=n)
    del_len = np.fromiter((len(u.del_pos) for u in units), np.int64, count=n)
    ins_len = np.fromiter((len(u.ins_pos) for u in units), np.int64, count=n)
    spans = int(sum(len(u.op_r_start) for u in units))
    table = SegmentTable(
        page_class=page_class,
        entry_idx=np.fromiter(
            (getattr(u, "sample_idx", 0) or 0 for u in units),
            np.int64, count=n,
        ).astype(np.int32),
        seg_start=seg_start.astype(np.int32),
        seg_len=lens.astype(np.int32),
        ev_off=np.concatenate(([0], np.cumsum(ev_len)[:-1])).astype(np.int32),
        ev_len=ev_len.astype(np.int32),
        del_off=np.concatenate(([0], np.cumsum(del_len)[:-1])).astype(np.int32),
        del_len=del_len.astype(np.int32),
        ins_off=np.concatenate(([0], np.cumsum(ins_len)[:-1])).astype(np.int32),
        ins_len=ins_len.astype(np.int32),
    )
    c = page_class
    if (
        n > c.rows
        or int(strides.sum()) > c.n_slots
        or int(strides.max()) > c.length
        or spans > c.o_cap
        or int(ev_len.sum()) > c.e_cap
        or int(del_len.sum()) > c.d_cap
        or int(ins_len.sum()) > c.i_cap
    ):
        raise RaggedCapacityError(
            f"{n} units (slots {int(strides.sum())}, events "
            f"{int(ev_len.sum())}) exceed page class {c.label()}"
        )
    return table


def pack_superbatch(units, table: SegmentTable, realign: bool = False):
    """Concatenate every unit's event tensors into the page class's
    fixed-capacity flat arrays (vectorized; loop-free by tier-1 AST
    guard). Positions are pre-offset by each unit's slot start, so the
    kernel's span reconstruction lands every event in flat coordinates
    with no per-event segment gather.

    Returns the kernel's array arguments:
      (op_r_start[o_cap], op_off[o_cap], base_packed[b_cap],
       del_pos[d_cap], ins_pos[i_cap], ins_cnt[i_cap],
       seg_starts[s_pad], seg_lens[s_pad], n_events)
    plus, under `realign`, the flat clip-projection channels
      (csw_pos[c_cap], csw_base[c_cap], cew_pos[c_cap], cew_base[c_cap]).
    Clip events at positions >= a unit's own reference length are
    dropped at pack time: unlike the row-structured cohort kernel
    (where they scatter into that row's private pad tail) a flat
    over-length position would land in another segment's slots, and no
    decode surface ever reads a clip channel past L (the CDR walk's
    windows are bounded to [0, L))."""
    from kindel_tpu.call_jax import unpack_base_codes

    c = table.page_class
    total_events = int(table.ev_len.sum())

    def flat(parts, cap, fill, dtype=np.int32):
        out = np.full(cap, fill, dtype=dtype)
        if parts:
            arr = np.concatenate(parts)
            out[: len(arr)] = arr
        return out

    op_r_start = flat(
        [u.op_r_start + s for u, s in zip(units, table.seg_start)],
        c.o_cap, PAD_POS,
    )
    # pad spans mark slot `total_events` of the flat event stream — the
    # same sentinel pack_cohort uses per row, so the masked tail of the
    # marks/cumsum span-id reconstruction behaves identically
    op_off = flat(
        [u.op_off + e for u, e in zip(units, table.ev_off)],
        c.o_cap, np.int32(total_events),
    )
    codes = flat(
        [unpack_base_codes(u.base_packed, u.n_events) for u in units],
        c.e_cap, 0, np.uint8,
    )
    base_packed = (codes[0::2] << 4) | codes[1::2]
    del_pos = flat(
        [u.del_pos + s for u, s in zip(units, table.seg_start)],
        c.d_cap, PAD_POS,
    )
    ins_pos = flat(
        [u.ins_pos + s for u, s in zip(units, table.seg_start)],
        c.i_cap, PAD_POS,
    )
    ins_cnt = flat([u.ins_cnt for u in units], c.i_cap, 0)
    seg_starts = np.full(c.s_pad, PAD_POS, np.int32)
    seg_starts[: table.n_segments] = table.seg_start
    seg_lens = np.zeros(c.s_pad, np.int32)
    seg_lens[: table.n_segments] = table.seg_len
    out = (
        op_r_start, op_off, base_packed, del_pos, ins_pos, ins_cnt,
        seg_starts, seg_lens, np.int32(total_events),
    )
    if not realign:
        return out

    def clip_pair(pos_attr, base_attr):
        # see docstring: the in-segment filter keeps the flat scatter
        # from crossing into a neighboring segment's slots
        pairs = [
            (p[keep] + s, getattr(u, base_attr)[keep])
            for u, s in zip(units, table.seg_start)
            if (p := getattr(u, pos_attr, None)) is not None and len(p)
            for keep in ((p < u.L),)
        ]
        return (
            flat([a for a, _ in pairs], c.c_cap, PAD_POS),
            flat([b for _, b in pairs], c.c_cap, 0),
        )

    csw_pos, csw_base = clip_pair("csw_pos", "csw_base")
    cew_pos, cew_base = clip_pair("cew_pos", "cew_base")
    return out + (csw_pos, csw_base, cew_pos, cew_base)
