"""Per-request extraction from one superbatch wire — byte-identical to
the shape-keyed lanes path (pinned by tests/test_ragged.py's parity
suite).

Because every segment sits on an 8-slot granule, each unit's share of
the dense wire planes is a byte-aligned numpy slice; the sparse
deletion/insertion flag planes slice by the segment table's flat stream
offsets. From there the decode is the SAME host code the lanes path
runs (`decode_fast` / `masks_from_wire` / `assemble`), so any divergence
would have to come from the device math — which `ragged/kernel.py`
shares with the cohort kernel position-for-position.
"""

from __future__ import annotations

import numpy as np

from kindel_tpu.call import _insertion_calls, assemble
from kindel_tpu.call_jax import decode_fast, masks_from_wire
from kindel_tpu.io.fasta import Sequence
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.ragged.kernel import wire_sizes


def unpack_superbatch(buf, table, units, opts, pool, paths=None) -> list:
    """Download one superbatch wire and splice per-unit results (host,
    thread-parallel) — the ragged counterpart of
    `batch._assemble_outputs`, returning the same (Sequence,
    changes|None, report|None) per unit, in unit order."""
    buf = np.asarray(buf)  # blocks on the device→host copy
    obs_runtime.transfer_counters()[1].inc(int(buf.nbytes))
    cls = table.page_class
    sizes = wire_sizes(cls, opts.want_masks)
    offs = np.cumsum([0] + sizes)
    segs = [buf[offs[k]: offs[k + 1]] for k in range(len(sizes))]
    seg_dmin = np.frombuffer(segs[-2].tobytes(), np.int32)
    seg_dmax = np.frombuffer(segs[-1].tobytes(), np.int32)
    if opts.want_masks:
        emit_w, del_b, n_b, ins_b = segs[:4]
    else:
        plane_w, exc_w, del_f, ins_f = segs[:4]
        # one unpack of the flat sparse-flag planes; per-unit slices
        # repack for decode_fast (tiny arrays — a few flags per unit)
        del_bits = np.unpackbits(del_f)
        ins_bits = np.unpackbits(ins_f)

    def one(i_u):
        i, u = i_u
        o = int(table.seg_start[i])
        L = u.L
        if opts.want_masks:
            emit_s = emit_w[o // 2: o // 2 + -(-L // 2)]
            masks_s = tuple(
                b[o // 8: o // 8 + -(-L // 8)] for b in (del_b, n_b, ins_b)
            )
            _emit, masks = masks_from_wire(emit_s, masks_s, L)
        else:
            d0, dn = int(table.del_off[i]), int(table.del_len[i])
            i0, inn = int(table.ins_off[i]), int(table.ins_len[i])
            masks = decode_fast(
                plane_w[o // 4: o // 4 + -(-L // 4)],
                exc_w[o // 8: o // 8 + -(-L // 8)],
                np.packbits(del_bits[d0: d0 + dn]),
                np.packbits(ins_bits[i0: i0 + inn]),
                L, u.del_pos, u.ins_pos,
            )
        ins_calls = (
            _insertion_calls(u.ins_table) if masks.ins_mask.any() else {}
        )
        res = assemble(
            masks, ins_calls, u.cdr_patches, opts.trim_ends,
            opts.min_depth, opts.uppercase,
            build_changes=opts.want_masks,
        )
        seq = Sequence(name=f"{u.ref_id}_cns", sequence=res.sequence)
        changes = res.changes if opts.build_changes else None
        report = None
        if opts.build_reports:
            from kindel_tpu.workloads import build_report

            report = build_report(
                u.ref_id, int(seg_dmin[i]), int(seg_dmax[i]), res.changes,
                u.cdr_patches, paths[u.sample_idx], opts.realign,
                opts.min_depth, opts.min_overlap,
                opts.clip_decay_threshold, opts.trim_ends, opts.uppercase,
            )
        return seq, changes, report

    return list(pool.map(one, enumerate(units)))
