"""Per-request extraction from one superbatch wire — byte-identical to
the shape-keyed lanes path (pinned by tests/test_ragged.py's parity
suite).

Because every segment sits on an 8-slot granule, each unit's share of
the dense wire planes is a byte-aligned numpy slice; the sparse
deletion/insertion flag planes slice by the segment table's flat stream
offsets. From there the decode is the SAME host code the lanes path
runs (`decode_fast` / `masks_from_wire` / `assemble`), so any divergence
would have to come from the device math — which `ragged/kernel.py`
shares with the cohort kernel position-for-position.

Realign traffic adds two trigger bitplanes to the wire and keeps the
dense (weights, deletions, csw, cew) tensors device-resident; the CDR
walk reads them through `SegmentCdrFetcher` — segment-windowed
dynamic-slice fetches into the FLAT tensors, the ragged counterpart of
the cohort path's `_RowCdrFetcher` (a few KB per clip-dominant region,
never a dense download).

`unpack_rows` extracts an arbitrary subset of segments, which is what
the paged pileup (kindel_tpu.paged) uses: a launch computes every
RESIDENT segment, but only the segments newly bound to requests are
extracted and settled — cached reference-panel segments ride along
unread.
"""

from __future__ import annotations

from functools import partial

from kindel_tpu.utils.jax_cache import ensure_compilation_cache

ensure_compilation_cache()

import jax
import jax.numpy as jnp
import numpy as np

from kindel_tpu.call import _insertion_calls, assemble
from kindel_tpu.call_jax import decode_fast, masks_from_wire
from kindel_tpu.emit import masks_from_emit_plane
from kindel_tpu.io.fasta import Sequence
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.pileup_jax import _bucket
from kindel_tpu.ragged.kernel import wire_sizes
from kindel_tpu.realign import LazyCdrWindows


@partial(jax.jit, static_argnames=("chunk",))
def _fetch_flat2d(arr, start, *, chunk: int):
    return jax.lax.dynamic_slice(arr, (start, 0), (chunk, arr.shape[1]))


@partial(jax.jit, static_argnames=("chunk",))
def _fetch_flat1d(arr, start, *, chunk: int):
    return jax.lax.dynamic_slice(arr, (start,), (chunk,))


class SegmentCdrFetcher(LazyCdrWindows):
    """Lazy CDR-window access into one segment's span of the FLAT
    device-resident channel tensors: fetches are dynamic slices at
    `seg_start + start`, bounded to the segment's stride (which always
    covers [0, L] plus zero-depth gap slots, so a clamped window never
    reads a neighboring segment)."""

    def __init__(self, dense, seg_start: int, stride: int, L: int):
        weights, deletions, csw, cew = dense
        self._arrs = {
            "weights": weights, "deletions": deletions,
            "csw": csw, "cew": cew,
        }
        self._base = int(seg_start)
        self.L = int(L)
        self.Lp = int(stride)
        self._chunk = min(4096, self.Lp)

    def _fetch(self, key: str, start: int) -> np.ndarray:
        from kindel_tpu.parallel import meshexec

        arr = self._arrs[key]

        def classic():
            fetch = _fetch_flat2d if arr.ndim == 2 else _fetch_flat1d
            return np.asarray(
                fetch(arr, jnp.int32(self._base + start),
                      chunk=self._chunk)
            )

        # dp-sharded flat tensors: stitch the window from the owning
        # shard(s) instead of the whole-tensor-resharding jit slice
        # (kindel_tpu.parallel.meshexec — the sharded-CDR-fetch fix)
        win = meshexec.fetch_window_flat(
            arr, self._base + start, self._chunk, classic
        )
        obs_runtime.transfer_counters()[1].inc(int(win.nbytes))
        return win

    def _empty(self, key: str) -> np.ndarray:
        return np.empty((0,) + self._arrs[key].shape[1:], np.int32)


def unpack_rows(out, table, row_units, opts, pool, paths=None) -> list:
    """Download one superbatch wire and splice results for the given
    `(row, unit)` pairs (host, thread-parallel) — the subset form of
    `unpack_superbatch`, returning the same (Sequence, changes|None,
    report|None) per pair, in pair order. `out` is launch_ragged's
    result: the wire buffer, or the (wire, weights, deletions, csw,
    cew) tuple under realign.

    With no pairs to read, NOTHING crosses the link: a paged tick whose
    resident set is all cached panel segments (amplicon replays) must
    not pay a whole-grid wire download for a result nobody extracts.

    Under device emission (--emit-mode device, kindel_tpu.emit) the
    ASCII plane downloads per segment (subset ticks) or as one payload
    prefix (whole-superbatch unpack) plus the small sparse tail — d2h
    is O(extracted consensus length), never the page grid's wire
    planes."""
    if not row_units:
        return []
    if opts.realign:
        wire, *dense = out
    else:
        wire, dense = out, None
    cls = table.page_class
    emit = opts.emit_device
    sizes = wire_sizes(cls, opts.want_masks, opts.realign, emit)
    offs = np.cumsum([0] + sizes)
    d2h = obs_runtime.transfer_counters()[1]
    if emit:
        n = cls.n_slots
        # sparse tail (packed insertion flags [+ trigger planes] + the
        # per-segment depth scalars) in ONE fetch; segs[0] (the plane)
        # never downloads whole — plane_for below fetches windows
        tail = np.asarray(wire[n:])
        d2h.inc(int(tail.nbytes))
        segs = [None] + [
            tail[offs[k] - n: offs[k + 1] - n]
            for k in range(1, len(sizes))
        ]
        subset = len(row_units) < table.n_segments
        prefix = None
        if not subset:
            end = int((table.seg_start + table.seg_len).max())
            chunk = min(_bucket(max(end, 8), 8), n)
            prefix = np.asarray(
                _fetch_flat1d(wire, jnp.int32(0), chunk=chunk)
            )
            d2h.inc(int(prefix.nbytes))

        def plane_for(o: int, L: int) -> np.ndarray:
            if prefix is not None:
                return prefix[o: o + L]
            # dynamic_slice clamps the start so the window always fits
            # the grid — index the segment's bytes relative to the
            # clamped origin
            chunk = min(_bucket(max(L, 8), 8), n)
            eff = min(o, n - chunk)
            win = np.asarray(
                _fetch_flat1d(wire, jnp.int32(eff), chunk=chunk)
            )
            d2h.inc(int(win.nbytes))
            return win[o - eff: o - eff + L]
    else:
        buf = np.asarray(wire)  # blocks on the device→host copy
        d2h.inc(int(buf.nbytes))
        segs = [buf[offs[k]: offs[k + 1]] for k in range(len(sizes))]
    seg_dmin = np.frombuffer(segs[-2].tobytes(), np.int32)
    seg_dmax = np.frombuffer(segs[-1].tobytes(), np.int32)
    if opts.realign:
        trig_f_w, trig_r_w = segs[-4], segs[-3]
    if emit:
        ins_bits = np.unpackbits(segs[1])
    elif opts.want_masks:
        emit_w, del_b, n_b, ins_b = segs[:4]
    else:
        plane_w, exc_w, del_f, ins_f = segs[:4]
        # one unpack of the flat sparse-flag planes; per-unit slices
        # repack for decode_fast (tiny arrays — a few flags per unit)
        del_bits = np.unpackbits(del_f)
        ins_bits = np.unpackbits(ins_f)

    def one(pair):
        i, u = pair
        o = int(table.seg_start[i])
        L = u.L
        if opts.realign:
            # byte-aligned by the 8-slot granule: this segment's trigger
            # bits are a plain byte slice of the flat planes
            trig_f = np.flatnonzero(
                np.unpackbits(trig_f_w[o // 8: o // 8 + -(-L // 8)])[:L]
            )
            trig_r = np.flatnonzero(
                np.unpackbits(trig_r_w[o // 8: o // 8 + -(-L // 8)])[:L]
            )
            from kindel_tpu.ragged.pack import stride_for

            u.cdr_patches = SegmentCdrFetcher(
                dense, o, stride_for(L), L
            ).cdr_patches_from_triggers(
                trig_f, trig_r, opts.clip_decay_threshold,
                opts.mask_ends, opts.min_overlap, max_gap=opts.cdr_gap,
                flank_dedup=opts.fix_clip_artifacts,
                min_depth=opts.min_depth,
            )
        if emit:
            i0, inn = int(table.ins_off[i]), int(table.ins_len[i])
            masks = masks_from_emit_plane(
                plane_for(o, L), np.packbits(ins_bits[i0: i0 + inn]),
                L, u.ins_pos,
            )
        elif opts.want_masks:
            emit_s = emit_w[o // 2: o // 2 + -(-L // 2)]
            masks_s = tuple(
                b[o // 8: o // 8 + -(-L // 8)] for b in (del_b, n_b, ins_b)
            )
            _emit, masks = masks_from_wire(emit_s, masks_s, L)
        else:
            d0, dn = int(table.del_off[i]), int(table.del_len[i])
            i0, inn = int(table.ins_off[i]), int(table.ins_len[i])
            masks = decode_fast(
                plane_w[o // 4: o // 4 + -(-L // 4)],
                exc_w[o // 8: o // 8 + -(-L // 8)],
                np.packbits(del_bits[d0: d0 + dn]),
                np.packbits(ins_bits[i0: i0 + inn]),
                L, u.del_pos, u.ins_pos,
            )
        ins_calls = (
            _insertion_calls(u.ins_table) if masks.ins_mask.any() else {}
        )
        res = assemble(
            masks, ins_calls, u.cdr_patches, opts.trim_ends,
            opts.min_depth, opts.uppercase,
            build_changes=opts.want_masks,
        )
        seq = Sequence(name=f"{u.ref_id}_cns", sequence=res.sequence)
        changes = res.changes if opts.build_changes else None
        report = None
        if opts.build_reports:
            from kindel_tpu.workloads import build_report

            report = build_report(
                u.ref_id, int(seg_dmin[i]), int(seg_dmax[i]), res.changes,
                u.cdr_patches, paths[u.sample_idx], opts.realign,
                opts.min_depth, opts.min_overlap,
                opts.clip_decay_threshold, opts.trim_ends, opts.uppercase,
            )
        return seq, changes, report

    return list(pool.map(one, row_units))


def unpack_superbatch(out, table, units, opts, pool, paths=None) -> list:
    """Extraction of EVERY table row, in unit order — the ragged
    counterpart of `batch._assemble_outputs` (see unpack_rows)."""
    return unpack_rows(out, table, list(enumerate(units)), opts, pool,
                       paths=paths)
