"""kindel_tpu.ragged — segment-table superbatching for the serve tier.

The shape-keyed micro-batcher (kindel_tpu/serve/batcher.py) keys its
coalescing lanes on per-flush pad shapes, so shape-diverse traffic
fragments into low-occupancy lanes and one compiled kernel per shape.
This package replaces that with pack-don't-pad superbatching in the
style of ragged paged attention (PAPERS.md): variable-length request
units pack end-to-end into ONE fixed-geometry slot axis with a segment
table, and a segment-aware call kernel whose jit signature depends only
on the superbatch geometry serves *all* request shapes — a handful of
tuned page classes, a handful of compiled (and AOT-exportable)
executables, arbitrary traffic.

    pack.py     page classes, segment table, vectorized superbatch packer
    kernel.py   segment-aware flat call kernel (+ gated Pallas reduction)
    unpack.py   per-request extraction, byte-identical to the lanes path
    batcher.py  RaggedBatcher — the MicroBatcher flush contract, superbatched
"""

from kindel_tpu.ragged.batcher import RaggedBatcher, RaggedFlush
from kindel_tpu.ragged.pack import (
    PageClass,
    RaggedCapacityError,
    SegmentTable,
    build_segment_table,
    classify_units,
    pack_superbatch,
    parse_classes,
)

__all__ = [
    "PageClass",
    "RaggedBatcher",
    "RaggedCapacityError",
    "RaggedFlush",
    "SegmentTable",
    "build_segment_table",
    "classify_units",
    "pack_superbatch",
    "parse_classes",
]
