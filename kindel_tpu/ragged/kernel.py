"""Segment-aware flat call kernel: one executable per page class.

The ragged kernel is the cohort kernel's scatter run ONCE over the
superbatch's flat slot axis instead of vmapped over per-sample rows.
Because `pack.py` pre-offsets every uploaded position by its segment's
slot start, the span-id reconstruction, the weighted scatter, and every
per-position call decision in `call_jax._call_core` apply verbatim with
`length = n_slots` — the decision logic is literally shared, which is
what makes ragged output byte-identical to the lanes path. The only
genuinely segment-aware step is the per-request depth report:
min/max coverage reduce **per segment** via segment_ids built on device
from the uploaded segment table (`segment_min`/`segment_max` — the
segment_sum-style reduction PAPERS.md "Ragged Paged Attention" packs
its pages with), with a Pallas block-tiled reduction as a gated fast
path on accelerator backends (same gate shape as
`call_jax._use_compact_wire`; `KINDEL_TPU_RAGGED_PALLAS` overrides,
interpret mode serves CPU tests).

The jit signature depends only on (page-class geometry, want_masks):
every request shape a class admits re-dispatches the same compiled —
and AOT-exportable (`kindel_tpu.aot.export_ragged`) — executable.

Wire layout (single uint8 buffer, one d2h transfer; `wire_sizes` is the
decoder's source of truth):

  fast path:  [plane n_slots/4 | exc n_slots/8 | del_flags d_cap/8 |
               ins_flags i_cap/8 | seg_dmin 4·s_pad | seg_dmax 4·s_pad]
  masks path: [emit n_slots/2 | del n/8 | n n/8 | ins n/8 |
               seg_dmin 4·s_pad | seg_dmax 4·s_pad]
"""

from __future__ import annotations

from functools import partial

from kindel_tpu.utils.jax_cache import ensure_compilation_cache

ensure_compilation_cache()

import jax
import jax.numpy as jnp
import numpy as np

from kindel_tpu.call_jax import _call_core
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs import trace as obs_trace
from kindel_tpu.resilience import faults as rfaults

_INT32_MAX = np.int32(2**31 - 1)

#: slot-block width of the Pallas segment reduction (page-class lengths
#: are multiples of 1024, so n_slots always divides)
_PALLAS_BLOCK = 1024


def use_pallas_segments() -> bool:
    """Gate of the Pallas segment-reduction fast path, resolved on the
    host at launch time (never inside the traced body — tier-1 guard):
    KINDEL_TPU_RAGGED_PALLAS=1/0 overrides; default on only off-CPU,
    where the block-tiled reduction beats XLA's generic segment scatter.
    On CPU the override runs the kernel in interpret mode (tests)."""
    import os

    override = os.environ.get("KINDEL_TPU_RAGGED_PALLAS")
    if override is not None:
        return override not in ("0", "")
    return jax.default_backend() != "cpu"


def _segment_depth_xla(acgt, slot_seg, in_ref, s_pad: int):
    """Per-segment min/max ACGT depth via jax.ops segment reductions.
    `in_ref` is the per-slot membership mask (slot inside its segment's
    true reference span) computed once by the caller."""
    dmin = jax.ops.segment_min(
        jnp.where(in_ref, acgt, _INT32_MAX), slot_seg, num_segments=s_pad
    )
    dmax = jax.ops.segment_max(
        jnp.where(in_ref, acgt, -1), slot_seg, num_segments=s_pad
    )
    # pad segments (no slots at all) take the reduction identities;
    # clamp the max identity (-2**31) to the -1 the Pallas path's
    # accumulator init uses, so the two fast paths emit one wire
    return dmin, jnp.maximum(dmax, -1)


def _pallas_seg_kernel(depth_ref, seg_ref, in_ref_ref, dmin_ref, dmax_ref,
                       *, s_tile: int):
    """One grid step: fold a slot block's depths into the running
    per-segment min/max (output block revisited across the sequential
    TPU grid — init at step 0, accumulate after)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dmin_ref[...] = jnp.full((1, s_tile), _INT32_MAX, jnp.int32)
        dmax_ref[...] = jnp.full((1, s_tile), -1, jnp.int32)

    depth = depth_ref[0, :]
    seg = seg_ref[0, :]
    in_ref = in_ref_ref[0, :] != 0
    # [BLOCK, S] one-hot segment membership → masked column reductions
    sid = jax.lax.broadcasted_iota(jnp.int32, (_PALLAS_BLOCK, s_tile), 1)
    mask = (seg[:, None] == sid) & in_ref[:, None]
    dmin_ref[...] = jnp.minimum(
        dmin_ref[...],
        jnp.where(mask, depth[:, None], _INT32_MAX).min(axis=0)[None, :],
    )
    dmax_ref[...] = jnp.maximum(
        dmax_ref[...],
        jnp.where(mask, depth[:, None], -1).max(axis=0)[None, :],
    )


def _segment_depth_pallas(acgt, slot_seg, in_ref, s_pad: int):
    """Pallas fast path of the per-segment depth reduction: grid over
    slot blocks, [BLOCK, S]-masked min/max per step, running fold into a
    revisited [1, S] output. Segment axis padded to a lane-friendly
    multiple of 128; interpret mode on CPU (the gate only reaches here
    off-CPU or under the env override)."""
    from jax.experimental import pallas as pl

    n_slots = int(acgt.shape[0])
    s_tile = max(128, -(-s_pad // 128) * 128)
    grid = n_slots // _PALLAS_BLOCK
    interpret = jax.default_backend() == "cpu"
    # in_ref, per slot, is what the block mask needs; the seg axis is
    # padded with an id (s_tile - 1 >= s_pad) no real slot carries
    dmin, dmax = pl.pallas_call(
        partial(_pallas_seg_kernel, s_tile=s_tile),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _PALLAS_BLOCK), lambda i: (0, i)),
            pl.BlockSpec((1, _PALLAS_BLOCK), lambda i: (0, i)),
            pl.BlockSpec((1, _PALLAS_BLOCK), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, s_tile), lambda i: (0, 0)),
            pl.BlockSpec((1, s_tile), lambda i: (0, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((1, s_tile), jnp.int32)] * 2,
        interpret=interpret,
    )(acgt[None, :], slot_seg[None, :],
      in_ref.astype(jnp.int32)[None, :])
    return dmin[0, :s_pad], dmax[0, :s_pad]


@partial(
    jax.jit,
    static_argnames=("n_slots", "s_pad", "want_masks", "realign",
                     "emit", "pallas_segments"),
)
def ragged_call_kernel(op_r_start, op_off, base_packed, del_pos, ins_pos,
                       ins_cnt, seg_starts, seg_lens, n_events, min_depth,
                       flags=0, csw_pos=None, csw_base=None, cew_pos=None,
                       cew_base=None, *, n_slots: int, s_pad: int,
                       want_masks: bool = False, realign: bool = False,
                       emit: bool = False,
                       pallas_segments: bool = False):
    """Scatter + call every packed segment of one superbatch; see the
    module docstring for the wire layout. Static only in the page-class
    geometry (array shapes + n_slots/s_pad) and the wire variant.

    Under `realign` the flat clip-projection channels scatter exactly
    like the cohort realign kernel's per-row ones (positions pre-offset
    by pack.py, so the same integer-exact dominance triggers
    2·csd > w+d+1 apply per slot), two trigger bitplanes join the wire,
    and the dense (weights, deletions, csw, cew) tensors are returned
    device-resident for the segment-windowed CDR fetches — the output
    tuple mirrors `batched_realign_call_kernel`.

    Under `emit` (--emit-mode device, kindel_tpu.emit) the wire's call
    segments are [ascii n_slots | ins_flags i_cap/8] — one rendered
    byte per slot, so per-request extraction is a plain (dynamic-slice)
    fetch of O(consensus length) instead of a whole-grid wire
    download."""
    out = _call_core(
        op_r_start, op_off, base_packed, del_pos, ins_pos, ins_cnt,
        n_events, min_depth, n_slots, want_masks, keep_dense=True,
        flags=flags, emit_ascii=emit,
    )
    (main, parts, _dmin, _dmax), (weights, deletions) = out[:4], out[4:]

    # segment ids + in-reference bounds from the uploaded segment table:
    # boundary scatter + prefix sum, the same trick the span-id
    # reconstruction uses (pad seg_starts carry PAD_POS → dropped). The
    # membership mask checks BOTH bounds: a paged pool may leave leading
    # or interior pages free, so a slot below its attributed segment's
    # start is free space, not segment 0 (ragged superbatches always
    # start at slot 0 — the lower bound is vacuous there).
    acgt = weights[:, :4].sum(axis=1)
    marks = jnp.zeros(n_slots, jnp.int32).at[seg_starts].add(1, mode="drop")
    slot_seg = jnp.clip(jnp.cumsum(marks) - 1, 0, s_pad - 1)
    slot = jnp.arange(n_slots, dtype=jnp.int32)
    in_ref = (slot >= seg_starts[slot_seg]) & (
        slot < (seg_starts + seg_lens)[slot_seg]
    )
    seg_fn = _segment_depth_pallas if pallas_segments else _segment_depth_xla
    seg_dmin, seg_dmax = seg_fn(acgt, slot_seg, in_ref, s_pad)

    extra = ()
    if realign:
        # flat clip-channel scatter + per-slot dominance triggers —
        # the decision math is shared with the cohort realign kernel
        # verbatim (reference kindel.py:182-185,229-238); in_ref plays
        # the per-row valid mask's role
        def clip_scatter(p, b):
            return (
                jnp.zeros(n_slots * weights.shape[1], jnp.int32)
                .at[p * weights.shape[1] + b]
                .add(1, mode="drop")
                .reshape(n_slots, weights.shape[1])
            )

        csw = clip_scatter(csw_pos, csw_base)
        cew = clip_scatter(cew_pos, cew_base)
        denom = weights.sum(axis=1) + deletions + 1
        trig_f = jnp.packbits((2 * csw[:, :4].sum(axis=1) > denom) & in_ref)
        trig_r = jnp.packbits((2 * cew[:, :4].sum(axis=1) > denom) & in_ref)
        parts = tuple(parts) + (trig_f, trig_r)
        extra = (weights, deletions, csw, cew)

    segs = [main]
    segs.extend(
        p if p.dtype == jnp.uint8 else jnp.packbits(p) for p in parts
    )
    segs.append(
        jax.lax.bitcast_convert_type(seg_dmin, jnp.uint8).reshape(-1)
    )
    segs.append(
        jax.lax.bitcast_convert_type(seg_dmax, jnp.uint8).reshape(-1)
    )
    wire = jnp.concatenate(segs)
    if realign:
        return (wire,) + extra
    return wire


def wire_sizes(page_class, want_masks: bool,
               realign: bool = False, emit: bool = False) -> list[int]:
    """Byte sizes of the ragged wire's segments, in producer order —
    the single source of truth `unpack.py` slices by. Under `realign`
    two n_slots/8 trigger bitplanes ride between the call segments and
    the per-segment depth scalars; under `emit` the call segments are
    the rendered ASCII plane + packed insertion flags
    (kindel_tpu.emit)."""
    n = page_class.n_slots
    if emit:
        sizes = [n, -(-page_class.i_cap // 8)]
    elif want_masks:
        sizes = [n // 2, n // 8, n // 8, n // 8]
    else:
        sizes = [n // 4, n // 8, -(-page_class.d_cap // 8),
                 -(-page_class.i_cap // 8)]
    if realign:
        sizes += [n // 8, n // 8]
    return sizes + [4 * page_class.s_pad, 4 * page_class.s_pad]


def launch_ragged(arrays, page_class, opts):
    """Upload one packed superbatch and launch the segment kernel
    (async, like every dispatch site). Consults the AOT registry first
    (kindel_tpu.aot — serve warmup loads/exports page-class executables
    exactly as it does lane shapes); a miss or rejected call runs the
    jit kernel, byte-identically. Under realign `arrays` carries the
    four clip channels (pack_superbatch realign=True) and the result is
    the (wire, weights, deletions, csw, cew) tuple."""
    from kindel_tpu import aot

    rfaults.hook("device.dispatch")
    h2d_bytes = sum(int(np.asarray(a).nbytes) for a in arrays)
    obs_runtime.transfer_counters()[0].inc(h2d_bytes)
    pallas = use_pallas_segments()
    with obs_trace.span("ragged.launch") as sp:
        dev = aot.ragged_args(arrays, opts)
        out = aot.call(
            aot.ragged_sig(page_class.key(), opts.want_masks,
                           opts.realign, opts.emit_device),
            dev,
        )
        aot_hit = out is not None
        if out is None:
            out = ragged_call_kernel(
                *dev, n_slots=page_class.n_slots, s_pad=page_class.s_pad,
                want_masks=opts.want_masks, realign=opts.realign,
                emit=opts.emit_device, pallas_segments=pallas,
            )
        if sp is not obs_trace.NOOP_SPAN:
            sp.set_attribute(
                page_class=page_class.label(), n_slots=page_class.n_slots,
                h2d_bytes=h2d_bytes, aot=aot_hit, pallas=pallas,
                realign=opts.realign, emit=opts.emit_device,
            )
    return out
