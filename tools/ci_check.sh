#!/usr/bin/env bash
# CI gate for the kindel_tpu repo: static hygiene + perf regression.
#
#   tools/ci_check.sh            # lint --strict, then perf --gate
#   tools/ci_check.sh --self-test  # additionally prove the perf gate
#                                  # FIRES on the committed regressed
#                                  # fixture (exits nonzero if it
#                                  # silently passes a known-bad line)
#
# Both stages run on CPU (JAX_PLATFORMS=cpu) so the gate is identical
# on dev boxes and accelerator-less CI runners.
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

export JAX_PLATFORMS=cpu

echo "== kindel lint --strict =="
python -m kindel_tpu.cli lint --strict

echo "== kindel perf --gate =="
python -m kindel_tpu.cli perf --gate

if [[ "${1:-}" == "--self-test" ]]; then
    echo "== perf gate self-test (regressed fixture must FAIL) =="
    if python -m kindel_tpu.cli perf --gate \
        --line tools/perfgate_regressed_fixture.json; then
        echo "self-test FAILED: gate passed a known-regressed line" >&2
        exit 1
    fi
    echo "self-test ok: gate fired on the regressed fixture"
fi

echo "ci_check: all stages green"
