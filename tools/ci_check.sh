#!/usr/bin/env bash
# CI gate for the kindel_tpu repo: static hygiene + perf regression.
#
#   tools/ci_check.sh            # lint --strict, then perf --gate
#   tools/ci_check.sh --self-test  # additionally prove the perf gate
#                                  # FIRES on the committed regressed
#                                  # fixture (exits nonzero if it
#                                  # silently passes a known-bad line)
#
# Both stages run on CPU (JAX_PLATFORMS=cpu) so the gate is identical
# on dev boxes and accelerator-less CI runners.
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

export JAX_PLATFORMS=cpu

echo "== kindel lint --strict =="
python -m kindel_tpu.cli lint --strict

echo "== pod two-process smoke (DESIGN.md §27) =="
# an actual localhost 2-process JAX group through the pod data plane:
# both workers must come up from the knob surface alone and produce
# identical digests across all three dispatch tiers (~15 s on CPU)
SMOKE_TMP="$(mktemp -d)"
trap 'rm -rf "$SMOKE_TMP"' EXIT
python - "$SMOKE_TMP" <<'PY'
import sys

sys.path.insert(0, "tests")
sys.path.insert(0, ".")
import distfixture

outs = distfixture.run_two_process(
    "tests/_dist_pod_worker.py", extra_argv=(2, sys.argv[1])
)
digests = []
for rc, out, err in outs:
    assert rc == 0, err[-2000:]
    digests.append(sorted(
        line for line in out.splitlines() if line.startswith("DIGEST:")
    ))
assert digests[0] and digests[0] == digests[1], "pod workers disagree"
print("pod smoke ok:", *digests[0], sep="\n  ")
PY

echo "== kindel perf --gate =="
python -m kindel_tpu.cli perf --gate

if [[ "${1:-}" == "--self-test" ]]; then
    echo "== perf gate self-test (regressed fixture must FAIL) =="
    if python -m kindel_tpu.cli perf --gate \
        --line tools/perfgate_regressed_fixture.json; then
        echo "self-test FAILED: gate passed a known-regressed line" >&2
        exit 1
    fi
    echo "self-test ok: gate fired on the regressed fixture"
fi

echo "ci_check: all stages green"
