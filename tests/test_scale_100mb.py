"""Scale headroom past the 6.1 Mb corpus (VERDICT r2 item 8): a 100 Mb
reference through the streamed × sharded path.

Pins the int32 flat-index arithmetic (L·N_CHANNELS ≈ 5·10⁸, inside the
guard but far past any corpus file), the block/packbits alignment math
of the product path at 12.5 M-position shards, and bounded host memory.
Cross-path correctness: the 8-shard mesh run must equal the
single-device streamed run (independently computed reductions).

Slow (~minutes): gated behind KINDEL_TPU_RUN_SLOW=1 so the default
suite stays fast; `benchmarks/rss_stream.py --ref-len 100000000` is the
measured counterpart recorded in BASELINE.md.
"""

import hashlib
import importlib.util
import os
from pathlib import Path

import pytest

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not os.environ.get("KINDEL_TPU_RUN_SLOW"),
        reason="100 Mb scale test: set KINDEL_TPU_RUN_SLOW=1",
    ),
]

REF_LEN = 100_000_000


def _synthesize(bam: Path, target_bytes: int) -> None:
    spec = importlib.util.spec_from_file_location(
        "rss_stream",
        Path(__file__).resolve().parent.parent / "benchmarks" / "rss_stream.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.synthesize(bam, target_bytes, ref_len=REF_LEN)


def test_100mb_streamed_sharded_matches_single_device(tmp_path, monkeypatch):
    import jax

    from kindel_tpu.streaming import streamed_consensus

    assert len(jax.devices()) >= 2, "virtual mesh missing"
    # the meshed leg must actually shard — a shell-exported FORCE_FUSED
    # would silently make both legs single-device (test vacuity)
    monkeypatch.delenv("KINDEL_TPU_FORCE_FUSED", raising=False)
    bam = tmp_path / "synth100mb.bam"
    _synthesize(bam, 48 << 20)  # ~200k reads x 140 bp over 100 Mb

    meshed = streamed_consensus(bam, backend="jax", chunk_bytes=32 << 20)
    seq_m = meshed.consensuses[0].sequence
    assert len(seq_m) == REF_LEN

    monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", "1")
    single = streamed_consensus(bam, backend="jax", chunk_bytes=32 << 20)
    seq_s = single.consensuses[0].sequence

    hm = hashlib.sha256(seq_m.encode()).hexdigest()
    hs = hashlib.sha256(seq_s.encode()).hexdigest()
    assert hm == hs, "sharded 100 Mb output diverged from single-device"
    assert meshed.refs_reports == single.refs_reports
