"""Online serving (kindel_tpu.serve): correctness, coalescing, admission
control, isolation, and the HTTP surface — all on synthetic SAM cohorts
(no golden corpus needed) over the CPU backend's multi-threaded harness.

The deterministic components (queue, batcher) are tested directly; the
assembled service is tested end-to-end against the bam_to_consensus
oracle, including the acceptance property that concurrent independent
requests coalesce into one device dispatch (batch occupancy > 1).
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from kindel_tpu.batch import BatchOptions
from kindel_tpu.serve import (
    AdmissionError,
    ConsensusClient,
    ConsensusService,
    DeadlineExceeded,
    MetricsRegistry,
    MicroBatcher,
    RequestQueue,
    ServeRequest,
)
from kindel_tpu.serve.worker import decode_request
from kindel_tpu.workloads import bam_to_consensus

MINI = Path(__file__).parent / "data" / "mini.sam"


def make_sam(dest: Path, *, ref: str = "refA", L: int = 400,
             n_reads: int = 40, seed: int = 0) -> Path:
    """Synthetic single-reference SAM with matches, deletions, insertions
    and soft clips — enough signal that different seeds give different
    consensuses."""
    rng = np.random.default_rng(seed)
    lines = ["@HD\tVN:1.6", f"@SQ\tSN:{ref}\tLN:{L}"]
    for i in range(n_reads):
        pos = int(rng.integers(0, L - 60))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=60))
        cigar = ("30M2D28M2S", "60M", "28M4I28M")[i % 3]
        lines.append(
            f"r{i}\t0\t{ref}\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*"
        )
    dest.write_text("\n".join(lines) + "\n")
    return dest


def _units_for(payload, **opt_kwargs):
    req = ServeRequest(payload=payload, opts=BatchOptions(**opt_kwargs))
    return req, decode_request(req)


# --------------------------------------------------------------- components


def test_batcher_flushes_on_max_wait_with_single_request():
    """A lone request must not wait for a batch that never fills: the
    oldest-lane age trigger flushes it after max_wait_s."""
    mb = MicroBatcher(max_batch_rows=64, max_wait_s=0.08)
    req, units = _units_for(str(MINI))
    t0 = time.monotonic()
    mb.add(req, units)
    flush = mb.poll(timeout=5.0)
    waited = time.monotonic() - t0
    assert flush is not None
    assert [r for r, _ in flush.entries] == [req]
    assert waited >= 0.08 * 0.8, f"flushed too early ({waited:.3f}s)"
    assert waited < 2.0, f"max-wait flush overshot ({waited:.3f}s)"


def test_batcher_flushes_immediately_on_batch_full():
    mb = MicroBatcher(max_batch_rows=2, max_wait_s=30.0)
    r1, u1 = _units_for(str(MINI))
    r2, u2 = _units_for(str(MINI))
    mb.add(r1, u1)
    mb.add(r2, u2)
    flush = mb.poll(timeout=0.5)  # far below max_wait: full-lane trigger
    assert flush is not None and len(flush.entries) == 2
    assert flush.n_rows == 2


def test_batcher_lanes_split_by_options():
    """Requests with different call options must never share a device
    dispatch."""
    mb = MicroBatcher(max_batch_rows=2, max_wait_s=0.01)
    r1, u1 = _units_for(str(MINI), min_depth=1)
    r2, u2 = _units_for(str(MINI), min_depth=2)
    mb.add(r1, u1)
    mb.add(r2, u2)
    flushes = [mb.poll(timeout=2.0), mb.poll(timeout=2.0)]
    assert all(f is not None and len(f.entries) == 1 for f in flushes)
    depths = sorted(f.opts.min_depth for f in flushes)
    assert depths == [1, 2]


def test_admission_rejects_past_watermark_and_recovers():
    reg = MetricsRegistry()
    q = RequestQueue(max_depth=8, high_watermark=2, metrics=reg)
    opts = BatchOptions()
    q.submit(ServeRequest(payload="a", opts=opts))
    q.submit(ServeRequest(payload="b", opts=opts))
    with pytest.raises(AdmissionError) as exc:
        q.submit(ServeRequest(payload="c", opts=opts))
    assert exc.value.retry_after_s > 0
    assert reg.snapshot()["kindel_serve_admission_rejects_total"] == 1
    # recovery: drain one, admission reopens
    assert q.get(timeout=1.0).payload == "a"
    q.submit(ServeRequest(payload="c", opts=opts))
    assert q.depth == 2


def test_queue_drops_expired_deadline_requests():
    q = RequestQueue(max_depth=8)
    opts = BatchOptions()
    # shrink the service-time EWMA so the deadline is feasible at
    # admission — the point here is the *get*-side expiry drop
    for _ in range(40):
        q.observe_service_time(0.001)
    req = ServeRequest(
        payload="x", opts=opts, deadline=time.monotonic() + 0.03
    )
    q.submit(req)
    time.sleep(0.06)
    fresh = ServeRequest(payload="y", opts=opts)
    q.submit(fresh)
    got = q.get(timeout=1.0)
    assert got is fresh, "expired request must be skipped"
    with pytest.raises(DeadlineExceeded):
        req.future.result(timeout=0)


def test_deadline_infeasible_rejected_at_admission():
    q = RequestQueue(max_depth=64)
    opts = BatchOptions()
    for _ in range(4):
        q.submit(ServeRequest(payload="filler", opts=opts))
    # 4 queued × DEFAULT_SERVICE_S estimate ≫ 1 ms budget
    with pytest.raises(AdmissionError):
        q.submit(ServeRequest(
            payload="x", opts=opts, deadline=time.monotonic() + 0.001
        ))


# ------------------------------------------------------------- the service


def test_single_request_matches_bam_to_consensus(tmp_path):
    sam = make_sam(tmp_path / "one.sam", seed=11)
    want = bam_to_consensus(str(sam))
    with ConsensusService(max_wait_s=0.01) as svc:
        got = ConsensusClient(svc).result(str(sam), timeout=120)
    assert [(r.name, r.sequence) for r in got.consensuses] == [
        (r.name, r.sequence) for r in want.consensuses
    ]
    assert got.refs_changes == want.refs_changes
    assert got.refs_reports == want.refs_reports


def test_empty_input_serves_empty_result(tmp_path):
    empty = tmp_path / "empty.sam"
    empty.write_text("@HD\tVN:1.6\n@SQ\tSN:refZ\tLN:100\n")
    with ConsensusService(max_wait_s=0.01) as svc:
        assert ConsensusClient(svc).consensus(str(empty), timeout=60) == []


def test_concurrent_mixed_requests_coalesce_and_each_is_correct(tmp_path):
    """The acceptance property: N concurrent independent requests each
    get their own correct FASTA, and ≥2 of them share one device
    dispatch (batch occupancy > 1)."""
    n = 6
    sams = [
        make_sam(tmp_path / f"s{i}.sam", ref=f"ref{i}", seed=100 + i)
        for i in range(n)
    ]
    oracles = [bam_to_consensus(str(p)).consensuses for p in sams]
    with ConsensusService(max_wait_s=0.5, decode_workers=4) as svc:
        client = ConsensusClient(svc)
        client.consensus(str(sams[0]), timeout=180)  # warm the kernel
        results: list = [None] * n
        errors: list = []

        def one(i):
            try:
                results[i] = client.consensus(str(sams[i]), timeout=180)
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = svc.metrics.snapshot()
    assert not errors, errors
    for i in range(n):
        assert [(r.name, r.sequence) for r in results[i]] == [
            (r.name, r.sequence) for r in oracles[i]
        ], f"sample {i} diverged from its oracle"
    assert snap["kindel_serve_batch_occupancy"]["max"] >= 2, (
        "no coalescing observed", snap,
    )
    assert (
        snap["kindel_serve_device_dispatches_total"] < n + 1
    ), "every request dispatched alone"


def test_corrupt_input_fails_only_its_own_request(tmp_path):
    good = make_sam(tmp_path / "good.sam", seed=7)
    bad = tmp_path / "bad.bam"
    bad.write_bytes(b"\x1f\x8b not actually a bam")
    want = bam_to_consensus(str(good)).consensuses
    with ConsensusService(max_wait_s=0.05) as svc:
        futures = [
            svc.submit(str(good)),
            svc.submit(str(bad)),
            svc.submit(str(good)),
        ]
        with pytest.raises(ValueError):
            futures[1].result(timeout=120)
        for f in (futures[0], futures[2]):
            res = f.result(timeout=120)
            assert [(r.name, r.sequence) for r in res.consensuses] == [
                (r.name, r.sequence) for r in want
            ]
        snap = svc.metrics.snapshot()
    assert snap["kindel_serve_requests_failed_total"] == 1
    assert snap["kindel_serve_requests_total"] == 3


def test_service_recovers_after_watermark_rejection(tmp_path):
    """Requests admitted while the worker is down drain once it starts;
    admission reopens as depth falls."""
    sam = make_sam(tmp_path / "wm.sam", seed=3)
    svc = ConsensusService(max_wait_s=0.02, high_watermark=2)
    try:
        f1 = svc.submit(str(sam))
        f2 = svc.submit(str(sam))
        with pytest.raises(AdmissionError):
            svc.submit(str(sam))
        svc.start()
        assert f1.result(timeout=120).consensuses
        assert f2.result(timeout=120).consensuses
        # queue drained → admission open again
        f3 = svc.submit(str(sam))
        assert f3.result(timeout=120).consensuses
    finally:
        svc.stop()


def test_lane_coalescing_fat_dispatch_is_byte_identical(tmp_path):
    """Fat dispatch (kindel_tpu.aot PR): ready flushes of one lane
    merged into a single device launch must produce byte-identical
    per-request results vs dispatching each flush alone, and the
    process-global coalescing counters must record the merge."""
    from kindel_tpu.obs.metrics import default_registry
    from kindel_tpu.serve.worker import ServeWorker

    n = 4
    sams = [
        make_sam(tmp_path / f"co{i}.sam", ref=f"ref{i}", seed=300 + i)
        for i in range(n)
    ]
    oracles = [
        [(r.name, r.sequence) for r in bam_to_consensus(str(p)).consensuses]
        for p in sams
    ]

    def run(width: int):
        q = RequestQueue(max_depth=16)
        # max_batch_rows=1: every request seals its own flush, so the
        # dispatch-side coalescer (not the batcher) does the merging
        mb = MicroBatcher(max_batch_rows=1, max_wait_s=30.0)
        w = ServeWorker(q, mb, supervise=False, lane_coalesce=width)
        try:
            reqs = []
            for p in sams:
                req, units = _units_for(str(p))
                mb.add(req, units)
                reqs.append(req)
            merged_widths = []
            while any(not r.future.done() for r in reqs):
                flush = mb.poll(timeout=2.0)
                assert flush is not None, "expected a sealed flush"
                flush = w._coalesce(flush)
                merged_widths.append(flush.coalesced)
                w._execute(flush)
            return [
                [
                    (s.name, s.sequence)
                    for s in r.future.result(timeout=60).consensuses
                ]
                for r in reqs
            ], merged_widths
        finally:
            w.stop(drain=False)

    before = default_registry().snapshot().get(
        "kindel_dispatch_coalesced_flushes_total", 0
    )
    fat, fat_widths = run(width=n)
    lone, lone_widths = run(width=1)
    after = default_registry().snapshot().get(
        "kindel_dispatch_coalesced_flushes_total", 0
    )
    # same lane shapes by construction → ONE fat launch of all four
    assert fat_widths[0] == n - 1 and len(fat_widths) == 1
    assert all(wd == 0 for wd in lone_widths) and len(lone_widths) == n
    assert after - before == n - 1
    assert fat == lone == oracles, (
        "coalesced launch diverged from per-flush launches"
    )


# ----------------------------------------------------------------- HTTP


def test_http_metrics_healthz_and_ingest(tmp_path):
    sam = make_sam(tmp_path / "http.sam", seed=42)
    body = sam.read_bytes()
    want_fasta = "".join(
        f">{r.name}\n{r.sequence}\n"
        for r in bam_to_consensus(str(sam)).consensuses
    )
    with ConsensusService(max_wait_s=0.02, http_port=0) as svc:
        host, port = svc.http_address
        base = f"http://{host}:{port}"

        # ingest: SAM bytes in, FASTA out, byte-identical to the oracle
        req = urllib.request.Request(
            f"{base}/v1/consensus", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
            assert resp.read().decode() == want_fasta

        # undecodable body → 400, not a 500 or a hang
        bad = urllib.request.Request(
            f"{base}/v1/consensus", data=b"garbage", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=60)
        assert exc.value.code == 400

        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "ok"
        for field in ("queue_depth", "pending_rows", "watermark",
                      "uptime_s"):
            assert field in health, health

        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = resp.read().decode()
    for name in (
        "kindel_serve_queue_depth",
        "kindel_serve_admission_rejects_total",
        "kindel_serve_requests_total",
        "kindel_serve_requests_failed_total",
        "kindel_serve_device_dispatches_total",
        "kindel_serve_batch_occupancy_bucket",
        "kindel_serve_batch_occupancy_max",
        "kindel_serve_request_latency_seconds_p50",
        "kindel_serve_request_latency_seconds_p99",
    ):
        assert name in metrics, f"{name} missing from /metrics"
    # the corrupt POST failed its own request and was counted
    assert "kindel_serve_requests_failed_total 1" in metrics


# ----------------------------------------------------------------- warmup


def test_healthz_flips_warming_to_ok(monkeypatch):
    """/healthz reports `warming` from construction until the AOT
    warmup thread finishes, then `ok` — deterministically, via a gated
    stand-in for the real shape warmer."""
    gate = threading.Event()

    def gated_warm_shapes(opts, row_bucket=8, payloads=(), **kw):
        assert gate.wait(10), "test gate never opened"
        return {"r8xL1024o64b256d64i64cNone": 0.01}

    monkeypatch.setattr(
        "kindel_tpu.serve.warmup.warm_shapes", gated_warm_shapes
    )
    svc = ConsensusService(max_wait_s=0.01, warmup=True)
    try:
        assert svc.healthz()["status"] == "warming"  # pending before start
        svc.start()
        assert svc.healthz()["status"] == "warming"
        gate.set()
        assert svc.wait_warm(timeout=10)
        health = svc.healthz()
        assert health["status"] == "ok"
        assert health["warmup"] == "ok"
        snap = svc.metrics.snapshot()
        assert snap["kindel_serve_warmup_shapes_total"] == 1
        assert snap["kindel_serve_warmup_seconds"] >= 0
    finally:
        svc.stop()


def test_warmup_disabled_is_ok_immediately():
    with ConsensusService(max_wait_s=0.01) as svc:
        health = svc.healthz()
        assert health["status"] == "ok"
        assert health["warmup"] == "off"


def test_warmup_failure_degrades_to_serving(monkeypatch, tmp_path):
    """A warmup crash must not take the service down — requests still
    serve (paying their own compile), and /healthz surfaces the error."""

    def broken_warm_shapes(opts, row_bucket=8, payloads=(), **kw):
        raise RuntimeError("synthetic warmup failure")

    monkeypatch.setattr(
        "kindel_tpu.serve.warmup.warm_shapes", broken_warm_shapes
    )
    sam = make_sam(tmp_path / "wf.sam", seed=9)
    with ConsensusService(max_wait_s=0.01, warmup=True) as svc:
        assert svc.wait_warm(timeout=10)
        health = svc.healthz()
        assert health["status"] == "ok"
        assert "synthetic warmup failure" in health["warmup_error"]
        assert ConsensusClient(svc).consensus(str(sam), timeout=120)


def test_warmup_first_request_compiles_nothing(tmp_path):
    """The acceptance property: after /healthz flips to ok, the first
    request on a warmed lane triggers NO new kernel compile (asserted
    via the jit cache-entry counter of the cohort kernel) and its output
    still matches the bam_to_consensus oracle."""
    from kindel_tpu.call_jax import batched_call_kernel

    sam = make_sam(tmp_path / "warm.sam", seed=5)
    want = bam_to_consensus(str(sam)).consensuses
    with ConsensusService(
        max_wait_s=0.01, warm_payloads=[str(sam)]
    ) as svc:
        assert svc.wait_warm(timeout=300), "warmup never finished"
        assert svc.healthz()["status"] == "ok"
        cache_size = getattr(batched_call_kernel, "_cache_size", None)
        if cache_size is None:
            pytest.skip("jit cache counter unavailable on this jax")
        before = cache_size()
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
        assert cache_size() == before, (
            "first post-warmup request compiled a new kernel shape"
        )
        snap = svc.metrics.snapshot()
    assert [(r.name, r.sequence) for r in got] == [
        (r.name, r.sequence) for r in want
    ]
    # synthetic minimal lane + the warm payload's lane
    assert snap["kindel_serve_warmup_shapes_total"] >= 2
    assert snap["kindel_serve_warmup_seconds"] > 0
