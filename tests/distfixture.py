"""Deterministic synthetic cohort + output digest shared between the
2-process distributed test's workers and its single-process reference
(tests/test_distributed.py, tests/_dist_worker.py)."""

import hashlib

import numpy as np

REF_LEN = 512
AXES = {"dp": 2, "sp": 4}


def make_samples(n: int = 4, seed: int = 7) -> list[dict]:
    rng = np.random.default_rng(seed)
    samples = []
    for _ in range(n):
        e = 256
        samples.append(
            {
                "match_pos": rng.integers(0, REF_LEN, size=e).astype(np.int64),
                "match_base": rng.integers(0, 4, size=e).astype(np.int64),
                "del_pos": rng.integers(0, REF_LEN, size=5).astype(np.int64),
                "ins_pos": rng.integers(0, REF_LEN, size=3).astype(np.int64),
                "ins_cnt": rng.integers(1, 4, size=3).astype(np.int64),
            }
        )
    return samples


def digest(outs) -> str:
    h = hashlib.sha256()
    for o in outs:
        h.update(np.ascontiguousarray(o).tobytes())
    return h.hexdigest()
