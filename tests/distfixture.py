"""Deterministic synthetic cohort + output digest shared between the
2-process distributed test's workers and its single-process reference
(tests/test_distributed.py, tests/_dist_worker.py), plus the shared
two-process launch harness (port reservation, bind-race retry, cleanup)
used by every 2-process test and benchmark."""

import hashlib

import numpy as np

REF_LEN = 512
AXES = {"dp": 2, "sp": 4}
#: chunk size for the streamed×sharded worker: small enough that the
#: ~10 KB product SAM splits into several chunks (multi-chunk
#: accumulation is the behavior under test)
STREAM_CHUNK_BYTES = 2048


def make_samples(n: int = 4, seed: int = 7) -> list[dict]:
    rng = np.random.default_rng(seed)
    samples = []
    for _ in range(n):
        e = 256
        samples.append(
            {
                "match_pos": rng.integers(0, REF_LEN, size=e).astype(np.int64),
                "match_base": rng.integers(0, 4, size=e).astype(np.int64),
                "del_pos": rng.integers(0, REF_LEN, size=5).astype(np.int64),
                "ins_pos": rng.integers(0, REF_LEN, size=3).astype(np.int64),
                "ins_cnt": rng.integers(1, 4, size=3).astype(np.int64),
            }
        )
    return samples


def digest(outs) -> str:
    h = hashlib.sha256()
    for o in outs:
        h.update(np.ascontiguousarray(o).tobytes())
    return h.hexdigest()


def product_sam(ref_len: int = 2048, seed: int = 5) -> bytes:
    """Synthetic SAM for the cross-process product-path test.

    Layout engineered so realign actually produces a CDR patch (the lazy
    window fetches and LCS merge run for real): an uncovered gap at
    [1000, 1020) flanked by 20 forward-clipping reads (48M16S ending at
    1000, clips = gap[0:16]) and 20 reverse-clipping reads (16S48M
    starting at 1020, clips = gap[4:20]) — the 12-base clip overlap >=
    min_overlap 7 merges into one gap-closing patch. Background random
    reads plus deletion/insertion reads exercise every other channel."""
    rng = np.random.default_rng(seed)
    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:prod1\tLN:{ref_len}".encode()]
    bases = "ACGT"

    def rand_seq(n):
        return "".join(bases[b] for b in rng.integers(0, 4, size=n))

    gap = rand_seq(20)  # the "true" sequence across the uncovered gap
    left_match = rand_seq(48)
    right_match = rand_seq(48)
    k = 0

    def read(pos1, cigar, seq):
        nonlocal k
        lines.append(
            f"r{k}\t0\tprod1\t{pos1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*".encode()
        )
        k += 1

    for _ in range(20):
        read(953, "48M16S", left_match + gap[:16])     # matches 952..1000
        read(1021, "16S48M", gap[4:20] + right_match)  # matches 1020..1068
    # background coverage away from the gap (none inside [1000, 1020))
    for _ in range(40):
        pos = int(rng.integers(0, 900))
        read(pos + 1, "64M", rand_seq(64))
    for _ in range(10):
        pos = int(rng.integers(1100, ref_len - 80))
        read(pos + 1, "30M4D30M", rand_seq(60))
        pos = int(rng.integers(1100, ref_len - 80))
        read(pos + 1, "30M6I24M", rand_seq(60))
    return b"\n".join(lines) + b"\n"


def run_two_process(worker, extra_argv=(), timeout: float = 300,
                    retries: int = 3):
    """Launch a worker script twice as a localhost 2-process JAX group.

    Reserves a coordinator port (bind-then-close), passes
    `<process_id> <port> *extra_argv` to each worker, scrubs the
    accelerator hook, retries the inherent port-reservation race (another
    process can steal the just-released port before the coordinator
    binds), and never leaks a worker blocked in initialize(). Returns
    [(returncode, stdout, stderr), ...]; raises RuntimeError when a
    worker fails for a non-race reason, races persist past `retries`, or
    the pair exceeds `timeout` (both workers killed, stderr tails
    attached; the two communicates share one deadline so the worst-case
    wall matches the documented budget).
    """
    import os
    import socket
    import subprocess
    import sys
    import time

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def run_pair():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(i), str(port),
                 *map(str, extra_argv)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env,
            )
            for i in range(2)
        ]
        try:
            # one shared deadline for BOTH communicates: the second waits
            # only for whatever budget the first left, so the worst-case
            # wall time is `timeout`, not 2×timeout
            deadline = time.monotonic() + timeout
            pair = []
            for p in procs:
                try:
                    out, err = p.communicate(
                        timeout=max(0.0, deadline - time.monotonic())
                    )
                except subprocess.TimeoutExpired as e:
                    tails = []
                    for q in procs:  # kill BOTH before harvesting stderr
                        if q.poll() is None:
                            q.kill()
                    for q in procs:
                        try:
                            _, err_q = q.communicate(timeout=10)
                        except Exception:
                            err_q = "<stderr unavailable>"
                        tails.append((err_q or "")[-1500:])
                    raise RuntimeError(
                        f"2-process group timed out after {timeout:.0f}s:\n"
                        f"stderr[0] tail: {tails[0]}\n"
                        f"stderr[1] tail: {tails[1]}"
                    ) from e
                pair.append((p.returncode, out, err))
            return pair
        finally:
            for p in procs:  # never leak a worker blocked in initialize()
                if p.poll() is None:
                    p.kill()
                    p.wait()

    for attempt in range(retries):
        outs = run_pair()
        if all(rc == 0 for rc, _, _ in outs):
            return outs
        bind_race = any(
            "bind" in err.lower() or "address already in use" in err.lower()
            for _, _, err in outs
        )
        if not bind_race:
            break
    raise RuntimeError(
        f"2-process group failed (rc={[rc for rc, _, _ in outs]}):\n"
        f"stderr[0] tail: {outs[0][2][-1500:]}\n"
        f"stderr[1] tail: {outs[1][2][-1500:]}"
    )


def product_digest(res, dmin: int, dmax: int, cdr) -> str:
    """Digest of a sharded_consensus result tuple — shared by the
    2-process product worker and its single-process oracle so the two
    sides can never drift apart."""
    payload = (
        res.sequence
        + f"|{dmin}|{dmax}|"
        + str([(r.start, r.end, r.seq) for r in (cdr or [])])
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]
