"""Fleet chaos suite (kindel_tpu.fleet): the replica-level version of
the serving invariant — **no admitted request lost when a replica
dies**. DESIGN.md §17's claims, asserted:

  * rendezvous placement is sticky (lane locality) and re-homes only a
    removed replica's keys;
  * a killed replica (abrupt death, futures abandoned) is detected by
    consecutive failed probes, evicted, and its admitted work replayed
    onto survivors — every future resolves exactly once, byte-identical
    to the single-replica answer;
  * drain is zero-downtime: admission stops, in-flight finishes,
    queued-but-unstarted work is handed back and re-queued, the replica
    warm-restarts while the fleet keeps serving;
  * failover/hedging move requests off shedding/straggling replicas,
    with the outer future as the exactly-once settle point;
  * the flagship: closed-loop load (benchmarks/serve_load.py) with
    KINDEL_TPU_FAULTS active, one of three replicas killed and another
    drained mid-run → every request resolves exactly once, FASTA
    digest identical to a single-replica reference run, fleet counter
    deltas matching the injected plan.

Satellites ride along: /readyz liveness-vs-readiness split, jittered
retry-after hints, RequestQueue hand-back exactly-once, SIGTERM drain
handlers. Everything runs on the CPU backend; probes and waits are
tuned for determinism, not realism.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future

import pytest

from kindel_tpu.batch import BatchOptions
from kindel_tpu.fleet import FleetRouter, FleetService, Replica, routing_key
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience.breaker import FlushTimeout
from kindel_tpu.resilience.faults import FaultPlan
from kindel_tpu.resilience.policy import ProbePolicy
from kindel_tpu.resilience import policy as rpolicy
from kindel_tpu.serve import (
    AdmissionError,
    ConsensusService,
    RequestQueue,
    ServeRequest,
    ServiceDegraded,
)
from kindel_tpu.serve.queue import jittered_retry_after
from kindel_tpu.serve.worker import _settle
from kindel_tpu.workloads import bam_to_consensus

from tests.test_serve import make_sam


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Process-global fault plans / policies must not leak (same hygiene
    as test_resilience.py)."""
    rfaults.deactivate()
    prev = rpolicy.set_default_policy(None)
    yield
    rfaults.deactivate()
    rpolicy.set_default_policy(prev)


def _names_seqs(records) -> list:
    return [(r.name, r.sequence) for r in records]


def _fleet_delta(before: dict, after: dict, name: str) -> int:
    return int(after.get(name, 0)) - int(before.get(name, 0))


# ----------------------------------------------------------- probe policy


def test_probe_policy_consecutive_scoring():
    p = ProbePolicy(degraded_after=2, dead_after=3)
    assert p.observe("degraded") == "ok"        # one flake: no demotion
    assert p.observe("degraded") == "degraded"  # a run demotes
    assert p.observe("ok") == "ok"              # recovery is instant
    assert p.observe("failed") == "ok"
    assert p.observe("failed") == "degraded"    # failed counts not-ok too
    assert p.observe("failed") == "dead"        # 3 consecutive fails
    assert p.observe("ok") == "ok"              # ladder resets
    # a degraded probe breaks a failed run (dead needs CONSECUTIVE fails)
    p2 = ProbePolicy(degraded_after=2, dead_after=2)
    assert p2.observe("failed") == "ok"
    assert p2.observe("degraded") == "degraded"
    assert p2.observe("failed") == "degraded"


def test_probe_policy_classifies_probe_errors_via_transient_vocab():
    p = ProbePolicy()
    assert p.classify_error(RuntimeError("UNAVAILABLE: flap")) == "degraded"
    assert p.classify_error(RuntimeError("boom")) == "failed"


# ------------------------------------------------------- router (stubs)


class _FakeQueue:
    def __init__(self, depth=0, high_watermark=64):
        self.depth = depth
        self.high_watermark = high_watermark

    def estimated_wait_s(self, depth=None) -> float:
        return 0.1


class _FakeService:
    """Minimal replica-service stub for router-level tests: `mode`
    selects the submit behavior."""

    def __init__(self, mode="ok", result="res"):
        self.queue = _FakeQueue()
        self.live = True
        self.mode = mode
        self.result = result
        self.submitted = []

    def start(self):
        return self

    def stop(self, drain=True):
        pass

    def kill(self):
        self.live = False

    def healthz(self):
        return {"status": "ok"}

    def submit(self, payload, deadline_s=None, **opts) -> Future:
        self.submitted.append(payload)
        if self.mode == "shed":
            raise ServiceDegraded("stub shedding", 0.1)
        fut: Future = Future()
        if self.mode == "flush_timeout":
            fut.set_exception(FlushTimeout("stub hung flush"))
        elif self.mode == "hang":
            pass  # never settles — the hedging straggler
        else:
            fut.set_result(self.result)
        return fut


def _stub_replica(rid: str, svc: _FakeService) -> Replica:
    return Replica(rid, lambda: svc).start()


def test_rendezvous_routing_is_sticky_and_rehomes_only_removed_keys():
    reps = [_stub_replica(f"r{i}", _FakeService()) for i in range(3)]
    router = FleetRouter(reps)
    keys = [routing_key(f"/data/sample{i}.bam", {}) for i in range(40)]
    first = {k: router.rank(k)[0].replica_id for k in keys}
    # sticky: the same key always ranks the same replica first
    assert first == {k: router.rank(k)[0].replica_id for k in keys}
    # spread: rendezvous actually uses all three replicas
    assert len(set(first.values())) == 3
    # removing one replica re-homes ONLY its keys
    gone = reps[1].replica_id
    reps[1].set_state("dead")
    for k in keys:
        now = router.rank(k)[0].replica_id
        if first[k] != gone:
            assert now == first[k], "a surviving replica's key moved"
        else:
            assert now != gone


def test_capacity_weighted_rendezvous_treats_pod_group_as_big_replica():
    """A pod group registered as ONE capacity-k replica (DESIGN.md
    §27) wins ~k/(k + peers) of the keyspace; equal-capacity fleets
    rank exactly as the classic unweighted score did (the weighted
    score is a monotone transform of it), so placement is still sticky
    and nothing moved for existing rosters."""
    from kindel_tpu.fleet import weighted_rendezvous_score

    keys = [routing_key(f"/data/s{i}.bam", {}) for i in range(400)]
    # equal capacity ⇒ identical ranking to the classic digest order
    from kindel_tpu.fleet.router import rendezvous_score

    for k in keys[:50]:
        classic = sorted(
            ("r0", "r1", "r2"),
            key=lambda r: rendezvous_score(k, r), reverse=True,
        )
        weighted = sorted(
            ("r0", "r1", "r2"),
            key=lambda r: weighted_rendezvous_score(k, r, 1),
            reverse=True,
        )
        assert classic == weighted
    # a capacity-4 pod group vs two singles: ~4/6 of keys land on it
    reps = [_stub_replica("pod", _FakeService()),
            _stub_replica("a", _FakeService()),
            _stub_replica("b", _FakeService())]
    reps[0].capacity = 4
    router = FleetRouter(reps)
    wins = sum(router.rank(k)[0].replica_id == "pod" for k in keys)
    assert 0.5 < wins / len(keys) < 0.8, (
        f"capacity-4 pod won {wins}/{len(keys)} keys"
    )
    # placement stays sticky under weighting
    assert [router.rank(k)[0].replica_id for k in keys[:20]] \
        == [router.rank(k)[0].replica_id for k in keys[:20]]


def test_parse_replica_roster_pod_capacity_grammar():
    from kindel_tpu.fleet import parse_replica_roster, static_fleet

    assert parse_replica_roster("a:1, b:2*4,") \
        == [("a", 1, 1), ("b", 2, 4)]
    with pytest.raises(ValueError, match="capacity"):
        parse_replica_roster("a:1*0")
    with pytest.raises(ValueError, match="capacity"):
        parse_replica_roster("a:1*pod")
    # the static roster hands capacities to the fleet's replicas
    fleet = static_fleet("10.0.0.1:7701,10.0.0.2:7701*4")
    try:
        assert [r.capacity for r in fleet.roster()] == [1, 4]
        assert fleet.roster()[1].snapshot()["capacity"] == 4
    finally:
        fleet.stop(drain=False)


def test_router_fails_over_past_a_shedding_replica():
    before = default_registry().snapshot()
    reps = [
        _stub_replica("a", _FakeService(mode="shed")),
        _stub_replica("b", _FakeService(mode="shed")),
    ]
    router = FleetRouter(reps)
    key = routing_key("x.bam", {})
    preferred = router.rank(key)[0]
    other = next(r for r in reps if r is not preferred)
    other.service.mode = "ok"
    fut = router.submit("x.bam")
    assert fut.result(timeout=5) == "res"
    after = default_registry().snapshot()
    assert _fleet_delta(
        before, after, "kindel_fleet_failovers_total"
    ) >= 1


def test_router_fails_over_on_flush_timeout_and_surfaces_request_errors():
    before = default_registry().snapshot()
    reps = [
        _stub_replica("a", _FakeService()),
        _stub_replica("b", _FakeService()),
    ]
    router = FleetRouter(reps)
    key = routing_key("y.bam", {})
    preferred = router.rank(key)[0]
    other = next(r for r in reps if r is not preferred)
    preferred.service.mode = "flush_timeout"
    fut = router.submit("y.bam")
    # the replica-level FlushTimeout fails over; the other stub serves
    assert fut.result(timeout=5) == "res"
    after = default_registry().snapshot()
    assert _fleet_delta(before, after, "kindel_fleet_failovers_total") >= 1

    # request-level failures surface immediately (no pointless retry)
    class _Bad(_FakeService):
        def submit(self, payload, deadline_s=None, **opts):
            fut = Future()
            fut.set_exception(ValueError("undecodable"))
            return fut

    router2 = FleetRouter([
        _stub_replica("c", _Bad()), _stub_replica("d", _Bad()),
    ])
    with pytest.raises(ValueError):
        router2.submit("z.bam").result(timeout=5)


def test_fleet_watermark_rejects_with_jittered_hint():
    reps = [
        _stub_replica("a", _FakeService()),
        _stub_replica("b", _FakeService()),
    ]
    for r in reps:
        r.service.queue.depth = 5
        r.service.queue.high_watermark = 4
    router = FleetRouter(reps)  # fleet watermark defaults to 4+4=8 <= 10
    hints = set()
    for _ in range(20):
        with pytest.raises(AdmissionError) as exc:
            router.submit("w.bam")
        assert not isinstance(exc.value, ServiceDegraded)
        hints.add(round(exc.value.retry_after_s, 6))
    assert len(hints) > 1, "fleet watermark hint is not jittered"


def test_router_hedges_a_straggling_primary():
    before = default_registry().snapshot()
    reps = [
        _stub_replica("a", _FakeService()),
        _stub_replica("b", _FakeService()),
    ]
    router = FleetRouter(reps, hedge_s=0.05)
    key = routing_key("h.bam", {})
    preferred = router.rank(key)[0]
    other = next(r for r in reps if r is not preferred)
    preferred.service.mode = "hang"  # the straggler
    other.service.result = "hedged"
    fut = router.submit("h.bam")
    assert fut.result(timeout=5) == "hedged"
    after = default_registry().snapshot()
    assert _fleet_delta(before, after, "kindel_fleet_hedges_total") == 1
    # exactly-once: the hang stub's inner future is abandoned, the
    # outer settled once
    assert fut.done()


# --------------------------------------------------- satellites: serve tier


def test_jittered_retry_after_is_bounded_and_spread():
    import random

    rng = random.Random(7)
    vals = [jittered_retry_after(1.0, rng=rng) for _ in range(500)]
    assert all(0.75 <= v <= 1.25 for v in vals)
    assert max(vals) - min(vals) > 0.2, "jitter did not spread"
    assert jittered_retry_after(0.0, rng=rng) == 0.05  # floor

    # integration: repeated watermark rejections carry distinct hints —
    # synchronized clients desynchronize instead of herding
    q = RequestQueue(max_depth=8, high_watermark=1)
    q.submit(ServeRequest(payload="a", opts=BatchOptions()))
    hints = set()
    for _ in range(30):
        with pytest.raises(AdmissionError) as exc:
            q.submit(ServeRequest(payload="b", opts=BatchOptions()))
        hints.add(exc.value.retry_after_s)
    assert len(hints) > 1


def test_queue_handback_settles_or_hands_back_every_future_exactly_once():
    """Satellite: concurrent submitters + drain hand-back — every
    admitted future is either settled by the consumer exactly once or
    returned unresolved by handback() exactly once; none lost, none
    double-settled (extends PR 4's exactly-once queue test to drain)."""
    q = RequestQueue(max_depth=100000)
    opts = BatchOptions()
    admitted: list = []
    lock = threading.Lock()
    served = []

    def submitter(i: int):
        for j in range(300):
            req = ServeRequest(payload=f"{i}-{j}", opts=opts)
            try:
                q.submit(req)
            except AdmissionError:
                return  # admission closed mid-loop: future untouched
            with lock:
                admitted.append(req)

    def consumer():
        while True:
            req = q.get(timeout=0.02)
            if req is None:
                if not q.admitting:
                    return
                continue
            assert _settle(req, result="served")
            with lock:
                served.append(req)
            time.sleep(0.001)  # slower than arrivals: depth builds

    subs = [threading.Thread(target=submitter, args=(i,)) for i in range(4)]
    cons = threading.Thread(target=consumer)
    for t in subs + [cons]:
        t.start()
    time.sleep(0.05)  # let the races build up
    handed = q.handback()
    for t in subs:
        t.join()
    cons.join()
    handed_set = set(id(r) for r in handed)
    served_set = set(id(r) for r in served)
    assert handed, "nothing left to hand back — the race never happened"
    assert not (handed_set & served_set), "a request was served AND handed back"
    assert len(served) + len(handed) == len(admitted)
    for req in handed:
        assert not req.future.done(), "handback settled a future"
    for req in served:
        assert req.future.result(timeout=0) == "served"
    # and a handed-back request re-queues cleanly on another queue
    q2 = RequestQueue(max_depth=len(handed) + 1)
    q2.submit(handed[0])
    assert q2.get(timeout=1.0) is handed[0]


def test_readyz_splits_from_healthz(monkeypatch):
    """Satellite: /readyz is 503 during warmup and drain while /healthz
    keeps its original always-200 semantics."""
    gate = threading.Event()

    def gated_warm_shapes(opts, row_bucket=8, payloads=(), **kw):
        assert gate.wait(10), "test gate never opened"
        return {"stub": 0.01}

    monkeypatch.setattr(
        "kindel_tpu.serve.warmup.warm_shapes", gated_warm_shapes
    )
    svc = ConsensusService(max_wait_s=0.01, warmup=True, http_port=0)
    try:
        svc.start()
        host, port = svc.http_address
        base = f"http://{host}:{port}"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "warming"
        # /healthz unchanged: 200 with a status string
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "warming"
        gate.set()
        assert svc.wait_warm(timeout=30)
        with urllib.request.urlopen(f"{base}/readyz", timeout=30) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["ready"] is True
        # drain posture: 503 again (readiness), healthz still answers
        svc._draining = True
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "draining"
        svc._draining = False
    finally:
        svc.stop()


def test_single_service_drain_serves_queued_then_rejects(tmp_path):
    """Satellite: the SIGTERM drain path — queued requests are SERVED
    (not dropped), then admission stays closed."""
    sam = make_sam(tmp_path / "dr.sam", seed=21)
    want = _names_seqs(bam_to_consensus(str(sam)).consensuses)
    svc = ConsensusService(max_wait_s=5.0)
    svc.start()
    futs = [svc.submit(str(sam)) for _ in range(3)]
    handed = svc.drain()  # blocks until everything queued is served
    assert handed == []
    for f in futs:
        assert _names_seqs(f.result(timeout=0).consensuses) == want
    with pytest.raises(AdmissionError):
        svc.submit(str(sam))
    assert svc.readyz()["ready"] is False


def test_install_drain_handlers_first_signal_drains_second_forces():
    import signal

    from kindel_tpu.cli import install_drain_handlers

    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    try:
        ev = threading.Event()
        install_drain_handlers(ev)
        handler = signal.getsignal(signal.SIGTERM)
        assert signal.getsignal(signal.SIGINT) is handler
        handler(signal.SIGTERM, None)  # first signal: request drain
        assert ev.is_set()
        with pytest.raises(KeyboardInterrupt):  # second: force
            handler(signal.SIGINT, None)
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)


# ------------------------------------------------------- assembled fleet


def test_fleet_serves_byte_identical_to_oracle(tmp_path):
    sams = [
        make_sam(tmp_path / f"f{i}.sam", ref=f"ref{i}", seed=400 + i)
        for i in range(4)
    ]
    oracles = [
        _names_seqs(bam_to_consensus(str(p)).consensuses) for p in sams
    ]
    with FleetService(replicas=2, max_wait_s=0.01) as svc:
        for p, want in zip(sams, oracles):
            got = _names_seqs(svc.request(str(p), timeout=120).consensuses)
            assert got == want
        health = svc.healthz()
    assert health["status"] == "ok"
    assert set(health["replicas"]) == {"r0", "r1"}
    assert all(
        doc["healthz"]["status"] == "ok"
        for doc in health["replicas"].values()
    )


def test_fleet_kill_evicts_replays_and_warm_restarts(tmp_path):
    """The core invariant, deterministically: requests sitting in a
    replica's batcher (max_wait far out) when it is KILLED are replayed
    onto the survivor and resolve byte-identical; the dead replica is
    evicted and warm-restarted."""
    sam = make_sam(tmp_path / "kill.sam", seed=31)
    want = _names_seqs(bam_to_consensus(str(sam)).consensuses)
    before = default_registry().snapshot()
    with FleetService(
        replicas=2, max_wait_s=0.8, probe_interval_s=0.02
    ) as svc:
        victim = svc.router.rank(routing_key(str(sam), {}))[0]
        survivor = next(r for r in svc.replicas if r is not victim)
        futs = [svc.submit(str(sam)) for _ in range(2)]
        time.sleep(0.1)  # decoded into the victim's batcher, unflushed
        svc.kill_replica(victim.replica_id)
        for f in futs:
            assert _names_seqs(f.result(timeout=60).consensuses) == want
        # the survivor did the work
        assert survivor.state in ("ok", "degraded")
        deadline = time.monotonic() + 10
        while victim.state != "ok" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert victim.state == "ok", "victim was not warm-restarted"
        assert victim.generation == 1
        # the restarted replica serves again
        got = _names_seqs(svc.request(str(sam), timeout=60).consensuses)
        assert got == want
    after = default_registry().snapshot()
    assert _fleet_delta(before, after, "kindel_fleet_evictions_total") == 1
    assert _fleet_delta(
        before, after, "kindel_fleet_replayed_requests_total"
    ) == 2
    assert _fleet_delta(before, after, "kindel_fleet_restarts_total") == 1


def test_service_drain_handback_returns_unstarted_requests(tmp_path):
    """Deterministic hand-back mechanics: a service whose worker never
    ran (requests certainly still queued) hands every one of them back
    unresolved — the fleet building block, isolated."""
    sam = make_sam(tmp_path / "hb.sam", seed=43)
    svc = ConsensusService(max_wait_s=0.01)  # never started
    futs = [svc.submit(str(sam)) for _ in range(3)]
    handed = svc.drain(handback=True)
    assert len(handed) == 3
    assert [r.future for r in handed] == futs
    assert not any(f.done() for f in futs), "handback settled a future"
    with pytest.raises(AdmissionError):
        svc.submit(str(sam))


def test_fleet_drain_requeues_pending_tickets_onto_survivor():
    """Zero-downtime drain with the hand-back path pinned via stubs: a
    replica sitting on never-completing inners is drained — its tickets
    re-queue on the survivor, resolve there, and the drained counter
    records exactly the hand-back count."""
    before = default_registry().snapshot()
    fakes: dict = {}

    def factory(rid, registry):
        if rid not in fakes:
            fakes[rid] = _FakeService()
            fakes[rid].drain = lambda handback=False: []
        return fakes[rid]

    with FleetService(
        replicas=2, service_factory=factory, supervise=False
    ) as svc:
        target = svc.router.rank(routing_key("p.bam", {}))[0]
        other = next(r for r in svc.replicas if r is not target)
        fakes[target.replica_id].mode = "hang"  # inners never settle
        fakes[other.replica_id].result = "survivor"
        futs = [svc.submit("p.bam") for _ in range(2)]
        assert not any(f.done() for f in futs)
        handed = svc.drain(target.replica_id)
        assert handed == 2
        assert [f.result(timeout=5) for f in futs] == ["survivor"] * 2
        assert target.state == "ok" and target.generation == 1
    after = default_registry().snapshot()
    assert _fleet_delta(
        before, after, "kindel_fleet_drained_requests_total"
    ) == 2
    assert _fleet_delta(before, after, "kindel_fleet_evictions_total") == 0


def test_fleet_drain_finishes_in_flight_and_keeps_serving(tmp_path):
    """Zero-downtime drain end-to-end with real replicas: everything
    admitted before the drain resolves byte-identical (in-flight work
    finishes on the draining replica, hand-backs complete on the
    survivor), the replica warm-restarts, and the fleet serves on."""
    sam = make_sam(tmp_path / "drain.sam", seed=41)
    want = _names_seqs(bam_to_consensus(str(sam)).consensuses)
    before = default_registry().snapshot()
    with FleetService(
        replicas=2, max_wait_s=5.0, probe_interval_s=0.02
    ) as svc:
        target = svc.router.rank(routing_key(str(sam), {}))[0]
        futs = [svc.submit(str(sam)) for _ in range(3)]
        handed = svc.drain(target.replica_id)
        for f in futs:
            assert _names_seqs(f.result(timeout=60).consensuses) == want
        assert target.state == "ok"
        assert target.generation == 1
        got = _names_seqs(svc.request(str(sam), timeout=60).consensuses)
        assert got == want
    after = default_registry().snapshot()
    # whatever was still unstarted at drain time (timing-dependent: the
    # intake loop races the drain) was counted, nothing else
    assert _fleet_delta(
        before, after, "kindel_fleet_drained_requests_total"
    ) == handed
    assert _fleet_delta(before, after, "kindel_fleet_evictions_total") == 0


def test_fleet_http_surface(tmp_path):
    sam = make_sam(tmp_path / "http.sam", seed=51)
    body = sam.read_bytes()
    want_fasta = "".join(
        f">{r.name}\n{r.sequence}\n"
        for r in bam_to_consensus(str(sam)).consensuses
    )
    with FleetService(replicas=2, max_wait_s=0.02, http_port=0) as svc:
        host, port = svc.http_address
        base = f"http://{host}:{port}"
        req = urllib.request.Request(
            f"{base}/v1/consensus", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
            assert resp.read().decode() == want_fasta
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["fleet"] is True
        assert health["status"] == "ok"
        assert set(health["replicas"]) == {"r0", "r1"}
        with urllib.request.urlopen(f"{base}/readyz", timeout=30) as resp:
            assert json.loads(resp.read())["ready"] is True
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = resp.read().decode()
    for name in (
        "kindel_fleet_replica_state",
        "kindel_fleet_evictions_total",
        "kindel_fleet_failovers_total",
        "kindel_serve_requests_total",
    ):
        assert name in metrics, f"{name} missing from fleet /metrics"


# ---------------------------------------------------------- the flagship


def test_fleet_chaos_kill_and_drain_under_load_exactly_once():
    """The flagship: closed-loop load (benchmarks/serve_load.py) against
    3 supervised replicas with an active KINDEL_TPU_FAULTS-style plan;
    one replica is KILLED mid-run and another DRAINED. Every admitted
    request resolves exactly once, the FASTA digest is byte-identical
    to a single-replica reference run, and the fleet counter deltas
    match the injected plan: exactly one eviction (the kill), at least
    one restart beyond it (the drain), and the fault ledger records
    exactly the injected flush faults."""
    from benchmarks.serve_load import run_load

    # single-replica reference, no faults: the byte-identity anchor
    reference = run_load(clients=2, requests_per_client=3)
    assert reference["errors"] == 0
    assert reference["fasta_distinct"] == 1

    # transient flush faults are on for the fleet run: the in-replica
    # retry ladder (PR 4) must absorb them while the fleet layer
    # handles the kill and the drain
    plan = rfaults.activate(
        FaultPlan.parse("seed=5,serve.flush:error:times=2:after=1")
    )
    before = default_registry().snapshot()

    def chaos(svc):
        time.sleep(0.15)
        svc.kill_replica("r1")
        time.sleep(0.25)
        svc.drain("r2")

    report = run_load(
        clients=3, requests_per_client=3, replicas=3,
        probe_interval_s=0.02, chaos=chaos,
    )
    after = default_registry().snapshot()

    # exactly once: every admitted request resolved, none errored,
    # none duplicated (completed counts client-side completions)
    assert "chaos_errors" not in report, report.get("chaos_errors")
    assert report["errors"] == 0
    assert report["completed"] == report["requests"] == 9
    # byte-identical to the single-replica reference
    assert report["fasta_distinct"] == 1
    assert report["fasta_sha256"] == reference["fasta_sha256"]
    # the injected plan fired exactly as written
    assert plan.fired == {("serve.flush", "error"): 2}
    # counter deltas match the chaos script: one kill -> one eviction,
    # kill + drain -> two restarts; the drain registered
    assert _fleet_delta(before, after, "kindel_fleet_evictions_total") == 1
    assert _fleet_delta(before, after, "kindel_fleet_restarts_total") == 2
    assert report["fleet"]["evictions"] >= 1
    # the fleet ended healthy: every replica back to ok
    assert set(report["fleet"]["replicas"].values()) == {"ok"}
