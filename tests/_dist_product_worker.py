"""Worker for the 2-process PRODUCT-path test: join the localhost group
(4 virtual CPU devices per process → 8 global), build a 1-D sp=8 mesh
whose position axis SPANS the process boundary, run sharded_consensus
with realign on (ppermute halo + lazy CDR window fetches cross
non-addressable shards), and print the consensus digest.

Usage: python tests/_dist_product_worker.py <process_id> <coordinator_port>
(underscore prefix: not collected by pytest)."""

import os
import sys

proc_id = int(sys.argv[1])
port = int(sys.argv[2])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import distfixture  # noqa: E402  (shared sample geometry)

from kindel_tpu.parallel import initialize_distributed  # noqa: E402

assert (
    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=proc_id,
    )
    is True
), "process group did not come up"
assert jax.process_count() == 2
assert jax.device_count() == 8

from jax.sharding import Mesh  # noqa: E402

from kindel_tpu.events import extract_events  # noqa: E402
from kindel_tpu.io.sam import parse_sam_bytes  # noqa: E402
from kindel_tpu.parallel.product import sharded_consensus  # noqa: E402

# sp axis across ALL devices of BOTH processes — the halo ppermute at
# shard edge 3→4 crosses the process boundary
mesh = Mesh(jax.devices(), ("sp",))
procs_spanned = {d.process_index for d in mesh.devices.flat}
assert procs_spanned == {0, 1}, procs_spanned

ev = extract_events(parse_sam_bytes(distfixture.product_sam()))
rid = ev.present_ref_ids[0]
res, dmin, dmax, cdr = sharded_consensus(
    ev, rid, mesh=mesh, realign=True, min_overlap=7,
)
print("DIGEST:" + distfixture.product_digest(res, dmin, dmax, cdr),
      flush=True)
