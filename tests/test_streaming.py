"""Streamed single-file ingest (kindel_tpu.io.stream + kindel_tpu.streaming).

Contract (VERDICT r1, next-round item 4): chunked decode + additive
reduction must reproduce the slurped pipeline exactly — consensus
sequences, changes, reports, and pileup tensors — while touching only
O(chunk) of the file at a time. Chunk sizes here are tiny (KBs) so every
corpus file exercises many chunk boundaries.
"""

import os
from pathlib import Path

import numpy as np
import pytest

from kindel_tpu.io import load_alignment
from kindel_tpu.io.stream import stream_alignment
from kindel_tpu.pileup import build_pileups
from kindel_tpu.events import extract_events
from kindel_tpu.streaming import stream_pileups, streamed_consensus
from kindel_tpu.workloads import bam_to_consensus

_DATA_ROOT = Path(
    os.environ.get("KINDEL_TPU_TEST_DATA", "/root/reference/tests")
)


def require_data(*rel) -> Path:
    path = _DATA_ROOT.joinpath(*rel)
    if not path.exists():
        pytest.skip(f"golden corpus not available: {path}")
    return path


TINY_CHUNK = 64 << 10  # 64 KB — forces many chunk boundaries on the corpus


# ---------------------------------------------------------------------------
# stream_alignment: chunked decode equals slurped decode
# ---------------------------------------------------------------------------


def _concat_batches(batches):
    reads = []
    for b in batches:
        for i in range(b.n_reads):
            reads.append(
                (
                    int(b.ref_id[i]),
                    int(b.pos[i]),
                    int(b.flag[i]),
                    b.seq[b.seq_off[i] : b.seq_off[i + 1]].tobytes(),
                    b.cig_op[b.cig_off[i] : b.cig_off[i + 1]].tobytes(),
                    tuple(b.cig_len[b.cig_off[i] : b.cig_off[i + 1]]),
                )
            )
    return reads


@pytest.mark.parametrize(
    "rel",
    [
        ("data_bwa_mem", "1.1.sub_test.bam"),
        ("data_minimap2", "1.1.multi.bam"),
        ("data_ext", "1.issue23.debug.sam"),
    ],
)
def test_stream_equals_slurp_decode(rel):
    path = require_data(*rel)
    slurped = load_alignment(path)
    batches = list(stream_alignment(path, chunk_bytes=TINY_CHUNK))
    assert len(batches) >= 1
    assert batches[0].ref_names == slurped.ref_names
    got = _concat_batches(batches)
    want = _concat_batches([slurped])
    assert got == want


def test_stream_chunking_actually_chunks():
    path = require_data("data_bwa_mem", "1.1.sub_test.bam")
    batches = list(stream_alignment(path, chunk_bytes=TINY_CHUNK))
    assert len(batches) > 3  # ~2 MB decompressed / 64 KB


# ---------------------------------------------------------------------------
# stream_pileups: accumulated counts equal the slurped pileups
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_stream_pileups_match(backend):
    path = require_data("data_bwa_mem", "1.1.sub_test.bam")
    want = build_pileups(extract_events(load_alignment(path)))
    got = stream_pileups(path, chunk_bytes=TINY_CHUNK, backend=backend)
    assert list(got) == list(want)
    for chrom in want:
        g, w = got[chrom], want[chrom]
        assert np.array_equal(g.weights, w.weights)
        assert np.array_equal(g.deletions, w.deletions)
        assert np.array_equal(g.clip_start_weights, w.clip_start_weights)
        assert np.array_equal(g.clip_end_weights, w.clip_end_weights)
        assert np.array_equal(g.clip_starts, w.clip_starts)
        assert np.array_equal(g.clip_ends, w.clip_ends)
        assert np.array_equal(g.ins.totals, w.ins.totals)
        assert g.ins.at(1) == w.ins.at(1)


# ---------------------------------------------------------------------------
# streamed_consensus: byte-identical product output
# ---------------------------------------------------------------------------


def _assert_same(a, b):
    assert [s.sequence for s in a.consensuses] == [
        s.sequence for s in b.consensuses
    ]
    assert a.refs_changes == b.refs_changes
    assert a.refs_reports == b.refs_reports


@pytest.mark.parametrize("backend", ["numpy", "jax"])
@pytest.mark.parametrize("realign", [False, True])
def test_streamed_consensus_matches(backend, realign):
    path = require_data("data_bwa_mem", "1.1.sub_test.bam")
    want = bam_to_consensus(path, realign=realign, backend="numpy")
    got = streamed_consensus(
        path, realign=realign, backend=backend, chunk_bytes=TINY_CHUNK
    )
    _assert_same(got, want)


def test_streamed_consensus_multicontig():
    path = require_data("data_minimap2", "1.1.multi.bam")
    want = bam_to_consensus(path, backend="numpy")
    got = streamed_consensus(path, chunk_bytes=TINY_CHUNK)
    _assert_same(got, want)


def test_streamed_consensus_sam_text():
    path = require_data("data_ext", "1.issue23.debug.sam")
    want = bam_to_consensus(path, realign=True, backend="numpy")
    got = streamed_consensus(path, realign=True, chunk_bytes=TINY_CHUNK)
    _assert_same(got, want)


def test_bam_to_consensus_stream_param_routes():
    path = require_data("data_bwa_mem", "1.1.sub_test.bam")
    want = bam_to_consensus(path, backend="numpy")
    got = bam_to_consensus(
        path, backend="numpy", stream_chunk_mb=TINY_CHUNK / (1 << 20)
    )
    _assert_same(got, want)


def test_stream_gzip_with_foreign_fextra(tmp_path):
    """A conforming gzip member whose FEXTRA holds a non-BC subfield wider
    than the 18-byte BGZF header probe must fall back to generic inflate,
    not crash."""
    import struct
    import zlib

    src = require_data("data_bwa_mem", "1.1.sub_test.bam")
    from kindel_tpu.io import bgzf

    raw = bgzf.decompress(src.read_bytes())
    extra = struct.pack("<BBH", ord("Z"), ord("Q"), 8) + b"\x00" * 8
    co = zlib.compressobj(1, zlib.DEFLATED, -15)
    deflated = co.compress(raw) + co.flush()
    member = (
        b"\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff"
        + struct.pack("<H", len(extra)) + extra
        + deflated
        + struct.pack("<II", zlib.crc32(raw), len(raw) & 0xFFFFFFFF)
    )
    path = tmp_path / "fextra.bam"
    path.write_bytes(member)
    batches = list(stream_alignment(path, chunk_bytes=TINY_CHUNK))
    assert sum(b.n_reads for b in batches) == load_alignment(src).n_reads


def test_stream_truncated_bam_raises(tmp_path):
    src = require_data("data_bwa_mem", "1.1.sub_test.bam")
    import gzip

    from kindel_tpu.io import bgzf

    raw = bgzf.decompress(src.read_bytes())
    cut = tmp_path / "trunc.bam"
    cut.write_bytes(gzip.compress(raw[: len(raw) - 37], 1))
    with pytest.raises(ValueError, match="truncated"):
        list(stream_alignment(cut, chunk_bytes=TINY_CHUNK))


def test_stream_gzipped_sam(tmp_path):
    """A gzip-compressed SAM must stream through the line-chunking path
    (ADVICE r2: it used to raise 'not a BAM stream'); output equals the
    eager load and the plain-text stream."""
    import gzip

    src = require_data("data_ext", "1.issue23.debug.sam")
    gz = tmp_path / "1.issue23.debug.sam.gz"
    gz.write_bytes(gzip.compress(src.read_bytes()))

    eager = bam_to_consensus(src)
    streamed = streamed_consensus(gz, chunk_bytes=16 << 10)
    assert [c.sequence for c in streamed.consensuses] == [
        c.sequence for c in eager.consensuses
    ]
    assert streamed.refs_changes == eager.refs_changes

    # decode-level identity too: same records from .sam and .sam.gz
    plain = _concat_batches(stream_alignment(src, 16 << 10))
    gzed = _concat_batches(stream_alignment(gz, 16 << 10))
    assert plain == gzed


def test_stream_empty_gzip_raises_like_eager(tmp_path):
    """Empty / record-free gzipped content must error like the eager
    loader, not silently stream zero batches (review r3)."""
    import gzip

    from kindel_tpu.io import load_alignment

    for name, payload in (
        ("empty.sam.gz", b""),
        ("empty.sam", b""),
        ("blank.sam.gz", b"\n\n"),
    ):
        f = tmp_path / name
        f.write_bytes(
            gzip.compress(payload) if name.endswith(".gz") else payload
        )
        with pytest.raises(ValueError, match="not a recognizable"):
            list(stream_alignment(f, 16 << 10))
        with pytest.raises(ValueError):
            load_alignment(f)
