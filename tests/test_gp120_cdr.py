"""The reference's own disabled gp120 CDR case, exceeded.

/root/reference/tests/test_kindel.py:302-319 ships a commented-out
("Not yet implemented") test expecting `kindel consensus -r` on
hxb2-gp120-mutated reads to reconstruct a junction subsequence across a
divergent region "wrapping 700-1200bp". The input file was never shipped,
but the failure mode is structural: when the divergent segment is wider
than the soft-clip extensions, the facing CDR spans never intersect, so
the reference's pairing (kindel.py:310-316) finds nothing — even though
the clips from BOTH flanks carry the full novel sequence and merge
perfectly.

This file reconstructs that scenario (same geometry: a novel segment
replacing ref[700:1200), the reference's exact expected 56-mer planted
inside it) and pins that gap pairing (--cdr-gap / cdr_gap=)
recovers it while the default stays reference-exact.
"""

import numpy as np
import pytest

from kindel_tpu.workloads import bam_to_consensus

#: the reference's expected junction subsequence, verbatim
#: (/root/reference/tests/test_kindel.py:304-306)
EXPECTED_56MER = (
    "ATCAACTCAACTGCTGTTAAATGGCAGTCTAGCAGAAGAAGAGGTAGTAATTAGAT"
)

REF_LEN = 1500
SEG_START, SEG_END = 700, 1200  # divergent ref span ("wraps 700-1200bp")
READ_LEN = 150


def _gp120_like_sam(tmp_path):
    """Reads simulated from sample = ref[:700] + NOVEL(100bp, carrying
    the expected 56-mer) + ref[1200:]: an aligner anchors each read on
    its longer flank match and soft-clips the rest — exactly the
    clip-projection structure of the reference's gp120 case."""
    rng = np.random.default_rng(17)
    bases = "ACGT"

    def rand_seq(n):
        return "".join(bases[b] for b in rng.integers(0, 4, size=n))

    novel = rand_seq(22) + EXPECTED_56MER + rand_seq(22)  # 100 bp
    ref_left = rand_seq(SEG_START)
    ref_right = rand_seq(REF_LEN - SEG_END)
    sample = ref_left + novel + ref_right
    nov_a, nov_b = SEG_START, SEG_START + len(novel)  # novel in sample coords

    def ref_pos(sample_pos):  # sample coord → ref coord (flanks only)
        return (
            sample_pos
            if sample_pos < nov_a
            else sample_pos - nov_b + SEG_END
        )

    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:gp120\tLN:{REF_LEN}".encode()]
    k = 0
    for s in range(0, len(sample) - READ_LEN + 1, 10):
        e = s + READ_LEN
        seq = sample[s:e]
        left_anchor = max(0, min(e, nov_a) - s) if s < nov_a else 0
        right_anchor = max(0, e - max(s, nov_b)) if e > nov_b else 0
        if left_anchor >= READ_LEN:
            cigar, pos1 = f"{READ_LEN}M", s + 1
        elif right_anchor >= READ_LEN:
            cigar, pos1 = f"{READ_LEN}M", ref_pos(s) + 1
        elif left_anchor >= right_anchor and left_anchor > 0:
            cigar, pos1 = f"{left_anchor}M{READ_LEN - left_anchor}S", s + 1
        elif right_anchor > 0:
            cigar = f"{READ_LEN - right_anchor}S{right_anchor}M"
            pos1 = ref_pos(e - right_anchor) + 1
        else:  # fully inside the novel segment: unmapped, aligner drops it
            continue
        lines.append(
            f"r{k}\t0\tgp120\t{pos1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*".encode()
        )
        k += 1
    sam = tmp_path / "hxb2-gp120-mutated.sam"
    sam.write_bytes(b"\n".join(lines) + b"\n")
    return sam, sample


@pytest.mark.parametrize(
    "backend,stream_mb",
    [("numpy", None), ("jax", None), ("numpy", 0.05), ("jax", 0.05)],
)
def test_gp120_gap_pairing_recovers_expected_subseq(tmp_path, backend,
                                                    stream_mb):
    """With gap pairing on, realign reconstructs the full novel segment
    (the reference's expected 56-mer included) across the 500 bp
    divergent span — the assertion the reference's disabled test makes.
    Covered on the eager AND streamed (chunked-decode) routes of both
    backends; the cohort path is pinned separately below."""
    sam, sample = _gp120_like_sam(tmp_path)
    res = bam_to_consensus(sam, realign=True, min_overlap=7,
                           backend=backend, cdr_gap=600,
                           stream_chunk_mb=stream_mb)
    consensus = res.consensuses[0].sequence.upper()
    assert EXPECTED_56MER in consensus
    # the patch reconstructs the entire sample across the junction
    assert sample.upper() in consensus


def test_gp120_default_stays_reference_exact(tmp_path):
    """Default (gap 0) must reproduce the reference's behavior on this
    case — no pairing across the gap, so the divergent span stays
    unpatched — proving the recovery above is non-vacuous AND that
    default outputs cannot drift from reference parity."""
    sam, _sample = _gp120_like_sam(tmp_path)
    res = bam_to_consensus(sam, realign=True, min_overlap=7)
    assert EXPECTED_56MER not in res.consensuses[0].sequence.upper()


def test_gap_pairing_false_pair_rejected(tmp_path, caplog):
    """Facing clips across a gap that share no real sequence must not
    merge: gap pairs take the stricter GAP_PAIR_MIN_OVERLAP gate (a
    chance shared 7-mer between unrelated ~80 bp segments is near-likely;
    a chance 16-mer is ~1e-6), so the pair logs the no-overlap warning
    and writes NO patch — the gapped span stays untouched Ns."""
    import logging

    from kindel_tpu.realign import GAP_PAIR_MIN_OVERLAP, merge_by_lcs

    rng = np.random.default_rng(23)
    bases = "ACGT"

    def rand_seq(n):
        return "".join(bases[b] for b in rng.integers(0, 4, size=n))

    # two unrelated divergent events far apart: left reads clip into
    # segment A, right reads clip into unrelated segment B
    ref = rand_seq(REF_LEN)
    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:ctrl\tLN:{REF_LEN}".encode()]
    seg_a, seg_b = rand_seq(80), rand_seq(80)
    # non-vacuity: the unrelated extensions must NOT clear the gap gate
    # (they may well share a >=7-mer — that is exactly the hazard)
    assert merge_by_lcs(seg_a, seg_b, GAP_PAIR_MIN_OVERLAP) is None
    k = 0
    for _ in range(15):
        lines.append(
            f"a{k}\t0\tctrl\t{601 - 70}\t60\t70M80S\t*\t0\t0\t"
            f"{ref[530:600] + seg_a}\t*".encode()
        )
        lines.append(
            f"b{k}\t0\tctrl\t1101\t60\t80S70M\t*\t0\t0\t"
            f"{seg_b + ref[1100:1170]}\t*".encode()
        )
        k += 1
    sam = tmp_path / "falsepair.sam"
    sam.write_bytes(b"\n".join(lines) + b"\n")
    with caplog.at_level(logging.WARNING):
        res = bam_to_consensus(sam, realign=True, min_overlap=7,
                               cdr_gap=600)
    # the failed merge is logged with the escalated gate...
    assert any(
        "No overlap found" in r.message
        and f"min_overlap = {GAP_PAIR_MIN_OVERLAP}" in r.message
        for r in caplog.records
    )
    # ...and the uncovered span stays unpatched Ns (no invented sequence)
    consensus = res.consensuses[0].sequence.upper()
    assert seg_a not in consensus and seg_b not in consensus
    span = consensus[700:1050]
    assert set(span) == {"N"}


def test_gp120_gap_pairing_cohort_path(tmp_path):
    """The cohort batch realign path (device CDR triggers + lazy window
    fetches) honors cdr_gap too and matches the single-file result."""
    from kindel_tpu.batch import batch_bam_to_results

    sam, sample = _gp120_like_sam(tmp_path)
    single = bam_to_consensus(sam, realign=True, min_overlap=7, cdr_gap=600)
    cohort = batch_bam_to_results(
        [sam], realign=True, min_overlap=7, cdr_gap=600
    )[sam]
    assert [s.sequence for s in cohort.consensuses] == [
        s.sequence for s in single.consensuses
    ]
    assert EXPECTED_56MER in cohort.consensuses[0].sequence.upper()


def test_gap_pairing_composes_with_fix_clip_artifacts(tmp_path):
    """Both beyond-the-reference flags at once: gap pairing still closes
    the gp120 junction with --fix-clip-artifacts active (the strict-ins
    and flank-dedup rules must not interfere with the gap merge)."""
    sam, sample = _gp120_like_sam(tmp_path)
    res = bam_to_consensus(
        sam, realign=True, min_overlap=7, cdr_gap=600,
        fix_clip_artifacts=True,
    )
    assert EXPECTED_56MER in res.consensuses[0].sequence.upper()


def test_shipped_gp120_bam_recovers_expected_junction():
    """Round 5: the reference DOES ship a minimap2-aligned gp120 BAM
    (data_minimap2/hxb2-gp120-mutated.bam — the disabled test referenced
    an unshipped .sam from a different aligner). On this real input the
    disabled test's expected junction 56-mer
    (/root/reference/tests/test_kindel.py:304-306) must appear in the
    realigned consensus — under default (reference-exact) pairing, since
    minimap2's clips here do intersect, AND unchanged under --cdr-gap
    (the corpus sweep pins byte-identity; this pins the positive)."""
    from conftest import require_data

    bam = require_data("data_minimap2", "hxb2-gp120-mutated.bam")
    for gap in (0, 600):
        res = bam_to_consensus(
            bam, realign=True, min_overlap=7, cdr_gap=gap
        )
        seq = res.consensuses[0].sequence.upper()
        assert EXPECTED_56MER in seq, f"cdr_gap={gap}"
