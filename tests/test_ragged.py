"""kindel_tpu.ragged — segment-table superbatching.

Covers the three subsystem layers directly (page classes / segment
table / pack, the segment kernel, unpack) and the assembled serve path:
the flagship property is that `--batch-mode ragged` produces
BYTE-IDENTICAL FASTA to the shape-keyed lanes path for randomized
mixed-shape request streams — with decode workers, fat-dispatch
coalescing, and injected faults on — while the jit-cache counter records
at most one kernel compile per page geometry instead of one per shape.
"""

import threading
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np
import pytest

from kindel_tpu.batch import BatchOptions
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs.metrics import (
    DEFAULT_LABEL_CAP,
    LabelCapper,
    default_registry,
)
from kindel_tpu.ragged import (
    PageClass,
    RaggedBatcher,
    RaggedCapacityError,
    RaggedFlush,
    build_segment_table,
    classify_units,
    pack_superbatch,
    parse_classes,
)
from kindel_tpu.ragged.kernel import launch_ragged, ragged_call_kernel
from kindel_tpu.ragged.pack import GRANULE, stride_for
from kindel_tpu.ragged.unpack import unpack_superbatch
from kindel_tpu.serve import ConsensusClient, ConsensusService
from kindel_tpu.serve.queue import ServeRequest
from kindel_tpu.serve.worker import decode_request
from kindel_tpu.tune import TuningConfig
from kindel_tpu.workloads import bam_to_consensus

from tests.test_serve import make_sam


def _decode(payload, **opt_kwargs):
    return decode_request(
        ServeRequest(payload=payload, opts=BatchOptions(**opt_kwargs))
    )


def _units_for_sams(sams, **opt_kwargs):
    units = []
    for i, p in enumerate(sams):
        for u in _decode(str(p), **opt_kwargs):
            u.sample_idx = i
            units.append(u)
    return units


def _mixed_sams(tmp_path, n, seed_base=0, l_lo=260, l_hi=5200):
    rng = np.random.default_rng(seed_base)
    return [
        make_sam(
            tmp_path / f"mix{i}.sam", ref=f"mref{i}",
            L=int(rng.integers(l_lo, l_hi)),
            n_reads=int(rng.integers(10, 45)), seed=seed_base * 100 + i,
        )
        for i in range(n)
    ]


CLASSES = parse_classes("small:32x2048,medium:16x8192")


# ------------------------------------------------------- pack / page classes


def test_parse_classes_validates_and_sorts():
    classes = parse_classes("big:8x65536, tiny:64x1024")
    assert [c.name for c in classes] == ["tiny", "big"]  # ascending length
    with pytest.raises(ValueError):
        parse_classes("bad:8x1000")  # not a 1024 multiple
    with pytest.raises(ValueError):
        parse_classes("")
    with pytest.raises(ValueError):
        parse_classes("a:2x2048,a:4x2048")  # duplicate name
    with pytest.raises(ValueError):
        parse_classes("nonsense")


def test_segment_table_layout_invariants(tmp_path):
    sams = _mixed_sams(tmp_path, 6, seed_base=3)
    units = _units_for_sams(sams)
    cls = CLASSES[classify_units(units, CLASSES)]
    table = build_segment_table(units, cls)
    starts, lens = table.seg_start, table.seg_len
    # granule alignment + at least one gap slot between segments
    assert (starts % GRANULE == 0).all()
    ends = starts + lens
    assert (starts[1:] > ends[:-1]).all(), "segments must not touch"
    assert int(ends[-1]) < cls.n_slots
    # back-pointers route every segment to its request
    assert list(table.entry_idx) == [u.sample_idx for u in units]
    # flat stream offsets partition exactly
    assert (np.diff(table.ev_off) == table.ev_len[:-1]).all()
    assert table.occupancy == pytest.approx(
        lens.sum() / cls.n_slots
    )


def test_stride_always_leaves_a_gap_slot():
    for L in (1, 7, 8, 9, 1023, 1024, 4096):
        s = stride_for(L)
        assert s % GRANULE == 0 and s > L, (L, s)


def test_capacity_overflow_raises(tmp_path):
    sam = make_sam(tmp_path / "big.sam", ref="big", L=3000, seed=1)
    units = _units_for_sams([sam] * 40)
    tiny = PageClass("tiny", 2, 4096)
    with pytest.raises(RaggedCapacityError):
        build_segment_table(units, tiny)


def test_classify_routes_by_largest_unit_and_oversize(tmp_path):
    small = _units_for_sams([make_sam(tmp_path / "s.sam", L=400, seed=2)])
    big = _units_for_sams(
        [make_sam(tmp_path / "b.sam", ref="b", L=5000, seed=3)]
    )
    assert classify_units(small, CLASSES) == 0
    assert classify_units(big, CLASSES) == 1
    assert classify_units(small + big, CLASSES) == 1  # request is atomic
    huge = _units_for_sams(
        [make_sam(tmp_path / "h.sam", ref="h", L=9000, seed=4)]
    )
    assert classify_units(huge, CLASSES) is None  # oversize → lanes path


# ----------------------------------------------------------- kernel parity


def test_kernel_parity_fast_and_masks_paths(tmp_path):
    """Direct pack→kernel→unpack round trip vs the bam_to_consensus
    oracle, both wire variants, on mixed shapes in one superbatch."""
    sams = _mixed_sams(tmp_path, 5, seed_base=7)
    pool = ThreadPoolExecutor(4)
    for opts in (
        BatchOptions(),
        BatchOptions(build_changes=True, build_reports=True),
    ):
        units = _units_for_sams(sams)
        cls = CLASSES[classify_units(units, CLASSES)]
        table = build_segment_table(units, cls)
        arrays = pack_superbatch(units, table)
        wire = launch_ragged(arrays, cls, opts)
        outs = unpack_superbatch(
            wire, table, units, opts, pool, paths=[str(p) for p in sams]
        )
        for i, p in enumerate(sams):
            want = bam_to_consensus(str(p))
            seq, changes, report = outs[i]
            assert seq.name == want.consensuses[0].name
            assert seq.sequence == want.consensuses[0].sequence
            if opts.build_changes:
                ref = seq.name[: -len("_cns")]
                assert changes == want.refs_changes[ref]
                assert report == want.refs_reports[ref]


def _clip_dominant_sam(dest, ref="cref", L=400, seed=0):
    """Facing soft-clip pileups around ~position 200 whose projections
    overlap — clip depth dominates aligned depth, so the CDR triggers
    fire and a merged patch materializes (a realign test that never
    triggers would pin nothing)."""
    rng = np.random.default_rng(seed)
    lines = ["@HD\tVN:1.6", f"@SQ\tSN:{ref}\tLN:{L}"]
    novel = "".join("ACGT"[b] for b in rng.integers(0, 4, size=40))
    body = "".join("ACGT"[b] for b in rng.integers(0, 4, size=60))
    body2 = "".join("ACGT"[b] for b in rng.integers(0, 4, size=60))
    for i in range(25):
        lines.append(
            f"f{i}\t0\t{ref}\t141\t60\t60M30S\t*\t0\t0\t"
            f"{body}{novel[:30]}\t*"
        )
    for i in range(25):
        lines.append(
            f"r{i}\t0\t{ref}\t221\t60\t30S60M\t*\t0\t0\t"
            f"{novel[10:40]}{body2}\t*"
        )
    dest.write_text("\n".join(lines) + "\n")
    return dest


def test_realign_kernel_parity_with_live_cdr_patch(tmp_path):
    """The clip-channel segment kernel vs the bam_to_consensus oracle on
    clip-dominant data: the dominance triggers fire, the segment-
    windowed CDR walk produces a real merged patch, and sequence /
    changes / report are byte-identical."""
    sam = _clip_dominant_sam(tmp_path / "clip.sam")
    opts = BatchOptions(
        realign=True, build_changes=True, build_reports=True,
        mask_ends=20,
    )
    units = _units_for_sams([sam], realign=True, build_changes=True,
                            build_reports=True, mask_ends=20)
    cls = CLASSES[classify_units(units, CLASSES)]
    table = build_segment_table(units, cls)
    arrays = pack_superbatch(units, table, realign=True)
    out = launch_ragged(arrays, cls, opts)
    pool = ThreadPoolExecutor(2)
    (res,) = unpack_superbatch(
        out, table, units, opts, pool, paths=[str(sam)]
    )
    seq, changes, report = res
    patches = units[0].cdr_patches
    assert patches, "clip-dominant data produced no CDR patch"
    want = bam_to_consensus(str(sam), realign=True, mask_ends=20)
    assert seq.sequence == want.consensuses[0].sequence
    ref = seq.name[: -len("_cns")]
    assert changes == want.refs_changes[ref]
    assert report == want.refs_reports[ref]


def test_pallas_segment_reduction_matches_xla(tmp_path, monkeypatch):
    """The gated Pallas fast path (interpret mode on CPU) must emit a
    wire byte-identical to the XLA segment-reduction path."""
    sams = _mixed_sams(tmp_path, 4, seed_base=11)
    units = _units_for_sams(sams)
    opts = BatchOptions()
    cls = CLASSES[classify_units(units, CLASSES)]
    arrays = pack_superbatch(units, build_segment_table(units, cls))
    monkeypatch.setenv("KINDEL_TPU_RAGGED_PALLAS", "0")
    w_xla = np.asarray(launch_ragged(arrays, cls, opts))
    monkeypatch.setenv("KINDEL_TPU_RAGGED_PALLAS", "1")
    w_pl = np.asarray(launch_ragged(arrays, cls, opts))
    assert np.array_equal(w_xla, w_pl)


# --------------------------------------------------------------- batcher


def test_ragged_batcher_max_wait_flush(tmp_path):
    sam = make_sam(tmp_path / "one.sam", seed=21)
    mb = RaggedBatcher(CLASSES, max_wait_s=0.05)
    req = ServeRequest(payload=str(sam), opts=BatchOptions())
    mb.add(req, _decode(str(sam)))
    flush = mb.poll(timeout=5.0)
    assert isinstance(flush, RaggedFlush)
    assert flush.page_class is CLASSES[0]
    assert [r for r, _ in flush.entries] == [req]


def test_ragged_batcher_seals_at_segment_cap(tmp_path):
    sams = [
        make_sam(tmp_path / f"c{i}.sam", ref=f"c{i}", L=300, seed=30 + i)
        for i in range(3)
    ]
    mb = RaggedBatcher(CLASSES, max_batch_rows=2, max_wait_s=30.0)
    for p in sams:
        mb.add(ServeRequest(payload=str(p), opts=BatchOptions()),
               _decode(str(p)))
    flush = mb.poll(timeout=0.5)  # sealed by the segment cap, not age
    assert isinstance(flush, RaggedFlush) and len(flush.entries) == 2
    assert mb.pending_rows == 1  # the third stays in an open lane


def test_ragged_batcher_joins_open_larger_lane(tmp_path):
    """Occupancy-first placement: a small-class request arriving while a
    larger lane is open (same opts) fills that lane instead of opening
    its own grid."""
    big = make_sam(tmp_path / "jb.sam", ref="jb", L=5000, seed=41)
    small = make_sam(tmp_path / "js.sam", ref="js", L=300, seed=42)
    mb = RaggedBatcher(CLASSES, max_wait_s=30.0)
    mb.add(ServeRequest(payload=str(big), opts=BatchOptions()),
           _decode(str(big)))
    mb.add(ServeRequest(payload=str(small), opts=BatchOptions()),
           _decode(str(small)))
    flushes = mb.flush_all()
    assert len(flushes) == 1 and flushes[0].page_class.name == "medium"
    assert len(flushes[0].entries) == 2


def test_only_oversize_falls_back_and_realign_fallback_pinned_zero(tmp_path):
    """Since the segment kernel learned the clip-channel scatter +
    windowed CDR fetches, realign rides a superbatch like everything
    else: `kindel_ragged_fallback_total{reason="realign"}` is a
    regression tripwire PINNED AT ZERO, and only oversize requests take
    the shape-keyed lanes path."""
    reg = default_registry()
    before = {
        k: v for k, v in reg.snapshot().items()
        if k.startswith("kindel_ragged_fallback_total")
    }
    sam = make_sam(tmp_path / "fb.sam", seed=51)
    huge = make_sam(tmp_path / "fh.sam", ref="fh", L=9000, seed=52)
    mb = RaggedBatcher(CLASSES, max_wait_s=30.0)
    mb.add(ServeRequest(payload=str(sam), opts=BatchOptions(realign=True)),
           _decode(str(sam), realign=True))
    mb.add(ServeRequest(payload=str(huge), opts=BatchOptions()),
           _decode(str(huge)))
    flushes = mb.flush_all()
    assert len(flushes) == 2
    ragged_flushes = [f for f in flushes if isinstance(f, RaggedFlush)]
    assert len(ragged_flushes) == 1  # the realign request superbatches
    assert ragged_flushes[0].opts.realign
    snap = reg.snapshot()
    delta = {
        reason: snap.get(
            'kindel_ragged_fallback_total{reason="%s"}' % reason, 0
        ) - before.get(
            'kindel_ragged_fallback_total{reason="%s"}' % reason, 0
        )
        for reason in ("realign", "oversize")
    }
    assert delta == {"realign": 0, "oversize": 1}


def test_take_ready_degrades_to_one_batch_for_superbatches(tmp_path):
    """Fat-dispatch coalescing must not merge sealed superbatches — a
    superbatch is already the fattest launch its geometry allows."""
    sams = [
        make_sam(tmp_path / f"t{i}.sam", ref=f"t{i}", L=300, seed=60 + i)
        for i in range(4)
    ]
    mb = RaggedBatcher(CLASSES, max_batch_rows=1, max_wait_s=30.0)
    for p in sams:
        mb.add(ServeRequest(payload=str(p), opts=BatchOptions()),
               _decode(str(p)))
    first = mb.poll(timeout=1.0)
    assert isinstance(first, RaggedFlush)
    assert mb.take_ready(first, limit=8) == []
    # the remaining sealed flushes still drain one at a time
    rest = [mb.poll(timeout=1.0) for _ in range(3)]
    assert all(isinstance(f, RaggedFlush) for f in rest)


# ------------------------------------------------- serve path, end to end


def _serve_all(sams, mode, *, lane_coalesce=2, faults=None, **svc_kwargs):
    """Serve every sam concurrently under `mode`; returns (fasta list in
    input order, service metrics snapshot, healthz doc)."""
    results = [None] * len(sams)
    errors: list = []
    with ConsensusService(
        tuning=TuningConfig(batch_mode=mode, lane_coalesce=lane_coalesce),
        max_wait_s=0.15, decode_workers=4, **svc_kwargs,
    ) as svc:
        client = ConsensusClient(svc)

        def one(i):
            try:
                results[i] = client.fasta(str(sams[i]), timeout=300)
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [
            threading.Thread(target=one, args=(i,))
            for i in range(len(sams))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = svc.metrics.snapshot()
        health = svc.healthz()
    assert not errors, errors
    return results, snap, health


def test_mixed_shape_stream_ragged_equals_lanes_byte_identical(tmp_path):
    """The flagship parity property: randomized mixed-shape request
    streams produce byte-identical FASTA under ragged and lanes modes
    (workers + fat-dispatch coalescing on), and the ragged run compiles
    at most one kernel per page geometry."""
    sams = _mixed_sams(tmp_path, 10, seed_base=5)
    lanes, _snap_l, _h = _serve_all(sams, "lanes")
    cache_before = obs_runtime.jit_cache_sizes().get("ragged_call_kernel", 0)
    ragged, snap_r, health = _serve_all(sams, "ragged")
    cache_after = obs_runtime.jit_cache_sizes().get("ragged_call_kernel", 0)
    assert ragged == lanes, "ragged FASTA diverged from the lanes path"
    geometries = len({
        classify_units(_decode(str(p)), CLASSES) for p in sams
    })
    assert cache_after - cache_before <= len(CLASSES), (
        "more ragged kernel compiles than page classes", cache_after,
    )
    assert health["batch_mode"] == "ragged"
    assert geometries >= 2, "stream was not shape-diverse enough"


def test_realign_traffic_rides_superbatches_byte_identical(tmp_path):
    """Realign requests served through ragged mode produce byte-identical
    FASTA to the lanes path (the clip-channel kernel + segment-windowed
    CDR fetches), and no request takes the realign fallback — the
    counter stays a zeroed tripwire end to end."""
    reg = default_registry()

    def realign_fallbacks():
        return reg.snapshot().get(
            'kindel_ragged_fallback_total{reason="realign"}', 0
        )

    sams = _mixed_sams(tmp_path, 5, seed_base=23)
    lanes, _s, _h = _serve_all(sams, "lanes", realign=True)
    before = realign_fallbacks()
    ragged, _snap, _health = _serve_all(sams, "ragged", realign=True)
    assert ragged == lanes, "realign ragged FASTA diverged from lanes"
    assert realign_fallbacks() == before, (
        "realign traffic fell back to shape-keyed lanes"
    )


def test_mixed_stream_with_faults_still_byte_identical(tmp_path):
    """Chaos on: transient flush faults retry/degrade through the
    resilience ladder and the served bytes still match the lanes path."""
    from kindel_tpu.resilience import FaultPlan
    from kindel_tpu.resilience import faults as rfaults

    sams = _mixed_sams(tmp_path, 6, seed_base=9)
    lanes, _s, _h = _serve_all(sams, "lanes")
    rfaults.activate(FaultPlan.parse("serve.flush:error:times=2"))
    try:
        ragged, _snap, _health = _serve_all(sams, "ragged")
    finally:
        rfaults.deactivate()
    assert ragged == lanes


def test_ragged_occupancy_metrics_recorded(tmp_path):
    reg = default_registry()

    def totals():
        snap = reg.snapshot()
        return (
            sum(
                int(v) for k, v in snap.items()
                if k.startswith("kindel_ragged_superbatches_total")
                and not isinstance(v, dict)
            ),
            snap.get("kindel_dispatch_payload_bases_total", 0),
            snap.get("kindel_dispatch_padded_bases_total", 0),
        )

    sams = _mixed_sams(tmp_path, 4, seed_base=13)
    s0, payload0, padded0 = totals()
    _r, _s, _h = _serve_all(sams, "ragged")
    s1, payload1, padded1 = totals()
    assert s1 > s0, "no superbatch counted"
    payload, padded = payload1 - payload0, padded1 - padded0
    want_payload = sum(u.L for p in sams for u in _decode(str(p)))
    assert payload == want_payload
    assert padded > payload  # occupancy < 1 by construction
    occ = reg.snapshot().get("kindel_ragged_occupancy", {})
    assert occ.get("count", 0) > 0 and 0 < occ["mean"] <= 1


def test_healthz_reports_batch_mode_and_classes(tmp_path):
    with ConsensusService(
        tuning=TuningConfig(batch_mode="ragged"), max_wait_s=0.01
    ) as svc:
        health = svc.healthz()
    assert health["batch_mode"] == "ragged"
    labels = health["ragged"]["classes"]
    assert labels and all(":r" in lab for lab in labels)
    with ConsensusService(max_wait_s=0.01) as svc:
        health = svc.healthz()
    assert health["batch_mode"] == "lanes"
    assert "ragged" not in health


def test_ragged_warmup_zero_compile_covers_arbitrary_traffic(
    tmp_path, monkeypatch
):
    """After a ragged warmup, a request of a NEVER-SEEN shape (the
    zero-compile claim's whole point: arbitrary traffic, not
    startup-derivable shapes) triggers no new kernel compile."""
    monkeypatch.setenv(
        "KINDEL_TPU_TUNE_CACHE", str(tmp_path / "tune.json")
    )
    cache_size = getattr(ragged_call_kernel, "_cache_size", None)
    if cache_size is None:
        pytest.skip("jit cache counter unavailable on this jax")
    sam = make_sam(tmp_path / "novel.sam", ref="novel", L=777, seed=99)
    want = bam_to_consensus(str(sam)).consensuses
    with ConsensusService(
        tuning=TuningConfig(
            batch_mode="ragged", ragged_classes="only:16x2048"
        ),
        max_wait_s=0.01, warmup=True,
    ) as svc:
        assert svc.wait_warm(timeout=300)
        before = cache_size()
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
        assert cache_size() == before, (
            "post-warmup request of an unseen shape compiled a kernel"
        )
        snap = svc.metrics.snapshot()
    assert [(r.name, r.sequence) for r in got] == [
        (r.name, r.sequence) for r in want
    ]
    shapes = snap.get("kindel_serve_warmup_shape", [])
    ragged_marks = [
        s for s in shapes if s.get("shape", "").startswith("ragged:")
    ]
    assert ragged_marks, "warmup Info carries no ragged geometries"
    assert all(s.get("batch_mode") == "ragged" for s in ragged_marks)


# ------------------------------------------------ label-cardinality guard


def test_label_capper_pins_the_cap():
    capper = LabelCapper(cap=4)
    seen = {capper.see(f"shape{i}") for i in range(50)}
    assert len(seen) == 5  # 4 admitted + "other"
    assert "other" in seen
    # admitted values keep reporting under their own name
    assert capper.see("shape0") == "shape0"
    assert capper.see("shape49") == "other"
    assert DEFAULT_LABEL_CAP == 24  # the documented serve-tier bound


def test_dispatch_histogram_shape_labels_are_bounded(tmp_path):
    """Under shape-diverse lanes traffic the per-shape dispatch
    histogram must stay within the label cap (+1 for `other`)."""
    sams = _mixed_sams(tmp_path, 8, seed_base=17)
    _r, snap, _h = _serve_all(sams, "lanes")
    labels = {
        k for k in snap
        if k.startswith("kindel_serve_dispatch_seconds{")
    }
    assert labels, "dispatch histogram recorded nothing"
    assert len(labels) <= DEFAULT_LABEL_CAP + 1


# ----------------------------------------------------------- tune knobs


def test_batch_mode_resolution_precedence(monkeypatch):
    from kindel_tpu import tune

    monkeypatch.delenv("KINDEL_TPU_BATCH_MODE", raising=False)
    assert tune.resolve_batch_mode() == ("lanes", "default")
    monkeypatch.setenv("KINDEL_TPU_BATCH_MODE", "ragged")
    assert tune.resolve_batch_mode() == ("ragged", "env")
    assert tune.resolve_batch_mode("lanes") == ("lanes", "explicit")
    monkeypatch.setenv("KINDEL_TPU_BATCH_MODE", "garbage")
    assert tune.resolve_batch_mode() == ("lanes", "default")
    with pytest.raises(ValueError):
        tune.resolve_batch_mode("garbage")


def test_ragged_classes_resolution_precedence(tmp_path, monkeypatch):
    from kindel_tpu import tune

    monkeypatch.setenv(
        "KINDEL_TPU_TUNE_CACHE", str(tmp_path / "tune.json")
    )
    monkeypatch.delenv("KINDEL_TPU_RAGGED_CLASSES", raising=False)
    spec, src = tune.resolve_ragged_classes()
    assert src == "default" and parse_classes(spec)
    tune.record(tune.ragged_store_key(), {"classes": "a:8x2048"})
    assert tune.resolve_ragged_classes() == ("a:8x2048", "cache")
    monkeypatch.setenv("KINDEL_TPU_RAGGED_CLASSES", "b:4x2048")
    assert tune.resolve_ragged_classes() == ("b:4x2048", "env")
    assert tune.resolve_ragged_classes("c:2x2048") == (
        "c:2x2048", "explicit",
    )


def test_search_ragged_classes_picks_the_fastest():
    from kindel_tpu import tune

    walls = {"a": 0.3, "b": 0.1, "c": 0.2}
    chosen, timings = tune.search_ragged_classes(
        lambda spec: walls[spec], candidates=("a", "b", "c"),
        budget_s=10.0,
    )
    assert chosen == "b" and len(timings) == 3
