"""Randomized CIGAR property fuzz for the device ingest tier (sits next
to tests/test_decode_fuzz.py, which owns the malformed-bytes surface).

Property: for randomly generated — but structurally consistent — BAM
records covering every CIGAR op code (M/I/D/N/S/H/P/=/X), zero-length
ops, leading/trailing clips, unmapped and negative-ref reads, records
straddling chunk boundaries, and truncated tails, the device
scan/fields/expand output equals the host oracle EVENT-FOR-EVENT: same
streams in the same order, same insertion Counter, same errors with
the same attribution."""

from __future__ import annotations

import struct

import numpy as np
import pytest

from kindel_tpu.devingest import extract_events_device, stream_device_events
from kindel_tpu.events import extract_events
from kindel_tpu.io.bam import parse_bam_bytes
from kindel_tpu.io.errors import TruncatedInputError
from kindel_tpu.io.stream import stream_alignment

from test_devingest import EV_FIELDS, assert_events_equal
from test_ingest import bgzf_compress

#: query-consuming op codes (M I S = X) — generated reads keep the
#: CIGAR/SEQ byte accounting consistent, like every real aligner does
_QRY_OPS = {0, 1, 4, 7, 8}


def fuzz_bam_raw(seed: int, n_reads: int = 80, ref_len: int = 300) -> bytes:
    """Valid-framing BAM with adversarial-but-consistent CIGARs: all 9
    op codes, zero-length ops, random positions (including ones whose
    clips project off either reference edge), unmapped reads, reads on
    either of two references."""
    rng = np.random.default_rng(seed)
    header_text = b"@HD\tVN:1.6\n"
    out = bytearray(b"BAM\x01")
    out += struct.pack("<i", len(header_text)) + header_text
    out += struct.pack("<i", 2)
    for name, ln in ((b"rA\x00", ref_len), (b"rB\x00", ref_len * 2)):
        out += struct.pack("<i", len(name)) + name + struct.pack("<i", ln)
    for r in range(n_reads):
        n_ops = int(rng.integers(1, 8))
        ops = [
            (int(rng.integers(0, 12)), int(rng.integers(0, 9)))
            for _ in range(n_ops)
        ]
        l_seq = sum(ln for ln, c in ops if c in _QRY_OPS)
        rid = int(rng.integers(-1, 2))
        pos = int(rng.integers(0, ref_len))
        flag = int(rng.choice([0, 0, 0, 4, 16]))
        name = f"q{r}".encode() + b"\x00"
        nib = rng.integers(1, 16, size=max(l_seq, 1))
        packed = bytearray()
        for i in range(0, l_seq, 2):
            hi = int(nib[i]) << 4
            lo = int(nib[i + 1]) if i + 1 < l_seq else 0
            packed.append(hi | lo)
        cig = b"".join(
            struct.pack("<I", (ln << 4) | c) for ln, c in ops
        )
        body = struct.pack(
            "<iiBBHHHiiii", rid, pos, len(name), 60, 0, len(ops), flag,
            l_seq, -1, -1, 0,
        )
        body += name + cig + bytes(packed) + b"\xff" * l_seq
        out += struct.pack("<i", len(body)) + body
    return bytes(out)


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_one_shot_event_parity(seed):
    raw = fuzz_bam_raw(seed)
    host_ev = extract_events(parse_bam_bytes(raw))
    dev_ev = extract_events_device(raw)
    assert_events_equal(host_ev, dev_ev, label=f"seed={seed}")


@pytest.mark.parametrize("seed", (1, 5, 9))
def test_fuzz_streamed_chunk_straddle_parity(seed, tmp_path):
    """Tiny chunk_bytes forces records to straddle chunk boundaries:
    the device carry logic must frame the same chunks and emit the same
    events as the host scanner, chunk for chunk."""
    raw = fuzz_bam_raw(seed, n_reads=120)
    path = tmp_path / "fuzz.bam"
    path.write_bytes(bgzf_compress(raw, member_bytes=512))
    for chunk_bytes in (512, 4096):
        host = [
            extract_events(b)
            for b in stream_alignment(path, chunk_bytes, ingest_workers=1)
        ]
        dev = [
            d.to_host() if hasattr(d, "to_host") else d
            for d in stream_device_events(path, chunk_bytes, 1)
        ]
        assert len(dev) == len(host)
        for i, (h, d) in enumerate(zip(host, dev)):
            assert_events_equal(h, d, label=f"seed={seed} chunk={i}")


@pytest.mark.parametrize("seed", (2, 7))
def test_fuzz_truncated_tail_parity(seed, tmp_path):
    """A mid-record truncated tail raises the same TruncatedInputError
    (message + chunk attribution) from both ingest modes."""
    raw = fuzz_bam_raw(seed)
    blob = bgzf_compress(raw, member_bytes=512)
    path = tmp_path / "cut.bam"
    path.write_bytes(blob[: int(len(blob) * 0.7)])
    outcomes = []
    for events_iter in (
        lambda: stream_alignment(path, 2048, ingest_workers=1),
        lambda: stream_device_events(path, 2048, 1),
    ):
        try:
            for _ in events_iter():
                pass
            outcomes.append(("ok",))
        except TruncatedInputError as e:
            outcomes.append((str(e), e.chunk_index, str(e.path)))
    assert outcomes[0] == outcomes[1]
    assert outcomes[0][0] != "ok"


def test_fuzz_zero_length_leading_clip_insertion():
    """Directed edge: zero-length I at the read head must dictionary-
    encode an EMPTY insertion string in both modes (the host oracle
    counts it; Counter equality would catch a device drop)."""
    raw = fuzz_bam_raw(3, n_reads=0)
    # one hand-built read: 0-length I, leading S, N skip, trailing S
    ops = [(4, 4), (0, 1), (6, 0), (5, 3), (2, 8), (3, 4)]
    l_seq = sum(ln for ln, c in ops if c in _QRY_OPS)
    name = b"edge\x00"
    cig = b"".join(struct.pack("<I", (ln << 4) | c) for ln, c in ops)
    nib = bytes(
        ((i % 15 + 1) << 4) | ((i + 7) % 15 + 1)
        for i in range((l_seq + 1) // 2)
    )
    body = struct.pack(
        "<iiBBHHHiiii", 0, 10, len(name), 60, 0, len(ops), 0,
        l_seq, -1, -1, 0,
    )
    body += name + cig + nib + b"\xff" * l_seq
    raw = raw + struct.pack("<i", len(body)) + bytes(body)
    host_ev = extract_events(parse_bam_bytes(raw))
    dev_ev = extract_events_device(raw)
    assert_events_equal(host_ev, dev_ev, label="edge")
    assert any(ins == b"" for (_r, _p, ins) in host_ev.insertions)
