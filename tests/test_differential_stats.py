"""Differential fuzz for the weights/features workloads + jax-sharded
consensus (VERDICT r2 item 6), with the reference implementation as a
live oracle (loader shared with tests/test_differential.py).

The two documented divergences are asserted AS divergences:

  * weights: the reference indexes the insertions column with a shifted
    1-based counter (ref kindel.py:579-581), putting it one row late
    relative to the base columns; kindel-tpu anchors every column at the
    same position. Every other column must match exactly.
  * features: the reference leaks the per-ref loop variable and fills
    the indel columns of ALL rows from whichever reference was last in
    scope, indexed by global row (ref kindel.py:644-646) — wrong for
    multi-reference inputs. kindel-tpu computes per reference; the
    oracle for multi-ref is therefore the concatenation of per-ref
    single-reference reference runs (which cannot leak).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np
import pandas as pd
import pytest
import test_differential as td

from kindel_tpu import workloads

pytestmark = pytest.mark.skipif(
    td.REF is None, reason="reference implementation not importable"
)


def _install_oracle(monkeypatch, alns: "OrderedDict"):
    monkeypatch.setattr(td.REF, "parse_bam", lambda path: alns)


def _write_sam(tmp_path, name, ref_len, reads):
    sam = tmp_path / name
    sam.write_bytes(td.to_sam(ref_len, reads))
    return sam


def _multi_ref_sam(tmp_path, name, refs):
    """refs: OrderedDict[ref_name -> (ref_len, reads)] → one SAM."""
    lines = [b"@HD\tVN:1.6"]
    for ref, (L, _) in refs.items():
        lines.append(f"@SQ\tSN:{ref}\tLN:{L}".encode())
    i = 0
    for ref, (_, reads) in refs.items():
        for r in reads:
            lines.append(
                f"r{i}\t0\t{ref}\t{r.pos}\t60\t{r.cigar_str()}\t*\t0\t0\t"
                f"{r.seq}\t*".encode()
            )
            i += 1
    sam = tmp_path / name
    sam.write_bytes(b"\n".join(lines) + b"\n")
    return sam


def _cmp(ours: pd.DataFrame, ref: pd.DataFrame, col: str, atol: float):
    a = np.asarray(ours[col], dtype=np.float64)
    b = np.asarray(ref[col], dtype=np.float64)
    np.testing.assert_allclose(
        a, b, rtol=0, atol=atol, equal_nan=True, err_msg=col
    )


# --------------------------------------------------------------- weights


EXACT = 1e-12


@pytest.mark.parametrize("seed", range(20))
@pytest.mark.parametrize("relative", [False, True])
def test_weights_matches_reference(seed, relative, monkeypatch, tmp_path):
    ref_len, reads = td.random_alignment(seed)
    aln = td.REF.parse_records("ref1", ref_len, reads)
    _install_oracle(monkeypatch, OrderedDict([("ref1", aln)]))
    ref_df = td.REF.weights("ignored", relative=relative, confidence=True)

    sam = _write_sam(tmp_path, f"w{seed}.sam", ref_len, reads)
    ours = workloads.weights(sam, relative=relative, confidence=True)

    assert list(ours["chrom"]) == list(ref_df["chrom"])
    for col in ["pos", "deletions", "clip_starts", "clip_ends", "depth"]:
        _cmp(ours, ref_df, col, EXACT)
    for col in ["A", "C", "G", "T", "N"]:
        _cmp(ours, ref_df, col, EXACT)
    for col in ["consensus", "shannon", "lower_ci", "upper_ci"]:
        _cmp(ours, ref_df, col, EXACT)

    # divergence asserted AS a divergence: the reference's insertions
    # column is one row late (1-based indexing into the L+1 array); ours
    # is position-aligned. ref row k carries totals[k] = our row k+1.
    ref_ins = np.asarray(ref_df["insertions"], dtype=np.int64)
    our_ins = np.asarray(ours["insertions"], dtype=np.int64)
    assert (ref_ins[:-1] == our_ins[1:]).all()
    assert our_ins[0] == sum(aln.insertions[0].values())
    assert ref_ins[-1] == sum(aln.insertions[ref_len].values())
    if our_ins.any() or ref_ins.any():
        # non-vacuous on seeds that produced insertions
        assert not (ref_ins == our_ins).all() or (
            (our_ins[0] == ref_ins[-1]) and len(set(our_ins)) <= 1
        )


@pytest.mark.parametrize("seed", [3, 11])
def test_weights_jax_backend_matches_reference(seed, monkeypatch, tmp_path):
    """Same oracle, jax backend: entropy/CI via jax kernels — identical
    after the shared 3dp rounding (tolerance covers betainc inversion)."""
    ref_len, reads = td.random_alignment(seed)
    aln = td.REF.parse_records("ref1", ref_len, reads)
    _install_oracle(monkeypatch, OrderedDict([("ref1", aln)]))
    ref_df = td.REF.weights("ignored", confidence=True)

    sam = _write_sam(tmp_path, f"wj{seed}.sam", ref_len, reads)
    ours = workloads.weights(sam, confidence=True, backend="jax")
    for col in ["pos", "A", "C", "G", "T", "N", "deletions", "depth"]:
        _cmp(ours, ref_df, col, EXACT)
    for col in ["consensus", "shannon"]:
        _cmp(ours, ref_df, col, 1e-3)
    for col in ["lower_ci", "upper_ci"]:
        _cmp(ours, ref_df, col, 2e-3)


def test_weights_multi_ref(monkeypatch, tmp_path):
    """weights has no leak in the reference — multi-ref must match
    column-for-column (modulo the insertion shift per reference)."""
    refs = OrderedDict()
    for i, seed in enumerate((2, 5)):
        L, reads = td.random_alignment(seed)
        for r in reads:
            r.rname = f"ref{i + 1}"
        refs[f"ref{i + 1}"] = (L, reads)
    alns = OrderedDict(
        (name, td.REF.parse_records(name, L, reads))
        for name, (L, reads) in refs.items()
    )
    _install_oracle(monkeypatch, alns)
    ref_df = td.REF.weights("ignored", confidence=True)
    sam = _multi_ref_sam(tmp_path, "wmulti.sam", refs)
    ours = workloads.weights(sam, confidence=True)
    assert list(ours["chrom"]) == list(ref_df["chrom"])
    for col in ["pos", "A", "C", "G", "T", "N", "deletions", "clip_starts",
                "clip_ends", "depth", "consensus", "shannon", "lower_ci",
                "upper_ci"]:
        _cmp(ours, ref_df, col, EXACT)


def test_weights_entropy_regression_detected(monkeypatch, tmp_path):
    """The harness is live: a deliberate entropy regression must fail."""
    ref_len, reads = td.random_alignment(1)
    aln = td.REF.parse_records("ref1", ref_len, reads)
    _install_oracle(monkeypatch, OrderedDict([("ref1", aln)]))
    ref_df = td.REF.weights("ignored", confidence=True)
    sam = _write_sam(tmp_path, "wreg.sam", ref_len, reads)

    monkeypatch.setattr(
        workloads, "_shannon", lambda rel: np.zeros(rel.shape[0])
    )
    broken = workloads.weights(sam, confidence=True)
    with pytest.raises(AssertionError):
        _cmp(broken, ref_df, "shannon", EXACT)


# -------------------------------------------------------------- features


@pytest.mark.parametrize("seed", range(20))
def test_features_matches_reference_single_ref(seed, monkeypatch, tmp_path):
    """Single reference: the loop-variable leak is harmless, so every
    column must match the reference exactly."""
    ref_len, reads = td.random_alignment(seed)
    aln = td.REF.parse_records("ref1", ref_len, reads)
    _install_oracle(monkeypatch, OrderedDict([("ref1", aln)]))
    ref_df = td.REF.features("ignored")

    sam = _write_sam(tmp_path, f"f{seed}.sam", ref_len, reads)
    ours = workloads.features(sam)
    assert list(ours["chrom"]) == list(ref_df["chrom"])
    for col in ["pos", "depth"]:
        _cmp(ours, ref_df, col, EXACT)
    for col in ["A", "C", "G", "T", "N", "i", "d", "consensus", "shannon"]:
        _cmp(ours, ref_df, col, EXACT)


def test_features_multi_ref_divergence(monkeypatch, tmp_path):
    """Multi-reference: the reference fills indel columns from the LAST
    reference's alignment indexed by global row (the leak) — asserted as
    a real divergence — while kindel-tpu must equal the leak-free oracle
    (per-ref single-reference reference runs, concatenated)."""
    refs = OrderedDict()
    for i, seed in enumerate((4, 9)):
        L, reads = td.random_alignment(seed)
        for r in reads:
            r.rname = f"ref{i + 1}"
        refs[f"ref{i + 1}"] = (L, reads)
    alns = OrderedDict(
        (name, td.REF.parse_records(name, L, reads))
        for name, (L, reads) in refs.items()
    )

    # leak-free oracle: one single-ref reference run per reference
    parts = []
    for name, aln in alns.items():
        _install_oracle(monkeypatch, OrderedDict([(name, aln)]))
        parts.append(td.REF.features("ignored"))
    oracle = pd.concat(parts, ignore_index=True)

    sam = _multi_ref_sam(tmp_path, "fmulti.sam", refs)
    ours = workloads.features(sam)
    assert list(ours["chrom"]) == list(oracle["chrom"])
    for col in ["pos", "A", "C", "G", "T", "N", "i", "d", "depth",
                "consensus", "shannon"]:
        _cmp(ours, oracle, col, EXACT)

    # and the leak is real: indexing the LAST reference's arrays by
    # GLOBAL row position overruns them whenever the first reference is
    # longer than 1 bp — the reference doesn't just mislabel multi-ref
    # indel fractions, it crashes outright
    _install_oracle(monkeypatch, alns)
    with pytest.raises(IndexError):
        td.REF.features("ignored")


# ---------------------------------------------- jax-sharded consensus fuzz


@pytest.mark.parametrize("seed", range(12))
@pytest.mark.parametrize("realign", [False, True])
def test_consensus_matches_reference_jax_sharded(seed, realign, tmp_path):
    """The round-2 fuzz ran the numpy backend only; this drives the same
    oracle through backend=jax on the virtual mesh (ref_len >= 30 >= 8
    devices → the position-sharded product path engages)."""
    import jax

    assert len(jax.devices()) >= 2, "virtual mesh missing"
    ref_len, reads = td.random_alignment(seed)
    aln = td.REF.parse_records("ref1", ref_len, reads)

    cdr_patches = None
    if realign:
        cdrps = td.REF.cdrp_consensuses(
            aln.weights, aln.deletions, aln.clip_start_weights,
            aln.clip_end_weights, aln.clip_start_depth, aln.clip_end_depth,
            0.1, 10,
        )
        cdr_patches = td.REF.merge_cdrps(cdrps, 7)
    ref_seq, ref_changes = td.REF.consensus_sequence(
        aln.weights, aln.insertions, aln.deletions, cdr_patches,
        trim_ends=False, min_depth=1, uppercase=False,
    )

    sam = tmp_path / f"jfuzz{seed}.sam"
    sam.write_bytes(td.to_sam(ref_len, reads))
    res = workloads.bam_to_consensus(
        sam, realign=realign, min_depth=1, min_overlap=7,
        clip_decay_threshold=0.1, mask_ends=10, trim_ends=False,
        uppercase=False, backend="jax",
    )
    assert res.consensuses[0].sequence == ref_seq, f"seed={seed}"
    assert res.refs_changes["ref1"] == ref_changes


def test_weights_tsv_backend_byte_identity(data_root, tmp_path):
    """VERDICT r4 item 7: the full weights/features/variants TSVs must be
    byte-for-byte identical between backends on the golden corpus — one
    decision procedure, no f32-vs-f64 rounding cracks."""
    from kindel_tpu import workloads

    for rel in (
        "data_bwa_mem/1.1.sub_test.bam",
        "data_minimap2/1.1.multi.bam",
    ):
        bam = data_root / rel
        for fn, kwargs in (
            (workloads.weights, {}),
            (workloads.weights, {"relative": True}),
            (workloads.features, {}),
            (workloads.variants, {}),
        ):
            np_tsv = fn(bam, backend="numpy", **kwargs).to_csv(sep="\t")
            jx_tsv = fn(bam, backend="jax", **kwargs).to_csv(sep="\t")
            assert np_tsv == jx_tsv, (rel, fn.__name__, kwargs)
