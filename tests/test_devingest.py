"""Device-side ingest (kindel_tpu.devingest) — the parity harness.

The contract under test extends tests/test_ingest.py's: device ingest
is an invisible optimization. For EVERY worker count and chunk size the
consensus FASTA, the per-chunk EventSet (element-for-element), the
truncation error (message / path / chunk attribution), and the
io.read_chunk fault replay are identical to the host oracle — only
where the scan/expand wall is spent may differ (pinned by the new
device counters). All tests run on the CPU jax backend (devingest
kernels are backend-agnostic; the Pallas gate's interpret mode is
exercised explicitly).
"""

from __future__ import annotations

import numpy as np
import pytest

from test_ingest import (  # shared synthetic BGZF builders (same rootdir)
    bgzf_compress,
    require_data,
    synth_bam_raw,
)

from kindel_tpu.io.errors import TruncatedInputError
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience.faults import FaultPlan
from kindel_tpu.streaming import streamed_consensus
from kindel_tpu.tune import TuningConfig

WORKER_COUNTS = (1, 2, 8)

EV_FIELDS = (
    "match_rid", "match_pos", "match_base", "del_rid", "del_pos",
    "cs_rid", "cs_pos", "ce_rid", "ce_pos",
    "csw_rid", "csw_pos", "csw_base", "cew_rid", "cew_pos", "cew_base",
)


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    rfaults.deactivate()


@pytest.fixture(scope="module")
def synth_bam(tmp_path_factory):
    raw = synth_bam_raw()
    path = tmp_path_factory.mktemp("devingest") / "synth.bam"
    path.write_bytes(bgzf_compress(raw))
    return path


def fasta(res):
    return [(s.name, s.sequence) for s in res.consensuses]


def assert_events_equal(host_ev, dev_ev, label=""):
    assert host_ev.present_ref_ids == dev_ev.present_ref_ids, label
    assert host_ev.insertions == dev_ev.insertions, label
    for f in EV_FIELDS:
        h = np.asarray(getattr(host_ev, f))
        d = np.asarray(getattr(dev_ev, f))
        assert np.array_equal(h, d), f"{label}: {f} diverged"


# ----------------------------------------------------------- FASTA parity


def test_fasta_identical_across_modes_and_workers(synth_bam):
    """The acceptance pin: byte-identical FASTA between --ingest-mode
    device and host for workers ∈ {1, 2, 8} on the synthetic
    many-member BGZF fixture."""
    want = fasta(streamed_consensus(
        synth_bam, backend="numpy", chunk_bytes=16 << 10,
        ingest_mode="host",
    ))
    assert want and want[0][1]
    for w in WORKER_COUNTS:
        got = fasta(streamed_consensus(
            synth_bam, backend="numpy", chunk_bytes=16 << 10,
            ingest_workers=w, ingest_mode="device",
        ))
        assert got == want, f"workers={w}"


def test_jax_backend_device_reduce_matches_oracle(synth_bam):
    """Device events scattering straight into donated device state —
    no host round-trip, clip channels included (full pileups) — still
    reproduce the host oracle's count tensors exactly."""
    from kindel_tpu.streaming import stream_pileups

    host = stream_pileups(
        synth_bam, chunk_bytes=16 << 10, backend="numpy",
        ingest_mode="host",
    )
    dev = stream_pileups(
        synth_bam, chunk_bytes=16 << 10, backend="jax",
        ingest_mode="device",
    )
    assert set(host) == set(dev)
    for ref in host:
        h, d = host[ref], dev[ref]
        for field in ("weights", "deletions", "clip_starts", "clip_ends",
                      "clip_start_weights", "clip_end_weights"):
            assert np.array_equal(
                getattr(h, field), getattr(d, field)
            ), (ref, field)


def test_tuning_config_threads_ingest_mode(synth_bam):
    """TuningConfig(ingest_mode=) reaches the driver: the mode Info
    metric reflects it, and output is unchanged."""
    from kindel_tpu.obs import runtime as obs_runtime

    res = streamed_consensus(
        synth_bam, backend="numpy", chunk_bytes=16 << 10,
        tuning=TuningConfig(ingest_mode="device"),
    )
    assert res.consensuses
    modes = obs_runtime.ingest_counters().mode.value
    assert {"mode": "device", "source": "explicit"} in modes


def test_pallas_gate_interpret_parity(synth_bam, monkeypatch):
    """KINDEL_TPU_DEVINGEST_PALLAS=1 on CPU runs the wrap kernel in
    interpret mode — output identical to the XLA path."""
    want = fasta(streamed_consensus(
        synth_bam, backend="numpy", chunk_bytes=16 << 10,
        ingest_mode="host",
    ))
    monkeypatch.setenv("KINDEL_TPU_DEVINGEST_PALLAS", "1")
    got = fasta(streamed_consensus(
        synth_bam, backend="numpy", chunk_bytes=16 << 10,
        ingest_mode="device",
    ))
    assert got == want


def test_realign_clip_channels_identical_across_modes(synth_bam):
    """Realign consumes the clip channels (cs/ce/csw/cew) through full
    pileups — device mode must reproduce them too (numpy oracle; the
    jax sharded route needs shard_map, absent on this jaxlib — its own
    tests pin that path)."""
    want = fasta(streamed_consensus(
        synth_bam, backend="numpy", realign=True, chunk_bytes=16 << 10,
        ingest_mode="host",
    ))
    got = fasta(streamed_consensus(
        synth_bam, backend="numpy", realign=True, chunk_bytes=16 << 10,
        ingest_mode="device",
    ))
    assert got == want


@pytest.mark.parametrize(
    "rel",
    [
        ("data_bwa_mem", "1.1.sub_test.bam"),
        ("data_minimap2", "1.1.multi.bam"),
    ],
)
def test_refsuite_fasta_identical_across_modes(rel):
    path = require_data(*rel)
    want = fasta(streamed_consensus(
        path, backend="numpy", chunk_bytes=64 << 10, ingest_mode="host",
    ))
    for w in WORKER_COUNTS:
        got = fasta(streamed_consensus(
            path, backend="numpy", chunk_bytes=64 << 10,
            ingest_workers=w, ingest_mode="device",
        ))
        assert got == want, f"workers={w}"


# --------------------------------------------------- event-level parity


def test_chunk_events_identical_to_host(synth_bam):
    """Element-for-element EventSet parity per chunk — not just the
    reduced FASTA: same streams, same order, same insertion Counter."""
    from kindel_tpu import devingest
    from kindel_tpu.events import extract_events
    from kindel_tpu.io.stream import stream_alignment

    host = [
        extract_events(b)
        for b in stream_alignment(synth_bam, 16 << 10, ingest_workers=1)
    ]
    dev = list(devingest.stream_device_events(synth_bam, 16 << 10, 1))
    assert len(dev) == len(host) > 3  # the file genuinely chunks
    for i, (h, d) in enumerate(zip(host, dev)):
        d = d.to_host() if hasattr(d, "to_host") else d
        assert_events_equal(h, d, label=f"chunk {i}")


def test_one_shot_payload_parity(synth_bam):
    """extract_events_device (the serve decode path) == the host slurp
    decode on raw and BGZF payloads."""
    import gzip

    from kindel_tpu import devingest
    from kindel_tpu.events import extract_events
    from kindel_tpu.io.bam import parse_bam_bytes

    blob = synth_bam.read_bytes()
    raw = gzip.decompress(blob)
    host_ev = extract_events(parse_bam_bytes(raw))
    assert_events_equal(host_ev, devingest.extract_events_device(raw))
    assert_events_equal(host_ev, devingest.extract_events_device(blob))


def test_serve_decode_device_matches_host(synth_bam):
    """The worker decode stage under ingest_mode=device produces the
    same CallUnits surface (span ids/payload geometry) as host mode."""
    from kindel_tpu.batch import BatchOptions
    from kindel_tpu.serve.queue import ServeRequest
    from kindel_tpu.serve.worker import decode_request

    payload = synth_bam.read_bytes()
    req = ServeRequest(payload=payload, opts=BatchOptions())
    host_units = decode_request(req, ingest_mode="host")
    dev_units = decode_request(req, ingest_mode="device")
    assert len(host_units) == len(dev_units) > 0
    for h, d in zip(host_units, dev_units):
        assert h.L == d.L
        assert np.array_equal(h.op_r_start, d.op_r_start)
        assert np.array_equal(h.base_packed, d.base_packed)


def test_sam_text_falls_back_to_host(tmp_path):
    """SAM text input under device mode silently takes the host path —
    same consensus, no error."""
    sam = (
        b"@SQ\tSN:samref\tLN:60\n"
        b"r0\t0\tsamref\t3\t60\t10M\t*\t0\t0\tACGTACGTAC\t*\n"
    )
    p = tmp_path / "t.sam"
    p.write_bytes(sam)
    want = fasta(streamed_consensus(p, backend="numpy",
                                    chunk_bytes=16 << 10,
                                    ingest_mode="host"))
    got = fasta(streamed_consensus(p, backend="numpy",
                                   chunk_bytes=16 << 10,
                                   ingest_mode="device"))
    assert got == want


# --------------------------------------------------------- failure parity


def test_truncation_same_attribution_across_modes(synth_bam, tmp_path):
    blob = synth_bam.read_bytes()
    cut = tmp_path / "cut.bam"
    cut.write_bytes(blob[: int(len(blob) * 0.6)])
    seen = {}
    for mode in ("host", "device"):
        with pytest.raises(TruncatedInputError) as exc:
            streamed_consensus(cut, backend="numpy",
                               chunk_bytes=16 << 10, ingest_mode=mode)
        seen[mode] = (str(exc.value), exc.value.chunk_index,
                      str(exc.value.path))
    assert seen["host"] == seen["device"]


def test_read_chunk_fault_replay_identical_across_modes(synth_bam):
    """The §13 chaos contract is mode-invariant: an io.read_chunk
    truncate fault fires on the same chunk with the same downstream
    attribution under device ingest as under host ingest — both modes
    consume the ONE hook site (io.stream.iter_payload_chunks)."""
    outcomes = {}
    for mode in ("host", "device", "device"):
        plan = rfaults.activate(
            FaultPlan.parse("seed=3,io.read_chunk:truncate:after=1")
        )
        try:
            with pytest.raises(ValueError) as exc:
                streamed_consensus(
                    synth_bam, backend="numpy", chunk_bytes=16 << 10,
                    ingest_mode=mode,
                )
            outcomes.setdefault(mode, []).append((
                dict(plan.fired), plan.hits("io.read_chunk"),
                type(exc.value).__name__,
                getattr(exc.value, "chunk_index", None), str(exc.value),
            ))
        finally:
            rfaults.deactivate()
    assert outcomes["host"][0] == outcomes["device"][0]
    assert outcomes["device"][0] == outcomes["device"][1]  # replays


# ------------------------------------------------------- knobs & metrics


def test_resolve_ingest_mode_precedence(tmp_path, monkeypatch):
    from kindel_tpu import tune

    store = tmp_path / "tune.json"
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(store))
    monkeypatch.delenv("KINDEL_TPU_INGEST_MODE", raising=False)

    assert tune.resolve_ingest_mode() == ("host", "default")
    # store beats default
    assert tune.record(tune.ingest_store_key(), {"ingest_mode": "device"})
    assert tune.resolve_ingest_mode() == ("device", "cache")
    # env pin beats store
    monkeypatch.setenv("KINDEL_TPU_INGEST_MODE", "host")
    assert tune.resolve_ingest_mode() == ("host", "env")
    # explicit beats env
    assert tune.resolve_ingest_mode("device") == ("device", "explicit")
    # malformed env falls through (store next in line)
    monkeypatch.setenv("KINDEL_TPU_INGEST_MODE", "banana")
    assert tune.resolve_ingest_mode() == ("device", "cache")
    # malformed explicit is caller error
    with pytest.raises(ValueError):
        tune.resolve_ingest_mode("banana")
    # malformed store entry falls through to the default
    assert tune.record(tune.ingest_store_key(), {"ingest_mode": "tpu9"})
    monkeypatch.delenv("KINDEL_TPU_INGEST_MODE")
    assert tune.resolve_ingest_mode() == ("host", "default")


def test_search_ingest_mode_picks_faster_and_survives_probe_error():
    from kindel_tpu import tune

    chosen, timings = tune.search_ingest_mode(
        {"host": 3.0, "device": 1.5}.__getitem__, budget_s=100.0
    )
    assert chosen == "device" and set(timings) == {"host", "device"}

    def flaky(mode):
        if mode == "device":
            raise RuntimeError("no accelerator")
        return 2.0

    chosen, timings = tune.search_ingest_mode(flaky, budget_s=100.0)
    assert chosen == "host"
    assert timings["device"] == float("inf")


def test_device_counters_accumulate(synth_bam):
    """upload_bytes / scan_device / expand_device move under device
    mode; the host expand counter stays ~0 (the moved-work pin the
    bench `ingest` object reports)."""
    import gzip

    from kindel_tpu.obs.metrics import default_registry

    before = default_registry().snapshot()
    res = streamed_consensus(
        synth_bam, backend="numpy", chunk_bytes=16 << 10,
        ingest_mode="device",
    )
    assert res.consensuses
    after = default_registry().snapshot()

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    raw_len = len(gzip.decompress(synth_bam.read_bytes()))
    assert delta("kindel_ingest_upload_bytes_total") >= raw_len - (1 << 16)
    assert delta("kindel_ingest_scan_device_seconds_total") > 0
    assert delta("kindel_ingest_expand_device_seconds_total") > 0
    # the host expansion wall did NOT move (no fast-path host expand);
    # only slow-read residue could touch it, and this fixture has none
    assert delta("kindel_ingest_expand_seconds_total") == 0


def test_aot_ingest_scan_sig_roundtrip(tmp_path, monkeypatch):
    """The ingest-mode AOT dimension: export registers the scan
    executable (zero-compile dispatch through the registry) and the
    sig is stable per (buffer, capacity) bucket."""
    from kindel_tpu import aot
    from kindel_tpu.devingest import scan as dscan

    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(tmp_path / "t.json"))
    aot.clear_registry()
    pad = 1 << 16
    sig = aot.ingest_sig(pad, dscan.record_capacity(pad))
    assert sig[0] == "ingest_scan"
    aot.export_ingest_scan(pad)  # persistence may fail on CPU; registry must hold
    assert aot.lookup(sig) is not None
    out = aot.call(sig, (np.zeros(pad, np.uint8), np.int32(0)))
    if out is not None:  # rejected call falls back to jit — also fine
        assert int(np.asarray(out[1])) == 0  # zero records in zeros
    aot.clear_registry()
