"""Known-answer property: consensus must recover the sample genome.

The differential fuzz (test_differential.py) proves parity with the live
reference implementation; this file proves the pipeline does the JOB —
given reads simulated from a known sample genome (reference + SNPs +
a deletion + an insertion), the called consensus equals that sample
genome exactly, on both backends. Unanimous coverage everywhere means
any divergence is a pipeline bug, never an ambiguity artifact.
"""

from __future__ import annotations

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from kindel_tpu.workloads import bam_to_consensus

_B = "ACGT"


@st.composite
def genomes(draw):
    """(ref, variants) — variants are non-overlapping, away from the ends
    (read tiling guarantees full coverage only in the interior)."""
    L = draw(st.integers(240, 800))
    ref = "".join(
        _B[i] for i in draw(
            st.lists(st.integers(0, 3), min_size=L, max_size=L)
        )
    )
    # place up to 3 SNPs, one deletion, one insertion in distinct zones
    # of the interior so events can never overlap or touch read edges
    zone = (L - 120) // 3
    variants = []
    for z in range(3):
        lo = 60 + z * zone
        kind = draw(st.sampled_from(["snp", "del", "ins", "none"]))
        p = draw(st.integers(lo + 10, lo + zone - 20))
        if kind == "snp":
            alt = _B[(_B.index(ref[p]) + draw(st.integers(1, 3))) % 4]
            variants.append(("snp", p, alt))
        elif kind == "del":
            variants.append(("del", p, draw(st.integers(1, 4))))
        elif kind == "ins":
            s = "".join(
                _B[i] for i in draw(
                    st.lists(st.integers(0, 3), min_size=1, max_size=4)
                )
            )
            variants.append(("ins", p, s))
    return ref, variants


def _sample_genome(ref: str, variants) -> str:
    """Apply variants to ref: SNP replaces, del removes k bases,
    ins inserts BEFORE position p (the pipeline's insertion anchor)."""
    out = []
    skip = 0
    by_pos = {p: (k, v) for k, p, v in variants}
    for p, c in enumerate(ref):
        if p in by_pos:
            k, v = by_pos[p]
            if k == "ins":
                out.append(v.lower())  # insertions emit lowercase
            elif k == "del":
                skip = v
            elif k == "snp":
                c = v
        if skip > 0:
            skip -= 1
            continue
        out.append(c)
    return "".join(out)


def _read_at(ref: str, variants, a: int, b: int):
    """Simulated aligned read covering reference window [a, b):
    returns (pos, cigar, seq) in SAM terms, or None when the window cuts
    through a variant (the simulator only emits cleanly-spanning reads)."""
    for k, p, v in variants:
        span = v if k == "del" else 1
        # deletions can't sit at read edges (CIGAR can't start/end with D)
        # and insertions anchor BEFORE p, so p must be strictly inside
        if k in ("del", "ins") and (p <= a or p + span >= b):
            if a < p + span and p < b:
                return None  # cuts through: skip this read
    parts = []  # (op_char, length)
    seq = []

    def emit(op, n=1):
        if parts and parts[-1][0] == op:
            parts[-1][1] += n
        else:
            parts.append([op, n])

    by_pos = {p: (k, v) for k, p, v in variants}
    skip = 0
    for p in range(a, b):
        if p in by_pos:
            k, v = by_pos[p]
            if k == "ins":
                for c in v:
                    emit("I")
                    seq.append(c)
            elif k == "del":
                skip = v
            # snp handled via base substitution below
        if skip > 0:
            skip -= 1
            emit("D")
            continue
        emit("M")
        kv = by_pos.get(p)
        seq.append(kv[1] if kv and kv[0] == "snp" else ref[p])
    cigar = "".join(f"{n}{op}" for op, n in parts)
    return a, cigar, "".join(seq)


def _sim_sam_file(ref, variants, rng, stride, extras):
    """Write a tiled+random-extras simulated SAM for (ref, variants);
    returns the temp Path (caller unlinks)."""
    import tempfile
    from pathlib import Path

    L = len(ref)
    read_len = 50
    reads = []
    for a in list(range(0, L - read_len, stride)) + [
        int(rng.integers(0, L - read_len)) for _ in range(extras)
    ]:
        r = _read_at(ref, variants, a, a + read_len)
        if r is not None:
            reads.append(r)
    sam = ["@HD\tVN:1.6", f"@SQ\tSN:t1\tLN:{L}"]
    for i, (pos, cigar, seq) in enumerate(reads):
        sam.append(f"r{i}\t0\tt1\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*")
    with tempfile.NamedTemporaryFile(suffix=".sam", delete=False) as fh:
        fh.write(("\n".join(sam) + "\n").encode())
        return Path(fh.name)


@settings(max_examples=25, deadline=None)
@given(genomes(), st.integers(0, 10 ** 6))
def test_consensus_recovers_sample_genome(ex, seed):
    ref, variants = ex
    rng = np.random.default_rng(seed)
    L = len(ref)
    read_len = 50
    # dense tiling (stride 10 → depth ~5) plus random extras
    p = _sim_sam_file(ref, variants, rng, stride=10, extras=20)
    try:
        want = _sample_genome(ref, variants)
        for backend in ("numpy", "jax"):
            res = bam_to_consensus(p, backend=backend)
            got = res.consensuses[0].sequence
            # positions no simulated read covered call as N (the tiling
            # leaves only the last <read_len tail uncovered)
            got_core = got.rstrip("N")
            assert want.startswith(got_core), (backend, variants)
            # the covered core must reach every variant zone
            assert len(got_core) >= L - read_len - 10, backend
    finally:
        p.unlink()


@settings(max_examples=8, deadline=None)
@given(genomes(), st.integers(0, 10 ** 6))
def test_stats_backend_byte_identity_on_random_inputs(ex, seed):
    """The two-backend byte-identical invariant (SURVEY §7) on RANDOM
    inputs: weights/features/variants TSVs from the numpy oracle and the
    jax device path must be byte-equal — corpus files only sample a few
    depth/indel profiles; the generator sweeps many."""
    from kindel_tpu import workloads

    ref, variants = ex
    rng = np.random.default_rng(seed)
    p = _sim_sam_file(ref, variants, rng, stride=12, extras=10)
    try:
        for fn in (workloads.weights, workloads.features,
                   workloads.variants):
            a_ = fn(p, backend="numpy").to_csv(sep="\t", index=False)
            b_ = fn(p, backend="jax").to_csv(sep="\t", index=False)
            assert a_ == b_, fn.__name__
    finally:
        p.unlink()
