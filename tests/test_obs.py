"""kindel_tpu.obs: span tracer semantics, disabled-path overhead,
exposition-format conformance, and the serve end-to-end trace tree.

The acceptance properties pinned here:

  * one `kindel serve` request traced end-to-end produces ONE span tree
    (admission, queue wait, decode, batch dispatch, device launch, all
    sharing the request's trace id), verified over the JSONL export;
  * with tracing disabled, `span()` returns the shared no-op span and
    performs no allocation-bearing work (tracemalloc-pinned);
  * `/metrics` output — live, from a serving process — passes a
    promtool-style exposition-format conformance parse, including
    escaping of `\\`, `"` and newlines in help text and label values.
"""

import json
import re
import sys
import threading
import tracemalloc
import types
import urllib.request
from pathlib import Path

import pytest

from kindel_tpu.obs import metrics as obs_metrics
from kindel_tpu.obs import trace as obs_trace
from kindel_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    MultiRegistry,
    escape_help,
    escape_label_value,
)
from kindel_tpu.obs.trace import (
    ChromeTraceExporter,
    JsonlExporter,
    ListExporter,
    NOOP_SPAN,
    Tracer,
    disable_tracing,
    enable_tracing,
)


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """Every test starts and ends with tracing disabled — a leaked
    process tracer would silently instrument every later test."""
    disable_tracing()
    yield
    disable_tracing()


# ----------------------------------------------------------- span tracer


def test_stacked_spans_form_one_tree():
    exp = ListExporter()
    t = Tracer(exp)
    with t.span("root") as root:
        root.set_attribute(k="v")
        with t.span("child") as child:
            with t.span("grandchild"):
                pass
        with t.span("sibling"):
            pass
    by_name = {r["name"]: r for r in exp.records}
    assert set(by_name) == {"root", "child", "grandchild", "sibling"}
    assert len({r["trace_id"] for r in exp.records}) == 1
    assert by_name["root"]["parent_id"] is None
    assert by_name["child"]["parent_id"] == by_name["root"]["span_id"]
    assert by_name["grandchild"]["parent_id"] == by_name["child"]["span_id"]
    assert by_name["sibling"]["parent_id"] == by_name["root"]["span_id"]
    assert by_name["root"]["attrs"] == {"k": "v"}
    for r in exp.records:
        assert r["duration_s"] >= 0


def test_detached_span_finishes_on_another_thread():
    exp = ListExporter()
    t = Tracer(exp)
    root = t.start_span("request")
    child = t.start_span("stage", parent=root)

    done = threading.Event()

    def other():
        child.add_event("crossed", thread=True)
        child.finish()
        done.set()

    threading.Thread(target=other).start()
    assert done.wait(5)
    root.finish()
    root.finish()  # idempotent
    names = [r["name"] for r in exp.records]
    assert names == ["stage", "request"]
    stage = exp.records[0]
    assert stage["trace_id"] == exp.records[1]["trace_id"]
    assert stage["parent_id"] == exp.records[1]["span_id"]
    assert stage["events"][0]["name"] == "crossed"


def test_record_span_pretimed_interval():
    exp = ListExporter()
    t = Tracer(exp)
    root = t.start_span("root")
    sp = t.record_span("shared", root, 1.0, 3.5, flush_id=7)
    assert sp.parent_id == root.span_id
    rec = exp.records[0]
    assert rec["duration_s"] == 2.5
    assert rec["attrs"] == {"flush_id": 7}


def test_span_exit_records_exception_attr():
    exp = ListExporter()
    t = Tracer(exp)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("no")
    assert "ValueError" in exp.records[0]["attrs"]["error"]


def test_jsonl_exporter_one_object_per_line(tmp_path):
    p = tmp_path / "t.jsonl"
    enable_tracing(str(p))
    with obs_trace.span("a"):
        with obs_trace.span("b"):
            pass
    disable_tracing()
    recs = [json.loads(line) for line in p.read_text().splitlines()]
    assert [r["name"] for r in recs] == ["b", "a"]  # finish order
    assert recs[0]["parent_id"] == recs[1]["span_id"]


def test_chrome_exporter_produces_perfetto_document(tmp_path):
    p = tmp_path / "t.json"
    enable_tracing(str(p))  # .json suffix selects the Chrome exporter
    assert isinstance(
        obs_trace.active_tracer().exporter, ChromeTraceExporter
    )
    with obs_trace.span("outer") as sp:
        sp.add_event("tick", k=1)
    disable_tracing()
    doc = json.loads(p.read_text())
    phases = {e["ph"] for e in doc["traceEvents"]}
    assert phases == {"X", "i"}
    x = next(e for e in doc["traceEvents"] if e["ph"] == "X")
    assert x["name"] == "outer"
    assert x["dur"] >= 0
    assert "trace_id" in x["args"] and "span_id" in x["args"]


def test_open_exporter_suffix_selection(tmp_path):
    assert isinstance(
        obs_trace.open_exporter(tmp_path / "a.json"), ChromeTraceExporter
    )
    assert isinstance(
        obs_trace.open_exporter(tmp_path / "a.jsonl"), JsonlExporter
    )


# ------------------------------------------------------ disabled overhead


def test_disabled_span_is_the_shared_noop_singleton():
    assert obs_trace.active_tracer() is None
    assert obs_trace.span("anything") is NOOP_SPAN
    assert obs_trace.start_span("anything") is NOOP_SPAN
    assert obs_trace.record_span("x", None, 0.0, 1.0) is NOOP_SPAN
    # the full protocol surface is inert
    with obs_trace.span("x") as sp:
        sp.set_attribute(a=1)
        sp.add_event("e")
        sp.finish()
    assert sp is NOOP_SPAN


def test_disabled_span_performs_no_allocation(tmp_path):
    """The acceptance pin: with tracing disabled the span context
    manager allocates nothing inside obs/trace.py — the hot paths
    (serve decode, per-contig call) enter spans unconditionally."""
    assert obs_trace.active_tracer() is None
    span = obs_trace.span

    def burst(n):
        for _ in range(n):
            with span("serve.request"):
                pass

    burst(64)  # warm any lazy interpreter state
    tracemalloc.start()
    try:
        before = tracemalloc.take_snapshot()
        burst(2048)
        after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    trace_py = str(Path(obs_trace.__file__))
    leaked = [
        stat
        for stat in after.compare_to(before, "filename")
        if stat.traceback[0].filename == trace_py and stat.size_diff > 0
    ]
    # O(1) interpreter noise (frame free-list growth) is tolerated; a
    # real Span (or any string formatting) would allocate per iteration
    # — thousands of blocks, not a handful
    blocks = sum(stat.count_diff for stat in leaked)
    size = sum(stat.size_diff for stat in leaked)
    assert blocks < 16 and size < 2048, (
        f"disabled span allocates per call: {blocks} blocks, {size} B "
        f"over 2048 spans ({leaked})"
    )


# ------------------------------------- exposition-format conformance

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\\n]|\\\\|\\"|\\n)*",?)*)\})?'
    r' (?P<value>NaN|[+-]?Inf|[+-]?[0-9]+(?:\.[0-9]+)?(?:e[+-]?[0-9]+)?)$'
)
_HELP_RE = re.compile(
    r"^# HELP (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*) (?P<help>[^\n]*)$"
)
_TYPE_RE = re.compile(
    r"^# TYPE (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(?P<type>counter|gauge|histogram|summary|untyped)$"
)
_HISTO_SUFFIXES = ("_bucket", "_sum", "_count", "_max", "_p50", "_p99")


def unescape_label_value(raw: str) -> str:
    out, i = [], 0
    while i < len(raw):
        if raw[i] == "\\":
            nxt = raw[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
            i += 2
        else:
            out.append(raw[i])
            i += 1
    return "".join(out)


def parse_exposition(text: str) -> dict:
    """promtool-style conformance parse: every line must be a well-formed
    HELP/TYPE comment or sample; samples must belong to a declared
    family; histogram `_bucket`/`_sum`/`_count` invariants must hold.
    Returns {sample_key: float_value}."""
    assert text.endswith("\n"), "exposition must end with a newline"
    types_seen: dict[str, str] = {}
    samples: dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line) or _TYPE_RE.match(line)
            assert m, f"line {lineno}: malformed comment {line!r}"
            if m.re is _TYPE_RE:
                name = m.group("name")
                assert name not in types_seen, (
                    f"line {lineno}: duplicate TYPE for {name}"
                )
                types_seen[name] = m.group("type")
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: malformed sample {line!r}"
        name = m.group("name")
        family = name
        if types_seen.get(name) is None:
            for suffix in _HISTO_SUFFIXES:
                if name.endswith(suffix):
                    family = name[: -len(suffix)]
                    break
        assert types_seen.get(family) is not None, (
            f"line {lineno}: sample {name!r} has no TYPE"
        )
        if family != name:
            assert types_seen[family] == "histogram", (
                f"line {lineno}: {name!r} suffix on non-histogram family"
            )
        raw_value = m.group("value")
        value = float(raw_value.replace("Inf", "inf"))
        key = name + ("{" + m.group("labels") + "}" if m.group("labels")
                      else "")
        assert key not in samples, f"line {lineno}: duplicate sample {key}"
        samples[key] = value

    # histogram invariants per (family, non-le label set)
    for family, type_name in types_seen.items():
        if type_name != "histogram":
            continue
        series: dict[str, list] = {}
        for key, value in samples.items():
            if not key.startswith(family + "_bucket"):
                continue
            labels = key[len(family + "_bucket"):].strip("{}")
            pairs = dict(
                p.split("=", 1) for p in labels.split(",") if p
            )
            le = pairs.pop("le").strip('"')
            rest = ",".join(f"{k}={v}" for k, v in sorted(pairs.items()))
            bound = float("inf") if le == "+Inf" else float(le)
            series.setdefault(rest, []).append((bound, value))
        for rest, buckets in series.items():
            buckets.sort()
            counts = [c for _b, c in buckets]
            assert counts == sorted(counts), (
                f"{family}{{{rest}}}: bucket counts not cumulative"
            )
            assert buckets[-1][0] == float("inf"), (
                f"{family}{{{rest}}}: missing le=+Inf bucket"
            )
            suffix = "{" + rest + "}" if rest else ""
            count_key = f"{family}_count{suffix}"
            assert samples[count_key] == buckets[-1][1], (
                f"{family}{{{rest}}}: +Inf bucket != _count"
            )
            assert f"{family}_sum{suffix}" in samples
    return samples


def _nasty_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    c = reg.counter(
        "nasty_total", 'help with "quotes", a \\ backslash\nand a newline'
    )
    c.inc(2)
    c.labels(outcome='o"k', path="a\\b").inc(3)
    g = reg.gauge("plain_gauge", "a gauge")
    g.set(1.5)
    h = reg.histogram("lat_seconds", "latencies", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    h.labels(shape="64x1024").observe(0.2)
    info = reg.info("build_info", "constant marker")
    info.set(version="1.0", note="line\nbreak")
    return reg


def test_exposition_conformance_with_nasty_values():
    samples = parse_exposition(_nasty_registry().render())
    assert samples["nasty_total"] == 2
    labeled = next(k for k in samples if k.startswith("nasty_total{"))
    raw = dict(
        pair.split("=", 1)
        for pair in labeled[len("nasty_total{"):-1].split(",")
    )
    assert unescape_label_value(raw["outcome"].strip('"')) == 'o"k'
    assert unescape_label_value(raw["path"].strip('"')) == "a\\b"
    assert samples[labeled] == 3
    assert samples["lat_seconds_count"] == 3
    info_key = next(k for k in samples if k.startswith("build_info{"))
    assert samples[info_key] == 1


def test_escaping_helpers():
    assert escape_help("a\\b\nc") == "a\\\\b\\nc"
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    # quotes are legal raw in HELP text per the format spec
    assert escape_help('say "hi"') == 'say "hi"'


def test_registry_requires_help_text():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="help"):
        reg.counter("no_help_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("0bad", "help")


def test_labels_get_or_create_and_validation():
    reg = MetricsRegistry()
    c = reg.counter("family_total", "labeled family")
    child = c.labels(outcome="ok")
    assert c.labels(outcome="ok") is child
    assert c.labels(outcome="err") is not child
    with pytest.raises(ValueError):
        c.labels(**{"0bad": "x"})
    child.inc(4)
    snap = reg.snapshot()
    assert snap['family_total{outcome="ok"}'] == 4
    # untouched bare series is omitted from render when children exist
    assert "family_total 0" not in reg.render()


def test_multiregistry_union_and_refresh():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("one_total", "in a").inc()
    b.counter("two_total", "in b").inc(2)
    b.counter("one_total", "shadowed duplicate").inc(99)
    refreshed = []
    multi = MultiRegistry(a, b, refresh=lambda: refreshed.append(1))
    samples = parse_exposition(multi.render())
    assert refreshed, "refresh hook not invoked on render"
    assert samples["one_total"] == 1  # first registry wins on collision
    assert samples["two_total"] == 2
    assert multi.snapshot()["one_total"] == 1


def test_histogram_quantiles_and_snapshot():
    h = Histogram("h", "q", buckets=(1.0, 10.0))
    for v in range(1, 101):
        h.observe(float(v))
    assert h.quantile(0.5) == 51.0
    assert h.quantile(0.99) == 100.0
    snap = h.snapshot_value()
    assert snap["count"] == 100 and snap["max"] == 100.0


# ------------------------------------------------- profiling shim bridge


def test_phase_timer_resolves_trace_dir_at_start_not_init(
    monkeypatch, tmp_path
):
    """The satellite fix: KINDEL_TPU_TRACE_DIR exported AFTER the timer
    is constructed must still win — instrumented classes never cache
    ambient env state at __init__ time."""
    from kindel_tpu.utils.profiling import PhaseTimer

    calls = []
    fake_jax = types.SimpleNamespace(
        profiler=types.SimpleNamespace(
            start_trace=lambda d: calls.append(("start", d)),
            stop_trace=lambda: calls.append(("stop",)),
        )
    )
    monkeypatch.delenv("KINDEL_TPU_TRACE_DIR", raising=False)
    timer = PhaseTimer()  # env unset at construction
    monkeypatch.setenv("KINDEL_TPU_TRACE_DIR", str(tmp_path))
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    timer.start_trace()
    timer.stop_trace()
    assert calls == [("start", str(tmp_path)), ("stop",)]


def test_maybe_phase_records_into_timer_and_tracer():
    from kindel_tpu.utils.profiling import (
        disable_profiling,
        enable_profiling,
        maybe_phase,
    )

    exp = ListExporter()
    enable_tracing(exporter=exp)
    timer = enable_profiling()
    try:
        with maybe_phase("both worlds"):
            pass
    finally:
        disable_profiling()
        disable_tracing()
    assert [n for n, _d in timer.phases] == ["both worlds"]
    assert [r["name"] for r in exp.records] == ["both worlds"]
    assert timer.totals()["both worlds"] >= 0


# --------------------------------------------- serve end-to-end trace


def _make_sam(dest: Path, seed: int = 3) -> Path:
    import numpy as np

    rng = np.random.default_rng(seed)
    lines = ["@HD\tVN:1.6", "@SQ\tSN:refT\tLN:400"]
    for i in range(30):
        pos = int(rng.integers(0, 340))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=60))
        cigar = ("30M2D28M2S", "60M", "28M4I28M")[i % 3]
        lines.append(
            f"r{i}\t0\trefT\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*"
        )
    dest.write_text("\n".join(lines) + "\n")
    return dest


def test_serve_request_produces_one_span_tree(tmp_path):
    """Acceptance: one traced serve request = one span tree — admission,
    queue wait, decode, batch dispatch, device launch — all sharing the
    request's trace id, deterministic over the JSONL export."""
    from kindel_tpu.serve import ConsensusClient, ConsensusService

    sam = _make_sam(tmp_path / "traced.sam")
    trace_path = tmp_path / "serve.jsonl"
    enable_tracing(str(trace_path))
    try:
        with ConsensusService(max_wait_s=0.01) as svc:
            res = ConsensusClient(svc).result(str(sam), timeout=180)
    finally:
        disable_tracing()
    assert res.consensuses, "request must succeed to be worth tracing"

    recs = [
        json.loads(line) for line in trace_path.read_text().splitlines()
    ]
    roots = [r for r in recs if r["name"] == "serve.request"]
    assert len(roots) == 1
    root = roots[0]
    tree = [r for r in recs if r["trace_id"] == root["trace_id"]]
    names = {r["name"] for r in tree}
    assert {
        "serve.request",
        "serve.admission",
        "serve.queue_wait",
        "serve.decode",
        "serve.batch_dispatch",
        "serve.device_launch",
    } <= names, f"span tree incomplete: {sorted(names)}"
    assert len(tree) >= 5

    # every span chains up to the request root: one tree, not a forest
    by_id = {r["span_id"]: r for r in tree}
    for r in tree:
        node = r
        hops = 0
        while node["parent_id"] is not None:
            node = by_id[node["parent_id"]]
            hops += 1
            assert hops < 32
        assert node["span_id"] == root["span_id"], (
            f"{r['name']} not parented into the request tree"
        )

    # stage propagation detail: the micro-batcher stamped its coalescing
    # decision on the request's root span
    assert any(
        ev["name"] == "batcher.lane_add" for ev in root["events"]
    )
    assert root["attrs"]["outcome"] == "ok"
    dispatch = next(r for r in tree if r["name"] == "serve.batch_dispatch")
    launch = next(r for r in tree if r["name"] == "serve.device_launch")
    assert launch["parent_id"] == dispatch["span_id"]
    assert dispatch["attrs"]["occupancy"] >= 1
    # spans crossed at least two threads (submit/intake/dispatch pools)
    assert len({r["thread"] for r in tree}) >= 2


def test_serve_rejected_request_closes_its_tree(tmp_path):
    from kindel_tpu.serve import AdmissionError, ConsensusService
    from kindel_tpu.serve.queue import ServeRequest

    sam = _make_sam(tmp_path / "rej.sam")
    trace_path = tmp_path / "rej.jsonl"
    enable_tracing(str(trace_path))
    try:
        svc = ConsensusService(max_depth=4, high_watermark=1)
        # no worker started: the first submit fills the queue, the
        # second hits the watermark deterministically
        svc.queue.submit(
            ServeRequest(payload=str(sam), opts=svc.default_opts)
        )
        with pytest.raises(AdmissionError):
            svc.queue.submit(
                ServeRequest(payload=str(sam), opts=svc.default_opts)
            )
    finally:
        disable_tracing()
    recs = [
        json.loads(line) for line in trace_path.read_text().splitlines()
    ]
    rejected = [
        r for r in recs
        if r["name"] == "serve.request"
        and r["attrs"].get("outcome") == "rejected"
    ]
    assert len(rejected) == 1
    adm = [
        r for r in recs
        if r["name"] == "serve.admission"
        and r["trace_id"] == rejected[0]["trace_id"]
    ]
    assert adm and adm[0]["attrs"]["outcome"] == "rejected"


def test_live_metrics_endpoint_passes_conformance(tmp_path):
    """The satellite: a LIVE /metrics snapshot from a serving process —
    serve registry + process-global registry in one exposition — parses
    clean under the conformance grammar."""
    from kindel_tpu.serve import ConsensusClient, ConsensusService

    sam = _make_sam(tmp_path / "conf.sam", seed=9)
    with ConsensusService(max_wait_s=0.01, http_port=0) as svc:
        ConsensusClient(svc).result(str(sam), timeout=180)
        host, port = svc.http_address
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=30
        ) as resp:
            text = resp.read().decode()
    samples = parse_exposition(text)
    assert samples["kindel_serve_requests_total"] == 1
    assert samples['kindel_serve_requests_outcome_total{outcome="ok"}'] == 1
    # the process-global registry rides the same exposition (tentpole:
    # one spine) — the dispatch uploaded bytes through batch.py's counter
    assert samples["kindel_device_h2d_bytes_total"] > 0
    shape_key = next(
        k for k in samples
        if k.startswith("kindel_serve_dispatch_seconds_count{")
    )
    assert samples[shape_key] >= 1


def test_default_registry_is_shared_across_layers():
    reg = obs_metrics.default_registry()
    assert obs_metrics.default_registry() is reg
    from kindel_tpu.serve.metrics import default_registry as serve_default

    assert serve_default() is reg
