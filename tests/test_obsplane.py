"""Fleet-wide observability plane (DESIGN.md §26).

Covers the three tentpole pieces and their satellites:

  * **SLO engine** (kindel_tpu.obs.slo) — spec grammar, multi-window
    burn-rate math under a fake clock, future attachment, the gauges,
    and the live readyz degrade/recover loop over a real fleet front.
  * **Trace stitching** (kindel_tpu.obs.fleetview) — SpanTap ring +
    spool semantics, the journal-style torn-tail matrix, collector
    dedupe/merge/atomic-write, the /v1/trace drain route, and the two
    process-fleet flagships: one stitched Perfetto file whose span
    trees cross front → rpc → replica → device across real processes,
    and a SIGKILLed replica whose stream truncates at the last
    complete span while survivors' spans land whole.
  * **Perf-regression harness** (kindel_tpu.obs.perfgate) — ingestion
    of the committed BENCH_r*/MULTICHIP_r*/BENCH_tpu_live history,
    the history-replay gate, and the deliberately-regressed fixture
    that must make `kindel perf --gate` exit nonzero.
  * **Wire-latency buckets** — the re-bucketed `kindel_rpc_call_seconds`
    / `kindel_stream_update_seconds` histograms' invariants.
  * **Replica-labeled fleet /metrics** — exposition conformance of the
    union with `replica="<slot>"` labels on per-replica series.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from pathlib import Path
from types import SimpleNamespace

import pytest

from kindel_tpu.obs import fleetview, perfgate, slo
from kindel_tpu.obs import trace as obs_trace
from kindel_tpu.obs.metrics import (
    WIRE_LATENCY_BUCKETS,
    LabeledRegistry,
    MetricsRegistry,
    default_registry,
)
from tests.test_obs import parse_exposition

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------ SLO grammar


def test_slo_parse_basic_and_percent_budget():
    (spec,) = slo.parse_slo(
        "route=/v1/consensus p99_ms=500 err_budget=0.1%"
    )
    assert spec.route == "/v1/consensus"
    assert spec.p99_ms == 500.0
    assert spec.err_budget == pytest.approx(0.001)
    # defaults fill in
    assert spec.window_s == slo.DEFAULT_WINDOW_S
    assert spec.fast_burn == slo.DEFAULT_FAST_BURN


def test_slo_parse_multi_objective_and_overrides():
    specs = slo.parse_slo(
        "route=/v1/consensus p99_ms=500 err_budget=0.5 ; "
        "route=/v1/stream err_budget=5% window_s=30 fast_window_s=5 "
        "fast_burn=2"
    )
    assert [s.route for s in specs] == ["/v1/consensus", "/v1/stream"]
    stream = specs[1]
    assert stream.err_budget == pytest.approx(0.05)
    assert stream.window_s == 30.0
    assert stream.fast_window_s == 5.0
    assert stream.fast_burn == 2.0
    assert stream.p99_ms is None  # errors-only objective


@pytest.mark.parametrize("bad", [
    "p99_ms=500",                                # no route
    "route=/v1/x nonsense",                      # token without =
    "route=/v1/x budget=1%",                     # unknown key
    "route=/v1/x p99_ms=abc",                    # bad float
    "route=/v1/x err_budget=150%",               # fraction out of range
    "route=/v1/x err_budget=0",                  # zero budget
    "route=/v1/x window_s=-5",                   # nonpositive window
])
def test_slo_parse_rejects_malformed(bad):
    with pytest.raises(slo.SloParseError):
        slo.parse_slo(bad)


def test_tune_resolve_slo_precedence(monkeypatch):
    from kindel_tpu import tune

    monkeypatch.delenv("KINDEL_TPU_SLO", raising=False)
    assert tune.resolve_slo(None) == (None, "default")
    monkeypatch.setenv("KINDEL_TPU_SLO", "route=/v1/consensus p99_ms=9")
    spec, src = tune.resolve_slo(None)
    assert src == "env" and "p99_ms=9" in spec
    # a malformed env pin falls through to off (boot must survive it)
    monkeypatch.setenv("KINDEL_TPU_SLO", "not a spec")
    assert tune.resolve_slo(None) == (None, "default")
    # ... but a malformed EXPLICIT arg raises at the CLI
    with pytest.raises(slo.SloParseError):
        tune.resolve_slo("not a spec")
    spec, src = tune.resolve_slo("route=/v1/x err_budget=1%")
    assert src == "explicit"


# ---------------------------------------------------------- SLO burn math


class _FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def _engine(spec: str, clock):
    return slo.SloEngine(slo.parse_slo(spec), clock=clock)


def test_slo_burn_math_fires_and_recovers():
    clock = _FakeClock()
    eng = _engine(
        "route=/v1/consensus err_budget=10% window_s=100 "
        "fast_window_s=10 fast_burn=2",
        clock,
    )
    for _ in range(20):
        eng.observe("/v1/consensus", 0.01, True)
    doc = eng.evaluate()["/v1/consensus"]
    assert doc["burn_rate"] == 0.0
    assert doc["budget_remaining"] == 1.0
    assert doc["fast_burn_active"] is False
    # a failure burst: 10 bad vs 20 good -> bad fraction 1/3, burn
    # (1/3)/0.1 = 3.33 over both windows -> fast (>=2) AND slow (>=1)
    for _ in range(10):
        eng.observe("/v1/consensus", 0.01, False)
    doc = eng.evaluate()["/v1/consensus"]
    assert doc["burn_rate"] == pytest.approx(3.333, abs=0.01)
    assert doc["fast_burn_active"] is True
    assert doc["budget_remaining"] < 0  # budget blown over the window
    assert eng.degraded() is True
    # the burn window drains: everything ages out, the alert clears
    clock.now += 200.0
    doc = eng.evaluate()["/v1/consensus"]
    assert doc["fast_burn_active"] is False
    assert doc["burn_rate"] == 0.0
    assert eng.degraded() is False


def test_slo_latency_violation_spends_budget():
    clock = _FakeClock()
    eng = _engine(
        "route=/v1/consensus p99_ms=50 err_budget=50% window_s=100 "
        "fast_window_s=100 fast_burn=1",
        clock,
    )
    # ok=True but 200ms > the 50ms target: slow is the new down
    eng.observe("/v1/consensus", 0.2, True)
    eng.observe("/v1/consensus", 0.001, True)
    doc = eng.evaluate()["/v1/consensus"]
    assert doc["window"] == {"good": 1, "bad": 1}
    assert doc["burn_rate"] == pytest.approx(1.0)


def test_slo_attach_feeds_future_settlement():
    clock = _FakeClock()
    eng = _engine(
        "route=/v1/consensus err_budget=50% window_s=100 "
        "fast_window_s=100",
        clock,
    )
    ok_fut: Future = Future()
    eng.attach("/v1/consensus", ok_fut)
    clock.now += 0.25
    ok_fut.set_result("fine")
    bad_fut: Future = Future()
    eng.attach("/v1/consensus", bad_fut)
    bad_fut.set_exception(RuntimeError("boom"))
    # a route without an objective is ignored, not buffered
    eng.attach("/v1/other", Future())
    doc = eng.evaluate()["/v1/consensus"]
    assert doc["window"] == {"good": 1, "bad": 1}


def test_slo_gauges_land_in_default_registry():
    clock = _FakeClock()
    eng = _engine(
        "route=/v1/gaugecheck err_budget=10% window_s=100 "
        "fast_window_s=10",
        clock,
    )
    eng.observe("/v1/gaugecheck", 0.01, False)
    eng.evaluate()
    snap = default_registry().snapshot()
    assert snap['kindel_slo_burn_rate{route="/v1/gaugecheck"}'] > 1
    assert (
        snap['kindel_slo_budget_remaining{route="/v1/gaugecheck"}'] < 1
    )
    key = (
        'kindel_slo_observations_total'
        '{outcome="bad",route="/v1/gaugecheck"}'
    )
    assert snap[key] >= 1


# ------------------------------------------------------- wire buckets


def test_wire_latency_buckets_invariants():
    b = WIRE_LATENCY_BUCKETS
    assert list(b) == sorted(b), "buckets must be monotonic"
    assert len(set(b)) == len(b), "no duplicate bounds"
    assert b[0] <= 0.001, "sub-millisecond RPCs need a bucket"
    assert b[-1] == 10.0, "top bucket must reach the RPC deadline ceiling"
    # log-spaced: adjacent ratio bounded (the 1-2.5-5 decade ladder)
    ratios = [hi / lo for lo, hi in zip(b, b[1:])]
    assert max(ratios) <= 2.6 and min(ratios) >= 1.9, ratios


def test_rpc_and_stream_histograms_use_wire_buckets():
    from kindel_tpu.fleet.rpc import rpc_metrics

    assert rpc_metrics().seconds.buckets == tuple(
        sorted(WIRE_LATENCY_BUCKETS)
    )
    from kindel_tpu.sessions.registry import SessionRegistry

    fake = SimpleNamespace(
        metrics=MetricsRegistry(),
        queue=SimpleNamespace(high_watermark=8),
    )
    sr = SessionRegistry(fake, idle_s=1.0, emit_delta=1)
    assert sr._m_update_s.buckets == tuple(sorted(WIRE_LATENCY_BUCKETS))


# ------------------------------------------------------ labeled registry


def test_labeled_registry_injects_replica_label():
    reg = MetricsRegistry()
    reg.counter("plain_total", "bare series").inc(3)
    reg.counter("routed_total", "labeled series").labels(
        outcome="ok"
    ).inc(2)
    reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0)).observe(
        0.5
    )
    labeled = LabeledRegistry(reg, "replica", "r7")
    samples = parse_exposition(labeled.render())
    assert samples['plain_total{replica="r7"}'] == 3
    assert samples['routed_total{replica="r7",outcome="ok"}'] == 2
    assert samples['lat_seconds_count{replica="r7"}'] == 1
    assert 'lat_seconds_bucket{replica="r7",le="1"}' in samples
    snap = labeled.snapshot()
    assert snap['plain_total{replica="r7"}'] == 3


def test_fleet_metrics_union_exposition_conformance(tmp_path):
    """Satellite 1: the fleet /metrics union is grammar-conformant,
    per-replica series carry replica="<slot>", front series stay
    unlabeled, and no (name, labelset) pair renders twice."""
    from kindel_tpu.fleet import FleetService
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "union.sam", seed=31)
    with FleetService(replicas=2, max_wait_s=0.02, http_port=0) as svc:
        svc.request(sam.read_bytes(), timeout=120)
        host, port = svc.http_address
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=30
        ) as resp:
            text = resp.read().decode()
    samples = parse_exposition(text)  # the conformance pass itself
    # a per-replica serve series appears once per slot, labeled
    for slot in ("r0", "r1"):
        assert any(
            k.startswith("kindel_serve_queue_depth{")
            and f'replica="{slot}"' in k
            for k in samples
        ), f"missing replica={slot} serve series"
    # front/global series stay unlabeled
    fleet_keys = [
        k for k in samples if k.startswith("kindel_fleet_evictions_total")
    ]
    assert fleet_keys and all("replica=" not in k for k in fleet_keys)
    # no duplicate (name, labelset): every sample line is unique
    lines = [
        ln for ln in text.splitlines() if ln and not ln.startswith("#")
    ]
    keys = [ln.rsplit(" ", 1)[0] for ln in lines]
    assert len(keys) == len(set(keys)), "duplicate sample keys rendered"


# -------------------------------------------------------------- perfgate


def test_perfgate_ingests_committed_history():
    store = perfgate.load_history(REPO)
    assert len(store.samples) >= 10
    headline = store.series()[("cpu", "consensus_throughput_bacterial")]
    values = [s.value for s in headline]
    assert 27.932 in values  # BENCH_r05's best cpu round
    # the tpu live round lands under its own backend key
    assert ("tpu", "consensus_throughput_bacterial") in store.series()
    # mesh sweep widths become per-width occupancy series
    assert ("cpu", "mesh_ragged_occupancy_w4") in store.series()
    # failed/skipped rounds are recorded with reasons, not silently lost
    assert len(store.skipped) >= 5
    assert all(reason for _src, reason in store.skipped)


def test_perfgate_backend_normalization():
    assert perfgate.normalize_backend("cpu-fallback") == "cpu"
    assert perfgate.normalize_backend("cpu") == "cpu"
    assert perfgate.normalize_backend("tpu") == "tpu"
    assert perfgate.normalize_backend(None) == "unknown"


def test_perfgate_history_replay_is_clean():
    store = perfgate.load_history(REPO)
    result = perfgate.gate_history(store)
    assert result.ok, [c.detail for c in result.regressions]
    assert len(result.checks) >= 10


def test_perfgate_fresh_regression_fires_below_floor():
    store = perfgate.load_history(REPO)
    fresh = {
        "metric": "consensus_throughput_bacterial",
        "value": 5.0,
        "unit": "Mbases/s",
        "backend": "cpu-fallback",
    }
    result = perfgate.gate_fresh(store, fresh)
    assert not result.ok
    (reg,) = result.regressions
    # floor = best prior * (1 - tolerance) = 27.932 * 0.65
    assert "27.932" in reg.detail and "18.15" in reg.detail


def test_perfgate_no_prior_history_records_not_gates():
    store = perfgate.HistoryStore()
    result = perfgate.gate_fresh(
        store,
        {"metric": "novel_series", "value": 1.0, "backend": "cpu"},
    )
    assert result.ok
    (check,) = result.checks
    assert "no prior history" in check.detail


def test_perfgate_regressed_fixture_fails_cli_gate():
    """Satellite 5: the committed known-bad fixture proves the CI gate
    FIRES — `kindel perf --gate --line <fixture>` must exit nonzero."""
    fixture = REPO / "tools" / "perfgate_regressed_fixture.json"
    assert fixture.exists()
    proc = subprocess.run(
        [
            sys.executable, "-m", "kindel_tpu.cli", "perf", "--gate",
            "--line", str(fixture),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout
    # and the ci entrypoint that runs it is present + executable
    ci = REPO / "tools" / "ci_check.sh"
    assert ci.exists() and os.access(ci, os.X_OK)


def test_perfgate_provenance_object_shape():
    fresh = {
        "metric": "consensus_throughput_bacterial",
        "value": 5.0,
        "backend": "cpu-fallback",
    }
    doc = perfgate.provenance(REPO, fresh)
    assert doc["verdict"] == "regression"
    assert doc["best_prior"] == 27.932
    assert doc["tolerance"] == perfgate.DEFAULT_TOLERANCE
    ok_doc = perfgate.provenance(
        REPO,
        {
            "metric": "consensus_throughput_bacterial",
            "value": 30.0,
            "backend": "cpu",
        },
    )
    assert ok_doc["verdict"] == "pass"


# ----------------------------------------------------- SpanTap + parsing


def _span_line(trace_id="t1", span_id="s1", name="unit.test", **attrs):
    return json.dumps({
        "name": name, "trace_id": trace_id, "span_id": span_id,
        "parent_id": attrs.pop("parent_id", None), "start_s": 1.0,
        "duration_s": 0.5, "thread": "main", "attrs": attrs, "events": [],
    })


def test_spantap_ring_drops_oldest_and_drains():
    tap = fleetview.SpanTap(capacity=3)
    for i in range(5):
        tap.export({"trace_id": "t", "span_id": f"s{i}", "name": "n"})
    assert tap.dropped == 2
    lines = tap.drain_lines()
    assert [json.loads(ln)["span_id"] for ln in lines] == [
        "s2", "s3", "s4",
    ]
    assert tap.drain_payload() == b""  # drained empty


def test_spantap_spool_write_through_and_close(tmp_path):
    spool = tmp_path / "r0.123.trace.jsonl"
    tap = fleetview.SpanTap(spool_path=spool, capacity=16)
    tap.export({"trace_id": "t", "span_id": "a", "name": "one"})
    tap.export({"trace_id": "t", "span_id": "b", "name": "two"})
    # write-through: both lines durable BEFORE any drain/close
    records, truncated = fleetview.read_spool(spool)
    assert [r["span_id"] for r in records] == ["a", "b"]
    assert truncated == 0
    tap.close()
    tap.export({"trace_id": "t", "span_id": "c", "name": "late"})
    assert fleetview.read_spool(spool)[0] == records  # closed = no-op
    tap.close()  # idempotent


@pytest.mark.parametrize("payload,want_spans,want_truncated", [
    (b"", [], 0),
    (_span_line(span_id="a").encode() + b"\n", ["a"], 0),
    # torn tail: the last line lost its newline mid-write
    (
        _span_line(span_id="a").encode() + b"\n"
        + _span_line(span_id="b").encode()[:17],
        ["a"], 1,
    ),
    # corrupt line mid-stream cuts everything after it
    (
        _span_line(span_id="a").encode() + b"\n"
        + b"{garbage\n"
        + _span_line(span_id="c").encode() + b"\n",
        ["a"], 2,
    ),
    # valid JSON that is not a span record also cuts
    (
        _span_line(span_id="a").encode() + b"\n"
        + b'{"name": "no-ids"}\n',
        ["a"], 1,
    ),
    # blank lines are tolerated, not counted
    (
        _span_line(span_id="a").encode() + b"\n\n"
        + _span_line(span_id="b").encode() + b"\n",
        ["a", "b"], 0,
    ),
])
def test_parse_ndjson_torn_tail_matrix(payload, want_spans, want_truncated):
    records, truncated = fleetview.parse_ndjson(payload)
    assert [r["span_id"] for r in records] == want_spans
    assert truncated == want_truncated


def test_collector_dedupes_and_merges():
    col = fleetview.TraceCollector()
    line = _span_line(trace_id="t9", span_id="dup")
    assert col.add_ndjson("r0", (line + "\n").encode()) == 1
    # the same span re-read from a spool counts once (first wins)
    assert col.add_ndjson("r0-spool", (line + "\n").encode()) == 0
    col.add_ndjson(
        "front",
        (_span_line(trace_id="t9", span_id="root") + "\n").encode(),
    )
    assert col.span_count() == 2
    assert col.sources() == ["front", "r0", "r0-spool"]
    doc = col.merge()
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["args"]["name"] for e in meta} == {
        "kindel:r0", "kindel:r0-spool", "kindel:front",
    }
    xev = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert all(
        e["args"]["trace_id"] == "t9" and "span_id" in e["args"]
        for e in xev
    )
    # distinct sources render as distinct pseudo-pids
    assert len({e["pid"] for e in meta}) == 3


def test_collector_write_is_atomic(tmp_path):
    out = tmp_path / "merged.json"
    col = fleetview.TraceCollector(out)
    col.add_ndjson("front", (_span_line() + "\n").encode())
    path = col.write()
    assert path == str(out)
    doc = json.loads(out.read_text())
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["sources"] == ["front"]
    assert not (tmp_path / "merged.json.tmp").exists()


def test_collector_spool_dir_and_failures(tmp_path):
    (tmp_path / "r0.111.trace.jsonl").write_text(
        _span_line(span_id="x0") + "\n"
    )
    (tmp_path / "r1.222.trace.jsonl").write_text(
        _span_line(span_id="x1") + "\n" + '{"torn'
    )
    (tmp_path / "unrelated.txt").write_text("ignored")
    col = fleetview.TraceCollector()
    assert col.collect_spool_dir(tmp_path) == 2
    assert col.sources() == ["r0", "r1"]
    col.record_failure("r2", ConnectionError("wire down"))
    doc = col.merge()
    assert doc["otherData"]["truncated_tails"] == {"r1": 1}
    assert doc["otherData"]["collect_errors"] == 1


# --------------------------------------------- single-process integration


def test_serve_trace_collect_writes_merged_file(tmp_path):
    from kindel_tpu.serve import ConsensusService
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "one.sam", seed=41)
    out = tmp_path / "serve_trace.json"
    svc = ConsensusService(
        max_wait_s=0.01, warmup=False, trace_collect=str(out)
    ).start()
    try:
        svc.request(sam.read_bytes(), timeout=120)
    finally:
        svc.stop()
    doc = json.loads(out.read_text())
    names = {
        e["name"] for e in doc["traceEvents"] if e["ph"] == "X"
    }
    # the request's full tree: admission -> queue -> batch -> device
    for want in (
        "serve.request", "serve.admission", "serve.queue_wait",
        "serve.batch_dispatch", "serve.device_launch",
    ):
        assert want in names, f"{want} missing from {sorted(names)}"
    # stopping released the process tracer
    assert obs_trace.active_tracer() is None


def test_serve_v1_trace_route_drains_ndjson(tmp_path):
    from kindel_tpu.serve import ConsensusService
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "drain.sam", seed=43)
    spool = tmp_path / "local.0.trace.jsonl"
    svc = ConsensusService(
        max_wait_s=0.01, warmup=False, http_port=0,
        trace_spool=str(spool),
    ).start()
    try:
        host, port = svc.http_address
        base = f"http://{host}:{port}"
        req = urllib.request.Request(
            f"{base}/v1/consensus", data=sam.read_bytes(), method="POST"
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
        with urllib.request.urlopen(
            f"{base}/v1/trace", timeout=30
        ) as resp:
            assert resp.status == 200
            ctype = resp.headers.get("Content-Type", "")
            data = resp.read()
        assert fleetview.TRACE_CONTENT_TYPE in ctype
        records, truncated = fleetview.parse_ndjson(data)
        assert truncated == 0
        assert "serve.request" in {r["name"] for r in records}
        # the drain emptied the ring: an immediate second drain is empty
        with urllib.request.urlopen(
            f"{base}/v1/trace", timeout=30
        ) as resp:
            again, _ = fleetview.parse_ndjson(resp.read())
        assert not any(r["name"] == "serve.request" for r in again)
    finally:
        svc.stop()


def test_fleet_slo_fast_burn_degrades_readyz_and_recovers(tmp_path):
    """The SLO acceptance loop over a REAL fleet front: a burst of
    budget-burning requests flips /readyz to 503 slo_degraded with
    kindel_slo_burn_rate > 1 on /metrics, and readiness recovers once
    the burn window drains."""
    from kindel_tpu.fleet import FleetService
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "burn.sam", seed=47)
    body = sam.read_bytes()
    # p99_ms=0.001 makes every (successful) request a latency
    # violation: deterministic budget burn without error injection
    spec = (
        "route=/v1/consensus p99_ms=0.001 err_budget=50% "
        "window_s=2 fast_window_s=1 fast_burn=1"
    )
    with FleetService(
        replicas=1, max_wait_s=0.02, http_port=0, slo=spec
    ) as svc:
        host, port = svc.http_address
        base = f"http://{host}:{port}"
        for _ in range(3):
            svc.submit(body).result(timeout=120)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert exc_info.value.code == 503
        doc = json.loads(exc_info.value.read())
        assert doc["status"] == "slo_degraded"
        assert doc["ready"] is False
        route = doc["slo"]["/v1/consensus"]
        assert route["burn_rate"] > 1
        assert route["fast_burn_active"] is True
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            samples = parse_exposition(r.read().decode())
        assert (
            samples['kindel_slo_burn_rate{route="/v1/consensus"}'] > 1
        )
        assert samples[
            'kindel_slo_fast_burn_active{route="/v1/consensus"}'
        ] == 1
        # recovery: the whole window ages out with no fresh burn
        time.sleep(2.2)
        with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
            doc = json.loads(r.read())
        assert doc["ready"] is True and doc["status"] == "ok"
        assert (
            doc["slo"]["/v1/consensus"]["fast_burn_active"] is False
        )


# ----------------------------------------------- process-fleet flagships


def _x_events(doc):
    return [e for e in doc["traceEvents"] if e["ph"] == "X"]


def test_flagship_stitched_trace_across_processes(tmp_path):
    """The tentpole flagship: a 3-process fleet under wire faults
    leaves ONE valid Perfetto file containing at least one request's
    span tree crossing front → rpc hop → replica serve path → device
    dispatch, joined across processes by the trace id that rode
    X-Kindel-Trace."""
    from kindel_tpu.fleet.procreplica import ProcessFleetService
    from kindel_tpu.resilience import faults as rfaults
    from kindel_tpu.resilience.faults import FaultPlan
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "flag.sam", seed=7)
    body = sam.read_bytes()
    out = tmp_path / "fleet_trace.json"
    plan = rfaults.activate(FaultPlan.parse(
        "rpc.call:drop_response:times=1:after=1,"
        "rpc.call:slow:times=1:delay=0.02"
    ))
    try:
        with ProcessFleetService(
            replicas=3,
            service_config={"max_wait_s": 0.01, "decode_workers": 2},
            probe_interval_s=0.05,
            trace_collect=str(out),
        ) as fleet:
            futs = [fleet.submit(body) for _ in range(6)]
            for f in futs:
                f.result(timeout=120)
    finally:
        rfaults.deactivate()
    assert plan.fired[("rpc.call", "drop_response")] == 1

    doc = json.loads(out.read_text())  # ONE well-formed merged file
    sources = set(doc["otherData"]["sources"])
    assert "front" in sources
    replica_sources = sources - {"front"}
    assert replica_sources, "no replica stream reached the collector"
    xev = _x_events(doc)
    assert all(
        "trace_id" in e["args"] and "span_id" in e["args"] for e in xev
    )
    by_span = {e["args"]["span_id"]: e for e in xev}

    # find a stitched tree: front rpc.call -> replica rpc.server ->
    # serve.request -> ... -> serve.device_launch, one trace id
    stitched = 0
    for e in xev:
        if e["name"] != "rpc.server":
            continue
        tid = e["args"]["trace_id"]
        parent = by_span.get(e["args"].get("parent_id"))
        if parent is None or parent["name"] != "rpc.call":
            continue
        if parent["args"]["source"] != "front":
            continue
        if e["args"]["source"] == "front":
            continue
        same_trace = [
            x for x in xev if x["args"]["trace_id"] == tid
        ]
        names = {x["name"] for x in same_trace}
        if {"serve.request", "serve.device_launch"} <= names:
            # the serve tree is parented INTO the rpc hop, not merely
            # sharing its trace id
            sreq = next(
                x for x in same_trace if x["name"] == "serve.request"
            )
            assert sreq["args"].get("parent_id") == e["args"]["span_id"]
            assert sreq["args"]["source"] == e["args"]["source"]
            stitched += 1
    assert stitched >= 1, (
        "no cross-process span tree found in the merged trace"
    )
    # distinct processes render as distinct pseudo-pid lanes
    front_pids = {
        e["pid"] for e in xev if e["args"]["source"] == "front"
    }
    replica_pids = {
        e["pid"] for e in xev if e["args"]["source"] != "front"
    }
    assert front_pids and replica_pids and not (
        front_pids & replica_pids
    )


def test_sigkill_replica_truncates_at_last_complete_span(tmp_path):
    """Satellite 3: SIGKILL a replica process mid-trace. The merged
    file stays well-formed, the dead replica contributes every span up
    to its last COMPLETE spool line (the torn tail is truncated and
    counted), and surviving replicas' spans land whole."""
    from kindel_tpu.fleet.procreplica import ProcessFleetService
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "kill.sam", seed=13)
    body = sam.read_bytes()
    out = tmp_path / "killed_trace.json"
    with ProcessFleetService(
        replicas=2,
        service_config={"max_wait_s": 0.01, "decode_workers": 2},
        probe_interval_s=0.05,
        trace_collect=str(out),
    ) as fleet:
        for _ in range(3):
            fleet.request(body, timeout=120)
        trace_dir = Path(fleet._trace_dir)
        # pick the replica whose spool proves it served traced work
        victim_spool = max(
            trace_dir.glob("*.trace.jsonl"),
            key=lambda p: p.stat().st_size,
        )
        victim_rid = victim_spool.name.split(".")[0]
        keep = fleetview.read_spool(victim_spool)[0]
        assert keep, "victim spool has no complete spans"
        keep_ids = {r["span_id"] for r in keep}
        fleet.kill_replica(victim_rid)
        # the tear a SIGKILL leaves: a record cut mid-write. Appended
        # deterministically because the kill itself races the spool.
        with open(victim_spool, "ab") as fh:
            fh.write(
                b'{"name": "serve.request", "trace_id": "torn-trace", '
                b'"span_'
            )
        # fleet recovers (respawn), survivors keep serving traced work
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                fleet.request(body, timeout=120)
                break
            except Exception:
                time.sleep(0.1)
        else:
            raise AssertionError("fleet never served after the SIGKILL")
    doc = json.loads(out.read_text())  # well-formed despite the tear
    xev = _x_events(doc)
    span_ids = {e["args"]["span_id"] for e in xev}
    # every complete span the dead process spooled made the merge
    assert keep_ids <= span_ids
    # the torn record did not: truncated at the last complete span
    assert "torn-trace" not in {e["args"]["trace_id"] for e in xev}
    assert doc["otherData"]["truncated_tails"].get(victim_rid, 0) >= 1
    # the survivor's post-kill request is in the stitched view too
    assert sum(1 for e in xev if e["name"] == "rpc.server") >= 4
