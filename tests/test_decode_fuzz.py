"""Adversarial decode-robustness fuzz (VERDICT r4 item 6).

The decode surface takes fully untrusted bytes: BGZF framing fields
(BSIZE/ISIZE/xlen), the BAM header dictionary (l_text/n_ref/l_name), and
per-record length fields (block_size/l_read_name/n_cigar/l_seq) can all
lie. The contract pinned here, for the pure-Python decoder, the native
C++ decoder, and the public `load_alignment` entry point:

- malformed input raises ValueError (never struct.error, IndexError,
  OverflowError, MemoryError via attacker-sized allocations, or a crash);
- the native and pure BAM decoders accept/reject the SAME inputs, and on
  accept produce identical batches (they share the validated header parse
  and field extraction; only the record walk and optional kernels differ);
- the native BGZF inflater is strictly more conservative than the pure
  path: whenever it returns bytes they equal the pure result, and it
  returns None (clean fallback) on anything it does not understand.

The C++ kernels' memory safety is additionally exercised under
AddressSanitizer by src/native/fuzz_driver.cpp (test_native_asan_driver).
"""

from __future__ import annotations

import struct

import numpy as np
import pytest

from kindel_tpu.io import bgzf
from kindel_tpu.io.bam import parse_bam_bytes

#: the only exception the decode surface may raise on malformed input
CLEAN = (ValueError,)


def _mini_bam(n_reads: int = 5, ref_len: int = 500) -> bytearray:
    """A valid decompressed BAM stream with reads exercising M/I/D/S ops."""
    rng = np.random.default_rng(11)
    header_text = b"@HD\tVN:1.6\n"
    out = bytearray(b"BAM\x01")
    out += struct.pack("<i", len(header_text)) + header_text
    out += struct.pack("<i", 2)  # n_ref
    for name, ln in ((b"refA\x00", ref_len), (b"refB\x00", ref_len * 2)):
        out += struct.pack("<i", len(name)) + name + struct.pack("<i", ln)
    for r in range(n_reads):
        name = f"rd{r}".encode() + b"\x00"
        cigar_ops = [(20, 0), (2, 1), (10, 0), (3, 4)]  # 20M 2I 10M 3S
        l_seq = sum(n for n, op in cigar_ops if op in (0, 1, 4))
        packed = bytes(
            (int(rng.integers(1, 15)) << 4) | int(rng.integers(1, 15))
            for _ in range((l_seq + 1) // 2)
        )
        body = struct.pack(
            "<iiBBHHHiiii",
            r % 2,                      # ref_id
            int(rng.integers(0, 400)),  # pos
            len(name), 60, 0,           # l_read_name, mapq, bin
            len(cigar_ops), 0,          # n_cigar, flag
            l_seq, -1, -1, 0,           # l_seq, next_ref, next_pos, tlen
        )
        body += name
        for n, op in cigar_ops:
            body += struct.pack("<I", (n << 4) | op)
        body += packed + b"\xff" * l_seq
        out += struct.pack("<i", len(body)) + body
    return out


def _decode_both(data: bytes):
    """(pure_outcome, native_outcome); each is ('ok', batch) or ('err', e)."""
    from kindel_tpu.io import native

    results = []
    for fn in (parse_bam_bytes,
               native.parse_bam_bytes if native.available() else None):
        if fn is None:
            results.append(None)
            continue
        try:
            results.append(("ok", fn(bytes(data))))
        except CLEAN as exc:
            results.append(("err", exc))
    return results


def _assert_agree(data: bytes):
    """Pure and native must both accept (identically) or both reject —
    and nothing but CLEAN exceptions may escape either."""
    pure, nat = _decode_both(data)
    if nat is None:
        return pure
    assert pure[0] == nat[0], (pure, nat)
    if pure[0] == "ok":
        pb, nb = pure[1], nat[1]
        np.testing.assert_array_equal(pb.pos, nb.pos)
        np.testing.assert_array_equal(pb.seq, nb.seq)
        np.testing.assert_array_equal(pb.cig_op, nb.cig_op)
        np.testing.assert_array_equal(pb.cig_len, nb.cig_len)
    return pure


def test_mini_bam_is_valid():
    outcome = _assert_agree(_mini_bam())
    assert outcome[0] == "ok"
    assert outcome[1].n_reads == 5


def _first_record_off(data: bytes) -> int:
    from kindel_tpu.io.bam import parse_bam_header

    return parse_bam_header(bytes(data))[2]


def test_structured_header_lies():
    base = _mini_bam()
    mutants = []
    for l_text in (-1, -(2 ** 31), 2 ** 31 - 1, len(base)):
        m = bytearray(base)
        struct.pack_into("<i", m, 4, l_text)
        mutants.append(m)
    l_text = struct.unpack_from("<i", base, 4)[0]
    n_ref_off = 8 + l_text
    for n_ref in (-1, -(2 ** 31), 2 ** 30, 10 ** 6):
        m = bytearray(base)
        struct.pack_into("<i", m, n_ref_off, n_ref)
        mutants.append(m)
    for l_name in (-1, 0, 2 ** 28, len(base)):
        m = bytearray(base)
        struct.pack_into("<i", m, n_ref_off + 4, l_name)
        mutants.append(m)
    for m in mutants:
        outcome = _assert_agree(m)
        assert outcome[0] == "err", "header lie was accepted"


def test_structured_record_lies():
    base = _mini_bam()
    rec = _first_record_off(base)  # offset of first block_size field
    mutants = []
    for block_size in (-1, 0, 31, 2 ** 31 - 1, len(base)):
        m = bytearray(base)
        struct.pack_into("<i", m, rec, block_size)
        mutants.append((m, "err"))
    body = rec + 4
    for l_seq in (-1, -(2 ** 31), 2 ** 20, 2 ** 31 - 1):
        m = bytearray(base)
        struct.pack_into("<i", m, body + 16, l_seq)
        mutants.append((m, "err"))
    for n_cigar in (2 ** 16 - 1,):  # u16 max: overruns the record
        m = bytearray(base)
        struct.pack_into("<H", m, body + 12, n_cigar)
        mutants.append((m, "err"))
    m = bytearray(base)
    m[body + 8] = 255  # l_read_name: overruns the record
    mutants.append((m, "err"))
    for ref_id in (2, -2, 2 ** 31 - 1):  # dict has 2 entries
        m = bytearray(base)
        struct.pack_into("<i", m, body, ref_id)
        mutants.append((m, "err"))
    # corrupt CIGAR op codes (9-15 are undefined) must still DECODE —
    # rejecting them is the event layer's business, not the parser's
    name_len = base[body + 8]
    cig0 = body + 32 + name_len
    for op in (9, 12, 15):
        m = bytearray(base)
        w = struct.unpack_from("<I", m, cig0)[0]
        struct.pack_into("<I", m, cig0, (w & ~0xF) | op)
        mutants.append((m, "ok"))
    for m, want in mutants:
        outcome = _assert_agree(m)
        assert outcome[0] == want


def test_corrupt_cigar_ops_survive_event_extraction():
    """Undefined op codes decode, then the event layer must not crash on
    them (they contribute no events, like H/P)."""
    from kindel_tpu.events import extract_events

    base = _mini_bam()
    rec = _first_record_off(base)
    body = rec + 4
    cig0 = body + 32 + base[body + 8]
    m = bytearray(base)
    w = struct.unpack_from("<I", m, cig0)[0]
    struct.pack_into("<I", m, cig0, (w & ~0xF) | 11)
    batch = parse_bam_bytes(bytes(m))
    ev = extract_events(batch)  # must not raise
    assert ev is not None


def test_random_byte_corruption_and_truncation():
    """Seeded random fuzz: single/multi-byte flips and truncations across
    the whole stream. Every mutant must decode identically on both paths
    or fail with CLEAN on both."""
    rng = np.random.default_rng(23)
    base = _mini_bam(n_reads=8)
    n = len(base)
    for _ in range(300):
        m = bytearray(base)
        for _ in range(int(rng.integers(1, 4))):
            m[int(rng.integers(0, n))] = int(rng.integers(0, 256))
        _assert_agree(m)
    for _ in range(100):
        cut = int(rng.integers(4, n))
        _assert_agree(base[:cut])


def test_bgzf_framing_fuzz(data_root):
    """BGZF-level attacks on a real corpus file: truncations, BSIZE lies,
    ISIZE lies, corrupt magic/payload. Pure path: bytes or ValueError.
    Native path: whenever it returns bytes they equal the pure result."""
    from kindel_tpu.io import native

    raw = (data_root / "data_minimap2" / "1.1.multi.bam").read_bytes()
    have_native = native.available()

    def check(mutant: bytes):
        try:
            pure = bgzf.decompress(mutant)
        except CLEAN:
            pure = None
        if have_native:
            nat = native.bgzf_decompress(mutant)
            if nat is not None:
                assert nat == pure
        return pure

    rng = np.random.default_rng(31)
    for _ in range(60):
        check(raw[: int(rng.integers(1, len(raw)))])
    # BSIZE lies on the first member: point it everywhere bogus
    first_bsize = bgzf._member_bsize(raw, 0)
    assert first_bsize is not None
    xoff = 12  # first member, first subfield is BC in htslib-style BGZF
    for bs in (0, 1, 17, 25, len(raw) + 9999, 2 ** 16 - 1):
        m = bytearray(raw)
        struct.pack_into("<H", m, xoff + 4, max(0, bs - 1) & 0xFFFF)
        check(bytes(m))
    # ISIZE lies on the first member (native pre-sizes from ISIZE sums and
    # must cleanly reject the mismatch, not overflow)
    for isize in (0, 1, 2 ** 32 - 1):
        m = bytearray(raw)
        struct.pack_into("<I", m, first_bsize - 4, isize)
        check(bytes(m))
    # corrupt deflate payload
    m = bytearray(raw)
    for k in range(30, 200, 7):
        m[k] ^= 0xAA
    check(bytes(m))
    # mid-stream garbage magic
    m = bytearray(raw)
    m[first_bsize] = 0x00
    check(bytes(m))


def test_load_alignment_clean_errors(tmp_path, data_root):
    """The public entry point must return a batch or raise ValueError for
    arbitrary files: garbage bytes, truncated BGZF, valid BGZF around a
    corrupt BAM, and binary junk that is neither gzip nor BAM nor SAM."""
    import gzip

    from kindel_tpu.io import load_alignment

    rng = np.random.default_rng(41)
    cases = {
        "junk.bam": bytes(rng.integers(0, 256, 4096, dtype=np.uint8)),
        "empty.bam": b"",
        "truncated.bam": (
            data_root / "data_minimap2" / "1.1.multi.bam"
        ).read_bytes()[:1337],
        "lying_header.bam": gzip.compress(
            b"BAM\x01" + struct.pack("<i", -5) + b"\x00" * 64
        ),
        "text.sam": b"not\ta\tsam\tfile\n" * 3,
    }
    corrupt = _mini_bam()
    struct.pack_into("<i", corrupt, _first_record_off(corrupt), 31)
    cases["bad_record.bam"] = gzip.compress(bytes(corrupt))
    ok = _mini_bam()
    cases["ok.bam"] = gzip.compress(bytes(ok))

    for name, blob in cases.items():
        f = tmp_path / name
        f.write_bytes(blob)
        try:
            batch = load_alignment(f)
            assert name == "ok.bam", f"{name} unexpectedly accepted"
            assert batch.n_reads == 5
        except CLEAN:
            assert name != "ok.bam"


@pytest.mark.slow
def test_native_asan_driver():
    """Build and run the C++ fuzz driver under ASan+UBSan (make asan):
    catches kernel overruns that land in mapped memory and are therefore
    invisible to the ctypes-level fuzz above. This run caught a real OOB
    read (bgzf_decompressed_size accepted BSIZE < 26) on first use."""
    import shutil
    import subprocess
    from pathlib import Path

    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("C++ toolchain unavailable")
    src = Path(__file__).resolve().parents[1] / "src" / "native"
    proc = subprocess.run(
        ["make", "-C", str(src), "asan"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "fuzz_driver: ok" in proc.stdout


def test_streamed_header_lies(tmp_path):
    """The third decoder path (io.stream's incremental header parse) must
    reject the same header attacks as the slurp parser — previously a
    lying n_ref sized a host allocation before any data was read, and
    negative l_ref was accepted (round-5 review finding)."""
    import gzip

    from kindel_tpu.io.stream import stream_alignment

    def run(blob: bytes):
        f = tmp_path / "m.bam"
        f.write_bytes(gzip.compress(blob))
        return list(stream_alignment(f, 4096))

    base = bytes(_mini_bam())
    assert len(run(base)) >= 1  # fixture sanity

    l_text = struct.unpack_from("<i", base, 4)[0]
    n_ref_off = 8 + l_text
    attacks = []
    for n_ref in (2 ** 27, 2 ** 31 - 1, -1):
        m = bytearray(base)
        struct.pack_into("<i", m, n_ref_off, n_ref)
        attacks.append(bytes(m))
    m = bytearray(base)  # negative l_ref on the first reference
    struct.pack_into("<i", m, n_ref_off + 4 + 4 + 5, -7)
    attacks.append(bytes(m))
    m = bytearray(base)  # huge l_text: must skip chunked then hit EOF
    struct.pack_into("<i", m, 4, 2 ** 31 - 1)
    attacks.append(bytes(m))
    for blob in attacks:
        with pytest.raises(ValueError):
            run(blob)
        with pytest.raises(ValueError):
            parse_bam_bytes(blob)  # slurp path agrees


def test_round5_review_reproductions(tmp_path):
    """Regression pins for the five round-5 review reproductions: each was
    a confirmed hole in the first cut of the hardening."""
    import gzip
    import zlib

    from kindel_tpu.io import native
    from kindel_tpu.io.stream import stream_alignment

    # 1. record overrunning its own block must be rejected by the STREAM
    # path even when a chunk boundary falls right after it (the old check
    # bounded the chunk's last record by the buffer end, tail included)
    base = _mini_bam(n_reads=3)
    rec = _first_record_off(base)
    lying = bytearray(base)
    struct.pack_into("<i", lying, rec + 4 + 16, 2 ** 16)  # l_seq lie
    f = tmp_path / "overrun.bam"
    f.write_bytes(gzip.compress(bytes(lying)))
    with pytest.raises(ValueError):
        list(stream_alignment(f, 4096))
    with pytest.raises(ValueError):
        parse_bam_bytes(bytes(lying))

    # 2. ISIZE bomb: hundreds of empty members claiming 4 GB each must
    # not pre-allocate in the native inflater (clean None fallback)
    empty_payload = zlib.compress(b"", 9)[2:-4]
    member = bytearray()
    member += b"\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff"
    member += struct.pack("<H", 6) + b"BC" + struct.pack("<H", 2)
    bsize = 18 + len(empty_payload) + 8
    member += struct.pack("<H", bsize - 1)
    member += empty_payload
    member += struct.pack("<I", 0) + struct.pack("<I", 2 ** 32 - 1)
    bomb = bytes(member) * 200
    if native.available():
        assert native.bgzf_decompress(bomb) is None
    with pytest.raises(CLEAN):
        from kindel_tpu.io import load_alignment

        fb = tmp_path / "bomb.bam"
        fb.write_bytes(bomb)
        load_alignment(fb)

    # 3. truncated generic (non-BGZF) gzip raises instead of returning a
    # silent partial result
    blob = gzip.compress(b"A" * 10000)
    with pytest.raises(ValueError):
        bgzf.decompress(blob[: len(blob) // 2])

    # 4. oversized reference name rejected identically by both parsers
    big_name = bytearray(base)
    l_text = struct.unpack_from("<i", base, 4)[0]
    struct.pack_into("<i", big_name, 8 + l_text + 4, 1 << 16)
    with pytest.raises(ValueError):
        parse_bam_bytes(bytes(big_name))
    fn = tmp_path / "name.bam"
    fn.write_bytes(gzip.compress(bytes(big_name)))
    with pytest.raises(ValueError):
        list(stream_alignment(fn, 4096))

    # 5. a lying giant block_size must fail fast in the streamer, not
    # buffer the whole remaining stream as carry first
    giant = bytearray(base)
    struct.pack_into("<i", giant, rec, 2 ** 31 - 1)
    fg = tmp_path / "giant.bam"
    fg.write_bytes(gzip.compress(bytes(giant)))
    with pytest.raises(ValueError):
        list(stream_alignment(fg, 4096))


def test_sam_text_garbage_clean_errors(tmp_path):
    """The SAM text decoder must also hold the ValueError-only contract:
    malformed numeric fields, bad CIGAR strings, binary junk lines."""
    from kindel_tpu.io import load_alignment
    from kindel_tpu.io.sam import parse_sam_bytes

    header = b"@SQ\tSN:r1\tLN:100\n"
    ok_line = b"a\t0\tr1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n"
    assert parse_sam_bytes(header + ok_line).n_reads == 1

    bad = [
        header + b"a\tNOTINT\tr1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\tNOTINT\t60\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\t1\tNOTINT\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\t1\t60\t4Q\t*\t0\t0\tACGT\t*\n",  # bad op
        header + b"a\t0\tr1\t1\t60\tM4\t*\t0\t0\tACGT\t*\n",  # bad order
        b"@SQ\tSN:r1\tLN:NOTINT\n" + ok_line,  # header LN lie
        # in-grammar but OUT-OF-RANGE integers: previously surfaced as
        # OverflowError from the columnar numpy conversions, violating
        # the ValueError-only contract (round-5 review finding)
        header + b"a\t70000\tr1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t-1\tr1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\t1\t300\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\t1\t-1\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\t" + str(10 ** 30).encode()
        + b"\t60\t4M\t*\t0\t0\tACGT\t*\n",
        header + b"a\t0\tr1\t1\t60\t99999999999999M\t*\t0\t0\tACGT\t*\n",
        b"@SQ\tSN:r1\tLN:" + str(10 ** 30).encode() + b"\n" + ok_line,
    ]
    for i, blob in enumerate(bad):
        with pytest.raises(ValueError):
            parse_sam_bytes(blob)
        f = tmp_path / f"bad{i}.sam"
        f.write_bytes(blob)
        with pytest.raises(ValueError):
            load_alignment(f)

    # binary junk that is neither gzip nor BAM routes to the SAM parser
    # and must come back as ValueError, not a decode crash
    rng = np.random.default_rng(53)
    junk = bytes(rng.integers(1, 256, 2048, dtype=np.uint8)).replace(b"\x1f", b"x")
    f = tmp_path / "junk.sam"
    f.write_bytes(junk)
    try:
        batch = load_alignment(f)
        # accepted as (degenerate) SAM: the batch must still be
        # structurally sound, not merely constructed
        assert batch.seq_off.shape[0] == batch.n_reads + 1
        assert batch.cig_off.shape[0] == batch.n_reads + 1
    except CLEAN:
        pass


def test_streamed_gzip_truncation_never_silent(tmp_path):
    """Truncating a generic-gzip (non-BGZF) BAM anywhere must raise from
    the STREAMED path too — the generic-member branch previously flushed
    partial output and returned on EOF, silently dropping trailing reads
    (round-5 finding; the slurp path had the same bug fixed earlier)."""
    import gzip

    from kindel_tpu.io.stream import stream_alignment

    blob = gzip.compress(bytes(_mini_bam()))
    rng = np.random.default_rng(71)
    cuts = set(int(c) for c in rng.integers(1, len(blob) - 1, 25))
    cuts |= {10, 50, len(blob) // 2, len(blob) - 5, len(blob) - 1}
    for cut in sorted(cuts):
        f = tmp_path / "t.bam"
        f.write_bytes(blob[:cut])
        with pytest.raises(ValueError):
            list(stream_alignment(f, 4096))
    # untruncated sanity: still decodes
    f = tmp_path / "ok.bam"
    f.write_bytes(blob)
    assert sum(b.n_reads for b in stream_alignment(f, 4096)) == 5
