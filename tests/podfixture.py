"""Deterministic dispatch-tier drivers + digests shared between the
two-process pod workers (tests/_dist_pod_worker.py) and their
in-process oracles (tests/test_meshexec.py pod matrix): the SPMD pod
contract needs every process to issue the SAME device programs in the
SAME order, so these drivers speak to the dispatch layer directly —
no threaded batcher timing in the loop — and the oracle runs the very
same code at dp=1, so the two sides can never drift apart."""

import hashlib
from pathlib import Path

from kindel_tpu.batch import BatchOptions, _call_and_assemble
from kindel_tpu.parallel import meshexec
from kindel_tpu.ragged import pack as rpack
from kindel_tpu.ragged import parse_classes
from kindel_tpu.serve.queue import ServeRequest
from kindel_tpu.serve.worker import decode_request

#: one small page class: 32 rows so every dp ∈ {1, 2, 4} (and every
#: procs-multiple width) divides the rows/pages evenly
CLASSES = parse_classes("small:32x2048")


def make_units(tmpdir, realign: bool = False, n: int = 6,
               seed_base: int = 31) -> list:
    """The fixed synthetic cohort of the pod matrix — varied lengths
    and depths, decoded with the realign channels when asked."""
    from tests import distfixture
    from tests.test_serve import make_sam

    tmpdir = Path(tmpdir)
    tmpdir.mkdir(parents=True, exist_ok=True)
    opts = BatchOptions(realign=realign)
    units = []
    for i in range(n):
        sam = make_sam(
            tmpdir / f"pod{i}.sam", ref=f"pod{i}", L=260 + 97 * i,
            n_reads=10 + 3 * i, seed=seed_base + i,
        )
        units.extend(
            decode_request(
                ServeRequest(payload=str(sam), opts=opts)
            )
        )
    # one clip-flanked-gap sample (distfixture.product_sam layout): under
    # realign the CDR walk actually produces a gap-closing patch, so the
    # pod matrix exercises the dense-tensor window fetches for real
    units.extend(
        decode_request(
            ServeRequest(
                payload=distfixture.product_sam(ref_len=1280,
                                                seed=seed_base),
                opts=opts,
            )
        )
    )
    return units


def _seq_digest(seqs) -> str:
    h = hashlib.sha256()
    for s in seqs:
        h.update(s.encode())
        h.update(b"\x00")
    return h.hexdigest()


def cohort_digest(units, opts: BatchOptions) -> str:
    """Lane-tier FASTA digest: pack + mesh-plan dispatch + assembly —
    the plan (and so the pod mesh) resolves from the environment inside
    `_dispatch_device_call`, exactly as the serve worker's flush
    does."""
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=2) as pool:
        outs = _call_and_assemble(units, opts, pool)
    return _seq_digest([o[0].sequence for o in outs])


def ragged_digest(units, plan, opts: BatchOptions) -> str:
    """Ragged-tier digest through the sharded sub-superbatch path; at a
    plan that cannot shard (dp=1) the classic single-device superbatch
    runs instead — the byte-identity oracle."""
    from kindel_tpu.paged.retire import _InlineMap

    cls = CLASSES[0]
    ssb = meshexec.shard_superbatch(units, cls, plan,
                                    realign=opts.realign)
    if ssb is None:
        from kindel_tpu.ragged.kernel import launch_ragged
        from kindel_tpu.ragged.unpack import unpack_superbatch

        table = rpack.build_segment_table(units, cls)
        arrays = rpack.pack_superbatch(units, table,
                                       realign=opts.realign)
        out = launch_ragged(arrays, cls, opts)
        outs = unpack_superbatch(out, table, units, opts, _InlineMap())
    else:
        out = meshexec.launch_sharded_superbatch(ssb, opts)
        outs = meshexec.unpack_sharded_superbatch(
            out, ssb, opts, _InlineMap()
        )
    return _seq_digest([o[0].sequence for o in outs])


def paged_digest(units, plan, opts: BatchOptions) -> str:
    """Paged-tier digest over a mesh-resident pool with admit/retire
    churn in the middle (the in-place patch + clear programs run for
    real before the final launch), extracted through the sharded or
    classic table as the plan dictates."""
    from kindel_tpu.paged import PagedBatcher
    from kindel_tpu.paged.retire import _InlineMap
    from kindel_tpu.ragged.unpack import unpack_rows

    cls = CLASSES[0]
    b = PagedBatcher([cls], mesh_plan=plan, max_wait_s=0.01)
    lane = b._lane_for(("podlane",), cls, opts)
    res = lane.pool.residency

    def admit(us):
        segs = []
        for u in us:
            seg = lane.pool.admit_unit(u, rpack.consumption([u]))
            assert seg is not None, f"unit {u.ref_id} did not place"
            segs.append(seg)
        return segs

    segs = admit(units[:4])
    # churn: retire two, admit the rest — clear + re-patch programs run
    for seg in segs[:2]:
        seg.panel = None
        lane.pool.release(seg)
    live = list(zip(segs[2:], units[2:4]))
    live += list(zip(admit(units[4:] + units[:2]),
                     units[4:] + units[:2]))
    u2, tables, row_of = res.table(lane.pool)
    out = res.launch(opts)
    pairs = [(row_of[seg.seg_id], u) for seg, u in live]
    if res.mesh_dp > 1:
        outs = meshexec.unpack_sharded_rows(
            out, tables, pairs, opts, _InlineMap()
        )
    else:
        out = meshexec.fetch_global(out)
        outs = unpack_rows(out, tables, pairs, opts, _InlineMap())
    return _seq_digest([o[0].sequence for o in outs])


def all_digests(tmpdir, plan, realign: bool = False) -> dict:
    """The full tier × digest map one pod (or oracle) process
    computes."""
    opts = BatchOptions(realign=realign)
    units = make_units(tmpdir, realign=realign)
    return {
        "cohort": cohort_digest(units, opts),
        "ragged": ragged_digest(units, plan, opts),
        "paged": paged_digest(units, plan, opts),
    }
