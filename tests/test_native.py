"""Native C++ decoder ↔ pure-Python decoder equivalence."""

import numpy as np
import pytest

from kindel_tpu.io import bgzf
from kindel_tpu.io.bam import parse_bam_bytes


@pytest.fixture(scope="module")
def native():
    from kindel_tpu.io import native as mod

    if not mod.available():
        pytest.skip("native library not built (make -C src/native)")
    return mod


def test_native_bgzf_matches_python(native, data_root):
    raw = (data_root / "data_bwa_mem" / "1.1.sub_test.bam").read_bytes()
    assert native.bgzf_decompress(raw) == bgzf.decompress(raw)


def test_native_bam_decode_matches_python(native, data_root):
    raw = (data_root / "data_minimap2" / "1.1.multi.bam").read_bytes()
    data = bgzf.decompress(raw)
    py = parse_bam_bytes(data)
    nt = native.parse_bam_bytes(data)
    assert py.ref_names == nt.ref_names
    np.testing.assert_array_equal(py.pos, nt.pos)
    np.testing.assert_array_equal(py.flag, nt.flag)
    np.testing.assert_array_equal(py.seq, nt.seq)
    np.testing.assert_array_equal(py.cig_op, nt.cig_op)
    np.testing.assert_array_equal(py.cig_len, nt.cig_len)


def test_native_rejects_garbage(native):
    assert native.bgzf_decompress(b"\x1f\x8b" + b"junkjunkjunkjunkjunk") is None


# --- expansion kernels: native one-pass C++ vs the numpy formulations ---


def _numpy_ragged_indices(starts, lens):
    starts = np.asarray(starts, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    ends = np.cumsum(lens)
    flat = np.arange(total, dtype=np.int64)
    base = np.repeat(ends - lens, lens)
    return np.repeat(starts, lens) + (flat - base)


def test_ragged_kernels_match_numpy_fuzz(native):
    rng = np.random.default_rng(7)
    for _ in range(50):
        n = int(rng.integers(0, 40))
        starts = rng.integers(-50, 50, size=n)
        lens = rng.integers(0, 20, size=n)  # includes empty ranges
        np.testing.assert_array_equal(
            native.ragged_indices(starts, lens),
            _numpy_ragged_indices(starts, lens),
        )
        exp_local = _numpy_ragged_indices(np.zeros(n, np.int64), lens)
        np.testing.assert_array_equal(
            native.ragged_local_offsets(lens), exp_local
        )


def test_fields_from_offsets_native_matches_numpy(native, data_root, monkeypatch):
    raw = (data_root / "data_minimap2" / "1.1.multi.bam").read_bytes()
    data = bgzf.decompress(raw)
    with_native = parse_bam_bytes(data)
    monkeypatch.setenv("KINDEL_TPU_DISABLE_NATIVE", "1")
    pure = parse_bam_bytes(data)
    np.testing.assert_array_equal(pure.seq, with_native.seq)
    np.testing.assert_array_equal(pure.cig_op, with_native.cig_op)
    np.testing.assert_array_equal(pure.cig_len, with_native.cig_len)
    np.testing.assert_array_equal(pure.seq_off, with_native.seq_off)


def test_extract_events_native_matches_numpy(native, data_root, monkeypatch):
    """End-to-end event-stream identity with the fused M/=/X expansion on
    vs off — covers the wrap/bounds/base-code semantics of
    expand_match_events against the numpy branch, on a real multi-contig
    BAM (clips, indels) and the clipped viral BAM."""
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment

    for rel in ("data_minimap2/1.1.multi.bam", "data_bwa_mem/1.1.sub_test.bam"):
        batch = load_alignment(data_root / rel)
        ev_native = extract_events(batch)
        monkeypatch.setenv("KINDEL_TPU_DISABLE_NATIVE", "1")
        ev_pure = extract_events(batch)
        monkeypatch.delenv("KINDEL_TPU_DISABLE_NATIVE")
        for f in (
            "match_rid", "match_pos", "match_base", "del_rid", "del_pos",
            "cs_rid", "cs_pos", "ce_rid", "ce_pos",
            "csw_rid", "csw_pos", "csw_base",
            "cew_rid", "cew_pos", "cew_base",
        ):
            np.testing.assert_array_equal(
                getattr(ev_pure, f), getattr(ev_native, f), err_msg=f
            )
        assert ev_pure.insertions == ev_native.insertions


def test_expand_match_events_wrap_and_bounds(native):
    """Negative start positions wrap Python-style exactly once (p in
    [-L, 0) → p+L); anything still outside [0, L) is dropped — pinned
    against the numpy branch's _wrap + mask semantics."""
    from kindel_tpu.events import BASE_CODE

    seq = np.frombuffer(b"ACGTACGTACGTACGTACGT", dtype=np.uint8).copy()
    r_start = np.array([-3, -25, 8], dtype=np.int64)
    q_abs = np.array([0, 5, 10], dtype=np.int64)
    lens = np.array([5, 4, 5], dtype=np.int64)
    rid = np.array([0, 0, 1], dtype=np.int64)
    L = np.array([10, 10, 10], dtype=np.int64)
    got = native.expand_match_events(r_start, q_abs, lens, rid, L, seq, BASE_CODE)
    pos = _numpy_ragged_indices(r_start, lens)
    qidx = _numpy_ragged_indices(q_abs, lens)
    rid_f = np.repeat(rid, lens)
    L_f = np.repeat(L, lens)
    pos = np.where(pos < 0, pos + L_f, pos)
    ok = (pos >= 0) & (pos < L_f)
    np.testing.assert_array_equal(got[0], rid_f[ok])
    np.testing.assert_array_equal(got[1], pos[ok])
    np.testing.assert_array_equal(got[2], BASE_CODE[seq[qidx[ok]]])
    # out-of-bounds query index → None (caller falls back to numpy)
    assert (
        native.expand_match_events(
            r_start, np.array([0, 5, 18], dtype=np.int64), lens, rid, L,
            seq, BASE_CODE,
        )
        is None
    )


def test_negative_lengths_rejected(native):
    """Mixed-sign lengths must never reach the C++ write loops: the Python
    allocation is sum(lens) while positive entries alone would write more
    (a heap overflow before this guard). Every ragged wrapper returns None
    so callers fall back to numpy, which raises a clean ValueError."""
    starts = np.array([0, 10], dtype=np.int64)
    bad = np.array([5, -3], dtype=np.int64)
    assert native.ragged_indices(starts, bad) is None
    assert native.ragged_local_offsets(bad) is None

    seq = np.frombuffer(b"ACGTACGT", dtype=np.uint8).copy()
    from kindel_tpu.events import BASE_CODE

    assert (
        native.expand_match_events(
            starts, starts, bad, np.zeros(2, np.int64),
            np.full(2, 100, np.int64), seq, BASE_CODE,
        )
        is None
    )
    buf = np.zeros(64, dtype=np.uint8)
    nt16 = np.frombuffer(b"=ACMGRSVTWYHKDBN", dtype=np.uint8).copy()
    assert native.unpack_seq(buf, starts, bad, nt16) is None
    assert native.parse_cigar(buf, starts, bad) is None


def test_negative_l_seq_bam_record_clean_error(native, data_root):
    """A BAM record carrying a negative l_seq (untrusted input; the field is
    signed <i4) must raise a clean ValueError through the full decode, not
    corrupt memory. Reproduces the advisor's segfault case."""
    from kindel_tpu.io import bgzf as bz
    from kindel_tpu.io.bam import parse_bam_bytes as py_parse
    import struct

    raw = (data_root / "data_minimap2" / "1.1.multi.bam").read_bytes()
    data = bytearray(bz.decompress(raw))
    # find the first record body offset: walk header exactly as the decoder
    l_text = struct.unpack_from("<i", data, 4)[0]
    off = 8 + l_text
    n_ref = struct.unpack_from("<i", data, off)[0]
    off += 4
    for _ in range(n_ref):
        l_name = struct.unpack_from("<i", data, off)[0]
        off += 8 + l_name
    body = off + 4  # past block_size
    struct.pack_into("<i", data, body + 16, -7)  # l_seq := negative
    for fn in (py_parse, native.parse_bam_bytes):
        with pytest.raises(ValueError):
            fn(bytes(data))


def test_decode_plane_matches_numpy(native):
    """Bit-for-bit parity of the fused C++ plane decode against the numpy
    expansion in call_jax.decode_fast, across tail lengths and exception
    densities (MSB-first bit order on both the 2-bit plane and the
    exception mask)."""
    from kindel_tpu.call_jax import EMIT_ASCII, N_CHANNELS

    rng = np.random.default_rng(61)
    for L in (0, 1, 3, 4, 5, 7, 8, 31, 32, 33, 1000, 65537):
        plane = rng.integers(0, 256, (L + 3) // 4, dtype=np.uint8)
        for dens in (0.0, 0.01, 0.5, 1.0):
            exc = np.packbits(rng.random(L) < dens)
            exc = np.pad(exc, (0, (L + 7) // 8 - len(exc)))
            got = native.decode_plane(
                plane, exc, L, EMIT_ASCII[1:5], int(EMIT_ASCII[N_CHANNELS])
            )
            p = np.empty(len(plane) * 4, np.uint8)
            p[0::4] = plane >> 6
            p[1::4] = (plane >> 4) & 3
            p[2::4] = (plane >> 2) & 3
            p[3::4] = plane & 3
            want = EMIT_ASCII[1:5][p[:L]]
            e = np.unpackbits(exc)[:L].astype(bool)
            want = np.where(e, EMIT_ASCII[N_CHANNELS], want)
            np.testing.assert_array_equal(got, want, err_msg=f"L={L} d={dens}")
    # short buffers: clean None (callers raise before reaching here)
    assert native.decode_plane(
        np.zeros(2, np.uint8), np.zeros(1, np.uint8), 16,
        EMIT_ASCII[1:5], int(EMIT_ASCII[N_CHANNELS])
    ) is None
