"""Native C++ decoder ↔ pure-Python decoder equivalence."""

import numpy as np
import pytest

from kindel_tpu.io import bgzf
from kindel_tpu.io.bam import parse_bam_bytes


@pytest.fixture(scope="module")
def native():
    from kindel_tpu.io import native as mod

    if not mod.available():
        pytest.skip("native library not built (make -C src/native)")
    return mod


def test_native_bgzf_matches_python(native, data_root):
    raw = (data_root / "data_bwa_mem" / "1.1.sub_test.bam").read_bytes()
    assert native.bgzf_decompress(raw) == bgzf.decompress(raw)


def test_native_bam_decode_matches_python(native, data_root):
    raw = (data_root / "data_minimap2" / "1.1.multi.bam").read_bytes()
    data = bgzf.decompress(raw)
    py = parse_bam_bytes(data)
    nt = native.parse_bam_bytes(data)
    assert py.ref_names == nt.ref_names
    np.testing.assert_array_equal(py.pos, nt.pos)
    np.testing.assert_array_equal(py.flag, nt.flag)
    np.testing.assert_array_equal(py.seq, nt.seq)
    np.testing.assert_array_equal(py.cig_op, nt.cig_op)
    np.testing.assert_array_equal(py.cig_len, nt.cig_len)


def test_native_rejects_garbage(native):
    assert native.bgzf_decompress(b"\x1f\x8b" + b"junkjunkjunkjunkjunk") is None
