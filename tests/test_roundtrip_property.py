"""Generative round-trip property: decode(serialize(truth)) == truth.

The corruption fuzz (test_decode_fuzz.py) proves malformed input is
rejected; this file proves the complementary property — for arbitrary
VALID alignments, every decode path reproduces the constructed ground
truth exactly. hypothesis drives the read/reference generator, then each
example is serialized three ways (SAM text, raw BAM, BGZF-compressed
BAM) and decoded through the pure-Python, native-C++ and streamed
decoders; every field must equal the truth, bit for bit.
"""

from __future__ import annotations

import gzip
import struct

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from kindel_tpu.io.bam import parse_bam_bytes
from kindel_tpu.io.records import CIGAR_OPS

#: op chars whose lengths consume query sequence (M I S = X)
_CONSUMES_QUERY = {0, 1, 4, 7, 8}

_BASES = "ACGTN"
_NT16_CODE = {"A": 1, "C": 2, "G": 4, "T": 8, "N": 15}


@st.composite
def alignments(draw):
    """(ref_names, ref_lens, reads) with structurally valid CIGARs."""
    n_ref = draw(st.integers(1, 3))
    ref_names = [f"ref{i}" for i in range(n_ref)]
    ref_lens = [draw(st.integers(50, 5000)) for _ in range(n_ref)]
    reads = []
    for r in range(draw(st.integers(0, 12))):
        rid = draw(st.integers(0, n_ref - 1))
        ops = []
        for _ in range(draw(st.integers(0, 5))):
            op = draw(st.sampled_from([0, 1, 2, 3, 4, 7, 8]))  # MIDNS=X
            ops.append((draw(st.integers(1, 30)), op))
        l_seq = sum(n for n, op in ops if op in _CONSUMES_QUERY)
        seq = "".join(
            draw(st.sampled_from(_BASES)) for _ in range(l_seq)
        )
        pos = draw(st.integers(0, max(ref_lens[rid] - 1, 0)))
        flag = draw(st.sampled_from([0, 4, 16, 99, 147, 2048]))
        mapq = draw(st.integers(0, 254))
        reads.append(
            {"rid": rid, "pos": pos, "flag": flag, "mapq": mapq,
             "ops": ops, "seq": seq, "name": f"rd{r}"}
        )
    return ref_names, ref_lens, reads


def _to_sam(ref_names, ref_lens, reads) -> bytes:
    lines = [b"@HD\tVN:1.6"]
    for n, ln in zip(ref_names, ref_lens):
        lines.append(f"@SQ\tSN:{n}\tLN:{ln}".encode())
    for rd in reads:
        cigar = "".join(
            f"{n}{'MIDNSHP=X'[op]}" for n, op in rd["ops"]
        ) or "*"
        lines.append(
            (
                f"{rd['name']}\t{rd['flag']}\t{ref_names[rd['rid']]}\t"
                f"{rd['pos'] + 1}\t{rd['mapq']}\t{cigar}\t*\t0\t0\t"
                f"{rd['seq'] or '*'}\t*"
            ).encode()
        )
    return b"\n".join(lines) + b"\n"


def _to_bam(ref_names, ref_lens, reads) -> bytes:
    out = bytearray(b"BAM\x01")
    text = b"@HD\tVN:1.6\n"
    out += struct.pack("<i", len(text)) + text
    out += struct.pack("<i", len(ref_names))
    for n, ln in zip(ref_names, ref_lens):
        nb = n.encode() + b"\x00"
        out += struct.pack("<i", len(nb)) + nb + struct.pack("<i", ln)
    for rd in reads:
        name = rd["name"].encode() + b"\x00"
        l_seq = len(rd["seq"])
        packed = bytearray()
        codes = [_NT16_CODE[c] for c in rd["seq"]]
        for i in range(0, l_seq, 2):
            hi = codes[i] << 4
            lo = codes[i + 1] if i + 1 < l_seq else 0
            packed.append(hi | lo)
        body = struct.pack(
            "<iiBBHHHiiii", rd["rid"], rd["pos"], len(name), rd["mapq"],
            0, len(rd["ops"]), rd["flag"], l_seq, -1, -1, 0,
        )
        body += name
        for n, op in rd["ops"]:
            body += struct.pack("<I", (n << 4) | op)
        body += bytes(packed) + b"\xff" * l_seq
        out += struct.pack("<i", len(body)) + body
    return bytes(out)


def _check_batch(batch, ref_names, ref_lens, reads):
    assert batch.ref_names == ref_names
    np.testing.assert_array_equal(batch.ref_lens, ref_lens)
    assert batch.n_reads == len(reads)
    for i, rd in enumerate(reads):
        assert int(batch.ref_id[i]) == rd["rid"], i
        assert int(batch.pos[i]) == rd["pos"], i
        assert int(batch.flag[i]) == rd["flag"], i
        assert int(batch.mapq[i]) == rd["mapq"], i
        o0, o1 = int(batch.cig_off[i]), int(batch.cig_off[i + 1])
        got_ops = [
            (int(n), int(op))
            for op, n in zip(batch.cig_op[o0:o1], batch.cig_len[o0:o1])
        ]
        assert got_ops == rd["ops"], i
        s0, s1 = int(batch.seq_off[i]), int(batch.seq_off[i + 1])
        got_seq = batch.seq[s0:s1].tobytes().decode()
        assert got_seq == rd["seq"], i
    assert len(CIGAR_OPS) == 9  # sanity anchor for the op table


@settings(max_examples=60, deadline=None)
@given(alignments())
def test_roundtrip_all_paths(ex):
    ref_names, ref_lens, reads = ex
    from kindel_tpu.io import native
    from kindel_tpu.io.stream import stream_alignment

    sam_bytes = _to_sam(ref_names, ref_lens, reads)
    bam_bytes = _to_bam(ref_names, ref_lens, reads)

    from kindel_tpu.io.sam import parse_sam_bytes

    _check_batch(parse_sam_bytes(sam_bytes), ref_names, ref_lens, reads)
    _check_batch(parse_bam_bytes(bam_bytes), ref_names, ref_lens, reads)
    if native.available():
        _check_batch(
            native.parse_bam_bytes(bam_bytes), ref_names, ref_lens, reads
        )
    # pure-Python decompressor round-trips a generic gzip member exactly
    from kindel_tpu.io import bgzf

    assert bgzf.decompress(gzip.compress(bam_bytes)) == bam_bytes

    # streamed decode in adversarially small chunks must concatenate to
    # the same truth
    import tempfile
    from pathlib import Path

    with tempfile.NamedTemporaryFile(suffix=".bam", delete=False) as fh:
        fh.write(gzip.compress(bam_bytes))
        p = Path(fh.name)
    try:
        chunks = list(stream_alignment(p, 256))
        assert sum(b.n_reads for b in chunks) == len(reads)
        flat_reads = []
        k = 0
        for b in chunks:
            for j in range(b.n_reads):
                o0, o1 = int(b.cig_off[j]), int(b.cig_off[j + 1])
                s0, s1 = int(b.seq_off[j]), int(b.seq_off[j + 1])
                flat_reads.append(
                    {
                        "rid": int(b.ref_id[j]),
                        "pos": int(b.pos[j]),
                        "flag": int(b.flag[j]),
                        "mapq": int(b.mapq[j]),
                        "ops": [
                            (int(n), int(op))
                            for op, n in zip(
                                b.cig_op[o0:o1], b.cig_len[o0:o1]
                            )
                        ],
                        "seq": b.seq[s0:s1].tobytes().decode(),
                    }
                )
                k += 1
        # names are not carried in ReadBatch; compare the decoded fields
        assert flat_reads == [
            {k2: v for k2, v in rd.items() if k2 != "name"} for rd in reads
        ]
    finally:
        p.unlink()


def test_sam_seq_star_with_consuming_cigar():
    """Directed case the generator cannot produce: SEQ '*' (omitted) with
    a query-consuming CIGAR — common for secondary/supplementary records.
    Must decode to an empty sequence, keep the offset tables consistent,
    and contribute no events (matching BAM l_seq=0 semantics)."""
    from kindel_tpu.events import extract_events
    from kindel_tpu.io.sam import parse_sam_bytes

    blob = (
        b"@SQ\tSN:r1\tLN:100\n"
        b"a\t256\tr1\t5\t60\t50M\t*\t0\t0\t*\t*\n"
        b"b\t0\tr1\t1\t60\t4M\t*\t0\t0\tACGT\t*\n"
    )
    batch = parse_sam_bytes(blob)
    assert batch.n_reads == 2
    assert int(batch.seq_off[1]) - int(batch.seq_off[0]) == 0  # '*' read
    assert batch.seq[
        int(batch.seq_off[1]):int(batch.seq_off[2])
    ].tobytes() == b"ACGT"
    ev = extract_events(batch)
    sel = ev.match_rid == 0
    # only read b's 4 matches may produce events
    assert len(ev.match_pos[sel]) == 4
