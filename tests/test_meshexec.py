"""kindel_tpu.parallel.meshexec — per-replica mesh-sharded dispatch.

Covers: knob precedence (explicit > env > host-keyed store >
all-local-devices default, malformed env/store fallback, the
FORCE_FUSED pin), the page-alignment properties of the ragged slot-axis
and paged page-grid sharding, the byte-identity matrix (dispatch tier ×
dp × realign × emit mode) on the conftest-forced 8-device CPU mesh,
sharded paged admit/retire churn parity against the single-device
oracle, the zero-compile warm-mesh pin, the owning-shard CDR-window
fetch (content parity + a wall-time budget — the jit dynamic-slice path
resharded the whole dp-sharded tensor per window), and the flagship:
mixed traffic through a 3-replica fleet on an active mesh under faults
with a kill + drain, FASTA identical to single-device lanes.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from kindel_tpu import tune
from kindel_tpu.batch import BatchOptions, _RowCdrFetcher, _dispatch_device_call
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.parallel import meshexec
from kindel_tpu.ragged import parse_classes
from kindel_tpu.ragged import pack as rpack
from kindel_tpu.resilience import FaultPlan
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.serve import ConsensusClient, ConsensusService
from kindel_tpu.serve.queue import ServeRequest
from kindel_tpu.serve.worker import decode_request
from kindel_tpu.tune import TuningConfig

from tests import podfixture
from tests.test_paged import _mixed_sams
from tests.test_serve import make_sam

CLASSES = parse_classes("small:32x2048,medium:16x8192")


def _decode(payload, **opt_kwargs):
    return decode_request(
        ServeRequest(payload=payload, opts=BatchOptions(**opt_kwargs))
    )


# --------------------------------------------------------------- knob


def test_mesh_knob_precedence(monkeypatch, tmp_path):
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(tmp_path / "t.json"))
    # default: auto (all local devices at plan build)
    assert tune.resolve_mesh_dp() == (None, "default")
    assert meshexec.plan().dp == meshexec.visible_devices()
    # store
    tune.record(tune.mesh_store_key(), {"mesh_dp": 2})
    assert tune.resolve_mesh_dp() == (2, "cache")
    assert meshexec.plan().dp == 2
    # env beats store
    monkeypatch.setenv("KINDEL_TPU_MESH", "4")
    assert tune.resolve_mesh_dp() == (4, "env")
    # explicit beats env
    assert tune.resolve_mesh_dp(2) == (2, "explicit")
    assert meshexec.plan(2).dp == 2
    # malformed env: operator intent to override the store → default
    monkeypatch.setenv("KINDEL_TPU_MESH", "bogus")
    assert tune.resolve_mesh_dp() == (None, "default")
    # malformed store entry is ignored → default
    monkeypatch.delenv("KINDEL_TPU_MESH")
    tune.record(tune.mesh_store_key(), {"mesh_dp": "three"})
    assert tune.resolve_mesh_dp() == (None, "default")
    # a request wider than the host clamps to the visible devices
    assert meshexec.plan(64).dp == meshexec.visible_devices()


def test_force_fused_pins_single_device(monkeypatch):
    monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", "1")
    p = meshexec.plan(8)
    assert p.dp == 1 and p.source == "forced-single"
    assert p.row_sharding_for(8) == (None, 1)


# ----------------------------------------------------- page alignment


def test_ragged_shard_page_alignment_property():
    """Every width ragged_dp offers splits the slot axis on page-class
    length multiples (hence 8-slot granule / wire-byte boundaries), and
    shard_superbatch never lets a segment cross a shard boundary —
    segments live wholly inside one sub-superbatch by construction."""
    from kindel_tpu.ragged.pack import GRANULE, PageClass

    for rows, length in ((32, 2048), (16, 8192), (24, 1024), (8, 65536)):
        cls = PageClass("t", rows, length)
        for dp in (1, 2, 3, 4, 5, 8, 16):
            d = meshexec.ragged_dp(cls, dp)
            assert d >= 1 and cls.rows % d == 0
            if d > 1:
                sub = meshexec.sub_class(cls, d)
                assert sub.n_slots * d == cls.n_slots
                # shard boundary = a whole number of class lengths →
                # page- and granule-aligned
                assert sub.n_slots % cls.length == 0
                assert sub.n_slots % GRANULE == 0


def test_paged_shard_alignment_property():
    """paged_dp only offers widths whose shard blocks are whole page
    runs large enough for the largest admissible segment, and the
    shard-constrained pool never places a run across a block."""
    from kindel_tpu.paged import PAGE_SLOTS, PagePool

    for cls in CLASSES:
        for dp in (2, 4, 8):
            d = meshexec.paged_dp(cls, PAGE_SLOTS, dp)
            n_pages = cls.n_slots // PAGE_SLOTS
            assert d >= 1 and n_pages % max(d, 1) == 0
            if d > 1:
                pps = n_pages // d
                assert pps * PAGE_SLOTS >= cls.length
    pool = PagePool(CLASSES[0], clock=time.monotonic)
    pool.shard_pages = 4
    # pages 0-2 used, 3 free: a 2-page run may NOT start at page 3
    # (it would cross the block boundary at page 4) — it lands at 4
    pool._used[:3] = True
    assert pool._find_run(2) == 4
    assert pool._find_run(1) == 3  # a 1-page run still fits the tail


# ------------------------------------------------------ byte identity


def _serve_all(sams, mode, mesh, **opt_kwargs):
    results = [None] * len(sams)
    errors: list = []
    with ConsensusService(
        tuning=TuningConfig(batch_mode=mode, mesh=mesh),
        max_wait_s=0.1, decode_workers=4, **opt_kwargs,
    ) as svc:
        client = ConsensusClient(svc)

        def one(i):
            try:
                results[i] = client.fasta(str(sams[i]), timeout=300)
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [
            threading.Thread(target=one, args=(i,))
            for i in range(len(sams))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    return results


def test_byte_identity_matrix_tier_by_dp(tmp_path):
    """The acceptance bar: dp∈{1,2,4,8} on the forced 8-device mesh
    produces identical FASTA through lanes, ragged, and paged modes."""
    sams = _mixed_sams(tmp_path, 6, seed_base=77)
    base = _serve_all(sams, "lanes", 1)
    for mode in ("lanes", "ragged", "paged"):
        for dp in (2, 4, 8):
            got = _serve_all(sams, mode, dp)
            assert got == base, (mode, dp)


def test_byte_identity_realign_and_emit_modes(tmp_path):
    """Realign traffic and both emit modes ride the mesh byte-
    identically (the realign CDR walk reads dp-sharded dense tensors
    through the owning-shard fetch; device emission extracts per-shard
    ASCII planes)."""
    sams = _mixed_sams(tmp_path, 5, seed_base=13)
    base_r = _serve_all(sams, "lanes", 1, realign=True)
    base_e = _serve_all(sams, "lanes", 1, emit_mode="device")
    for mode in ("lanes", "ragged", "paged"):
        assert _serve_all(sams, mode, 4, realign=True) == base_r, mode
        assert _serve_all(sams, mode, 4, emit_mode="device") == base_e, mode


# --------------------------------------------- paged residency churn


def test_sharded_paged_admit_retire_churn_parity(tmp_path):
    """Admit/retire churn over a mesh-resident pool (in-place patches
    on the [dp, block] donated arrays) stays byte-identical to the
    single-device oracle across launches."""
    from kindel_tpu.paged import PagedBatcher
    from kindel_tpu.paged.retire import _InlineMap
    from kindel_tpu.workloads import bam_to_consensus

    plan = meshexec.plan(4)
    b = PagedBatcher(CLASSES[:1], mesh_plan=plan, max_wait_s=0.01)
    opts = BatchOptions()
    lane = b._lane_for(("k",), CLASSES[0], opts)
    res = lane.pool.residency
    assert res is not None and res.mesh_dp == 4
    assert lane.pool.shard_pages == res.pages_per_shard

    def admit(i):
        sam = make_sam(tmp_path / f"u{i}.sam", ref=f"r{i}",
                       L=380 + 83 * i, n_reads=12, seed=i)
        (u,) = _decode(str(sam))
        seg = lane.pool.admit_unit(u, rpack.consumption([u]))
        assert seg is not None
        return seg, u, sam

    def check(trips):
        u2, stables, row_of = res.table(lane.pool)
        out = res.launch(opts)
        pairs = [(row_of[s.seg_id], u) for s, u, _p in trips]
        outs = meshexec.unpack_sharded_rows(
            out, stables, pairs, opts, _InlineMap()
        )
        for (_s, u, sam), r in zip(trips, outs):
            want = bam_to_consensus(str(sam), backend="numpy")
            seq = (
                want.consensuses[0].sequence
                if hasattr(want, "consensuses") else want[0][0].sequence
            )
            assert r[0].sequence == seq, u.ref_id

    trips = [admit(i) for i in range(5)]
    assert res.active
    check(trips)
    # churn: retire two, admit three more, launch again
    for seg, _u, _p in trips[:2]:
        seg.panel = None
        lane.pool.release(seg)
    trips = trips[2:] + [admit(i) for i in range(5, 8)]
    assert res.active
    check(trips)


# ------------------------------------------------- zero-compile pin


def test_zero_compile_warm_mesh(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "KINDEL_TPU_TUNE_CACHE", str(tmp_path / "tune.json")
    )
    _zero_compile_warm_mesh(tmp_path)


def _zero_compile_warm_mesh(tmp_path, spec=4):
    """Changing traffic on a warm mesh compiles nothing: after warmup
    of the synthetic lane + the page classes under an active plan,
    unseen requests that land in warmed lane shapes / page classes add
    zero jit-cache entries — cohort and sharded ragged alike."""
    from kindel_tpu.batch import (
        cohort_pad_shapes,
        launch_cohort_kernel,
        pack_cohort,
    )
    from kindel_tpu.pileup_jax import _bucket
    from kindel_tpu.serve import warmup

    plan = meshexec.plan(spec)
    opts = BatchOptions()
    warmup.warm_shapes(opts, mesh_plan=plan)
    warmup.warm_ragged(opts, CLASSES[:1], mesh_plan=plan)

    before = obs_runtime.jit_cache_entries()
    # cohort traffic landing in the warmed synthetic lane shapes
    synth = warmup.decode_payload(warmup._SYNTH_SAM, opts)
    shapes = cohort_pad_shapes(synth, opts)
    sam = make_sam(tmp_path / "w.sam", ref="w", L=333, n_reads=2, seed=9)
    units = _decode(str(sam))
    n_rows = plan.pad_rows(_bucket(len(units), 8))
    sharding, dp = plan.row_sharding_for(n_rows)
    arrays, meta = pack_cohort(units, opts, n_rows=n_rows, shapes=shapes)
    out, _ = launch_cohort_kernel(arrays, meta, opts, sharding=sharding,
                                  mesh_dp=dp)
    np.asarray(out)
    # sharded ragged traffic of a different unit mix, same class
    mix = []
    for i in range(4):
        s = make_sam(tmp_path / f"w{i}.sam", ref=f"w{i}",
                     L=200 + 50 * i, n_reads=6, seed=40 + i)
        mix.extend(_decode(str(s)))
    ssb = meshexec.shard_superbatch(mix, CLASSES[0], plan)
    assert ssb is not None and ssb.dp == 4
    np.asarray(meshexec.launch_sharded_superbatch(ssb, opts))
    assert obs_runtime.jit_cache_entries() == before, (
        "warm mesh compiled on unseen traffic"
    )


# ------------------------------------------------- sharded CDR fetch


def test_sharded_cdr_fetch_window_parity_and_budget(tmp_path):
    """The owning-shard window fetch returns exactly what the full
    download holds, and a burst of window fetches against a dp-sharded
    dense tensor stays far from the minutes the resharding jit path
    cost (generous wall bound — the fix is orders of magnitude under
    it)."""
    optsr = BatchOptions(realign=True)
    units = []
    for i in range(8):
        s = make_sam(tmp_path / f"c{i}.sam", ref=f"c{i}", L=900,
                     n_reads=30, seed=i)
        units.extend(_decode(str(s), realign=True))
    with tune.env_pin("KINDEL_TPU_MESH", "4"):
        out, meta = _dispatch_device_call(units, optsr)
    wire, *dense = out
    np.asarray(wire)
    assert len(getattr(dense[0], "sharding").device_set) > 1
    f = _RowCdrFetcher(dense, 3, 900)
    t0 = time.perf_counter()
    for _ in range(40):
        win = f._fetch("weights", 0)
    wall = time.perf_counter() - t0
    assert np.array_equal(win, np.asarray(dense[0])[3][: f._chunk])
    assert wall < 5.0, f"sharded CDR window fetches took {wall:.1f}s"


# ----------------------------------------------------- flagship fleet


def test_flagship_fleet_chaos_on_mesh_sha_identical(tmp_path):
    """Mixed-shape traffic through a 3-replica supervised fleet on an
    active mesh (dp=2) under injected flush faults with a replica kill
    and a drain mid-load: every request settles exactly once and the
    FASTA is identical to a single-device lanes run."""
    from kindel_tpu.fleet import FleetService
    from kindel_tpu.io.fasta import format_fasta

    sams = _mixed_sams(tmp_path, 8, seed_base=53)
    want = _serve_all(sams, "lanes", 1)
    plan_ = rfaults.activate(
        FaultPlan.parse("seed=9,serve.flush:error:times=2:after=1")
    )
    results = [None] * len(sams)
    errors: list = []
    try:
        svc = FleetService(
            replicas=3, probe_interval_s=0.02, max_wait_s=0.05,
            decode_workers=4,
            tuning=TuningConfig(batch_mode="ragged", mesh=2),
        ).start()
        try:
            barrier = threading.Barrier(len(sams) + 1)

            def one(i):
                barrier.wait()
                try:
                    res = svc.request(str(sams[i]), timeout=300)
                    results[i] = format_fasta(res.consensuses)
                except Exception as e:  # noqa: BLE001
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=one, args=(i,))
                for i in range(len(sams))
            ]
            for t in threads:
                t.start()
            barrier.wait()
            time.sleep(0.15)
            svc.kill_replica("r1")
            time.sleep(0.25)
            svc.drain("r2")
            for t in threads:
                t.join()
        finally:
            svc.stop()
    finally:
        rfaults.deactivate()
    assert not errors, errors
    assert results == want, "mesh fleet FASTA diverged from lanes"
    assert plan_.fired == {("serve.flush", "error"): 2}


# --------------------------------------------------------- misc bits


def test_shard_superbatch_falls_back_cleanly():
    """A flush that cannot shard (single unit) returns None — the
    caller launches the classic single-device superbatch."""
    plan = meshexec.plan(8)
    synth_units = _decode_synth()
    assert meshexec.shard_superbatch(synth_units[:1], CLASSES[0], plan) \
        is None


def _decode_synth():
    from kindel_tpu.serve.warmup import _SYNTH_SAM

    return _decode(bytes(_SYNTH_SAM))


def test_fetch_window_flat_stitches_across_shards():
    """A flat window that straddles a shard boundary stitches from
    both owning shards, byte-for-byte equal to the full download."""
    arr = np.arange(4096, dtype=np.int32)
    # place_stacked shards axis 0: a flat [4096] array splits into 4
    # contiguous 1024-element shard blocks
    flat = meshexec.place_stacked(4, [arr])[0]
    win = meshexec.fetch_window_flat(
        flat, 1000, 128, lambda: pytest.fail("fallback taken")
    )
    assert np.array_equal(win, arr[1000:1128])


# ----------------------------------------------------------- pod tier


def test_pod_mesh_spec_resolution(monkeypatch, tmp_path):
    """The `--mesh` grammar grew the pod forms: '<dp>' | 'pod' |
    'pod:<dp>', the pod flag surviving every resolution source
    (explicit > env > host-keyed store), a malformed explicit spec
    raising, and the width-only `resolve_mesh_dp` view staying exactly
    what the legacy callers pinned."""
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(tmp_path / "t.json"))
    monkeypatch.delenv("KINDEL_TPU_MESH", raising=False)
    assert tune.parse_mesh_spec(4) == (4, False)
    assert tune.parse_mesh_spec("4") == (4, False)
    assert tune.parse_mesh_spec("pod") == (None, True)
    assert tune.parse_mesh_spec("POD:2") == (2, True)
    assert tune.parse_mesh_spec("pod:x") is None
    assert tune.parse_mesh_spec(True) is None

    spec = tune.resolve_mesh_spec("pod:4")
    assert (spec.dp, spec.pod, spec.source) == (4, True, "explicit")
    with pytest.raises(ValueError, match="malformed mesh spec"):
        tune.resolve_mesh_spec("pod:")
    monkeypatch.setenv("KINDEL_TPU_MESH", "pod:2")
    spec = tune.resolve_mesh_spec()
    assert (spec.dp, spec.pod, spec.source) == (2, True, "env")
    assert tune.resolve_mesh_dp() == (2, "env")
    monkeypatch.delenv("KINDEL_TPU_MESH")
    tune.record(tune.mesh_store_key(), {"mesh_dp": 2, "mesh_pod": True})
    spec = tune.resolve_mesh_spec()
    assert (spec.dp, spec.pod, spec.source) == (2, True, "cache")
    # outside a cluster env the pod plan degrades to the local tier —
    # same width, one process, byte-identity intact
    p = meshexec.plan("pod:2")
    assert (p.dp, p.procs, p.pod) == (2, 1, False)


def test_pod_matrix_in_process(tmp_path, monkeypatch):
    """procs=1 half of the pod byte-identity matrix: the degraded
    single-process pod:<dp> plans at dp ∈ {2, 4} produce FASTA digests
    identical to the dp=1 oracle across all three dispatch tiers, and
    the realign leg (whose CDR patch fires on the clip-flanked-gap
    sample) matches the realign oracle — so the pod spec never changes
    bytes, only placement."""
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(tmp_path / "t.json"))
    with tune.env_pin("KINDEL_TPU_MESH", "1"):
        base = podfixture.all_digests(tmp_path / "base", meshexec.plan())
        base_re = podfixture.all_digests(
            tmp_path / "base_re", meshexec.plan(), realign=True
        )
    assert base != base_re, "realign changed nothing — fixture is inert"
    for dp in (2, 4):
        with tune.env_pin("KINDEL_TPU_MESH", f"pod:{dp}"):
            got = podfixture.all_digests(
                tmp_path / f"p{dp}", meshexec.plan()
            )
        assert got == base, f"pod:{dp} diverged from the dp=1 oracle"
    with tune.env_pin("KINDEL_TPU_MESH", "pod:4"):
        got = podfixture.all_digests(
            tmp_path / "p4r", meshexec.plan(), realign=True
        )
    assert got == base_re, "pod:4 realign diverged from the oracle"


@pytest.mark.parametrize("dp", [2, 4])
def test_pod_two_process_byte_identity(tmp_path, dp):
    """procs=2 half of the matrix: an actual two-process JAX group
    (localhost coordinator, 4 virtual CPU devices each, brought up by
    the plan builder purely from `KINDEL_TPU_MESH=pod:<dp>` + the
    cluster env) runs all three dispatch tiers over process-spanning
    NamedShardings — both workers' FASTA digests equal each other and
    the in-process single-device oracle, realign included at dp=4."""
    from pathlib import Path

    import distfixture

    worker = Path(__file__).parent / "_dist_pod_worker.py"
    with tune.env_pin("KINDEL_TPU_MESH", "1"):
        base = podfixture.all_digests(tmp_path / "base", meshexec.plan())

    def pod_digests(extra):
        outs = distfixture.run_two_process(worker, extra_argv=extra)
        got = []
        for rc, out, err in outs:
            assert rc == 0, (out[-2000:], err[-2000:])
            assert f"PODPLAN:dp={dp},procs=2" in out
            got.append(dict(
                line.split("DIGEST:", 1)[1].split("=", 1)
                for line in out.splitlines()
                if line.startswith("DIGEST:")
            ))
        assert got[0] == got[1], "pod workers disagree"
        return got[0]

    assert pod_digests((dp, str(tmp_path))) == base, (
        f"pod dp={dp} procs=2 diverged from the dp=1 oracle"
    )
    if dp == 4:
        with tune.env_pin("KINDEL_TPU_MESH", "1"):
            base_re = podfixture.all_digests(
                tmp_path / "base_re", meshexec.plan(), realign=True
            )
        assert pod_digests((dp, str(tmp_path / "re"), "realign")) \
            == base_re, "pod realign diverged from the realign oracle"


def test_zero_compile_warm_pod_mesh(tmp_path, monkeypatch):
    """The warm-mesh zero-compile pin holds under a pod spec: a
    pod:4 plan (degraded to one process here — the pod keying of
    warmup and the AOT digests is what's under test) warms the lane
    shapes and page classes, then unseen traffic adds zero jit-cache
    entries."""
    monkeypatch.setenv(
        "KINDEL_TPU_TUNE_CACHE", str(tmp_path / "tune.json")
    )
    _zero_compile_warm_mesh(tmp_path, spec="pod:4")