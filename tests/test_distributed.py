"""Multi-host topology layer (kindel_tpu.parallel.distributed) — exercised
single-process on the virtual 8-device CPU mesh, the same no-cluster
degradation every laptop/driver run takes."""

import numpy as np

import jax

from kindel_tpu.parallel import (
    batched_sharded_call,
    initialize_distributed,
    make_global_mesh,
)


def test_initialize_distributed_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_distributed() is False
    assert jax.process_count() == 1


def test_make_global_mesh_single_host_layout():
    mesh = make_global_mesh({"dp": 2, "sp": 4})
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("dp", "sp")
    # degenerate args behave like make_mesh
    assert make_global_mesh().devices.shape == (len(jax.devices()),)


def test_make_global_mesh_rejects_bad_multihost_tiling(monkeypatch):
    """Multi-host with a factorization that can't tile the hosts must
    raise — a silent local-only mesh would shard the cohort wrongly."""
    import pytest

    from kindel_tpu.parallel import distributed as d

    monkeypatch.setattr(d.jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        d.jax, "local_devices", lambda: jax.devices()[:4]
    )
    with pytest.raises(ValueError, match="do not tile"):
        d.make_global_mesh({"dp": 2, "sp": 2})  # 1*2 != 4 devices/host
    with pytest.raises(ValueError, match="do not tile"):
        d.make_global_mesh({"dp": 3, "sp": 4})  # 3 % 2 != 0


def test_global_mesh_runs_batched_step():
    mesh = make_global_mesh({"dp": 2, "sp": 4})
    rng = np.random.default_rng(0)
    ref_len = 512
    samples = []
    for _ in range(2):
        pos = rng.integers(0, ref_len, size=64)
        samples.append(
            {
                "match_pos": pos.astype(np.int64),
                "match_base": rng.integers(0, 4, size=64).astype(np.int64),
                "del_pos": np.asarray([3], np.int64),
                "ins_pos": np.asarray([5], np.int64),
                "ins_cnt": np.asarray([1], np.int64),
            }
        )
    w, bc, dm, nm, im = batched_sharded_call(samples, ref_len, mesh)
    assert w.shape == (2, ref_len, 5)
    assert int(w.sum()) == 2 * 64


def test_initialize_distributed_rejects_partial_config(monkeypatch):
    """Round-1 advisor finding: coordinator set but num_processes/
    process_id unset must raise a named error before touching
    jax.distributed.initialize."""
    import pytest

    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="partially-specified"):
        initialize_distributed(coordinator_address="127.0.0.1:9999")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:9999")
    with pytest.raises(ValueError, match="num_processes"):
        initialize_distributed()


def test_two_process_group_matches_single_process():
    """VERDICT r2 item 4: an actual 2-process JAX group (localhost
    coordinator, 4 virtual CPU devices each) builds the hybrid dp×sp
    mesh, runs the batched dp×sp step, and produces exactly the
    single-process result."""
    from pathlib import Path

    import distfixture

    # single-process reference on this process's 8-device mesh
    mesh = make_global_mesh(dict(distfixture.AXES))
    expected = distfixture.digest(
        batched_sharded_call(
            distfixture.make_samples(), distfixture.REF_LEN, mesh
        )
    )

    worker = Path(__file__).parent / "_dist_worker.py"
    outs = distfixture.run_two_process(worker)
    digests = [
        line.split("DIGEST:", 1)[1]
        for _rc, out, _err in outs
        for line in out.splitlines()
        if line.startswith("DIGEST:")
    ]
    assert len(digests) == 2, outs
    assert digests[0] == digests[1] == expected


def test_initialize_distributed_rejects_orphan_process_id(monkeypatch):
    """process_id alone (the other two unset) must raise, not silently
    run single-process on every worker."""
    import pytest

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    with pytest.raises(ValueError, match="coordinator_address"):
        initialize_distributed()


def test_two_process_product_path_matches_single_process():
    """VERDICT r4 item 4: the flagship product path (ppermute halo, lazy
    CDR window fetches) across a REAL 2-process group with the sp axis
    SPANNING the process boundary must equal the single-process result
    byte-for-byte. The halo crossing a non-addressable-device edge is
    exactly where a wrong out_spec would hide."""
    import os
    from pathlib import Path

    import distfixture

    from kindel_tpu.events import extract_events
    from kindel_tpu.io.sam import parse_sam_bytes
    from kindel_tpu.parallel import make_mesh
    from kindel_tpu.parallel.product import sharded_consensus

    # single-process oracle on this process's 8-device sp mesh
    ev = extract_events(parse_sam_bytes(distfixture.product_sam()))
    rid = ev.present_ref_ids[0]
    res, dmin, dmax, cdr = sharded_consensus(
        ev, rid, mesh=make_mesh({"sp": 8}), realign=True, min_overlap=7,
    )
    expected = distfixture.product_digest(res, dmin, dmax, cdr)
    # non-vacuity: realign actually produced patches on this layout
    assert cdr, "fixture produced no CDR patches; the lazy-fetch path is untested"

    worker = Path(__file__).parent / "_dist_product_worker.py"
    outs = distfixture.run_two_process(worker)
    digests = set()
    for _rc, out, _err in outs:
        lines = [l for l in out.splitlines() if l.startswith("DIGEST:")]
        assert lines, out
        digests.add(lines[-1][len("DIGEST:"):])
    assert digests == {expected}, (digests, expected)


def test_two_process_streamed_sharded_matches_single_process():
    """VERDICT r4 item 3: stream_product's chunked reduce-then-close —
    per-chunk shard-local scatters into globally-sharded state, then the
    product-kernel close — across a REAL 2-process group with sp spanning
    the process boundary, byte-identical to the single-process result.
    The per-chunk bucketing is exactly where a process-local vs global
    shard-index mistake would hide (each process must scatter into its
    OWN 4 shards of the global 8-way state)."""
    import os
    import tempfile
    from pathlib import Path

    import distfixture

    from kindel_tpu.io.stream import stream_alignment
    from kindel_tpu.parallel import make_mesh
    from kindel_tpu.parallel.product import close_sharded_ref
    from kindel_tpu.parallel.stream_product import ShardedStreamAccumulator

    # single-process oracle: same chunked accumulation on the 8-device mesh
    with tempfile.NamedTemporaryFile(suffix=".sam", delete=False) as fh:
        fh.write(distfixture.product_sam())
        sam_path = fh.name
    try:
        acc = ShardedStreamAccumulator(mesh=make_mesh({"sp": 8}), full=True)
        n_chunks = 0
        for batch in stream_alignment(
            sam_path, distfixture.STREAM_CHUNK_BYTES
        ):
            acc.add_batch(batch)
            n_chunks += 1
        assert n_chunks >= 2, "fixture must stream in several chunks"
        rid = next(iter(acc.present))
        sr = acc.finish(rid, realign=True)
        res, dmin, dmax, cdr = close_sharded_ref(
            sr, realign=True, min_depth=1, min_overlap=7,
            clip_decay_threshold=0.1, mask_ends=50, trim_ends=False,
            uppercase=False,
        )
        assert cdr, "fixture produced no CDR patches"
        expected = distfixture.product_digest(res, dmin, dmax, cdr)
    finally:
        os.unlink(sam_path)

    worker = Path(__file__).parent / "_dist_stream_worker.py"
    outs = distfixture.run_two_process(worker)
    digests = set()
    for _rc, out, _err in outs:
        assert any(l.startswith("CHUNKS:") for l in out.splitlines()), out
        lines = [l for l in out.splitlines() if l.startswith("DIGEST:")]
        assert lines, out
        digests.add(lines[-1][len("DIGEST:"):])
    assert digests == {expected}, (digests, expected)
