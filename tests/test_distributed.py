"""Multi-host topology layer (kindel_tpu.parallel.distributed) — exercised
single-process on the virtual 8-device CPU mesh, the same no-cluster
degradation every laptop/driver run takes."""

import numpy as np

import jax

from kindel_tpu.parallel import (
    batched_sharded_call,
    initialize_distributed,
    make_global_mesh,
)


def test_initialize_distributed_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_distributed() is False
    assert jax.process_count() == 1


def test_make_global_mesh_single_host_layout():
    mesh = make_global_mesh({"dp": 2, "sp": 4})
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("dp", "sp")
    # degenerate args behave like make_mesh
    assert make_global_mesh().devices.shape == (len(jax.devices()),)


def test_make_global_mesh_rejects_bad_multihost_tiling(monkeypatch):
    """Multi-host with a factorization that can't tile the hosts must
    raise — a silent local-only mesh would shard the cohort wrongly."""
    import pytest

    from kindel_tpu.parallel import distributed as d

    monkeypatch.setattr(d.jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        d.jax, "local_devices", lambda: jax.devices()[:4]
    )
    with pytest.raises(ValueError, match="do not tile"):
        d.make_global_mesh({"dp": 2, "sp": 2})  # 1*2 != 4 devices/host
    with pytest.raises(ValueError, match="do not tile"):
        d.make_global_mesh({"dp": 3, "sp": 4})  # 3 % 2 != 0


def test_global_mesh_runs_batched_step():
    mesh = make_global_mesh({"dp": 2, "sp": 4})
    rng = np.random.default_rng(0)
    ref_len = 512
    samples = []
    for _ in range(2):
        pos = rng.integers(0, ref_len, size=64)
        samples.append(
            {
                "match_pos": pos.astype(np.int64),
                "match_base": rng.integers(0, 4, size=64).astype(np.int64),
                "del_pos": np.asarray([3], np.int64),
                "ins_pos": np.asarray([5], np.int64),
                "ins_cnt": np.asarray([1], np.int64),
            }
        )
    w, bc, dm, nm, im = batched_sharded_call(samples, ref_len, mesh)
    assert w.shape == (2, ref_len, 5)
    assert int(w.sum()) == 2 * 64


def test_initialize_distributed_rejects_partial_config(monkeypatch):
    """Round-1 advisor finding: coordinator set but num_processes/
    process_id unset must raise a named error before touching
    jax.distributed.initialize."""
    import pytest

    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="partially-specified"):
        initialize_distributed(coordinator_address="127.0.0.1:9999")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:9999")
    with pytest.raises(ValueError, match="num_processes"):
        initialize_distributed()


def test_two_process_group_matches_single_process():
    """VERDICT r2 item 4: an actual 2-process JAX group (localhost
    coordinator, 4 virtual CPU devices each) builds the hybrid dp×sp
    mesh, runs the batched dp×sp step, and produces exactly the
    single-process result."""
    import socket
    import subprocess
    import sys
    from pathlib import Path

    import distfixture

    # single-process reference on this process's 8-device mesh
    mesh = make_global_mesh(dict(distfixture.AXES))
    expected = distfixture.digest(
        batched_sharded_call(
            distfixture.make_samples(), distfixture.REF_LEN, mesh
        )
    )

    worker = Path(__file__).parent / "_dist_worker.py"
    import os

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def run_pair():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(i), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env,
            )
            for i in range(2)
        ]
        try:
            return procs, [p.communicate(timeout=300) for p in procs]
        finally:
            for p in procs:  # never leak a worker blocked in initialize()
                if p.poll() is None:
                    p.kill()
                    p.wait()

    # the bind-then-close port reservation can race another process; a
    # coordinator bind failure gets a fresh port, real failures don't
    for attempt in range(3):
        procs, outs = run_pair()
        if all(p.returncode == 0 for p in procs):
            break
        bind_race = any(
            "bind" in err.lower() or "address already in use" in err.lower()
            for _, err in outs
        )
        if not bind_race:
            break
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
    digests = [
        line.split("DIGEST:", 1)[1]
        for out, _ in outs
        for line in out.splitlines()
        if line.startswith("DIGEST:")
    ]
    assert len(digests) == 2, outs
    assert digests[0] == digests[1] == expected


def test_initialize_distributed_rejects_orphan_process_id(monkeypatch):
    """process_id alone (the other two unset) must raise, not silently
    run single-process on every worker."""
    import pytest

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    with pytest.raises(ValueError, match="coordinator_address"):
        initialize_distributed()


def test_two_process_product_path_matches_single_process():
    """VERDICT r4 item 4: the flagship product path (ppermute halo, lazy
    CDR window fetches) across a REAL 2-process group with the sp axis
    SPANNING the process boundary must equal the single-process result
    byte-for-byte. The halo crossing a non-addressable-device edge is
    exactly where a wrong out_spec would hide."""
    import os
    import socket
    import subprocess
    import sys
    from pathlib import Path

    import distfixture

    from kindel_tpu.events import extract_events
    from kindel_tpu.io.sam import parse_sam_bytes
    from kindel_tpu.parallel import make_mesh
    from kindel_tpu.parallel.product import sharded_consensus

    # single-process oracle on this process's 8-device sp mesh
    ev = extract_events(parse_sam_bytes(distfixture.product_sam()))
    rid = ev.present_ref_ids[0]
    res, dmin, dmax, cdr = sharded_consensus(
        ev, rid, mesh=make_mesh({"sp": 8}), realign=True, min_overlap=7,
    )
    expected = distfixture.product_digest(res, dmin, dmax, cdr)
    # non-vacuity: realign actually produced patches on this layout
    assert cdr, "fixture produced no CDR patches; the lazy-fetch path is untested"

    worker = Path(__file__).parent / "_dist_product_worker.py"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def run_pair():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(i), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env,
            )
            for i in range(2)
        ]
        try:
            return procs, [p.communicate(timeout=300) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()

    for attempt in range(3):
        procs, outs = run_pair()
        if all(p.returncode == 0 for p in procs):
            break
        bind_race = any(
            "bind" in err.lower() or "address already in use" in err.lower()
            for _, err in outs
        )
        assert bind_race and attempt < 2, (
            f"worker rc={[p.returncode for p in procs]}; "
            f"stderr[0] tail: {outs[0][1][-1500:]}\n"
            f"stderr[1] tail: {outs[1][1][-1500:]}"
        )

    digests = set()
    for out, _err in outs:
        lines = [l for l in out.splitlines() if l.startswith("DIGEST:")]
        assert lines, out
        digests.add(lines[-1][len("DIGEST:"):])
    assert digests == {expected}, (digests, expected)
