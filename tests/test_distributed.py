"""Multi-host topology layer (kindel_tpu.parallel.distributed) — exercised
single-process on the virtual 8-device CPU mesh, the same no-cluster
degradation every laptop/driver run takes."""

import numpy as np

import jax

from kindel_tpu.parallel import (
    batched_sharded_call,
    initialize_distributed,
    make_global_mesh,
)


def test_initialize_distributed_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_distributed() is False
    assert jax.process_count() == 1


def test_make_global_mesh_single_host_layout():
    mesh = make_global_mesh({"dp": 2, "sp": 4})
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("dp", "sp")
    # degenerate args behave like make_mesh
    assert make_global_mesh().devices.shape == (len(jax.devices()),)


def test_make_global_mesh_rejects_bad_multihost_tiling(monkeypatch):
    """Multi-host with a factorization that can't tile the hosts must
    raise — a silent local-only mesh would shard the cohort wrongly."""
    import pytest

    from kindel_tpu.parallel import distributed as d

    monkeypatch.setattr(d.jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        d.jax, "local_devices", lambda: jax.devices()[:4]
    )
    with pytest.raises(ValueError, match="do not tile"):
        d.make_global_mesh({"dp": 2, "sp": 2})  # 1*2 != 4 devices/host
    with pytest.raises(ValueError, match="do not tile"):
        d.make_global_mesh({"dp": 3, "sp": 4})  # 3 % 2 != 0


def test_global_mesh_runs_batched_step():
    mesh = make_global_mesh({"dp": 2, "sp": 4})
    rng = np.random.default_rng(0)
    ref_len = 512
    samples = []
    for _ in range(2):
        pos = rng.integers(0, ref_len, size=64)
        samples.append(
            {
                "match_pos": pos.astype(np.int64),
                "match_base": rng.integers(0, 4, size=64).astype(np.int64),
                "del_pos": np.asarray([3], np.int64),
                "ins_pos": np.asarray([5], np.int64),
                "ins_cnt": np.asarray([1], np.int64),
            }
        )
    w, bc, dm, nm, im = batched_sharded_call(samples, ref_len, mesh)
    assert w.shape == (2, ref_len, 5)
    assert int(w.sum()) == 2 * 64
