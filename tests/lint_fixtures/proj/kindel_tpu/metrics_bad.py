"""Known-bad: registration without help text (metric-help-text), and a
helped metric that no docs table mentions (metric-doc)."""


def register(registry):
    helpless = registry.counter("kindel_fixture_helpless_total")
    documented_nowhere = registry.counter(
        "kindel_fixture_total", "fires the metric-doc conformance rule"
    )
    return helpless, documented_nowhere
