"""Known-bad: an inner future leaks on a connect-refused path
(future-settlement, fleet scope) — the submit created the waiter, the
dial failed, and no path settles, hands back, or re-raises: the
router's ticket would block forever on a replica that was never
reachable."""

from concurrent.futures import Future


def submit_over_wire(dial, body):
    fut = Future()
    try:
        conn = dial()
    except ConnectionRefusedError:
        return None  # refused: waiter stranded, nothing settled
    conn.send(body, fut)
    return fut
