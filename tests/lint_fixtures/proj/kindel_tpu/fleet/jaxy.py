"""Known-bad: the fleet tier touching the device (fleet-jax-free)."""

import jax


def peek_devices():
    return jax.devices()
