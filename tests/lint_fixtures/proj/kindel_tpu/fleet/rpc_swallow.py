"""Known-bad: a swallowed transport error in the fleet RPC tier
(silent-swallow, fleet scope) — a wire failure that neither re-raises,
settles a future, nor records anything is exactly how an admitted
request vanishes once replicas live on other hosts."""


def call_and_shrug(transport, body):
    try:
        return transport.call("POST", "/v1/consensus", body)
    except Exception:
        return None  # response lost, caller never told, nothing counted
