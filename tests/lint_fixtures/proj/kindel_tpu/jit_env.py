"""Known-bad: env read inside a jit-traced body (jit-env-read)."""

import os

import jax


@jax.jit
def bad_kernel(x):
    slabs = int(os.environ.get("KINDEL_TPU_SLABS", "4"))
    return x * slabs
