"""Known-bad fixture: raw version-sensitive jax multi-host spellings
outside compat.py (jax-compat-confinement) — the exact AttributeError
class that broke the seed's 9 shard_map tests on the jax pin."""

import jax
from jax.experimental.shard_map import shard_map as raw_shard_map  # BAD


def bad_mapped(mesh, spec, fn):
    # BAD: jax.shard_map attribute access outside compat.py
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec,), out_specs=spec)


def bad_probe() -> bool:
    # BAD: jax.distributed attribute access outside compat.py
    return jax.distributed.is_initialized()


def bad_raw_call(mesh, spec, fn):
    return raw_shard_map(fn, mesh, in_specs=(spec,), out_specs=spec)
