"""Known-bad: a created Future leaks on a sharded-launch failure path
(future-settlement, parallel scope — PR 14) — the handler logs the
shard failure but forgets the waiter."""

from concurrent.futures import Future


def sharded_launch_leaky(launch, log):
    fut = Future()
    try:
        fut.set_result(launch())
    except Exception:
        log("shard launch failed")  # waiter stranded forever
    return None
