"""Known-bad: silent swallow in parallel/ — proves the rule's scope
extension beyond the original serve/resilience/fleet set."""


def shard_and_forget(mesh, fn):
    try:
        return fn(mesh)
    except Exception:
        return None
