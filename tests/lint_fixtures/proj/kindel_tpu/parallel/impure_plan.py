"""Known-bad: a mesh kernel whose helper reads env one call deep
(trace-purity, parallel scope — PR 14): the mesh width must resolve at
plan-build time (kindel_tpu.tune / meshexec.plan), never inside a
traced body — a traced read bakes one width into the compiled program
and the knob silently stops responding."""

import os
from functools import partial

import jax


def _mesh_width():
    return int(os.environ.get("KINDEL_TPU_MESH", "1"))


@partial(jax.jit, static_argnames=())
def bad_mesh_kernel(state):
    return state[:: _mesh_width()]
