"""Known-bad: inflate outside the io/ chokepoint (zlib-confinement)."""

import zlib


def sneak_inflate(blob: bytes) -> bytes:
    return zlib.decompress(blob)
