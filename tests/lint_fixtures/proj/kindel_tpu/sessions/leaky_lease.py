"""Known-bad twice over (sessions scope, PR 16): an append ack future
leaks on the snapshot failure path (future-settlement — the client
blocks on the ack forever while the reaper sees the session as live),
and the lease's guarded depth counter is read outside its lock
(lock-guarded-by — the emission-gate decision races the merge)."""

import threading
from concurrent.futures import Future


def append_leaky(merge, payload):
    ack = Future()
    try:
        ack.set_result(merge(payload))
    except Exception:
        pass  # merged nothing, told nobody — ack stranded forever
    return None


class RacyLease:
    def __init__(self):
        self._lock = threading.Lock()
        self._depth = 0

    def admit(self, events):
        with self._lock:
            self._depth += events

    def gate_due(self, emit_delta):
        return self._depth >= emit_delta
