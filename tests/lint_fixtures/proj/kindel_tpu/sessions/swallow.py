"""Known-bad: silent swallow in sessions/ — a streaming lease holds
append acks and SSE subscribers across minutes, so a swallowed snapshot
failure strands a client mid-stream with no typed error and no final
emit (the ack future must be settled or the failure recorded)."""


def snapshot_or_shrug(lease, dispatch):
    try:
        return dispatch(lease.snapshot_units())
    except Exception:
        return None
