"""Known-bad: impurity two calls deep from a jit entry (trace-purity).
The entry body itself is clean — only the closure sees the hazard."""

import os

import jax


def _resolve_knob_chain():
    return _read_ambient_state()


def _read_ambient_state():
    return os.environ.get("KINDEL_TPU_SLABS")


@jax.jit
def chained_kernel(x):
    scale = _resolve_knob_chain()
    return x * (1 if scale is None else int(scale))
