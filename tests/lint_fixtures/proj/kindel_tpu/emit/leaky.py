"""Known-bad: a created Future leaks on the emission decode's failure
path (future-settlement, emit scope)."""

from concurrent.futures import Future


def emit_leaky(decode, plane):
    fut = Future()
    try:
        fut.set_result(decode(plane))
    except Exception:
        pass  # waiter stranded forever
    return None
