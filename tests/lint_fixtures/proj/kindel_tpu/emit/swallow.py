"""Known-bad: silent swallow in emit/ — the emission decode sits on the
admitted-request settle path; a swallowed decode failure strands the
request's future exactly like a swallowed wire-decode failure."""


def decode_or_forget(decode, plane):
    try:
        return decode(plane)
    except Exception:
        return None
