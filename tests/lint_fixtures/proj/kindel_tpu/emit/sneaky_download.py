"""Known-bad: an undeclared device→host download (download-confinement)
— a jax-importing module materializing a kernel result outside the
declared download sites under-reports transfer bytes and hides a
tunneled round trip."""

import jax
import numpy as np


def undeclared_fetch(kernel, buf):
    out = kernel(buf)
    return np.asarray(out)  # downloads outside every declared site


def undeclared_block(kernel, buf):
    return kernel(buf).block_until_ready()


def undeclared_get(out):
    return jax.device_get(out)
