"""Known-bad: wall clock used for a duration (time-time-duration)."""

import time


def measure(fn):
    t0 = time.time()
    fn()
    return time.time() - t0
