"""Known-bad: broad except that neither re-raises, settles a future,
nor records the failure (silent-swallow)."""


def dispatch_and_forget(flush):
    try:
        flush.launch()
    except Exception:
        pass
