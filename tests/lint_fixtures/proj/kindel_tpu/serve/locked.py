"""Known-bad: a lock-guarded field read outside the lock
(lock-guarded-by)."""

import threading


class Racy:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def peek(self):
        return self._count
