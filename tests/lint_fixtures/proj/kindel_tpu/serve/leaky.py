"""Known-bad: a created Future leaks on the except arm
(future-settlement). The handler exists — so the silent-swallow
handler-recognizer shape is satisfiable — but the failure path neither
settles, hands back, nor re-raises."""

from concurrent.futures import Future


def submit_leaky(work):
    fut = Future()
    try:
        work()
        fut.set_result(True)
    except Exception:
        record_metric_only()
    return None


def record_metric_only():
    pass
