"""Known-bad: a pre-claimed recovery future leaks on a replay failure
path (future-settlement, durable scope — PR 15): the handler logs the
resubmission failure but forgets the idempotency-cache claim, so every
wire resubmission of that key waits forever."""

from concurrent.futures import Future


def replay_leaky(resubmit, log):
    claim = Future()
    try:
        claim.set_result(resubmit())
    except Exception:
        log("replay resubmit failed")  # claim stranded forever
    return None
