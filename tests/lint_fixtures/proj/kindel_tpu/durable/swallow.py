"""Known-bad: silent swallow in durable/ — a journal append error that
nobody records or re-raises silently converts "durable admission" into
"best effort", exactly the lie the write-ahead journal exists to make
impossible (the admit must be rejected typed instead)."""


def append_or_shrug(journal, frame):
    try:
        journal.append(frame)
    except Exception:
        return False
