"""Known-bad: explicit loops on the superbatch hot path
(ragged-pack-vectorized)."""


def build_segment_table(units, cls):
    table = []
    for u in units:
        table.append(len(u))
    return table


def pack_superbatch(units, table):
    out = []
    while units:
        out.append(units.pop())
    return out
