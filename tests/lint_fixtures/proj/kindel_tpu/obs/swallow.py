"""Known-bad: silent swallow in obs/ — the observability plane is how
every other failure becomes visible, so a trace-collection handler that
eats an exception without recording it blinds the operator exactly when
the data mattered (the collector must record_failure or re-raise)."""


def collect_or_shrug(collector, drain):
    try:
        return collector.add_ndjson("r0", drain())
    except Exception:
        return None
