"""Known-bad (obs scope, PR 18): a collection ack future leaks on the
drain failure path — the caller awaiting the merged-trace handle blocks
forever while the collector believes the flush completed."""

from concurrent.futures import Future


def collect_leaky(drain, merge):
    ack = Future()
    try:
        ack.set_result(merge(drain()))
    except Exception:
        pass  # drained nothing, told nobody — ack stranded forever
    return None
