"""Known-bad: jax import inside the io/ layer (io-jax-free)."""

import jax.numpy as jnp


def not_allowed(x):
    return jnp.asarray(x)
