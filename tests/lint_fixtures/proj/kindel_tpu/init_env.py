"""Known-bad: env state cached at construction (init-env-read)."""

import os


class CachesEnv:
    def __init__(self):
        self.trace_dir = os.getenv("KINDEL_TPU_TRACE_DIR")
