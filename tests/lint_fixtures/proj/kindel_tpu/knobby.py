"""Known-bad: an env knob with no doc row and no tuning resolution
path (knob-doc fires twice for it)."""

import os


def rogue_knob() -> int:
    return int(os.environ.get("KINDEL_TPU_UNDOCUMENTED_KNOB", "0"))
