"""Known-bad: a second AOT lowering site (aot-confinement)."""


def rogue_compile(fn, args):
    return fn.lower(*args).compile()
