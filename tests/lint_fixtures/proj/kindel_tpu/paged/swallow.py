"""Known-bad: silent swallow in paged/ — the scope extension for the
continuous-superbatching tier (a swallowed launch failure strands the
tick's admitted futures AND leaks its page references)."""


def launch_or_forget(launch):
    try:
        return launch()
    except Exception:
        return None
