"""Known-bad: a paged kernel whose helper reads env one call deep
(trace-purity) — the page-pool tier's jit entries sit inside the
whole-program closure like every other kernel's."""

import os
from functools import partial

import jax


def _page_slots():
    return int(os.environ.get("KINDEL_TPU_PAGED_SLOTS", "256"))


@partial(jax.jit, static_argnames=())
def bad_pool_kernel(state):
    return state[:: _page_slots()]
