"""Known-bad: a created Future leaks on a launch tick's failure path
(future-settlement, paged scope) — the handler releases the page
references but forgets the waiter."""

from concurrent.futures import Future


def tick_leaky(launch, release):
    fut = Future()
    try:
        fut.set_result(launch())
    except Exception:
        release()  # pages freed, waiter stranded forever
    return None
