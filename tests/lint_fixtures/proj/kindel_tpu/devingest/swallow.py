"""Known-bad: silent swallow in devingest/ — the scope extension for
the device-ingest tier (its real oracle-fallback paths use TYPED
excepts; a broad swallow would hide a device/host divergence)."""


def expand_or_forget(launch):
    try:
        return launch()
    except Exception:
        return None
