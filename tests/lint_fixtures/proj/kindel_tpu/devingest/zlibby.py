"""Known-bad: zlib inside devingest/ (zlib-confinement) — the device
tier consumes the io/ inflate chokepoint's output; it never inflates
itself."""

import zlib


def inline_inflate(member: bytes) -> bytes:
    return zlib.decompress(member)
