"""Known-bad: a devingest kernel whose helper reads env one call deep
(trace-purity) — proves the new package's jitted kernels sit inside
the whole-program closure, not just the decorated-body guard."""

import os
from functools import partial

import jax


def _block_width():
    return int(os.environ.get("KINDEL_TPU_DEVINGEST_BLOCK", "128"))


@partial(jax.jit, static_argnames=())
def bad_scan_kernel(data):
    return data[:: _block_width()]
