"""SURVEY §7 step 7 — the ultimate compat check: the reference's own
pytest module (tests/test_kindel.py, 338 LoC of unit + golden-file tests)
runs UNMODIFIED against this framework.

Mechanism: copy the reference's test tree to a writable tmp dir (the
mounted reference is read-only and its `plot` test writes HTML to CWD),
then run pytest there with tests/refsuite/ on PYTHONPATH — which provides
the `kindel` package alias, a read-only `dnaio` shim, and a `kindel`
console script on PATH, all backed by kindel_tpu. The reference test file
itself is never committed to this repo; it is read from /root/reference at
run time.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from conftest import DATA_ROOT

REPO = Path(__file__).resolve().parent.parent
REFSUITE = REPO / "tests" / "refsuite"


def test_reference_suite_unmodified(tmp_path):
    ref_tests = DATA_ROOT
    test_file = ref_tests / "test_kindel.py"
    if not test_file.exists():
        pytest.skip(f"reference test module not available: {test_file}")

    work = tmp_path / "refrun"
    shutil.copytree(ref_tests, work / "tests")

    # generate the `kindel` console-script stand-in with THIS interpreter
    # (a static shebang could resolve to a different python on PATH) — the
    # reference suite shells out to it ~30×
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    script = bin_dir / "kindel"
    script.write_text(
        f"#!{sys.executable}\n"
        "import sys\n"
        "from kindel_tpu.cli import main\n"
        "sys.exit(main(sys.argv[1:]))\n"
    )
    script.chmod(0o755)

    env = dict(os.environ)
    # KINDEL_TPU_TEST_INSTALLED=1 (installed-package CI): omit the repo
    # checkout from the child's import path so `kindel_tpu` must resolve
    # from site-packages (the wheel under test), not be shadowed by the
    # source tree; the refsuite aliases stay — they only re-export.
    installed = env.get("KINDEL_TPU_TEST_INSTALLED", "0") not in ("0", "")
    roots = [str(REFSUITE)] + ([] if installed else [str(REPO)])
    env["PYTHONPATH"] = os.pathsep.join(roots + [env.get("PYTHONPATH", "")])
    env["PATH"] = str(bin_dir) + os.pathsep + env.get("PATH", "")
    # the reference suite runs the CLI ~30×; numpy backend needs no device
    env.setdefault("JAX_PLATFORMS", "cpu")

    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/test_kindel.py",
            "-q",
            "-p",
            "no:cacheprovider",
        ],
        cwd=work,
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    tail = "\n".join(proc.stdout.splitlines()[-25:])
    assert proc.returncode == 0, (
        f"reference suite failed:\n{tail}\n{proc.stderr[-2000:]}"
    )
    assert " passed" in proc.stdout and "failed" not in tail, tail
