"""Device stats kernels vs scipy oracles."""

import numpy as np
import pytest

scipy_stats = pytest.importorskip("scipy.stats")


def test_entropy_matches_scipy():
    from kindel_tpu.stats_jax import entropy_rows_host

    rng = np.random.default_rng(0)
    rel = rng.random((500, 4)).astype(np.float64)
    rel[::17] = 0.0  # all-zero rows → nan, like scipy
    ours = entropy_rows_host(rel)
    ref = np.array([scipy_stats.entropy(r) for r in rel])
    np.testing.assert_allclose(ours, ref, rtol=1e-5, equal_nan=True)


def test_jeffreys_ci_matches_scipy():
    from kindel_tpu.stats_jax import jeffreys_interval_host

    count = np.array([0.0, 1, 5, 50, 499, 500, 22, 13])
    nobs = np.array([0.0, 2, 10, 100, 500, 500, 22, 500])
    lo, hi = jeffreys_interval_host(count, nobs, 0.01)
    ref_lo, ref_hi = scipy_stats.beta.interval(
        0.99, count + 0.5, nobs - count + 0.5
    )
    np.testing.assert_allclose(lo, ref_lo, atol=2e-4)
    np.testing.assert_allclose(hi, ref_hi, atol=2e-4)


def test_weights_workload_jax_close_to_numpy(data_root):
    from kindel_tpu.workloads import weights

    bam = data_root / "data_minimap2" / "1.1.multi.bam"
    df_np = weights(bam)
    df_jx = weights(bam, backend="jax")
    assert list(df_np.columns) == list(df_jx.columns)
    for col in ["A", "C", "G", "T", "N", "depth", "insertions", "deletions"]:
        np.testing.assert_array_equal(df_np[col].values, df_jx[col].values)
    for col in ["shannon", "lower_ci", "upper_ci", "consensus"]:
        np.testing.assert_allclose(
            df_np[col].values.astype(float),
            df_jx[col].values.astype(float),
            atol=2e-3, equal_nan=True,
        )


def test_fetch_counts_host_compact_bit_exact(monkeypatch):
    """The compact nonzero-rows u16 stats download (VERDICT r4 item 3)
    must be bit-exact vs the dense fetch: sparse rows, zero rows, 1-D
    scalar channels, and the >= 2^16 overflow fallback."""
    import jax.numpy as jnp
    import numpy as np

    from kindel_tpu.pileup_jax import fetch_counts_host
    from kindel_tpu.utils import wirestats

    rng = np.random.default_rng(11)
    w = np.zeros((5000, 5), np.int32)
    hot = rng.choice(5000, size=700, replace=False)
    w[hot] = rng.integers(0, 300, size=(700, 5))
    dev = jnp.asarray(w.reshape(-1))

    monkeypatch.setenv("KINDEL_TPU_COMPACT_STATS", "1")  # force on CPU
    wirestats.reset()
    compact = fetch_counts_host(dev, 4800)
    compact_bytes = wirestats.snapshot()["d2h_bytes"]
    dense = fetch_counts_host(dev, 4800, force_dense=True)
    np.testing.assert_array_equal(compact, dense)
    assert compact.dtype == np.int32
    # the compact wire must actually be smaller than the dense one
    assert compact_bytes < w.nbytes // 2

    # 1-D scalar channel
    d = np.zeros(5001, np.int32)
    d[rng.choice(5001, size=40, replace=False)] = 3
    got = fetch_counts_host(jnp.asarray(d), 5001, n_cols=1)
    np.testing.assert_array_equal(got, d)
    assert got.ndim == 1

    # overflow: values >= 2^16 must take the exact dense fallback
    w2 = w.copy()
    w2[hot[0], 2] = 70000
    got2 = fetch_counts_host(jnp.asarray(w2.reshape(-1)), 5000)
    np.testing.assert_array_equal(got2, w2[:5000])

    # negative values (int32 scatter wrap) must also go dense so the
    # caller's depth-ceiling check can see them
    w3 = w.copy()
    w3[hot[1], 0] = -5
    got3 = fetch_counts_host(jnp.asarray(w3.reshape(-1)), 5000)
    np.testing.assert_array_equal(got3, w3[:5000])


def test_stats_workloads_compact_parity(data_root, monkeypatch):
    """weights/features/variants TSVs must be byte-identical with the
    compact stats wire forced on vs dense, and clip-weight channels are
    never materialized on the jax stats path."""
    from kindel_tpu import workloads

    bam = data_root / "data_bwa_mem" / "1.1.sub_test.bam"
    # the stats loaders must skip the clip-weight channels entirely
    from kindel_tpu.workloads import _load_pileups

    p = next(iter(_load_pileups(bam, "jax", clip_weights=False).values()))
    assert p.clip_start_weights is None and p.clip_end_weights is None
    frames = {}
    for mode in ("compact", "dense"):
        if mode == "compact":
            monkeypatch.setenv("KINDEL_TPU_COMPACT_STATS", "1")
            monkeypatch.delenv("KINDEL_TPU_DENSE_STATS", raising=False)
        else:
            monkeypatch.delenv("KINDEL_TPU_COMPACT_STATS", raising=False)
            monkeypatch.setenv("KINDEL_TPU_DENSE_STATS", "1")
        frames[mode] = (
            workloads.weights(bam, backend="jax").to_csv(sep="\t"),
            workloads.features(bam, backend="jax").to_csv(sep="\t"),
            workloads.variants(bam, backend="jax").to_csv(sep="\t"),
        )
    assert frames["compact"] == frames["dense"]


def test_plot_envelope_decimation(tmp_path, monkeypatch):
    """VERDICT r4 item 8: the SVG chart must decimate by min/max
    envelope, not stride sampling — a 6 Mb trace keeps narrow spikes and
    dropouts. No JS runtime is available here, so this (a) pins the
    template's envelope markers and full-resolution payload, and (b)
    checks a faithful Python port of the bucket loop keeps both extrema
    stride sampling provably drops."""
    import json
    import re

    import numpy as np
    from types import SimpleNamespace

    import kindel_tpu.workloads as w

    L = 120_000
    y = np.full(L, 10, np.int32)
    spike_pos, drop_pos = 34_567, 91_113  # off any 4000-bucket stride grid
    y[spike_pos] = 500
    y[drop_pos] = 0
    zeros = np.zeros(L, np.int32)
    p = SimpleNamespace(
        ref_len=L, aligned_depth=y, clip_depth=zeros,
        clip_start_depth=zeros, clip_end_depth=zeros,
        clip_starts=np.zeros(L + 1, np.int32),
        clip_ends=np.zeros(L + 1, np.int32),
        deletions=np.zeros(L + 1, np.int32),
        ins=SimpleNamespace(totals=np.zeros(L + 1, np.int32)),
    )
    monkeypatch.setattr(w, "_load_pileups", lambda *a, **k: {"s": p})
    out = tmp_path / "spike.html"
    w.plot_clips("spike.bam", out_path=str(out))
    html = out.read_text()

    # template must carry the envelope loop, not a bare stride sample
    assert "let mi=j, ma=j" in html
    assert "if(t.y[k]<t.y[mi]) mi=k" in html
    # payload is full resolution (decimation is render-time only)
    payload = json.loads(
        re.search(r"const data = (\[.*?\]);\n", html, re.S).group(1)
    )
    trace = payload[0]["y"]
    assert len(trace) == L and trace[spike_pos] == 500

    # faithful Python port of the template's bucket loop
    def envelope(yv, a, b):
        step = max(1, (b - a) // 4000)
        kept = []
        j = a
        while j < b:
            e = min(b, j + step)
            mi = ma = j
            for k in range(j + 1, e):
                if yv[k] < yv[mi]:
                    mi = k
                if yv[k] > yv[ma]:
                    ma = k
            kept.append(yv[min(mi, ma)])
            if ma != mi:
                kept.append(yv[max(mi, ma)])
            j += step
        return kept

    kept = envelope(y, 0, L)
    assert max(kept) == 500 and min(kept) == 0
    # and plain stride sampling would have missed both (non-vacuity)
    step = max(1, L // 4000)
    strided = y[::step]
    assert 500 not in strided and 0 not in strided

def test_plot_hover_readout(tmp_path, monkeypatch):
    """VERDICT r4 item 8 (round 5): the dashboard must give per-position
    hover readouts on all traces, like the reference's plotly hover
    (kindel.py:679-696). No JS runtime here, so this pins (a) the hover
    machinery in the emitted HTML — crosshair, tooltip, a mousemove
    handler reading the FULL-resolution payload (t.y[pos], exact even
    when the rendered trace is envelope-decimated) — and (b) a Python
    port of the pixel→position mapping used by the handler."""
    import numpy as np
    from types import SimpleNamespace

    import kindel_tpu.workloads as w

    L = 5_000
    zeros = np.zeros(L, np.int32)
    p = SimpleNamespace(
        ref_len=L, aligned_depth=np.arange(L, dtype=np.int32),
        clip_depth=zeros, clip_start_depth=zeros, clip_end_depth=zeros,
        clip_starts=np.zeros(L + 1, np.int32),
        clip_ends=np.zeros(L + 1, np.int32),
        deletions=np.zeros(L + 1, np.int32),
        ins=SimpleNamespace(totals=np.zeros(L + 1, np.int32)),
    )
    monkeypatch.setattr(w, "_load_pileups", lambda *a, **k: {"s": p})
    out = tmp_path / "hover.html"
    w.plot_clips("hover.bam", out_path=str(out))
    html = out.read_text()

    assert 'id="tip"' in html and 'id="hline"' in html
    assert 'addEventListener("mouseleave",hideHover)' in html
    # the tooltip reads the raw payload, one row per visible trace
    assert "t.y[pos]" in html and "pos ${pos+1}" in html
    # stale-readout guards: zoom, drag-release, and legend toggles must
    # all dismiss the crosshair/tooltip (their values are position-bound)
    assert html.count("hideHover();") >= 3

    # the handler's pixel->position mapping must be the exact inverse of
    # the render path's x-scale: both expressions live in the template,
    # pinned here so a one-sided change to either breaks the test
    assert "const sx = (W-2*PAD)/(x1-x0)" in html  # render scale
    assert "Math.round(x0+(px-PAD)/((W-2*PAD)/(x1-x0)))" in html  # inverse
    # and the crosshair snap re-applies the forward scale to the snapped
    # position (so the line lands on the position, not the cursor)
    assert "(pos-x0)*(W-2*PAD)/(x1-x0)+PAD" in html
