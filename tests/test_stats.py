"""Device stats kernels vs scipy oracles."""

import numpy as np
import pytest

scipy_stats = pytest.importorskip("scipy.stats")


def test_entropy_matches_scipy():
    from kindel_tpu.stats_jax import entropy_rows_host

    rng = np.random.default_rng(0)
    rel = rng.random((500, 4)).astype(np.float64)
    rel[::17] = 0.0  # all-zero rows → nan, like scipy
    ours = entropy_rows_host(rel)
    ref = np.array([scipy_stats.entropy(r) for r in rel])
    np.testing.assert_allclose(ours, ref, rtol=1e-5, equal_nan=True)


def test_jeffreys_ci_matches_scipy():
    from kindel_tpu.stats_jax import jeffreys_interval_host

    count = np.array([0.0, 1, 5, 50, 499, 500, 22, 13])
    nobs = np.array([0.0, 2, 10, 100, 500, 500, 22, 500])
    lo, hi = jeffreys_interval_host(count, nobs, 0.01)
    ref_lo, ref_hi = scipy_stats.beta.interval(
        0.99, count + 0.5, nobs - count + 0.5
    )
    np.testing.assert_allclose(lo, ref_lo, atol=2e-4)
    np.testing.assert_allclose(hi, ref_hi, atol=2e-4)


def test_weights_workload_jax_close_to_numpy(data_root):
    from kindel_tpu.workloads import weights

    bam = data_root / "data_minimap2" / "1.1.multi.bam"
    df_np = weights(bam)
    df_jx = weights(bam, backend="jax")
    assert list(df_np.columns) == list(df_jx.columns)
    for col in ["A", "C", "G", "T", "N", "depth", "insertions", "deletions"]:
        np.testing.assert_array_equal(df_np[col].values, df_jx[col].values)
    for col in ["shannon", "lower_ci", "upper_ci", "consensus"]:
        np.testing.assert_allclose(
            df_np[col].values.astype(float),
            df_jx[col].values.astype(float),
            atol=2e-3, equal_nan=True,
        )
