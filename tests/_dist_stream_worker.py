"""Worker for the 2-process STREAMED x SHARDED test: join the localhost
group (4 virtual CPU devices per process -> 8 global), build a 1-D sp=8
mesh whose position axis SPANS the process boundary, stream the fixture
SAM in small chunks into a ShardedStreamAccumulator (per-chunk shard-local
scatters into globally-sharded state), close through the product kernel,
and print the consensus digest.

This is the per-chunk scatter + close sequence VERDICT r4 weak 3 flagged
as never having crossed a real process boundary — a process-local/global
addressing mistake in the chunk bucketing would produce a digest mismatch
or a collective hang here.

Usage: python tests/_dist_stream_worker.py <process_id> <coordinator_port>
(underscore prefix: not collected by pytest)."""

import os
import sys
import tempfile

proc_id = int(sys.argv[1])
port = int(sys.argv[2])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import distfixture  # noqa: E402  (shared sample geometry)

from kindel_tpu.parallel import initialize_distributed  # noqa: E402

assert (
    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=proc_id,
    )
    is True
), "process group did not come up"
assert jax.process_count() == 2
assert jax.device_count() == 8

from jax.sharding import Mesh  # noqa: E402

from kindel_tpu.io.stream import stream_alignment  # noqa: E402
from kindel_tpu.parallel.product import close_sharded_ref  # noqa: E402
from kindel_tpu.parallel.stream_product import (  # noqa: E402
    ShardedStreamAccumulator,
)

mesh = Mesh(jax.devices(), ("sp",))
procs_spanned = {d.process_index for d in mesh.devices.flat}
assert procs_spanned == {0, 1}, procs_spanned

with tempfile.NamedTemporaryFile(suffix=".sam", delete=False) as fh:
    fh.write(distfixture.product_sam())
    sam_path = fh.name

try:
    acc = ShardedStreamAccumulator(mesh=mesh, full=True)
    n_chunks = 0
    for batch in stream_alignment(sam_path, distfixture.STREAM_CHUNK_BYTES):
        acc.add_batch(batch)
        n_chunks += 1
    # the whole point is multi-chunk accumulation across the boundary
    assert n_chunks >= 2, f"fixture streamed in {n_chunks} chunk(s)"
    rid = next(iter(acc.present))
    sr = acc.finish(rid, realign=True)
    res, dmin, dmax, cdr = close_sharded_ref(
        sr, realign=True, min_depth=1, min_overlap=7,
        clip_decay_threshold=0.1, mask_ends=50, trim_ends=False,
        uppercase=False,
    )
    assert cdr, "no CDR patches — the lazy-fetch close went untested"
    print(
        "CHUNKS:%d" % n_chunks, flush=True,
    )
    print(
        "DIGEST:" + distfixture.product_digest(res, dmin, dmax, cdr),
        flush=True,
    )
finally:
    os.unlink(sam_path)
