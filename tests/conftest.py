"""Test configuration.

Tests run JAX on a virtual 8-device CPU mesh (the TPU-world analogue of the
reference's "real data, no mocks" stance — see SURVEY.md §4): sharding and
collective behavior is validated without a pod. These env vars must be set
before jax is imported anywhere.
"""

import os
import sys
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax (registering the TPU plugin)
# before this conftest runs, so the env vars above are latched too late —
# override through the config API before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402

#: Golden corpus: the reference's test data, mounted read-only. Overridable
#: so the suite can run against a relocated copy.
DATA_ROOT = Path(
    os.environ.get("KINDEL_TPU_TEST_DATA", "/root/reference/tests")
)


def require_data(*rel) -> Path:
    path = DATA_ROOT.joinpath(*rel)
    if not path.exists():
        pytest.skip(f"golden corpus not available: {path}")
    return path


@pytest.fixture(scope="session")
def data_root() -> Path:
    if not DATA_ROOT.exists():
        pytest.skip(f"golden corpus not available: {DATA_ROOT}")
    return DATA_ROOT
