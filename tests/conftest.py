"""Test configuration.

Tests run JAX on a virtual 8-device CPU mesh (the TPU-world analogue of the
reference's "real data, no mocks" stance — see SURVEY.md §4): sharding and
collective behavior is validated without a pod. These env vars must be set
before jax is imported anywhere.
"""

import os
import sys
from pathlib import Path


REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import _hermetic  # noqa: E402  (stdlib-only; shared relay-probe logic)


def _axon_relay_dead() -> bool:
    """True when the container advertises a tunneled accelerator pool but
    its local relay is not accepting connections. In that state *importing
    jax hangs* (the registered plugin retries the dead endpoint), so the
    suite must restart itself with the pool hook disabled — CPU tests need
    no accelerator anyway.

    NB: port liveness, not protocol identity (see _hermetic.relay_alive) —
    fine in this sandboxed container where 808x is reserved for the relay;
    a foreign listener there would defeat the guard, which is why the
    jax import below additionally runs under a SIGALRM watchdog."""
    return _hermetic.pool_advertised() and not _hermetic.relay_alive()


def _restore_real_stdio() -> None:
    """Point fds 1/2 back at the invoker's stdout/stderr before exec.

    pytest's fd-level global capture is already active while conftest
    imports: fds 1/2 target unlinked temp files, and the real ones were
    saved via dup() as higher fds. An exec'd child would inherit the temp
    files and its output would vanish, so find the saved originals — the
    two lowest fds > 2 that are a terminal, pipe, or live regular file
    (never sockets, /dev/null, or the deleted capture temps). This is a
    best-effort heuristic for a degraded mode: a plugin fd opened before
    capture start could be misidentified, costing only misrouted output —
    the exit code is unaffected."""
    try:
        # only act when capture is provably active: fd 1 targets an
        # unlinked capture temp. With capture off (pytest -s) fds 1/2 are
        # already the real ones and must not be touched.
        if not os.readlink("/proc/self/fd/1").endswith("(deleted)"):
            return
        fds = sorted(int(fd) for fd in os.listdir("/proc/self/fd"))
    except OSError:
        return
    import fcntl

    saved = []
    for fd in fds:
        if fd <= 2:
            continue
        try:
            tgt = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue
        if tgt.endswith("(deleted)") or tgt.startswith("socket:"):
            continue
        if tgt == "/dev/null":
            continue
        try:
            # pytest also dup-saves *stdin* (FDCapture(0)), and in the
            # redirected/piped cases where misidentifying it matters that
            # save is read-only — writable-only filtering drops it. (A tty
            # stdin dup is O_RDWR, but then stdout/stderr are the same
            # terminal, so picking it is harmless.)
            if fcntl.fcntl(fd, fcntl.F_GETFL) & os.O_ACCMODE == os.O_RDONLY:
                continue
        except OSError:
            continue
        if tgt.startswith(("pipe:", "/")):
            saved.append(fd)
        if len(saved) == 2:
            break
    # pytest saves stdout before stderr, so the lower fd is stdout. If
    # only one save qualifies (the other stream was sent to /dev/null) we
    # cannot tell WHICH survived; restoring it to the wrong fd would
    # reroute a stream the user explicitly silenced, so restore nothing —
    # the exit code still propagates, only the output stays captured.
    if len(saved) == 2:
        os.dup2(saved[0], 1)
        os.dup2(saved[1], 2)


def _looks_like_pytest_argv() -> bool:
    """Re-exec can only faithfully rebuild a plain `pytest ...` /
    `python -m pytest ...` command line. Programmatic pytest.main() or
    xdist-worker argv would turn into garbage — fail loudly instead."""
    argv0 = os.path.basename(sys.argv[0] or "")
    return argv0 in ("pytest", "py.test") or (
        argv0 == "__main__.py" and "pytest" in sys.argv[0]
    )


if _axon_relay_dead() and not os.environ.get("KINDEL_TPU_NO_REEXEC"):
    if not _looks_like_pytest_argv():
        raise RuntimeError(
            "accelerator relay unreachable and this pytest invocation "
            "cannot be re-exec'd (non-CLI argv). Re-run with "
            "PALLAS_AXON_POOL_IPS unset and JAX_PLATFORMS=cpu."
        )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["KINDEL_TPU_NO_REEXEC"] = "1"  # single retry — never loop
    _restore_real_stdio()
    os.write(
        2,
        b"[conftest] accelerator relay unreachable; re-running test "
        b"process on CPU with the pool hook disabled\n",
    )
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest", *sys.argv[1:]],
        env,
    )

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize registers the TPU plugin hook at
# interpreter start (before this conftest), so jax may have latched env
# state early — override through the config API before any backend
# initializes. NB the first *full* `import jax` in this process is the one
# below; with a dead relay it would hang, which is exactly why the
# re-exec guard above must run before this line. The port probe cannot
# rule out a foreign listener or a half-dead relay, so the import itself
# runs under a SIGALRM watchdog that turns an indefinite hang into a loud
# failure with re-run instructions (ADVICE.md round 1, conftest finding).
import signal  # noqa: E402

_JAX_IMPORT_TIMEOUT_S = 120  # first import may genuinely compile/probe


def _jax_import_watchdog(signum, frame):
    raise RuntimeError(
        "`import jax` did not complete within "
        f"{_JAX_IMPORT_TIMEOUT_S}s — the accelerator plugin is likely "
        "retrying a dead relay behind an open port. Re-run with "
        "PALLAS_AXON_POOL_IPS unset and JAX_PLATFORMS=cpu."
    )


_can_alarm = hasattr(signal, "SIGALRM")
if _can_alarm:
    _prev_handler = signal.signal(signal.SIGALRM, _jax_import_watchdog)
    signal.alarm(_JAX_IMPORT_TIMEOUT_S)
try:
    import jax  # noqa: E402
finally:
    if _can_alarm:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, _prev_handler)

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

#: Golden corpus: the reference's test data, mounted read-only. Overridable
#: so the suite can run against a relocated copy.
DATA_ROOT = Path(
    os.environ.get("KINDEL_TPU_TEST_DATA", "/root/reference/tests")
)


def require_data(*rel) -> Path:
    path = DATA_ROOT.joinpath(*rel)
    if not path.exists():
        pytest.skip(f"golden corpus not available: {path}")
    return path


@pytest.fixture(scope="session")
def data_root() -> Path:
    if not DATA_ROOT.exists():
        pytest.skip(f"golden corpus not available: {DATA_ROOT}")
    return DATA_ROOT
