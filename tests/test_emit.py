"""Device-rendered emission (kindel_tpu.emit) + donated paged residency
(kindel_tpu.paged.residency) — the PR 13 parity and transfer harness.

The contract: ``--emit-mode device`` and the paged tier's delta
residency are invisible optimizations. FASTA bytes are identical to the
host oracle across batch modes, worker counts, realign, trim/N-run/gap
edges, and randomized fuzz; what changes is only WHERE the final base
plane renders (device) and WHAT crosses the link (an extent patch per
admission, O(consensus length) per extraction) — both pinned here by
the transfer counters, not by prose.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from test_ingest import require_data  # shared golden-corpus gate
from test_serve import make_sam

from kindel_tpu.batch import BatchOptions, batch_bam_to_results
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.serve.queue import ServeRequest
from kindel_tpu.serve.worker import decode_request

WORKER_COUNTS = (1, 2, 8)


# (PR 14) These parity tests previously pinned KINDEL_TPU_FORCE_FUSED=1
# because the realign path's lazy CDR window fetches against dp-sharded
# dense tensors crawled (each jit dynamic-slice resharded the whole
# tensor). The mesh executor's owning-shard window fetch
# (kindel_tpu.parallel.meshexec.fetch_window_rows) removed the crawl,
# so emission parity now runs on the conftest-forced 8-device mesh —
# the sharded layout IS the served layout.


def _counter(name: str) -> float:
    snap = default_registry().snapshot()
    return sum(
        float(v) for k, v in snap.items()
        if (k == name or k.startswith(name + "{"))
        and not isinstance(v, dict)
    )


def _fasta(results: dict) -> list:
    return [
        (str(p), s.name, s.sequence)
        for p, res in results.items()
        for s in res.consensuses
    ]


def _decode(payload, **opt_kwargs):
    return decode_request(
        ServeRequest(payload=payload, opts=BatchOptions(**opt_kwargs))
    )


@pytest.fixture(scope="module")
def synth_sams(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("emit")
    rng = np.random.default_rng(13)
    return [
        make_sam(
            tmp / f"e{i}.sam", ref=f"eref{i}",
            L=int(rng.integers(260, 2400)),
            n_reads=int(rng.integers(8, 40)), seed=100 + i,
        )
        for i in range(5)
    ]


# ------------------------------------------------------- FASTA identity


@pytest.mark.parametrize("workers", WORKER_COUNTS)
def test_batch_identity_device_vs_host(synth_sams, workers):
    want = _fasta(batch_bam_to_results(
        synth_sams, build_reports=False, build_changes=False,
        emit_mode="host", num_workers=workers,
    ))
    got = _fasta(batch_bam_to_results(
        synth_sams, build_reports=False, build_changes=False,
        emit_mode="device", num_workers=workers,
    ))
    assert got == want


def test_batch_identity_realign_and_flags(synth_sams):
    for kw in (
        {"realign": True},
        {"trim_ends": True, "uppercase": True},
        {"realign": True, "trim_ends": True, "min_depth": 3},
    ):
        want = _fasta(batch_bam_to_results(
            synth_sams, build_reports=False, build_changes=False,
            emit_mode="host", **kw,
        ))
        got = _fasta(batch_bam_to_results(
            synth_sams, build_reports=False, build_changes=False,
            emit_mode="device", **kw,
        ))
        assert got == want, kw


def test_masks_variant_ignores_emit_mode(synth_sams):
    """Change lists need the dense masks wire, so the knob must gate
    OFF there (opts.emit_device is False under want_masks) — output
    including the change lists stays identical."""
    want = batch_bam_to_results(
        synth_sams[:2], build_changes=True, emit_mode="host",
    )
    got = batch_bam_to_results(
        synth_sams[:2], build_changes=True, emit_mode="device",
    )
    assert _fasta(want) == _fasta(got)
    for p in synth_sams[:2]:
        assert want[p].refs_changes == got[p].refs_changes
    assert not BatchOptions(
        emit_mode="device", build_changes=True
    ).emit_device
    assert BatchOptions(emit_mode="device").emit_device


@pytest.mark.parametrize(
    "rel",
    [
        ("data_bwa_mem", "1.1.sub_test.bam"),
        ("data_minimap2", "1.1.multi.bam"),
    ],
)
def test_refsuite_identity(rel):
    path = require_data(*rel)
    want = _fasta(batch_bam_to_results(
        [path], build_reports=False, build_changes=False,
        emit_mode="host",
    ))
    got = _fasta(batch_bam_to_results(
        [path], build_reports=False, build_changes=False,
        emit_mode="device",
    ))
    assert got == want
    # realign too (acceptance: sha-pinned identity including realign)
    want = _fasta(batch_bam_to_results(
        [path], build_reports=False, build_changes=False,
        emit_mode="host", realign=True,
    ))
    got = _fasta(batch_bam_to_results(
        [path], build_reports=False, build_changes=False,
        emit_mode="device", realign=True,
    ))
    assert got == want


def _edge_sam(dest, rng, L):
    """A consensus full of the awkward cases the emission plane must
    reproduce: uncovered interior runs (N), deletion-dominant spans at
    both sequence edges (trim interacts with leading/trailing emission),
    insertions adjacent to deletions, and tie positions."""
    lines = ["@HD\tVN:1.6", f"@SQ\tSN:edge\tLN:{L}"]
    n = 0

    def read(pos, cigar, span):
        nonlocal n
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=span))
        lines.append(
            f"x{n}\t0\tedge\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*"
        )
        n += 1

    # deletion-dominant right at position 0 and at the tail
    for _ in range(3):
        read(0, "4M6D26M", 30)
        read(L - 40, "30M8D2M", 32)
    # an interior island leaving uncovered (N) runs on both sides
    island = int(rng.integers(L // 3, L // 2))
    for _ in range(int(rng.integers(1, 4))):
        read(island, "12M2I10M3D8M", 32)
    # two overlapping reads engineered to tie at their overlap
    read(island + 60, "20M", 20)
    read(island + 60, "20M", 20)
    dest.write_text("\n".join(lines) + "\n")
    return dest


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_fuzz_trim_n_run_gap_edges(tmp_path, seed):
    rng = np.random.default_rng(seed)
    sams = [
        _edge_sam(tmp_path / f"f{seed}_{i}.sam", rng,
                  int(rng.integers(160, 900)))
        for i in range(3)
    ]
    for kw in ({}, {"trim_ends": True}, {"trim_ends": True,
                                         "uppercase": True}):
        want = _fasta(batch_bam_to_results(
            sams, build_reports=False, build_changes=False,
            emit_mode="host", **kw,
        ))
        got = _fasta(batch_bam_to_results(
            sams, build_reports=False, build_changes=False,
            emit_mode="device", **kw,
        ))
        assert got == want, (seed, kw)


# ------------------------------------------------------- emission decode


def test_emit_plane_short_raises():
    from kindel_tpu.emit import masks_from_emit_plane

    with pytest.raises(ValueError):
        masks_from_emit_plane(
            np.zeros(4, np.uint8), np.zeros(1, np.uint8), 10,
            np.empty(0, np.int32),
        )


def test_emit_wire_bytes_helper():
    from kindel_tpu.emit import emit_plane_wire_bytes

    assert emit_plane_wire_bytes(100, 16) == 102


# --------------------------------------------------------- knob plumbing


def test_resolve_emit_mode_precedence(tmp_path, monkeypatch):
    from kindel_tpu import tune

    store = tmp_path / "tune.json"
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(store))
    monkeypatch.delenv("KINDEL_TPU_EMIT_MODE", raising=False)

    assert tune.resolve_emit_mode() == ("host", "default")
    assert tune.record(tune.emit_store_key(), {"emit_mode": "device"})
    assert tune.resolve_emit_mode() == ("device", "cache")
    monkeypatch.setenv("KINDEL_TPU_EMIT_MODE", "host")
    assert tune.resolve_emit_mode() == ("host", "env")
    assert tune.resolve_emit_mode("device") == ("device", "explicit")
    # malformed env falls through (store next in line)
    monkeypatch.setenv("KINDEL_TPU_EMIT_MODE", "banana")
    assert tune.resolve_emit_mode() == ("device", "cache")
    with pytest.raises(ValueError):
        tune.resolve_emit_mode("banana")
    # malformed store entry falls through to the default
    assert tune.record(tune.emit_store_key(), {"emit_mode": "tpu9"})
    monkeypatch.delenv("KINDEL_TPU_EMIT_MODE")
    assert tune.resolve_emit_mode() == ("host", "default")


def test_search_emit_mode_picks_faster_and_survives_probe_error():
    from kindel_tpu import tune

    chosen, timings = tune.search_emit_mode(
        {"host": 3.0, "device": 1.1}.__getitem__, budget_s=100.0
    )
    assert chosen == "device" and set(timings) == {"host", "device"}

    def half_broken(mode):
        if mode == "device":
            raise RuntimeError("no accelerator")
        return 2.0

    chosen, timings = tune.search_emit_mode(half_broken, budget_s=100.0)
    assert chosen == "host"
    assert timings["device"] == float("inf")


def test_sig_emit_dimension():
    from kindel_tpu import aot
    from kindel_tpu.ragged import parse_classes

    (cls,) = parse_classes("small:32x2048")
    assert aot.ragged_sig(cls.key(), False, False, True) != aot.ragged_sig(
        cls.key(), False, False, False
    )
    assert aot.fused_sig((1, 2, 3, 4, 5), 100, False, None, True) != (
        aot.fused_sig((1, 2, 3, 4, 5), 100, False, None, False)
    )
    assert aot.cohort_sig(8, (1,), 100, False, False, True) != (
        aot.cohort_sig(8, (1,), 100, False, False, False)
    )


# ----------------------------------------- transfer-side wins, measured


def test_unpack_rows_empty_retiring_set_downloads_nothing(tmp_path):
    """Satellite: a tick with nothing to extract must not pay ANY d2h
    — cached panel segments ride the launch unread."""
    from kindel_tpu.ragged import build_segment_table, pack_superbatch
    from kindel_tpu.ragged import parse_classes
    from kindel_tpu.ragged.kernel import launch_ragged
    from kindel_tpu.ragged.unpack import unpack_rows

    sam = make_sam(tmp_path / "r.sam", ref="rr", L=500, seed=3)
    units = _decode(str(sam))
    (cls,) = parse_classes("small:32x2048")
    table = build_segment_table(units, cls)
    arrays = pack_superbatch(units, table)
    opts = BatchOptions()
    out = launch_ragged(arrays, cls, opts)
    d2h0 = _counter("kindel_device_d2h_bytes_total")
    assert unpack_rows(out, table, [], opts, None) == []
    assert _counter("kindel_device_d2h_bytes_total") == d2h0


def test_paged_delta_admission_uploads_only_the_newcomer(tmp_path):
    """Acceptance (b), unit form: admit 1 segment into a 7-resident
    pool — the upload is ONE extent patch (+ the refreshed segment
    table), not the resident set, and it is byte-exact against the
    newcomer's quota extents."""
    from kindel_tpu.paged.residency import DeviceResidency
    from kindel_tpu.paged.state import PAGE_SLOTS, PagePool
    from kindel_tpu.ragged import pack as rpack
    from kindel_tpu.ragged import parse_classes

    (cls,) = parse_classes("small:32x2048")
    pool = PagePool(cls, clock=time.monotonic)
    res = DeviceResidency(cls, PAGE_SLOTS, realign=False)
    assert res.supported
    pool.residency = res
    units = []
    for i in range(8):
        sam = make_sam(tmp_path / f"d{i}.sam", ref=f"dr{i}",
                       L=380 + 16 * i, seed=50 + i, n_reads=12)
        units.extend(_decode(str(sam)))
    for u in units[:7]:
        assert pool.admit_unit(u, rpack.consumption([u])) is not None
    h2d0 = _counter("kindel_paged_admit_h2d_bytes_total")
    seg = pool.admit_unit(units[7], rpack.consumption([units[7]]))
    assert seg is not None and res.active
    patched = _counter("kindel_paged_admit_h2d_bytes_total") - h2d0
    po, pb, pd, pi, pc, s_pad = res._sizes_for(seg)
    expected = 4 * po * 2 + pb + 4 * pd + 4 * pi * 2 + 8 * s_pad
    assert patched == expected
    # ~2 pages' extents, nowhere near the 7-resident set's streams
    full_set = sum(
        u.n_events // 2 + 4 * (len(u.op_r_start) * 2 + len(u.del_pos)
                               + 2 * len(u.ins_pos))
        for u in units[:7]
    )
    assert patched < full_set


def test_residency_launch_identical_to_legacy_after_churn(tmp_path):
    """The donated-residency wire decodes to the SAME per-segment
    results as a classic full re-assembly launch over the same resident
    set — including after retire/re-admit churn fragments the page grid
    (the layout-invariant argument, pinned end to end)."""
    from kindel_tpu.paged.residency import DeviceResidency
    from kindel_tpu.paged.retire import _InlineMap
    from kindel_tpu.paged.state import PAGE_SLOTS, PagePool
    from kindel_tpu.ragged import pack as rpack
    from kindel_tpu.ragged import parse_classes
    from kindel_tpu.ragged.kernel import launch_ragged
    from kindel_tpu.ragged.unpack import unpack_rows

    (cls,) = parse_classes("small:32x2048")
    pool = PagePool(cls, clock=time.monotonic)
    res = DeviceResidency(cls, PAGE_SLOTS, realign=False)
    pool.residency = res
    segs = []
    for i in range(5):
        sam = make_sam(tmp_path / f"c{i}.sam", ref=f"cr{i}",
                       L=300 + 210 * i, seed=80 + i, n_reads=14 + i)
        (u,) = _decode(str(sam))
        s = pool.admit_unit(u, rpack.consumption([u]))
        assert s is not None
        segs.append(s)
    # churn: retire two non-adjacent segments, admit a replacement into
    # the freed (fragmented) space
    for s in (segs[1], segs[3]):
        s.panel = None  # force a real free, not a panel park
        pool.release(s)
    sam = make_sam(tmp_path / "c9.sam", ref="cr9", L=340, seed=99,
                   n_reads=10)
    (u9,) = _decode(str(sam))
    assert pool.admit_unit(u9, rpack.consumption([u9])) is not None
    assert res.active, "churn must not deactivate the residency"

    opts = BatchOptions()
    units, table, row_of = res.table(pool)
    out_res = res.launch(opts)
    got = [
        seq.sequence for seq, _c, _r in unpack_rows(
            out_res, table, list(enumerate(units)), opts, _InlineMap()
        )
    ]
    # legacy oracle over the SAME ledger
    units2, table2, _row2 = pool.assemble()
    arrays = rpack.pack_superbatch(units2, table2)
    out_legacy = launch_ragged(arrays, cls, opts)
    want = [
        seq.sequence for seq, _c, _r in unpack_rows(
            out_legacy, table2, list(enumerate(units2)), opts,
            _InlineMap()
        )
    ]
    assert got == want


def test_residency_quota_overflow_falls_back_and_recovers(tmp_path):
    """A segment whose span footprint overflows its page run's quota
    deactivates the residency (classic launches, byte-identical) until
    the pool empties — then a fresh admission reactivates it."""
    from kindel_tpu.paged.residency import DeviceResidency
    from kindel_tpu.paged.state import PAGE_SLOTS, PagePool
    from kindel_tpu.ragged import pack as rpack
    from kindel_tpu.ragged import parse_classes

    (cls,) = parse_classes("small:32x2048")
    pool = PagePool(cls, clock=time.monotonic)
    res = DeviceResidency(cls, PAGE_SLOTS, realign=False)
    pool.residency = res
    # many short scattered reads → far more op spans than the ~2-page
    # run's quota (opp = o_cap/n_pages = 32 spans/page here, so >64
    # spans on an L≈400 unit overflows)
    rng = np.random.default_rng(5)
    lines = ["@HD\tVN:1.6", "@SQ\tSN:frag\tLN:420"]
    for j in range(90):
        pos = int(rng.integers(0, 400))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=8))
        lines.append(
            f"q{j}\t0\tfrag\t{pos + 1}\t60\t8M\t*\t0\t0\t{seq}\t*"
        )
    sam = tmp_path / "frag.sam"
    sam.write_text("\n".join(lines) + "\n")
    (u,) = _decode(str(sam))
    assert len(u.op_r_start) > 64
    s = pool.admit_unit(u, rpack.consumption([u]))
    assert s is not None, "quota overflow must not refuse admission"
    assert not res.active
    s.panel = None
    pool.release(s)
    # pool drained: the next well-behaved admission reactivates
    sam2 = make_sam(tmp_path / "ok.sam", ref="ok", L=400, seed=7,
                     n_reads=10)
    (u2,) = _decode(str(sam2))
    assert pool.admit_unit(u2, rpack.consumption([u2])) is not None
    assert res.active


def test_delta_gate_env_override(monkeypatch):
    from kindel_tpu.paged.residency import use_delta_residency

    monkeypatch.delenv("KINDEL_TPU_PAGED_DELTA", raising=False)
    assert use_delta_residency()
    monkeypatch.setenv("KINDEL_TPU_PAGED_DELTA", "0")
    assert not use_delta_residency()
    monkeypatch.setenv("KINDEL_TPU_PAGED_DELTA", "1")
    assert use_delta_residency()


# ------------------------------------------------- warmup / compile pins


def test_warm_ragged_covers_emit_and_first_request_compiles_nothing():
    """Acceptance: zero new jit entries beyond the emit variant per
    geometry — warmup covers BOTH emission modes, so the first
    device-emit request after warmup compiles nothing (pinned by the
    tracked jit-cache counter)."""
    from kindel_tpu.serve import ConsensusService
    from kindel_tpu.tune import TuningConfig

    sam = make_sam(
        __import__("pathlib").Path(
            __import__("tempfile").mkdtemp()
        ) / "w.sam",
        ref="warm1", L=420, seed=11,
    )
    with ConsensusService(
        tuning=TuningConfig(batch_mode="ragged",
                            ragged_classes="small:32x2048"),
        max_wait_s=0.05, warmup=True, http_port=None,
    ) as svc:
        deadline = time.monotonic() + 600
        while svc.healthz()["warmup"] in ("pending", "warming"):
            assert time.monotonic() < deadline, "warmup wedged"
            time.sleep(0.05)
        assert svc.healthz()["warmup"] == "ok"
        before = obs_runtime.jit_cache_entries()
        res = svc.request(str(sam), timeout=300, emit_mode="device")
        assert res.consensuses
        assert obs_runtime.jit_cache_entries() == before, (
            "first device-emit request compiled a tracked kernel after "
            "warmup"
        )
