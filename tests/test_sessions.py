"""Streaming consensus lane (kindel_tpu.sessions): DESIGN.md §25's
claims, asserted.

  * merge_event_sets is an order-independent reduce (appends commute)
    and rejects cross-roster batches typed (ValueError → HTTP 400);
  * a streamed session's final FASTA is byte-identical to the one-shot
    consensus over the concatenation of its batches — the lane's whole
    correctness contract;
  * the depth-delta emission gate: below-gate appends ack deferred,
    the crossing append acks at the emission decision, epochs advance
    exactly with published updates (strictly monotone), a snapshot
    whose called bases did not change is suppressed (no epoch, no SSE
    event), and CLOSE always publishes a final update;
  * the idle reaper vs an in-flight append: every append future
    settles exactly once — typed or acked, never stranded;
  * admission sheds with the /v1/consensus taxonomy, every hint
    through queue.jittered_retry_after (the PR 11 substitution pin);
  * OPEN/APPEND/EMIT/CLOSE journal frames replay a killed replica's
    sessions under their original ids (epoch fast-forwarded);
  * warm-host streaming adds ZERO jit-cache entries across epochs —
    snapshots ride the shared ticks and the warmed executables;
  * drain re-homes live sessions onto survivors (rendezvous affinity);
  * the flagship: a 3-replica fleet under wire faults with 4 live
    sessions, one replica SIGKILLed and another drained mid-stream —
    every session converges, each final FASTA sha-identical to the
    one-shot consensus over its concatenated batches, and no journal
    leaks a live session frame.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from kindel_tpu.durable import recovery as drec
from kindel_tpu.durable.journal import PoisonRequestError
from kindel_tpu.io.fasta import format_fasta
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience import policy as rpolicy
from kindel_tpu.resilience.faults import FaultPlan
from kindel_tpu.serve import (
    AdmissionError,
    ConsensusService,
    DeadlineExceeded,
    ServiceDegraded,
)
from kindel_tpu.serve import queue as squeue
from kindel_tpu.serve.service import stream_post_response
from kindel_tpu.serve.worker import decode_events
from kindel_tpu.sessions import SessionRegistry, session_key
from kindel_tpu.sessions import registry as sreg
from kindel_tpu.sessions.lease import LeaseRetired
from kindel_tpu.sessions.pileup import event_count, merge_event_sets
from kindel_tpu.workloads import bam_to_consensus

from tests.test_serve import make_sam


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Process-global fault plans / policies must not leak (same
    hygiene as test_fleet.py)."""
    rfaults.deactivate()
    prev = rpolicy.set_default_policy(None)
    yield
    rfaults.deactivate()
    rpolicy.set_default_policy(prev)


def _service(**kw):
    kw.setdefault("warmup", False)
    kw.setdefault("http_port", None)
    kw.setdefault("max_wait_s", 0.02)
    return ConsensusService(**kw)


def _wait(pred, timeout=120.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def _concat_sam(dest: Path, parts) -> Path:
    """The one-shot oracle input: every batch's alignment lines under
    the first batch's header (the roster is shared by construction)."""
    lines = []
    for i, p in enumerate(parts):
        for ln in p.read_text().splitlines():
            if ln.startswith("@"):
                if i == 0:
                    lines.append(ln)
            else:
                lines.append(ln)
    dest.write_text("\n".join(lines) + "\n")
    return dest


def _oracle_fasta(tmp_path: Path, name: str, parts) -> str:
    cat = _concat_sam(tmp_path / name, parts)
    return format_fasta(bam_to_consensus(str(cat)).consensuses)


def _delta(before: dict, after: dict, name: str) -> int:
    return int(after.get(name, 0)) - int(before.get(name, 0))


# ------------------------------------------------------------ the reduce


def test_merge_event_sets_is_order_independent(tmp_path):
    """Appends commute: a⊕b and b⊕a hold the same multiset of pileup
    events (the kernel's input is counts, so the consensus is equal by
    construction)."""
    a = decode_events(
        make_sam(tmp_path / "a.sam", seed=1).read_bytes(), "host"
    )
    b = decode_events(
        make_sam(tmp_path / "b.sam", seed=2).read_bytes(), "host"
    )
    ab = merge_event_sets(merge_event_sets(None, a), b)
    ba = merge_event_sets(merge_event_sets(None, b), a)
    assert event_count(ab) == event_count(ba) == (
        event_count(a) + event_count(b)
    )
    assert ab.insertions == ba.insertions
    # same (pos, base) multiset either way — order is the only freedom
    for pos_f, base_f in (("match_pos", "match_base"), ("del_pos", None)):
        pa = getattr(ab, pos_f)
        pb = getattr(ba, pos_f)
        if base_f is None:
            assert sorted(pa.tolist()) == sorted(pb.tolist())
        else:
            za = sorted(zip(pa.tolist(), getattr(ab, base_f).tolist()))
            zb = sorted(zip(pb.tolist(), getattr(ba, base_f).tolist()))
            assert za == zb
    assert ab.ref_names == ba.ref_names


def test_merge_rejects_cross_roster_batch(tmp_path):
    """A batch aligned against a different reference roster is a typed
    decode rejection, never a best-effort merge."""
    a = decode_events(
        make_sam(tmp_path / "ra.sam", ref="refA", seed=1).read_bytes(),
        "host",
    )
    b = decode_events(
        make_sam(tmp_path / "rb.sam", ref="refB", seed=1).read_bytes(),
        "host",
    )
    with pytest.raises(ValueError):
        merge_event_sets(merge_event_sets(None, a), b)


# -------------------------------------------- streamed == one-shot


def test_stream_converges_to_one_shot_consensus(tmp_path):
    """The lane's correctness contract: open/append/close over three
    batches produces the byte-identical FASTA of one /v1/consensus
    request over the concatenated batches."""
    parts = [
        make_sam(tmp_path / f"p{k}.sam", seed=30 + k) for k in range(3)
    ]
    want = _oracle_fasta(tmp_path, "oracle.sam", parts)
    with _service(emit_delta=1) as svc:
        sid = svc.sessions.open(parts[0].read_bytes())
        for p in parts[1:]:
            ack = svc.sessions.append(sid, p.read_bytes()).result(
                timeout=120
            )
            assert ack["session"] == sid
        final = svc.sessions.close(sid).result(timeout=120)
    assert final["closed"] is True
    assert final["emitted"] is True
    assert final["fasta"] == want


# ------------------------------------------------------- emission gate


def test_emission_gate_defers_below_delta_and_epochs_are_monotone(
    tmp_path,
):
    """Below --emit-delta an append acks deferred with the epoch
    unchanged; the crossing append acks at the emission decision with
    the epoch advanced; CLOSE always emits. Epochs never move except
    with a published update."""
    parts = [
        make_sam(tmp_path / f"g{k}.sam", seed=40 + k) for k in range(3)
    ]
    n1 = event_count(decode_events(parts[0].read_bytes(), "host"))
    with _service(emit_delta=n1 + 1) as svc:
        sid = svc.sessions.open()
        a1 = svc.sessions.append(sid, parts[0].read_bytes()).result(
            timeout=120
        )
        assert a1["emitted"] is False and a1.get("deferred") is True
        assert a1["epoch"] == 0
        a2 = svc.sessions.append(sid, parts[1].read_bytes()).result(
            timeout=120
        )
        assert a2["emitted"] is True
        assert a2["epoch"] == 1
        a3 = svc.sessions.append(sid, parts[2].read_bytes()).result(
            timeout=120
        )
        assert a3.get("deferred") is True
        assert a3["epoch"] == 1  # no update published, no epoch burned
        final = svc.sessions.close(sid).result(timeout=120)
    assert final["emitted"] is True  # forced final emit below the gate
    assert final["epoch"] == 2
    assert final["fasta"]
    epochs = [a1["epoch"], a2["epoch"], a3["epoch"], final["epoch"]]
    assert epochs == sorted(epochs)


def test_unchanged_bases_suppress_update(tmp_path):
    """A snapshot whose called bases did not change publishes nothing:
    no epoch advance, the suppression counter moves instead (appending
    the SAME batch doubles every count — the argmax is unchanged)."""
    sam = make_sam(tmp_path / "same.sam", seed=7)
    with _service(emit_delta=1) as svc:
        sid = svc.sessions.open()
        a1 = svc.sessions.append(sid, sam.read_bytes()).result(
            timeout=120
        )
        assert a1["emitted"] is True and a1["epoch"] == 1
        before = svc.metrics.snapshot()
        a2 = svc.sessions.append(sid, sam.read_bytes()).result(
            timeout=120
        )
        after = svc.metrics.snapshot()
        assert a2["emitted"] is False
        assert a2["epoch"] == 1
        assert _delta(
            before, after, "kindel_stream_suppressed_total"
        ) == 1
        assert _delta(before, after, "kindel_stream_emits_total") == 0
        final = svc.sessions.close(sid).result(timeout=120)
    # CLOSE still force-publishes the final answer
    assert final["emitted"] is True and final["epoch"] == 2


def test_close_of_empty_session_acks_empty_fasta():
    with _service(emit_delta=1) as svc:
        sid = svc.sessions.open()
        final = svc.sessions.close(sid).result(timeout=60)
    assert final == {
        "session": sid, "epoch": 0, "emitted": False, "fasta": "",
        "closed": True,
    }


# ------------------------------------------------- reap vs append race


def test_reap_vs_inflight_append_settles_exactly_once(tmp_path):
    """The exactly-once contract of the reap-vs-append race: however
    the interleaving lands, every append future settles exactly once
    (deferred ack or typed LeaseRetired), the lease never holds a
    stranded pending future, and the table ends empty."""
    payload = make_sam(tmp_path / "race.sam", seed=9).read_bytes()
    svc = _service()  # unstarted: deferred appends never hit the queue
    fake = [0.0]
    reg = SessionRegistry(
        svc, idle_s=10.0, emit_delta=10 ** 9, clock=lambda: fake[0]
    )
    for _round in range(10):
        sid = reg.open()
        lease = reg._lease(sid)
        fake[0] += 10.0  # the session is now exactly idle
        barrier = threading.Barrier(2)
        futs, typed = [], []

        def do_append():
            barrier.wait()
            try:
                futs.append(reg.append(sid, payload))
            except (KeyError, LeaseRetired) as e:
                typed.append(e)  # not merged — a client would retry

        def do_reap():
            barrier.wait()
            reg.reap_idle()

        threads = [
            threading.Thread(target=do_append),
            threading.Thread(target=do_reap),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        if reg.has(sid):
            # the append won and refreshed last_active: idle it out
            fake[0] += 10.0
            assert reg.reap_idle() == 1
        assert not reg.has(sid)
        assert len(futs) + len(typed) == 1
        for f in futs:
            assert f.done(), "append future stranded by the reap race"
            if f.exception() is not None:
                assert isinstance(f.exception(), LeaseRetired)
            else:
                assert f.result()["session"] == sid
        assert not lease.pending, "lease retired with a pending future"
        assert lease.state == "retired"


# -------------------------------------------------- admission taxonomy


def test_admission_hints_ride_queue_jittered_retry_after(
    tmp_path, monkeypatch
):
    """PR 11 substitution pin, sessions edition: the registry's shed
    hints are computed by serve.queue.jittered_retry_after — swap the
    function, every hint follows."""
    assert sreg.jittered_retry_after is squeue.jittered_retry_after
    monkeypatch.setattr(
        sreg, "jittered_retry_after", lambda *a, **k: 42.0
    )
    svc = _service()
    full = SessionRegistry(svc, idle_s=60, emit_delta=1, max_sessions=0)
    with pytest.raises(AdmissionError) as ei:
        full.open()
    assert "full" in str(ei.value)
    assert ei.value.retry_after_s == 42.0

    draining = SessionRegistry(svc, idle_s=60, emit_delta=1)
    draining._admitting = False
    with pytest.raises(AdmissionError) as ei:
        draining.open()
    assert "draining" in str(ei.value)
    assert ei.value.retry_after_s == 42.0

    monkeypatch.setattr(svc.breaker, "allow_admission", lambda: False)
    open_reg = SessionRegistry(svc, idle_s=60, emit_delta=1)
    with pytest.raises(ServiceDegraded) as ei:
        open_reg.open()
    assert ei.value.retry_after_s == 42.0


def test_stream_post_response_status_taxonomy():
    """The /v1/stream POST handlers share the /v1/consensus status
    taxonomy plus 404 for an unknown/retired lease."""

    def boom(exc):
        def fn():
            raise exc
        return fn

    cases = [
        (ServiceDegraded("breaker open", 3.0), 503),
        (AdmissionError("table full", 1.0), 429),
        (DeadlineExceeded("too slow"), 504),
        (LeaseRetired("session x reaped"), 404),
        (KeyError("unknown session x"), 404),
        (PoisonRequestError("quarantined"), 422),
        (ValueError("undecodable batch"), 400),
        (RuntimeError("wires crossed"), 500),
    ]
    for exc, want in cases:
        status, ctype, body, headers = stream_post_response(boom(exc))
        assert status == want, f"{type(exc).__name__} -> {status}"
        if want in (503, 429):
            assert "Retry-After" in headers
            assert json.loads(body)["retry_after_s"] == pytest.approx(
                exc.retry_after_s
            )
    status, ctype, body, headers = stream_post_response(
        lambda: {"session": "abc"}
    )
    assert status == 200 and ctype == "application/json"
    assert json.loads(body) == {"session": "abc"}


# ------------------------------------------------- journal replay


def test_session_replays_on_respawn_under_original_id(tmp_path):
    """A killed replica's open sessions come back on the next life:
    OPEN/APPEND frames replay under the ORIGINAL session id, and the
    close after respawn serves the one-shot-identical answer."""
    parts = [
        make_sam(tmp_path / f"j{k}.sam", seed=60 + k) for k in range(2)
    ]
    want = _oracle_fasta(tmp_path, "joracle.sam", parts)
    jd = tmp_path / "journal"

    svc = _service(journal_dir=str(jd), emit_delta=1).start()
    sid = svc.sessions.open(parts[0].read_bytes())
    ack = svc.sessions.append(sid, parts[1].read_bytes()).result(
        timeout=120
    )
    pre_epoch = ack["epoch"]
    svc.stop()  # leases retire typed; the journal frames stay open

    before = default_registry().snapshot()
    svc2 = _service(journal_dir=str(jd), emit_delta=1).start()
    try:
        # replay runs on the recovery thread: the replays counter moves
        # once the session's appends are re-decoded and merged
        assert _wait(lambda: svc2.metrics.snapshot().get(
            "kindel_stream_replays_total", 0
        ) >= 1, 120)
        assert svc2.sessions.has(sid)
        final = svc2.sessions.close(sid).result(timeout=120)
    finally:
        svc2.stop()
    assert final["fasta"] == want
    # epoch fast-forwarded past every journalled emit: still monotone
    assert final["epoch"] > pre_epoch
    # the close tombstoned the session: nothing live left to replay
    assert not drec.scan(jd).sessions
    _ = before


# ---------------------------------------------------------------- SSE


def test_sse_subscription_streams_updates_and_final(tmp_path):
    parts = [
        make_sam(tmp_path / f"s{k}.sam", seed=70 + k) for k in range(2)
    ]
    with _service(emit_delta=1) as svc:
        sid = svc.sessions.open(parts[0].read_bytes())
        # let the open's own snapshot settle: the NEXT append must be
        # the gate-crossing one, not a deferred rider on this one
        assert _wait(lambda: svc.sessions._lease(sid).epoch >= 1)
        events = svc.sessions.subscribe(sid)
        ack = svc.sessions.append(sid, parts[1].read_bytes()).result(
            timeout=120
        )
        assert ack["emitted"] is True
        frame = next(events)
        assert frame.startswith("event: update\n")
        doc = json.loads(frame.split("data: ", 1)[1].strip())
        assert doc["session"] == sid
        assert doc["epoch"] == ack["epoch"]
        assert doc["fasta"]
        final = svc.sessions.close(sid).result(timeout=120)
        frame = next(events)
        assert frame.startswith("event: final\n")
        doc = json.loads(frame.split("data: ", 1)[1].strip())
        assert doc["fasta"] == final["fasta"]
        assert next(events).startswith("event: close\n")
        with pytest.raises(StopIteration):
            next(events)
        with pytest.raises(KeyError):
            svc.sessions.subscribe(sid)  # retired lease is a 404


def test_stream_http_surface_end_to_end(tmp_path):
    """The wire-level lane: open → SSE subscribe → append (ack after
    the emission decision) → close, plus the 400/404 edges of the
    events endpoint."""
    parts = [
        make_sam(tmp_path / f"h{k}.sam", seed=80 + k) for k in range(2)
    ]
    want = _oracle_fasta(tmp_path, "horacle.sam", parts)
    with _service(emit_delta=1, http_port=0) as svc:
        host, port = svc.http_address
        base = f"http://{host}:{port}"

        req = urllib.request.Request(
            f"{base}/v1/stream", data=parts[0].read_bytes(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
            sid = json.loads(resp.read())["session"]
        assert _wait(lambda: svc.sessions._lease(sid).epoch >= 1)

        events = urllib.request.urlopen(
            f"{base}/v1/stream/events?session={sid}", timeout=120
        )

        req = urllib.request.Request(
            f"{base}/v1/stream/append", data=parts[1].read_bytes(),
            method="POST", headers={"X-Kindel-Session": sid},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            ack = json.loads(resp.read())
        assert ack["session"] == sid and ack["emitted"] is True

        # the update the append just published is on the SSE wire
        line = events.readline().decode()
        while not line.startswith("event:"):
            line = events.readline().decode()
        assert line == "event: update\n"
        data = events.readline().decode()
        assert json.loads(data.split("data: ", 1)[1])["epoch"] == (
            ack["epoch"]
        )

        req = urllib.request.Request(
            f"{base}/v1/stream/close", data=b"", method="POST",
            headers={"X-Kindel-Session": sid},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            final = json.loads(resp.read())
        assert final["closed"] is True
        assert final["fasta"] == want
        events.close()

        # append to the retired session: 404, the address error
        req = urllib.request.Request(
            f"{base}/v1/stream/append", data=b"x", method="POST",
            headers={"X-Kindel-Session": sid},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 404
        # events endpoint edges: missing param 400, unknown sid 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/v1/stream/events", timeout=30
            )
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/v1/stream/events?session=nope", timeout=30
            )
        assert ei.value.code == 404


# ------------------------------------------------------ zero recompiles


def test_warm_host_streaming_adds_zero_jit_cache_entries(tmp_path):
    """Snapshots are ordinary requests downstream of admission: on a
    warmed host a whole session (≥2 published epochs) adds ZERO
    jit-cache entries — the shared ticks reuse the warmed geometry-
    keyed executables."""
    parts = [
        make_sam(tmp_path / f"w{k}.sam", seed=90 + k) for k in range(3)
    ]
    g_before = default_registry().snapshot()
    with _service(emit_delta=1) as svc:
        def run_session():
            sid = svc.sessions.open(parts[0].read_bytes())
            # settle the open's snapshot so every later append is the
            # gate-crossing one (its ack IS the emission decision)
            assert _wait(lambda: svc.sessions._lease(sid).epoch >= 1)
            epochs = 0
            for p in parts[1:]:
                a = svc.sessions.append(sid, p.read_bytes()).result(
                    timeout=120
                )
                epochs += int(a["emitted"])
            final = svc.sessions.close(sid).result(timeout=120)
            return final["fasta"], epochs + 1  # close always emits

        fasta1, _ = run_session()  # warms every snapshot geometry
        cache_before = obs_runtime.jit_cache_sizes()
        fasta2, epochs2 = run_session()
        cache_after = obs_runtime.jit_cache_sizes()
    assert epochs2 >= 2
    assert fasta2 == fasta1
    assert cache_after == cache_before, (
        "warm-host streaming compiled something new"
    )
    # the paged instrumentation saw the session rows (PR 16 satellite)
    g_after = default_registry().snapshot()
    _ = (g_before, g_after)


# ------------------------------------------------------- fleet re-home


def test_fleet_drain_rehomes_live_session_on_survivor(tmp_path):
    from kindel_tpu.fleet import FleetService

    parts = [
        make_sam(tmp_path / f"d{k}.sam", seed=100 + k) for k in range(2)
    ]
    want = _oracle_fasta(tmp_path, "doracle.sam", parts)
    with FleetService(
        replicas=2, max_wait_s=0.02, probe_interval_s=0.05,
        emit_delta=1,
    ) as fleet:
        sid = fleet.open_stream(parts[0].read_bytes())
        home = fleet.locate_session(sid)
        assert _wait(
            lambda: home.service.sessions._lease(sid).epoch >= 1
        )
        ack = fleet.append_stream(sid, parts[1].read_bytes()).result(
            timeout=120
        )
        fleet.drain(home.replica_id)
        survivor = fleet.locate_session(sid)
        assert survivor.replica_id != home.replica_id
        assert int(
            survivor.service.metrics.snapshot().get(
                "kindel_stream_replays_total", 0
            )
        ) >= 1
        final = fleet.close_stream(sid).result(timeout=120)
    assert final["fasta"] == want
    # the epoch watermark survived the hand-off: still monotone
    assert final["epoch"] > ack["epoch"] >= 1


# ------------------------------------------------------- the flagship


def _stream_retry(fn, timeout=180.0):
    """Client-side retry ladder for the chaos window: every typed shed
    or address error means NOT merged (WAL-then-accept), so retrying
    can never double-count."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return fn()
        except (AdmissionError, KeyError, LeaseRetired) as e:
            last = e
            time.sleep(0.05)
    raise AssertionError(f"stream retries exhausted: {last!r}")


def test_fleet_chaos_streams_converge_exactly_once(tmp_path):
    """The flagship: 3 supervised replicas (per-slot journals) under an
    active wire-fault plan, 4 concurrent sessions; one replica is
    KILLED (journal replay brings its sessions back on the next life)
    and another DRAINED (hand-off re-homes its sessions on survivors)
    mid-stream. Every session converges: each final FASTA is
    sha-identical to the one-shot consensus over its concatenated
    batches — an append merged twice or dropped once would change the
    counts — and no slot's journal leaks a live session frame."""
    from kindel_tpu.fleet import FleetService

    n_sessions, n_batches = 4, 3
    batches = {
        s: [
            make_sam(
                tmp_path / f"c{s}_{k}.sam", seed=200 + 10 * s + k
            )
            for k in range(n_batches)
        ]
        for s in range(n_sessions)
    }
    oracles = {
        s: _oracle_fasta(tmp_path, f"c{s}_oracle.sam", batches[s])
        for s in range(n_sessions)
    }
    jd = tmp_path / "journal"
    plan = rfaults.activate(
        FaultPlan.parse("seed=7,serve.flush:error:times=2:after=1")
    )

    acks = {s: [] for s in range(n_sessions)}
    with FleetService(
        replicas=3, probe_interval_s=0.02, max_wait_s=0.02,
        journal_dir=str(jd), emit_delta=1,
    ) as fleet:
        sids = {
            s: _stream_retry(
                lambda s=s: fleet.open_stream(
                    batches[s][0].read_bytes()
                )
            )
            for s in range(n_sessions)
        }

        def append_all(k):
            for s in range(n_sessions):
                acks[s].append(_stream_retry(
                    lambda s=s: fleet.append_stream(
                        sids[s], batches[s][k].read_bytes()
                    ).result(timeout=180)
                ))

        append_all(1)

        # chaos, phase 1: SIGKILL the replica holding session 0 — the
        # supervisor evicts and respawns it, and the respawned life
        # replays its journal's OPEN/APPEND frames
        victim = fleet.locate_session(sids[0])
        fleet.kill_replica(victim.replica_id)

        def _all_located():
            try:
                return all(
                    fleet.locate_session(sids[s]) is not None
                    for s in range(n_sessions)
                )
            except KeyError:
                return False

        assert _wait(_all_located, 180), (
            "sessions did not come back after the kill"
        )

        # chaos, phase 2: DRAIN a different replica — its live leases
        # hand off and re-home on survivors by rendezvous rank
        other = next(
            r.replica_id for r in fleet.roster()
            if r.replica_id != victim.replica_id
        )
        fleet.drain(other)
        assert _wait(_all_located, 180)

        append_all(2)
        finals = {
            s: _stream_retry(
                lambda s=s: fleet.close_stream(sids[s]).result(
                    timeout=180
                )
            )
            for s in range(n_sessions)
        }

    # every session converged to its one-shot answer, exactly once:
    # the byte-identity is the double-count/drop detector
    for s in range(n_sessions):
        assert finals[s]["closed"] is True
        assert finals[s]["fasta"] == oracles[s], (
            f"session {s} diverged from its one-shot oracle"
        )
        # every settled append ack was a normal emission-decision ack
        for ack in acks[s]:
            assert ack["session"] == sids[s]
    # the injected wire faults fired as written (the in-replica retry
    # ladder absorbed them)
    assert plan.fired == {("serve.flush", "error"): 2}
    # zero journal leaks: every slot's journal scans to no live session
    for slot in sorted(jd.iterdir()):
        result = drec.scan(slot)
        assert not result.sessions, (
            f"{slot.name} leaked live session frames: "
            f"{sorted(result.sessions)}"
        )
