"""Unit tier — pure-function checks mirroring the reference's unit tests
(/root/reference/tests/test_kindel.py:22-57) plus kindel-tpu-specific
primitives."""

import numpy as np

from kindel_tpu import consensus, merge_by_lcs
from kindel_tpu.io.records import (
    ragged_indices,
    ragged_local_offsets,
)
from kindel_tpu.pileup import argmax_base_and_tie


def test_consensus_caller():
    pos_weight = {"A": 1, "C": 2, "G": 3, "T": 4, "N": 5}
    assert consensus(pos_weight)[0] == "N"
    assert consensus(pos_weight)[1] == 5
    assert consensus(pos_weight)[2] == 0.33
    assert consensus(pos_weight)[3] is False
    pos_weight_tie = {"A": 5, "C": 5, "G": 3, "T": 4, "N": 1}
    assert consensus(pos_weight_tie)[3] is True
    assert consensus({"A": 0, "C": 0, "G": 0, "T": 0, "N": 0}) == ("N", 0, 0, False)


def test_merge_by_lcs():
    one = (
        "AACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGG",
        "GCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACA",
    )
    two = (
        "AACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACATC",
        "GCAGATACCTACACCACCGGGGGAACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACA",
    )
    assert (
        merge_by_lcs(*one, min_overlap=7)
        == "AACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACA"
    )
    assert (
        merge_by_lcs(*two, min_overlap=7)
        == "AACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACA"
    )
    assert merge_by_lcs("AT", "CG", min_overlap=7) is None


def test_ragged_primitives():
    starts = np.array([5, 10, 0])
    lens = np.array([3, 0, 2])
    np.testing.assert_array_equal(
        ragged_indices(starts, lens), [5, 6, 7, 0, 1]
    )
    np.testing.assert_array_equal(
        ragged_local_offsets(lens), [0, 1, 2, 0, 1]
    )


def test_argmax_base_tie_semantics():
    counts = np.array(
        [
            [3, 1, 0, 0, 0],  # clear A
            [2, 2, 0, 0, 0],  # tie A/T -> argmax picks A, tie flagged
            [0, 0, 0, 0, 0],  # zero depth -> N, no tie
            [0, 0, 0, 0, 7],  # N wins outright
        ],
        dtype=np.int32,
    )
    idx, freq, tie = argmax_base_and_tie(counts)
    np.testing.assert_array_equal(idx, [0, 0, 4, 4])
    np.testing.assert_array_equal(freq, [3, 2, 0, 7])
    np.testing.assert_array_equal(tie, [False, True, False, False])


def test_version_cli(capsys):
    from kindel_tpu.cli import main

    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("kindel-tpu ")

def test_negative_cdr_gap_rejected_on_both_subcommands(capsys):
    """--cdr-gap < 0 must error (exit 2) on consensus AND batch — round 4
    validated only the consensus subcommand (ADVICE r4)."""
    import pytest

    from kindel_tpu.cli import main

    for argv in (
        ["consensus", "--cdr-gap", "-3", "x.bam"],
        ["batch", "--cdr-gap", "-3", "x.bam"],
    ):
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == 2
        assert "must be >= 0" in capsys.readouterr().err


def test_assemble_matches_per_position_oracle():
    """assemble()'s run-collapsed emit loop vs a per-position reference
    implementation, over randomized dense/sparse deletion and insertion
    masks (round 5: the emit loop stopped boolean-gathering and now cuts
    at insertion positions and deletion-run starts)."""
    from kindel_tpu.call import CallMasks, assemble

    rng = np.random.default_rng(9)
    for trial in range(300):
        L = int(rng.integers(4, 60))
        base = rng.integers(65, 69, L).astype(np.uint8)
        dm = rng.random(L) < rng.choice([0.05, 0.5, 0.9])
        im = (rng.random(L) < 0.2) & ~dm
        ins_calls = {
            int(p): b"xy" for p in np.flatnonzero(im) if rng.random() < 0.7
        }
        masks = CallMasks(
            base_char=base.copy(), del_mask=dm,
            n_mask=np.zeros(L, bool), ins_mask=im,
        )
        out = []
        for p in range(L):
            if im[p]:
                s = ins_calls.get(p)
                out.append((s.lower() if s is not None else b"N").decode())
            if not dm[p]:
                out.append(chr(base[p]))
        want = "".join(out)
        got = assemble(masks, ins_calls, None, False, 1, False).sequence
        assert got == want, (trial, got, want)
