"""Worker for the 2-process distributed test: join the localhost process
group (4 virtual CPU devices per process → 8 global), build the hybrid
dp×sp mesh, run the batched dp×sp step, print the output digest.

Usage: python tests/_dist_worker.py <process_id> <coordinator_port>
(underscore prefix: not collected by pytest)."""

import os
import sys

proc_id = int(sys.argv[1])
port = int(sys.argv[2])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import distfixture  # noqa: E402

from kindel_tpu.parallel import (  # noqa: E402
    batched_sharded_call,
    initialize_distributed,
    make_global_mesh,
)

assert (
    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=proc_id,
    )
    is True
), "process group did not come up"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert len(jax.local_devices()) == 4

mesh = make_global_mesh(dict(distfixture.AXES))
assert mesh.devices.shape == (2, 4)
# the dcn (dp) axis must be laid across processes so the sp halo stays
# within one process's devices (the ICI analogue)
for row in range(2):
    procs = {d.process_index for d in mesh.devices[row].flat}
    assert len(procs) == 1, f"sp row {row} spans processes {procs}"

outs = batched_sharded_call(
    distfixture.make_samples(), distfixture.REF_LEN, mesh
)
print("DIGEST:" + distfixture.digest(outs), flush=True)
