"""Tier-1 static guard suite — a thin driver over the whole-program
lint engine (kindel_tpu.analysis, DESIGN.md §18).

History: these invariants started life as 13 flat, single-function AST
checks in this file, each re-reading and re-parsing all of kindel_tpu/
(13 full passes per suite run). They are now rules over one shared,
parsed-once project model — the migrated hygiene guards keep their
exact recognizers and allowlists (kindel_tpu/analysis/rules/hygiene.py),
and the whole-program analyses the flat checks could not express
(trace-purity closure, lock discipline, future-settlement, knob/metric
doc conformance) run beside them. This driver asserts three things:

  1. zero non-baselined findings, per rule (the baseline —
     tools/lint_baseline.json — is the reviewed legacy-debt ledger;
     anything new fails here with the offending file:line);
  2. no stale baseline entries (a fixed finding must take its ledger
     row with it — the baseline only ever burns down);
  3. the shared model parsed each file exactly once (the perf fix this
     migration bought; the counter would catch a regression to
     per-rule re-parsing).

Rule blindness (`min_sites`) is engine-enforced: a rule that lost its
inputs emits a finding against itself, so it fails assertion 1. Per-rule
liveness against known-bad fixtures is pinned in tests/test_analysis.py.
"""

import pytest

from kindel_tpu.analysis import engine as lint_engine
from kindel_tpu.analysis import load_project

lint_engine._ensure_rules_loaded()


@pytest.fixture(scope="module")
def lint_state():
    model = load_project()
    results = lint_engine.run(model)
    baseline = lint_engine.load_baseline(
        lint_engine.default_baseline_path()
    )
    new, stale = lint_engine.diff_baseline(
        lint_engine.all_findings(results), baseline
    )
    return model, results, new, stale


@pytest.mark.parametrize("rule_id", sorted(lint_engine.RULES))
def test_rule_has_no_new_findings(lint_state, rule_id):
    _model, results, new, _stale = lint_state
    mine = [f for f in new if f.rule == rule_id]
    spec = lint_engine.RULES[rule_id]
    assert not mine, (
        f"[{rule_id}] {spec.doc.splitlines()[0]}\n"
        "new non-baselined finding(s):\n"
        + "\n".join(f"  {f.path}:{f.line}: {f.message}" for f in mine)
    )


def test_baseline_has_no_stale_entries(lint_state):
    _model, _results, _new, stale = lint_state
    assert not stale, (
        "baseline entries no longer produced by the tree — delete them "
        "from tools/lint_baseline.json so the ledger burns down:\n"
        + "\n".join(
            f"  [{e['rule']}] {e['path']}: {e['message']} "
            f"(frozen {e['frozen']}, present {e['present']})"
            for e in stale
        )
    )


def test_model_parses_each_file_exactly_once(lint_state):
    """The migration's perf contract: the whole rule set runs off one
    parse per file, and repeated loads reuse the cached model."""
    model, _results, _new, _stale = lint_state
    assert model.parse_count == len(model.modules)
    assert load_project() is model  # memoized — no second parse pass
