"""Tier-1 guard: tuning knobs resolve at config-build time, never at
trace time — no `os.environ` / `os.getenv` read may appear inside a
jit-decorated function body anywhere in kindel_tpu/ (the refactor
invariant of the tune subsystem, kindel_tpu/tune.py).

An env read inside a traced body is doubly wrong: it only runs at trace
time (so the knob silently stops responding once the kernel is cached),
and it makes compiled behavior depend on ambient process state that the
compile cache key does not capture."""

import ast
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "kindel_tpu"


def _dotted_parts(node) -> set:
    """Every Name id / Attribute attr reachable in an expression — enough
    to recognize jit in `jax.jit`, `jit`, `partial(jax.jit, ...)`,
    `functools.partial(jit, static_argnames=...)`."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _is_jit_decorated(fn) -> bool:
    return any("jit" in _dotted_parts(d) for d in fn.decorator_list)


def _env_read_lines(fn) -> list:
    hits = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr == "environ":
            hits.append(n.lineno)
        elif isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr == "getenv") or (
                isinstance(f, ast.Name) and f.id == "getenv"
            ):
                hits.append(n.lineno)
    return hits


def test_no_env_reads_inside_jit_traced_function_bodies():
    offenders = []
    jitted = 0
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_jit_decorated(node):
                continue
            jitted += 1
            for line in _env_read_lines(node):
                offenders.append(
                    f"{py.relative_to(PKG.parent)}:{line} "
                    f"(inside jitted `{node.name}`)"
                )
    assert not offenders, (
        "os.environ read inside a jit-traced body — tuning knobs must "
        "resolve at config-build time (kindel_tpu.tune):\n"
        + "\n".join(offenders)
    )
    # the guard must actually be seeing the kernels: if this count ever
    # drops to ~0 the detector went blind, not the codebase clean
    assert jitted >= 8, f"only {jitted} jit-decorated functions found"
