"""Tier-1 AST guards over kindel_tpu/ — invariants that are cheap to
state and expensive to debug when broken:

  1. tuning knobs resolve at config-build time, never at trace time —
     no `os.environ` / `os.getenv` read inside a jit-decorated body
     (the refactor invariant of the tune subsystem, kindel_tpu/tune.py);
  2. no env read inside `__init__` either — instrumented classes must
     not cache ambient env state at construction (the PhaseTimer
     trace-dir bug: an env var exported between construction and
     trace-start silently lost);
  3. durations come from `time.perf_counter()` — `time.time()` is a
     wall clock subject to NTP steps and is banned except for an
     explicit timestamp allowlist;
  4. every metric registered through an obs registry carries help text
     (also enforced at runtime by MetricsRegistry, but the static guard
     catches sites the tests never execute);
  5. zlib is a single-chokepoint dependency — `zlib.decompress` /
     `zlib.decompressobj` (and `import zlib` itself) may only appear
     inside `kindel_tpu/io/`, so every inflate goes through the
     parallel-ingest path (kindel_tpu/io/inflate.py) and its metrics /
     ordering / RSS-bound invariants;
  6. nothing under `kindel_tpu/io/` imports jax — inflate pool workers
     execute only io/ code, and a worker thread tripping a lazy backend
     initialization mid-stream would deadlock or double-init the
     runtime.

An env read inside a traced body is doubly wrong: it only runs at trace
time (so the knob silently stops responding once the kernel is cached),
and it makes compiled behavior depend on ambient process state that the
compile cache key does not capture."""

import ast
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "kindel_tpu"


def _dotted_parts(node) -> set:
    """Every Name id / Attribute attr reachable in an expression — enough
    to recognize jit in `jax.jit`, `jit`, `partial(jax.jit, ...)`,
    `functools.partial(jit, static_argnames=...)`."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _is_jit_decorated(fn) -> bool:
    return any("jit" in _dotted_parts(d) for d in fn.decorator_list)


def _env_read_lines(fn) -> list:
    hits = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr == "environ":
            hits.append(n.lineno)
        elif isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr == "getenv") or (
                isinstance(f, ast.Name) and f.id == "getenv"
            ):
                hits.append(n.lineno)
    return hits


def test_no_env_reads_inside_jit_traced_function_bodies():
    offenders = []
    jitted = 0
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_jit_decorated(node):
                continue
            jitted += 1
            for line in _env_read_lines(node):
                offenders.append(
                    f"{py.relative_to(PKG.parent)}:{line} "
                    f"(inside jitted `{node.name}`)"
                )
    assert not offenders, (
        "os.environ read inside a jit-traced body — tuning knobs must "
        "resolve at config-build time (kindel_tpu.tune):\n"
        + "\n".join(offenders)
    )
    # the guard must actually be seeing the kernels: if this count ever
    # drops to ~0 the detector went blind, not the codebase clean
    assert jitted >= 8, f"only {jitted} jit-decorated functions found"


def test_no_env_reads_inside_init_methods():
    """Instrumented classes (PhaseTimer, tracers, workers) must resolve
    env state where it is used, never cache it at construction — an env
    var exported between __init__ and use must win."""
    offenders = []
    inits = 0
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for fn in node.body:
                if (
                    isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name == "__init__"
                ):
                    inits += 1
                    for line in _env_read_lines(fn):
                        offenders.append(
                            f"{py.relative_to(PKG.parent)}:{line} "
                            f"({node.name}.__init__)"
                        )
    assert not offenders, (
        "os.environ read cached at __init__ time — resolve it where it "
        "is used instead:\n" + "\n".join(offenders)
    )
    assert inits >= 10, f"only {inits} __init__ methods found"


#: wall-clock *timestamps* (not durations) where time.time() is the
#: point: the tune store's recorded_at field is read by humans
_TIME_TIME_ALLOWLIST = {("tune.py", "record")}


def test_no_time_time_for_durations():
    """Durations must come from time.perf_counter() — time.time() is
    subject to NTP steps/smearing, and a negative "duration" in a span
    or a latency histogram is a debugging rabbit hole. Timestamp uses
    must be allowlisted explicitly."""

    def enclosing_functions(tree):
        out = {}  # node -> function name

        def visit(node, fname):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fname = node.name
            out[node] = fname
            for child in ast.iter_child_nodes(node):
                visit(child, fname)

        visit(tree, "<module>")
        return out

    offenders = []
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        owners = enclosing_functions(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr == "time"
                and isinstance(f.value, ast.Name)
                and f.value.id == "time"
            ):
                continue
            key = (py.name, owners.get(node, "<module>"))
            if key in _TIME_TIME_ALLOWLIST:
                continue
            offenders.append(
                f"{py.relative_to(PKG.parent)}:{node.lineno} "
                f"(in {owners.get(node, '<module>')})"
            )
    assert not offenders, (
        "time.time() used outside the timestamp allowlist — use "
        "time.perf_counter() for durations:\n" + "\n".join(offenders)
    )


def test_metric_registrations_carry_help_text():
    """Every `.counter(...)` / `.gauge(...)` / `.histogram(...)` /
    `.info(...)` registration call passes help text (second positional
    arg or help_text=), and a literal help string is non-empty — the
    exposition renders `# HELP` verbatim, and a blank one is useless to
    whoever is staring at the dashboard."""
    offenders = []
    registrations = 0
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr in ("counter", "gauge", "histogram", "info")
            ):
                continue
            registrations += 1
            help_arg = None
            if len(node.args) >= 2:
                help_arg = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "help_text":
                        help_arg = kw.value
            loc = f"{py.relative_to(PKG.parent)}:{node.lineno}"
            if help_arg is None:
                offenders.append(f"{loc} (.{f.attr} without help text)")
            elif isinstance(help_arg, ast.Constant) and not help_arg.value:
                offenders.append(f"{loc} (.{f.attr} with empty help)")
    assert not offenders, (
        "metric registered without help text:\n" + "\n".join(offenders)
    )
    # blindness check, as for the jit guard above
    assert registrations >= 15, (
        f"only {registrations} registration calls found"
    )


def test_zlib_only_inside_io_package():
    """The inflate chokepoint invariant: any `import zlib` (or direct
    `zlib.decompress` / `zlib.decompressobj` call) outside kindel_tpu/io/
    bypasses the parallel inflater — its ordering guarantee, its bounded
    in-flight window, and its ingest metrics. New decompression sites
    must route through kindel_tpu.io.inflate / kindel_tpu.io.bgzf."""
    offenders = []
    io_sites = 0
    for py in sorted(PKG.rglob("*.py")):
        inside_io = "io" in py.relative_to(PKG).parts[:1]
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Import):
                if any(a.name.split(".")[0] == "zlib" for a in node.names):
                    hit = "import zlib"
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "zlib":
                    hit = "from zlib import"
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("decompress", "decompressobj")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "zlib"
                ):
                    hit = f"zlib.{f.attr}"
            if hit is None:
                continue
            if inside_io:
                io_sites += 1
            else:
                offenders.append(
                    f"{py.relative_to(PKG.parent)}:{node.lineno} ({hit})"
                )
    assert not offenders, (
        "zlib used outside kindel_tpu/io/ — all inflation must go "
        "through the single chokepoint (kindel_tpu.io.inflate):\n"
        + "\n".join(offenders)
    )
    # blindness check: the chokepoint itself must be visible
    assert io_sites >= 3, f"only {io_sites} zlib sites found in io/"


def test_io_package_never_imports_jax():
    """Inflate pool workers (kindel_tpu/io/inflate.py) run arbitrary
    io/-resident code on non-main threads; an `import jax` reachable
    from io/ could make a worker thread initialize the backend (slow,
    non-reentrant, and on a tunneled relay potentially hanging the whole
    ingest). io/ stays a jax-free layer — L0 by construction."""
    offenders = []
    checked = 0
    for py in sorted((PKG / "io").rglob("*.py")):
        checked += 1
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name == "jax" or name.startswith("jax."):
                    offenders.append(
                        f"{py.relative_to(PKG.parent)}:{node.lineno} "
                        f"(imports {name})"
                    )
    assert not offenders, (
        "jax import inside kindel_tpu/io/ — the ingest layer (and the "
        "inflate worker threads that execute it) must stay jax-free:\n"
        + "\n".join(offenders)
    )
    assert checked >= 8, f"only {checked} io/ modules found"


def test_fleet_package_never_imports_jax():
    """The fleet tier (kindel_tpu/fleet/) routes tickets and supervises
    replicas; only the ConsensusServices it assembles ever touch the
    device. A direct jax import here would let the supervisor's probe
    thread or the router's placement path trip backend initialization —
    and would silently couple eviction/drain decisions to device state.
    L8 stays jax-free by construction, the same bar as io/."""
    offenders = []
    checked = 0
    for py in sorted((PKG / "fleet").rglob("*.py")):
        checked += 1
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name == "jax" or name.startswith("jax."):
                    offenders.append(
                        f"{py.relative_to(PKG.parent)}:{node.lineno} "
                        f"(imports {name})"
                    )
    assert not offenders, (
        "jax import inside kindel_tpu/fleet/ — the fleet tier "
        "(router/supervisor) must never touch the device:\n"
        + "\n".join(offenders)
    )
    assert checked >= 4, f"only {checked} fleet/ modules found"


#: handler calls that count as "the failure was handled, not swallowed":
#: resolving a request future, recording it on the breaker/metrics/
#: probe ladder, or handing it to the degrade ladder (which itself
#: settles every future). `record_probe_failure` is the fleet
#: supervisor's handler: a probe/restart exception folds into the
#: replica's consecutive-probe score (and /healthz surfaces it).
_FAILURE_HANDLERS = {
    "_fail", "fail", "_settle", "set_exception", "record_failure",
    "_recover", "record_degrade", "record_probe_failure",
}

#: deliberately-swallowing sites, each with a local reason:
#: service._warm — warmup is best-effort, failure is recorded on
#: _warm_error and /healthz; service.consensus_post_response — the
#: handler IS the failure path (it converts to an HTTP 5xx response,
#: shared by the single service and the fleet front);
#: service._aot_provenance — a health probe that must answer even when
#: the AOT store layer is broken (degrades to "disabled", loses no
#: request); fleet service._replica_healthz — the fleet health document
#: must render even when one replica's healthz is broken (that IS the
#: finding: the replica reports "down")
_SWALLOW_ALLOWLIST = {
    ("serve/service.py", "_warm"),
    ("serve/service.py", "consensus_post_response"),
    ("serve/service.py", "_aot_provenance"),
    ("fleet/service.py", "_replica_healthz"),
}


def test_aot_compile_surface_confined_to_aot_module():
    """One AOT surface: `.lower(...).compile(...)` chains and PjRt
    executable (de)serialization may only appear in kindel_tpu/aot.py.
    A second lowering/deserialization site would fork the store keying,
    the parity discipline, and the warn-once fallback — exactly the
    kind of drift that ends with a replica silently serving a kernel
    the store never verified. Dispatch sites consult the aot registry;
    they never compile or deserialize themselves."""
    _AOT_ATTRS = {
        "deserialize_and_load",
        "deserialize_executable",
        "serialize_executable",
        "runtime_executable",
    }
    offenders = []
    aot_sites = 0
    for py in sorted(PKG.rglob("*.py")):
        is_aot = py.relative_to(PKG).as_posix() == "aot.py"
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "compile"
                    and isinstance(f.value, ast.Call)
                    and isinstance(f.value.func, ast.Attribute)
                    and f.value.func.attr == "lower"
                ):
                    hit = ".lower().compile()"
                elif isinstance(f, ast.Attribute) and f.attr in _AOT_ATTRS:
                    hit = f".{f.attr}()"
            elif isinstance(node, ast.Import):
                if any(
                    "serialize_executable" in a.name for a in node.names
                ):
                    hit = "import serialize_executable"
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if "serialize_executable" in mod or any(
                    a.name == "serialize_executable" for a in node.names
                ):
                    hit = "import serialize_executable"
            if hit is None:
                continue
            if is_aot:
                aot_sites += 1
            else:
                offenders.append(
                    f"{py.relative_to(PKG.parent)}:{node.lineno} ({hit})"
                )
    assert not offenders, (
        "AOT lowering/executable-(de)serialization outside "
        "kindel_tpu/aot.py — route it through the one AOT surface:\n"
        + "\n".join(offenders)
    )
    # blindness check: the surface itself must be visible
    assert aot_sites >= 3, f"only {aot_sites} AOT sites found in aot.py"


#: ragged/pack.py functions on the superbatch hot path — they run once
#: per dispatched flush, so per-request Python cost must stay O(1) array
#: bookkeeping (comprehensions feeding concatenate/cumsum/fromiter),
#: never an explicit loop that could hide per-element work
_RAGGED_HOT_FUNCTIONS = {"build_segment_table", "pack_superbatch"}


def test_ragged_pack_hot_path_is_vectorized():
    """Vectorized-only lint over the ragged packer (same style as the
    zlib/jax confinement guards): no `for`/`while` statement anywhere
    inside the hot functions of kindel_tpu/ragged/pack.py — numpy does
    the per-element work; Python touches each request exactly once via
    comprehensions. (The `.lower().compile()` confinement guard above
    already covers ragged/: its kernel consults the aot registry and
    never lowers anything itself.)"""
    path = PKG / "ragged" / "pack.py"
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    found = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in _RAGGED_HOT_FUNCTIONS:
            continue
        found.add(node.name)
        for n in ast.walk(node):
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
                offenders.append(
                    f"kindel_tpu/ragged/pack.py:{n.lineno} "
                    f"({type(n).__name__} inside `{node.name}`)"
                )
    assert not offenders, (
        "explicit loop on the ragged pack hot path — keep it vectorized "
        "(numpy concatenate/cumsum over per-request comprehensions):\n"
        + "\n".join(offenders)
    )
    # blindness check: renaming a hot function must fail the guard, not
    # silently skip it
    assert found == _RAGGED_HOT_FUNCTIONS, (
        f"hot functions missing from ragged/pack.py: "
        f"{_RAGGED_HOT_FUNCTIONS - found}"
    )


def test_no_silent_exception_swallow_in_serve_or_resilience():
    """Every `except Exception` / `except BaseException` in the
    serving, resilience, and fleet layers must re-raise, resolve a
    future, or record the failure — a handler that does none of those
    is exactly how an admitted request gets silently lost (the
    invariant the chaos suites enforce dynamically; this guard catches
    the sites tests never reach)."""

    def names_in(node) -> set:
        out = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                out.add(n.id)
            elif isinstance(n, ast.Attribute):
                out.add(n.attr)
        return out

    def catches_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:  # bare `except:`
            return True
        return bool(
            names_in(handler.type) & {"Exception", "BaseException"}
        )

    def handles_failure(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                f = n.func
                name = (
                    f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None
                )
                if name in _FAILURE_HANDLERS:
                    return True
        return False

    def enclosing_functions(tree):
        out = {}

        def visit(node, fname):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fname = node.name
            out[node] = fname
            for child in ast.iter_child_nodes(node):
                visit(child, fname)

        visit(tree, "<module>")
        return out

    offenders = []
    sites = 0
    for sub in ("serve", "resilience", "fleet"):
        for py in sorted((PKG / sub).rglob("*.py")):
            rel = str(py.relative_to(PKG)).replace("\\", "/")
            tree = ast.parse(py.read_text(), filename=str(py))
            owners = enclosing_functions(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not catches_broad(node):
                    continue
                sites += 1
                key = (rel, owners.get(node, "<module>"))
                if key in _SWALLOW_ALLOWLIST:
                    continue
                if not handles_failure(node):
                    offenders.append(
                        f"kindel_tpu/{rel}:{node.lineno} "
                        f"(in {owners.get(node, '<module>')})"
                    )
    assert not offenders, (
        "broad except that neither re-raises, resolves a future, nor "
        "records the failure — add handling or extend "
        "_SWALLOW_ALLOWLIST with a justification:\n" + "\n".join(offenders)
    )
    # blindness check: the serve/resilience layers deliberately hold
    # several isolation boundaries; ~0 means the detector went blind
    assert sites >= 5, f"only {sites} broad except sites found"
