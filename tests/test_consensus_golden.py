"""Functional tier — golden-file FASTA parity.

Every BAM/SAM in the corpus runs through the kindel-tpu CLI (in-process) and
the FASTA output is compared case-insensitively against the reference
repository's checked-in expected outputs — the same contract the reference's
own functional tests enforce (/root/reference/tests/test_kindel.py:114-278).
"""

import io
from contextlib import redirect_stderr, redirect_stdout

import pytest

from kindel_tpu.cli import main
from kindel_tpu.io.fasta import read_fasta


def run_consensus(path, *flags) -> dict[str, str]:
    out, err = io.StringIO(), io.StringIO()
    with redirect_stdout(out), redirect_stderr(err):
        rc = main(["consensus", *flags, str(path)])
    assert rc == 0
    records = {}
    name = None
    for line in out.getvalue().splitlines():
        if line.startswith(">"):
            name = line[1:]
            records[name] = ""
        elif name is not None:
            records[name] += line
    return records


def expected_records(fa_path) -> dict[str, str]:
    return {r.name: r.sequence for r in read_fasta(fa_path)}


def _bams(data_root, sub, suffix=".bam"):
    d = data_root / sub
    return sorted(p for p in d.iterdir() if p.suffix == suffix)


# ---- bwa_mem corpus: single-ref HCV BAMs ----

@pytest.mark.parametrize("i", range(1, 7))
def test_bwa_default(data_root, i):
    path = data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam"
    expected = next(iter(expected_records(path.with_suffix(".fa")).values()))
    observed = next(iter(run_consensus(path).values()))
    assert observed.upper() == expected.upper()


@pytest.mark.parametrize("i", range(1, 7))
def test_bwa_realign(data_root, i):
    path = data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam"
    expected = next(
        iter(expected_records(path.with_suffix(".realign.fa")).values())
    )
    observed = next(iter(run_consensus(path, "-r").values()))
    assert observed.upper() == expected.upper()


# ---- minimap2 corpus: multi-contig + gp120 ----

def test_mm2_default(data_root):
    for path in _bams(data_root, "data_minimap2"):
        expected = expected_records(path.with_suffix(".fa"))
        observed = run_consensus(path)
        for name, seq in expected.items():
            assert observed[name].upper() == seq.upper(), path.name


def test_mm2_realign(data_root):
    for path in _bams(data_root, "data_minimap2"):
        fa = path.with_suffix(".realign.fa")
        if not fa.exists():
            continue
        expected = expected_records(fa)
        observed = run_consensus(path, "-r")
        for name, seq in expected.items():
            assert observed[name].upper() == seq.upper(), path.name


# ---- ext corpus: five-contig SAMs from issue 23 ----

EXT_DEFAULT = ["1.issue23.debug.sam", "2.issue23.bc63.sam", "3.issue23.bc75.sam"]
EXT_REALIGN = ["1.issue23.debug.sam", "2.issue23.bc63.sam"]
# 3.issue23.bc75.sam realign is a known-failure in the reference itself
# ("Kindel 1.2 adds an unwanted insertion at 1284",
# /root/reference/tests/test_kindel.py:281-299) — excluded there, excluded here.


@pytest.mark.parametrize("fn", EXT_DEFAULT)
def test_ext_default(data_root, fn):
    path = data_root / "data_ext" / fn
    expected = next(iter(expected_records(path.with_suffix(".fa")).values()))
    observed = next(iter(run_consensus(path).values()))
    assert observed.upper() == expected.upper()


@pytest.mark.parametrize("fn", EXT_REALIGN)
def test_ext_realign(data_root, fn):
    path = data_root / "data_ext" / fn
    expected = next(
        iter(expected_records(path.with_suffix(".realign.fa")).values())
    )
    observed = next(iter(run_consensus(path, "-r").values()))
    assert observed.upper() == expected.upper()


# ---- CDR engine: exact clip-consensus strings ----

def test_cdrp_strings(data_root):
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment
    from kindel_tpu.pileup import build_pileups
    from kindel_tpu.realign import cdrp_consensuses

    ev = extract_events(
        load_alignment(data_root / "data_bwa_mem" / "1.1.sub_test.bam")
    )
    pileup = next(iter(build_pileups(ev).values()))
    cdrps = cdrp_consensuses(pileup, clip_decay_threshold=0.1, mask_ends=10)
    assert (
        cdrps[0][0].seq
        == "AACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACATCCAGCTGATCAACA"
    )
    assert (
        cdrps[0][1].seq
        == "AGCGTCGATGCAGATACCTACACCACCGGGGGAACTGCCGCTAGGGGCGCGTTCGGGCTCGCCAACATCTTCAGTCCGGGCGCTAAGCAGAACA"
    )
