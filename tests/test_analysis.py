"""kindel_tpu.analysis test suite: engine unit tests (model cache,
call-graph closure, baseline match/expiry, SARIF shape, blindness
floors), per-rule liveness against the known-bad fixture corpus under
tests/lint_fixtures/ (every registered rule MUST fire there — a
silently-blind analyzer is itself a test failure), mutation spot checks
over real package sources, and the `kindel lint` CLI contract that
tier-1 runs."""

import ast
import json
import textwrap
from pathlib import Path

import pytest

from kindel_tpu.analysis import build_project, load_project
from kindel_tpu.analysis import engine as lint_engine
from kindel_tpu.analysis.engine import (
    Finding,
    all_findings,
    diff_baseline,
    load_baseline,
    render_sarif,
    write_baseline,
)

lint_engine._ensure_rules_loaded()

REPO = Path(__file__).resolve().parent.parent
FIXTURE_PKG = Path(__file__).resolve().parent / "lint_fixtures" / "proj" / "kindel_tpu"


# ------------------------------------------------------------------ model

def _mk_pkg(tmp_path, files: dict) -> Path:
    pkg = tmp_path / "kindel_tpu"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return pkg


def test_call_graph_closure_crosses_modules(tmp_path):
    pkg = _mk_pkg(tmp_path, {
        "a.py": """
            from kindel_tpu.b import middle

            def entry():
                return middle()
            """,
        "b.py": """
            def middle():
                return deep()

            def deep():
                return 1
            """,
    })
    model = build_project(pkg)
    entry = next(f for f in model.functions if f.name == "entry")
    names = {f.name for f in model.reachable(entry)}
    assert {"entry", "middle", "deep"} <= names


def test_self_call_resolves_through_base_class(tmp_path):
    pkg = _mk_pkg(tmp_path, {
        "base.py": """
            class Base:
                def helper(self):
                    return 1
            """,
        "child.py": """
            from kindel_tpu.base import Base

            class Child(Base):
                def run(self):
                    return self.helper()
            """,
    })
    model = build_project(pkg)
    run = next(f for f in model.functions if f.name == "run")
    assert "helper" in {f.name for f in model.resolve_calls(run)}


def test_generic_attr_calls_do_not_resolve(tmp_path):
    """d.get(k) must not alias onto an unrelated first-party `get`."""
    pkg = _mk_pkg(tmp_path, {
        "q.py": """
            class Q:
                def get(self):
                    return 1
            """,
        "user.py": """
            def reads_dict(d):
                return d.get("k")
            """,
    })
    model = build_project(pkg)
    fn = next(f for f in model.functions if f.name == "reads_dict")
    assert model.resolve_calls(fn) == []


def test_model_cache_one_parse_per_file(tmp_path):
    pkg = _mk_pkg(tmp_path, {"a.py": "x = 1\n", "b.py": "y = 2\n"})
    m1 = load_project(pkg)
    m2 = load_project(pkg)
    assert m1 is m2
    assert m1.parse_count == len(m1.modules) == 2


def test_lock_facts_condition_aliases_wrapped_lock(tmp_path):
    pkg = _mk_pkg(tmp_path, {
        "c.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)
                    self._own = threading.Condition()
            """,
    })
    model = build_project(pkg)
    cinfo = model.classes[("kindel_tpu/c.py", "C")]
    assert cinfo.canonical_lock("_cond") == "_lock"
    assert cinfo.canonical_lock("_own") == "_own"
    assert cinfo.lock_names() == {"_lock", "_cond", "_own"}


# ----------------------------------------------------------------- engine

def test_baseline_match_and_expiry(tmp_path):
    f1 = Finding("r", "error", "p.py", 3, "legacy debt")
    f2 = Finding("r", "error", "p.py", 9, "fresh debt")
    path = tmp_path / "baseline.json"
    write_baseline(path, [f1])
    baseline = load_baseline(path)

    # exact match: nothing new, nothing stale
    new, stale = diff_baseline([f1], baseline)
    assert new == [] and stale == []

    # a line move does not churn the ledger (identity excludes line)
    moved = Finding("r", "error", "p.py", 42, "legacy debt")
    new, stale = diff_baseline([moved], baseline)
    assert new == [] and stale == []

    # new debt fails even while legacy debt persists
    new, stale = diff_baseline([f1, f2], baseline)
    assert [f.message for f in new] == ["fresh debt"] and stale == []

    # fixed debt leaves a stale entry (strict mode burns it down)
    new, stale = diff_baseline([f2], baseline)
    assert len(stale) == 1 and stale[0]["message"] == "legacy debt"

    # duplicate occurrences count: two of a once-baselined finding = new
    new, _ = diff_baseline([f1, f1], baseline)
    assert len(new) == 1


def test_blindness_floor_is_a_finding(tmp_path):
    """An (almost) empty package starves every min_sites rule — the
    engine must turn that into findings, not silence."""
    pkg = _mk_pkg(tmp_path, {"empty.py": "x = 1\n"})
    results = lint_engine.run(build_project(pkg))
    blind = [
        f for f in all_findings(results) if "detector blind" in f.message
    ]
    blind_rules = {f.rule for f in blind}
    assert "jit-env-read" in blind_rules
    assert "metric-help-text" in blind_rules
    # and with the floor waived (fixture mode), the same model is clean
    results = lint_engine.run(build_project(pkg), check_blindness=False)
    assert not any(
        "detector blind" in f.message for f in all_findings(results)
    )


def test_sarif_document_shape():
    model = build_project(FIXTURE_PKG)
    results = lint_engine.run(model, check_blindness=False)
    new, stale = diff_baseline(all_findings(results), {})
    doc = json.loads(render_sarif(results, new, stale))
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "kindel-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == set(lint_engine.RULES)
    assert run["results"], "fixture corpus must produce results"
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["level"] in ("error", "warning")
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
    assert all(r["baselineState"] == "new" for r in run["results"])


# ------------------------------------------------- fixture corpus liveness

@pytest.fixture(scope="module")
def fixture_results():
    model = build_project(FIXTURE_PKG)
    return lint_engine.run(model, check_blindness=False)


@pytest.mark.parametrize("rule_id", sorted(lint_engine.RULES))
def test_rule_fires_on_known_bad_fixture(fixture_results, rule_id):
    """Per-rule liveness: every registered rule must detect its
    deliberately-bad fixture (tests/lint_fixtures/proj). Registering a
    new rule without a firing fixture fails here — a silently-blind
    analyzer is itself a test failure."""
    result = next(r for r in fixture_results if r.spec.id == rule_id)
    assert result.findings, (
        f"rule {rule_id} found nothing in the known-bad fixture corpus "
        "— add a fixture it fires on under tests/lint_fixtures/proj/"
    )


def test_fixture_scope_extension_hits_parallel(fixture_results):
    """The silent-swallow scope extension (satellite): the rule must
    fire in parallel/ (and ragged/ shares the same scope list)."""
    swallow = next(
        r for r in fixture_results if r.spec.id == "silent-swallow"
    )
    assert any("parallel/" in f.path for f in swallow.findings)


def test_fixture_scope_extension_hits_meshexec(fixture_results):
    """The meshexec scope extension (PR 14 satellite): the parallel/
    tier now sits inside the future-settlement exactly-once contract
    (the sharded launch/unpack path owns admitted futures) and its jit
    kernels inside the trace-purity closure — one known-bad fixture per
    rule scope."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "parallel/leaky_future" in f.path
        for f in by_id["future-settlement"].findings
    )
    purity = [
        f for f in by_id["trace-purity"].findings
        if "parallel/" in f.path
    ]
    assert purity and all("_mesh_width" in f.message for f in purity)


def test_fixture_fleet_rpc_scope(fixture_results):
    """The fleet RPC tier (PR 12 satellite): the wire code paths sit
    inside both exactly-once disciplines — a swallowed transport error
    fires silent-swallow, and an inner future leaked on a
    connect-refused path fires future-settlement — each proven live on
    a known-bad fixture under fleet/."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "fleet/rpc_swallow" in f.path
        for f in by_id["silent-swallow"].findings
    )
    assert any(
        "fleet/rpc_leaky_future" in f.path
        for f in by_id["future-settlement"].findings
    )


def test_fixture_scope_extension_hits_devingest(fixture_results):
    """The devingest scope extension (PR 10 satellite): the new package
    is covered by the silent-swallow lint, zlib stays confined to io/
    (so devingest/ is zlib-free), and its jitted kernels sit inside the
    trace-purity closure — one known-bad fixture per rule scope."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "devingest/" in f.path for f in by_id["silent-swallow"].findings
    )
    assert any(
        "devingest/" in f.path for f in by_id["zlib-confinement"].findings
    )
    purity = [
        f for f in by_id["trace-purity"].findings
        if "devingest/" in f.path
    ]
    assert purity and all("_block_width" in f.message for f in purity)


def test_fixture_scope_extension_hits_paged(fixture_results):
    """The paged scope extension (PR 11 satellite): the continuous-
    superbatching tier is covered by the silent-swallow lint, the
    future-settlement exactly-once contract, and the trace-purity
    closure — one known-bad fixture per rule scope."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "paged/" in f.path for f in by_id["silent-swallow"].findings
    )
    assert any(
        "paged/" in f.path for f in by_id["future-settlement"].findings
    )
    purity = [
        f for f in by_id["trace-purity"].findings if "paged/" in f.path
    ]
    assert purity and all("_page_slots" in f.message for f in purity)


def test_fixture_scope_extension_hits_emit(fixture_results):
    """The emit scope extension (PR 13 satellite): the device-rendered
    emission tier is covered by the silent-swallow lint and the
    future-settlement contract, and the new download-confinement rule
    fires on an undeclared np.asarray/device_get/block_until_ready in
    a jax-importing module — one known-bad fixture per rule scope."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "emit/" in f.path for f in by_id["silent-swallow"].findings
    )
    assert any(
        "emit/" in f.path for f in by_id["future-settlement"].findings
    )
    dl = [
        f for f in by_id["download-confinement"].findings
        if "emit/sneaky_download" in f.path
    ]
    # all three undeclared-materialization spellings fire
    assert len(dl) == 3, dl


def test_fixture_scope_extension_hits_durable(fixture_results):
    """The durable scope extension (PR 15 satellite): the admission
    journal + recovery tier is covered by the silent-swallow lint (a
    swallowed journal error silently converts "durable" into "best
    effort") and the future-settlement contract (a leaked recovery
    claim strands every wire resubmission of that key) — one known-bad
    fixture per rule scope."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "durable/swallow" in f.path
        for f in by_id["silent-swallow"].findings
    )
    assert any(
        "durable/leaky_recovery" in f.path
        for f in by_id["future-settlement"].findings
    )


def test_fixture_scope_extension_hits_sessions(fixture_results):
    """The sessions scope extension (PR 16 satellite): the streaming
    lane is covered by the silent-swallow lint (a swallowed snapshot
    failure strands a client mid-stream), the future-settlement
    exactly-once contract (a leaked append ack blocks the client
    forever), and the lock-discipline rule (the emission-gate decision
    must not race the merge) — known-bad fixtures for all three."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "sessions/swallow" in f.path
        for f in by_id["silent-swallow"].findings
    )
    assert any(
        "sessions/leaky_lease" in f.path
        for f in by_id["future-settlement"].findings
    )
    assert any(
        "sessions/leaky_lease" in f.path
        for f in by_id["lock-guarded-by"].findings
    )


def test_fixture_scope_extension_hits_obs(fixture_results):
    """The obs scope extension (PR 18 satellite): the observability
    plane is covered by the silent-swallow lint (a swallowed
    trace-collection error blinds the operator exactly when the data
    mattered) and the future-settlement contract (a leaked collection
    ack blocks the caller forever) — one known-bad fixture per rule
    scope."""
    by_id = {r.spec.id: r for r in fixture_results}
    assert any(
        "obs/swallow" in f.path
        for f in by_id["silent-swallow"].findings
    )
    assert any(
        "obs/leaky_collect" in f.path
        for f in by_id["future-settlement"].findings
    )


def test_purity_fixture_needs_the_closure(fixture_results):
    """The chained fixture's jit body is clean — only the call-graph
    walk sees the env read two calls deep, which is exactly what the
    old decorated-body-only guard could not do."""
    purity = next(
        r for r in fixture_results if r.spec.id == "trace-purity"
    )
    chained = [
        f for f in purity.findings if f.path.endswith("purity_chain.py")
    ]
    assert chained and all(
        "_read_ambient_state" in f.message for f in chained
    )
    direct = next(
        r for r in fixture_results if r.spec.id == "jit-env-read"
    )
    assert not any(
        f.path.endswith("purity_chain.py") for f in direct.findings
    )


# -------------------------------------------------- mutation spot checks

def _mutate_first_jitted(src: str) -> str:
    """Insert an env read at the top of the first jit-decorated function
    of real package source (AST-level, so formatting never breaks it)."""
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any("jit" in ast.dump(d) for d in node.decorator_list):
                inject = ast.parse(
                    "import os\n_leak = os.environ.get('X')"
                ).body
                node.body = inject + node.body
                return ast.unparse(ast.fix_missing_locations(tree))
    raise AssertionError("no jit-decorated function found to mutate")


def test_mutated_real_kernel_is_detected(tmp_path):
    """Mutation spot check over real code: injecting an env read into a
    real jitted kernel must be flagged by the migrated rule (same
    offenders detected as the pre-migration guard)."""
    real = (REPO / "kindel_tpu" / "pileup_jax.py").read_text()
    pkg = _mk_pkg(tmp_path, {"pileup_jax.py": _mutate_first_jitted(real)})
    results = lint_engine.run(
        build_project(pkg), rule_ids=["jit-env-read"],
        check_blindness=False,
    )
    assert results[0].findings, "mutated jitted kernel not detected"


def test_mutated_real_pack_loop_is_detected(tmp_path):
    """Turning real ragged/pack.py's vectorized hot path into a loop
    must be flagged."""
    real = (REPO / "kindel_tpu" / "ragged" / "pack.py").read_text()
    tree = ast.parse(real)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "build_segment_table"
        ):
            loop = ast.parse("for _i in range(3):\n    pass").body
            node.body = loop + node.body
            break
    mutated = ast.unparse(ast.fix_missing_locations(tree))
    pkg = _mk_pkg(tmp_path, {"ragged/pack.py": mutated})
    results = lint_engine.run(
        build_project(pkg), rule_ids=["ragged-pack-vectorized"],
        check_blindness=False,
    )
    assert any("For loop" in f.message for f in results[0].findings)


# ---------------------------------------------------------- CLI contract

def test_cli_lint_strict_is_clean(capsys):
    """The tier-1 wrapper: `kindel lint --strict` exits 0 on the tree —
    all legacy findings baselined, none stale, no blind rules."""
    from kindel_tpu import cli

    rc = cli.main(["lint", "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"kindel lint --strict failed:\n{out}"
    assert "0 new" in out and "0 stale" in out


def test_cli_lint_json_format(capsys):
    from kindel_tpu import cli

    rc = cli.main(["lint", "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(doc["rules"]) == set(lint_engine.RULES)
    assert doc["new"] == []
    assert doc["wall_s"] >= 0


def test_cli_lint_sarif_format(capsys):
    from kindel_tpu import cli

    rc = cli.main(["lint", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["version"] == "2.1.0"


def test_cli_lint_unknown_rule_errors(capsys):
    from kindel_tpu import cli

    assert cli.main(["lint", "--rules", "no-such-rule"]) == 2


def test_cli_lint_without_baseline_reports_legacy(capsys):
    """--baseline none shows the raw debt: the baselined legacy findings
    become 'new' and the exit code says so."""
    from kindel_tpu import cli

    rc = cli.main(["lint", "--baseline", "none",
                   "--rules", "lock-guarded-by"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "lock-guarded-by" in out


def test_lint_provenance_object():
    """bench.py's `lint` provenance: rule count, finding count, wall
    seconds — the analysis cost tracked like every other stage."""
    from kindel_tpu.analysis import lint_provenance

    prov = lint_provenance()
    assert prov["rules"] == len(lint_engine.RULES)
    assert prov["new"] == 0 and prov["stale_baseline"] == 0
    assert prov["findings"] >= prov["new"]
    assert prov["wall_s"] >= 0
