"""Durable admission journal + replay-on-respawn + poison quarantine
(kindel_tpu.durable): DESIGN.md §24's claims, asserted.

  * frame codec + scan: admits/settles/marks/quarantines round-trip;
    blame counts exactly the lives that died with an entry in flight;
  * torn-write matrix: the journal blob cut at EVERY frame boundary
    (plus mid-frame cuts and corrupted frames) scans without crashing,
    never resurrects a settled key, never drops an unsettled one whose
    admit survived intact;
  * fsync/write faults (`journal.write`/`journal.fsync` sites): an
    admit the journal cannot make durable is rejected typed, and the
    journal keeps working once the fault clears;
  * rotation + retired-entry GC bound the on-disk footprint to live
    entries;
  * replay-on-respawn: a service restarted over the dead life's
    journal re-serves exactly the unsettled entries — settled keys are
    not re-applied, the journal drains to zero live entries;
  * quarantine ladder: an entry blamed for K crashes is quarantined
    (never replayed), identical payloads 422 at admission, suspects
    (blame ≥ 1) dispatch isolated from healthy traffic;
  * disabled path allocation-free (tracemalloc, PR 4 convention);
  * satellites: stale addr-file sweep, respawn-latency report fields,
    the static `--replica-addrs` roster;
  * the flagship: a 3-process fleet under wire faults, one replica
    SIGKILLed twice mid-load plus one injected poison request — every
    non-poison request settles exactly once with FASTA sha256 equal to
    the single-replica reference, the poison key is quarantined after
    exactly K blamed crashes, and every slot's journal drains to zero
    live entries.
"""

import hashlib
import json
import os
import threading
import time
import tracemalloc
from pathlib import Path

import pytest

from kindel_tpu.durable import journal as dj
from kindel_tpu.durable import recovery as dr
from kindel_tpu.durable.journal import (
    Journal,
    JournalWriteError,
    PoisonRequestError,
    encode_frame,
)
from kindel_tpu.obs import trace
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience import policy as rpolicy
from kindel_tpu.resilience.faults import FaultPlan
from kindel_tpu.serve.queue import AdmissionError


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Process-global fault plans / policies / tracers must not leak
    (same hygiene as test_resilience.py)."""
    rfaults.deactivate()
    prev = rpolicy.set_default_policy(None)
    yield
    rfaults.deactivate()
    rpolicy.set_default_policy(prev)
    trace.disable_tracing()


def _snap() -> dict:
    return default_registry().snapshot()


def _delta(before: dict, after: dict, name: str) -> int:
    return int(after.get(name, 0)) - int(before.get(name, 0))


def _sam_payload(seed: int = 0) -> bytes:
    import tempfile

    from benchmarks.serve_load import _synth_sam

    with tempfile.TemporaryDirectory() as d:
        return _synth_sam(Path(d) / "x.sam", seed=seed).read_bytes()


# ------------------------------------------------------- codec + scan


def test_frame_roundtrip_scan_and_blame(tmp_path):
    d = tmp_path / "j"
    j = Journal(d)
    j.record_admit("k1", b"payload-one", {"min_depth": 2})
    j.record_admit("k2", str(tmp_path / "some.bam"))
    j.record_mark(["k1", "k2"])
    j.record_mark(["k1"])  # second mark of one life: not double-blamed
    j.record_settle("k1", "ok")
    j.record_settle("k1", "ok")  # idempotent: no second tombstone
    j.record_admit("k3", b"payload-three")
    j.record_quarantine("kq", "deadbeef" * 4)
    assert j.live_count == 2  # k2, k3
    j.close()

    r = dr.scan(d)
    assert sorted(r.entries) == ["k2", "k3"]
    assert "k1" in r.settled
    # k1 marked then settled: no blame; k2 marked, never settled: 1
    assert r.blame.get("k1", 0) == 0
    assert r.blame["k2"] == 1
    assert ("deadbeef" * 4) in r.quarantined
    assert r.truncated == 0
    # payload round-trip: bytes come back as bytes, paths as paths
    assert r.entries["k3"].payload() == b"payload-three"
    assert r.entries["k2"].payload() == str(tmp_path / "some.bam")
    assert r.entries["k2"].opts == {}


def _model_scan(frames):
    """Reference model of the scan semantics over complete frames."""
    live, settled, marked, blame = {}, set(), set(), {}
    for rtype, doc in frames:
        if rtype == dj.REC_ADMIT:
            live[doc["k"]] = doc
            settled.discard(doc["k"])
            marked.discard(doc["k"])
        elif rtype == dj.REC_SETTLE:
            if doc["k"] in live:
                del live[doc["k"]]
                settled.add(doc["k"])
            if doc["k"] in marked:
                marked.discard(doc["k"])
                blame[doc["k"]] = max(0, blame.get(doc["k"], 0) - 1)
        elif rtype == dj.REC_MARK:
            for k in doc["ks"]:
                if k in live and k not in marked:
                    marked.add(k)
                    blame[k] = blame.get(k, 0) + 1
        elif rtype == dj.REC_QUARANTINE:
            if doc["k"] in live:
                del live[doc["k"]]
                settled.add(doc["k"])
    return live, settled, blame


def test_torn_write_matrix_every_frame_boundary(tmp_path):
    """The satellite matrix: cut the journal at every frame boundary
    and at mid-frame offsets; recovery never crashes, never replays a
    settled key, never drops an unsettled one whose admit survived."""
    frames = [
        (dj.REC_ADMIT, {"k": "k1", "d": "d1", "p": "QUJD"}),
        (dj.REC_ADMIT, {"k": "k2", "d": "d2", "p": "REVG"}),
        (dj.REC_MARK, {"ks": ["k1", "k2"]}),
        (dj.REC_SETTLE, {"k": "k1", "out": "ok"}),
        (dj.REC_ADMIT, {"k": "k3", "d": "d3", "p": "R0hJ"}),
        (dj.REC_SETTLE, {"k": "k2", "out": "error:X"}),
        (dj.REC_QUARANTINE, {"k": "k4", "d": "d4"}),
    ]
    blobs = [encode_frame(rt, doc) for rt, doc in frames]
    blob = b"".join(blobs)
    ends = []
    off = 0
    for b in blobs:
        off += len(b)
        ends.append(off)
    seg = tmp_path / "j" / "seg-00000000.kj"
    seg.parent.mkdir(parents=True)

    cuts = set(ends)
    for e in ends:  # mid-frame cuts: torn tails of every frame
        cuts.update({e - 1, e - 5, e - len(blobs[0]) // 2})
    cuts.update({0, 1, 3, len(blob)})
    for cut in sorted(c for c in cuts if 0 <= c <= len(blob)):
        seg.write_bytes(blob[:cut])
        r = dr.scan(seg.parent)  # must never raise
        complete = [
            frames[i] for i, e in enumerate(ends) if e <= cut
        ]
        live, settled, _blame = _model_scan(complete)
        assert set(r.entries) == set(live), f"cut={cut}"
        # a settled key is never live again
        assert not (set(r.entries) & settled), f"cut={cut}"
        # torn tail counted iff bytes remain past the last whole frame
        whole = sum(1 for e in ends if e <= cut)
        torn = cut > (ends[whole - 1] if whole else 0)
        assert r.truncated == (1 if torn else 0), f"cut={cut}"


def test_corrupt_frame_truncates_segment_there(tmp_path):
    frames = [
        (dj.REC_ADMIT, {"k": f"k{i}", "d": f"d{i}", "p": "QUJD"})
        for i in range(5)
    ]
    blobs = [encode_frame(rt, doc) for rt, doc in frames]
    seg = tmp_path / "j" / "seg-00000000.kj"
    seg.parent.mkdir(parents=True)
    for i in range(5):
        corrupted = b"".join(blobs)
        # flip one payload byte of frame i: CRC fails, scan stops there
        pos = sum(len(b) for b in blobs[:i]) + dj.FRAME_OVERHEAD - 2
        corrupted = (
            corrupted[:pos]
            + bytes([corrupted[pos] ^ 0xFF])
            + corrupted[pos + 1:]
        )
        seg.write_bytes(corrupted)
        r = dr.scan(seg.parent)
        assert sorted(r.entries) == [f"k{j}" for j in range(i)]
        assert r.truncated == 1


def test_scan_continues_past_damaged_middle_segment(tmp_path):
    d = tmp_path / "j"
    d.mkdir()
    (d / "seg-00000000.kj").write_bytes(
        encode_frame(dj.REC_ADMIT, {"k": "a", "d": "da", "p": "QQ=="})
    )
    (d / "seg-00000001.kj").write_bytes(b"\x00garbage\xff" * 3)
    (d / "seg-00000002.kj").write_bytes(
        encode_frame(dj.REC_ADMIT, {"k": "b", "d": "db", "p": "Qg=="})
    )
    r = dr.scan(d)
    assert sorted(r.entries) == ["a", "b"]
    assert r.truncated == 1
    assert r.next_index == 3


# ------------------------------------------------- write/fsync faults


def test_journal_write_fault_rejects_admit_then_recovers(tmp_path):
    j = Journal(tmp_path / "j")
    rfaults.activate(FaultPlan.parse("journal.write:error"))
    with pytest.raises(JournalWriteError):
        j.record_admit("k1", b"x", {})
    # fault exhausted: the journal keeps working, state consistent
    j.record_admit("k2", b"y", {})
    assert j.live_keys() == {"k2"}
    j.close()
    r = dr.scan(tmp_path / "j")
    assert sorted(r.entries) == ["k2"]


def test_journal_fsync_fault_rejects_admit(tmp_path):
    j = Journal(tmp_path / "j")
    rfaults.activate(FaultPlan.parse("journal.fsync:error"))
    with pytest.raises(JournalWriteError):
        j.record_admit("k1", b"x", {})
    rfaults.deactivate()
    # the frame reached the OS before the failed fsync: recovery may
    # see it live (at-least-once existence), and the CALLER saw a
    # rejection — replaying a rejected-but-durable admit is the
    # harmless direction (purity), dropping a confirmed one is not
    j.record_admit("k2", b"y", {})
    j.close()
    assert "k2" in dr.scan(tmp_path / "j").entries


def test_settle_and_mark_write_failures_degrade_not_raise(tmp_path):
    j = Journal(tmp_path / "j")
    j.record_admit("k1", b"x", {})
    rfaults.activate(FaultPlan.parse("journal.write:error:times=2"))
    j.record_settle("k1", "ok")  # swallowed + recorded, never raises
    j.record_mark(["k1"])
    rfaults.deactivate()
    j.close()


# --------------------------------------------------- rotation and GC


def test_rotation_and_retired_entry_gc(tmp_path):
    d = tmp_path / "j"
    j = Journal(d, segment_bytes=256)  # tiny: rotate every few frames
    for i in range(40):
        key = f"k{i}"
        j.record_admit(key, b"x" * 16, {})
        j.record_settle(key, "ok")
    j.record_admit("live-one", b"y", {})
    j.gc()
    segs = dj.segment_files(d)
    # fully-settled rotated segments were unlinked; what remains holds
    # the live entry and the (possibly empty) live segment
    assert len(segs) <= 3, [s.name for s in segs]
    r = dr.scan(d)
    assert sorted(r.entries) == ["live-one"]
    j.close()


# ------------------------------------------- service-level integration


def _service(journal_dir, **kw):
    from kindel_tpu.serve import ConsensusService

    kw.setdefault("warmup", False)
    kw.setdefault("http_port", None)
    return ConsensusService(journal_dir=str(journal_dir), **kw)


def _wait(pred, timeout=60.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def test_replay_on_restart_serves_only_unsettled(tmp_path):
    payload = _sam_payload()
    jd = tmp_path / "journal"

    svc = _service(jd).start()
    served = svc.request(payload, timeout=120)
    reference = [s.sequence for s in served.consensuses]
    assert svc._journal.live_count == 0
    svc.stop()

    # life 2: admit two orphans (worker pinned dead — nothing serves),
    # then die abruptly
    svc2 = _service(jd)
    svc2.worker._killed = True
    svc2.start()
    f1 = svc2.submit(payload)
    f2 = svc2.submit(payload, min_depth=2)
    assert svc2._journal.live_count == 2
    svc2.kill()
    assert not f1.done() and not f2.done()  # abandoned, like a SIGKILL

    # life 3: replay serves exactly the two orphans
    before = _snap()
    svc3 = _service(jd).start()
    assert _wait(lambda: svc3._journal.live_count == 0, 120)
    after = _snap()
    assert _delta(before, after, "kindel_journal_replayed_total") == 2
    # the replayed result is the same consensus the direct path produced
    r = dr.scan(jd)
    assert not r.entries
    svc3.stop()
    # the settled key from life 1 was never replayed: total replays
    # stayed at 2 and a fresh scan shows nothing live
    final = dr.scan(jd)
    assert not final.entries
    _ = reference


def test_replay_preserves_opt_overrides(tmp_path):
    payload = _sam_payload()
    jd = tmp_path / "journal"
    svc = _service(jd)
    svc.worker._killed = True
    svc.start()
    svc.submit(payload, min_depth=3, trim_ends=True)
    svc.kill()

    seen = {}
    svc2 = _service(jd)
    orig = svc2._submit_replay

    def spy(key, pl, opts, suspect=False):
        seen["opts"] = dict(opts)
        seen["suspect"] = suspect
        return orig(key, pl, opts, suspect=suspect)

    svc2._submit_replay = spy
    svc2.start()
    assert _wait(lambda: svc2._journal.live_count == 0, 120)
    svc2.stop()
    assert seen["opts"] == {"min_depth": 3, "trim_ends": True}
    assert seen["suspect"] is False  # never marked: not a suspect


def test_quarantine_after_k_blamed_crashes(tmp_path):
    payload = _sam_payload(seed=3)
    jd = tmp_path / "journal"
    key = dj.payload_digest(payload)[:16] + "-poisonpoisonpoi"
    # three process lives, each of which died with the entry mid-flush
    for _life in range(3):
        j = Journal(jd)
        j.record_admit(key, payload, {})
        j.record_mark([key])
        j._fh.flush()
        j._fh.close()  # abrupt: no close() bookkeeping, like os._exit
    assert dr.scan(jd).blame[key] == 3

    before = _snap()
    svc = _service(jd, quarantine_after=3).start()
    assert _wait(lambda: svc._journal.live_count == 0, 60)
    after = _snap()
    assert _delta(
        before, after, "kindel_quarantined_requests_total"
    ) == 1
    assert _delta(before, after, "kindel_journal_replayed_total") == 0
    # identical payloads are barred at the door, typed
    with pytest.raises(PoisonRequestError):
        svc.submit(payload)
    # ... and the verdict survives a restart (quarantine is durable)
    svc.stop()
    svc2 = _service(jd, quarantine_after=3).start()
    with pytest.raises(PoisonRequestError):
        svc2.submit(payload)
    # a DIFFERENT payload is unaffected
    ok = svc2.request(_sam_payload(seed=4), timeout=120)
    assert ok.consensuses
    svc2.stop()


def test_suspect_replays_isolated_from_batcher(tmp_path):
    payload = _sam_payload(seed=5)
    jd = tmp_path / "journal"
    j = Journal(jd)
    j.record_admit("susp-key-000000000000000000", payload, {})
    j.record_mark(["susp-key-000000000000000000"])  # blamed once
    j._fh.flush()
    j._fh.close()

    svc = _service(jd, quarantine_after=3)
    batched = []
    orig_add = svc.worker.batcher.add
    svc.worker.batcher.add = lambda req, units: (
        batched.append(req.key), orig_add(req, units)
    )
    svc.start()
    assert _wait(lambda: svc._journal.live_count == 0, 120)
    svc.stop()
    # the suspect was served (journal drained, tombstone ok) but NEVER
    # entered a shared batcher lane
    assert "susp-key-000000000000000000" not in batched
    assert not dr.scan(jd).entries


def test_poison_http_mapping_is_422_without_retry_after():
    from kindel_tpu.fleet.rpc import RpcServiceClient
    from kindel_tpu.serve.service import consensus_post_response

    def poisoned(_body):
        raise PoisonRequestError("payload abc is quarantined")

    status, ctype, body, headers = consensus_post_response(
        poisoned, b"x"
    )
    assert status == 422
    assert b"quarantined" in body
    assert "Retry-After" not in headers
    # ... and the RPC client maps it back to the same type, which the
    # router treats as a REQUEST failure (no failover: it would crash
    # the next replica too)
    exc = RpcServiceClient._status_error(422, {}, body)
    assert isinstance(exc, PoisonRequestError)
    from kindel_tpu.fleet.router import REPLICA_FAILURES

    assert not isinstance(exc, REPLICA_FAILURES)
    from kindel_tpu.fleet.rpc import wire_transient

    assert not wire_transient(exc)


def test_handback_tombstones_drain_the_journal(tmp_path):
    payload = _sam_payload()
    jd = tmp_path / "journal"
    svc = _service(jd)
    svc.worker._killed = True  # nothing pops the queue
    svc.start()
    svc.submit(payload)
    svc.submit(payload)
    assert svc._journal.live_count == 2
    handed = svc.drain(handback=True)
    assert len(handed) == 2
    # the hand-back IS this replica's settle: nothing left to replay
    assert not dr.scan(jd).entries


def test_queue_rejection_tombstones_the_admit(tmp_path):
    payload = _sam_payload()
    jd = tmp_path / "journal"
    svc = _service(jd, max_depth=1, high_watermark=1)
    svc.worker._killed = True
    svc.start()
    svc.submit(payload)
    with pytest.raises(AdmissionError):
        svc.submit(payload)  # watermark: rejected AFTER the WAL write
    # the rejected admit was tombstoned — only the accepted one is live
    assert svc._journal.live_count == 1
    svc.kill()


def test_journal_admit_fault_maps_to_admission_error(tmp_path):
    payload = _sam_payload()
    svc = _service(tmp_path / "journal")
    svc.worker._killed = True
    svc.start()
    rfaults.activate(FaultPlan.parse("journal.write:error"))
    with pytest.raises(AdmissionError) as exc:
        svc.submit(payload)
    assert exc.value.retry_after_s > 0
    rfaults.deactivate()
    svc.kill()


# -------------------------------------------- disabled-path allocation


def test_disabled_journal_hooks_are_allocation_free():
    """The acceptance pin: with journaling off, the dispatch-site and
    settle-site hooks are one None check (PR 4 convention)."""

    class _Req:
        __slots__ = ("key", "payload")

        def __init__(self):
            self.key = None
            self.payload = b"x"

    entries = [(_Req(), []) for _ in range(4)]

    def burst(n):
        for _ in range(n):
            dj.mark_if_active(None, entries)
            dj.settle_if_active(None, "k", "ok")

    burst(64)  # warm any lazy interning
    tracemalloc.start()
    try:
        before = tracemalloc.take_snapshot()
        burst(4096)
        after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    journal_py = str(Path(dj.__file__))
    leaked = sum(
        stat.size_diff
        for stat in after.compare_to(before, "filename")
        if stat.traceback[0].filename == journal_py and stat.size_diff > 0
    )
    assert leaked < 512, (
        f"disabled journal hooks allocated {leaked} bytes over 4096 calls"
    )


# ------------------------------------------------------ knob plumbing


def test_journal_knob_resolution_precedence(monkeypatch):
    from kindel_tpu import tune

    monkeypatch.delenv("KINDEL_TPU_JOURNAL_DIR", raising=False)
    monkeypatch.delenv("KINDEL_TPU_QUARANTINE_AFTER", raising=False)
    assert tune.resolve_journal_dir() == (None, "default")
    assert tune.resolve_quarantine_after() == (3, "default")
    monkeypatch.setenv("KINDEL_TPU_JOURNAL_DIR", "/var/j")
    monkeypatch.setenv("KINDEL_TPU_QUARANTINE_AFTER", "7")
    assert tune.resolve_journal_dir() == ("/var/j", "env")
    assert tune.resolve_quarantine_after() == (7, "env")
    # explicit beats env; "off" is an explicit disable
    assert tune.resolve_journal_dir("/x") == ("/x", "explicit")
    assert tune.resolve_journal_dir("off") == (None, "explicit")
    assert tune.resolve_quarantine_after(2) == (2, "explicit")
    # malformed env pins fall through, never crash a boot
    monkeypatch.setenv("KINDEL_TPU_QUARANTINE_AFTER", "banana")
    assert tune.resolve_quarantine_after() == (3, "default")
    monkeypatch.setenv("KINDEL_TPU_QUARANTINE_AFTER", "-1")
    assert tune.resolve_quarantine_after() == (3, "default")


def test_fault_spec_match_scopes_to_note():
    plan = FaultPlan.parse("serve.flush:crash:times=5:match=poisonkey")
    # without a matching note the spec neither fires nor burns budget
    plan.fire("serve.flush")
    plan.fire("serve.flush", "other|keys")
    assert plan.fired == {}
    assert plan.specs[0].match == "poisonkey"
    # crash would os._exit: assert reachability via the ledger of a
    # NON-crash kind with the same match plumbing
    plan2 = FaultPlan.parse("serve.flush:error:match=abc")
    with pytest.raises(rfaults.InjectedFault):
        plan2.fire("serve.flush", "xx|abc|yy")
    assert plan2.fired == {("serve.flush", "error"): 1}


# ---------------------------------------------------------- satellites


def test_spawn_failure_sweeps_addr_file(tmp_path):
    import sys

    from kindel_tpu.fleet.procreplica import (
        ReplicaProcess,
        ReplicaSpawnError,
    )

    addr = tmp_path / "r0.g0.addr"
    addr.write_text("{}")  # half-written handshake from a dying child
    proc = ReplicaProcess(
        [sys.executable, "-c", "import sys; sys.exit(3)"], str(addr),
        spawn_timeout_s=30.0,
    )
    with pytest.raises(ReplicaSpawnError):
        proc.start()
    assert not addr.exists()


def test_factory_sweeps_stale_generations(tmp_path):
    from kindel_tpu.fleet.procreplica import ProcessReplicaFactory

    for gen in range(3):
        (tmp_path / f"r7.g{gen}.addr").write_text("{}")
        (tmp_path / f"r7.g{gen}.json").write_text("{}")
    (tmp_path / "r8.g0.addr").write_text("{}")  # another slot: kept
    factory = ProcessReplicaFactory("r7", str(tmp_path))
    factory.sweep_stale_files(keep_generation=2)
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["r7.g2.addr", "r7.g2.json", "r8.g0.addr"]


def test_factory_routes_journal_dir_per_slot(tmp_path):
    from kindel_tpu.fleet.procreplica import ProcessReplicaFactory

    factory = ProcessReplicaFactory(
        "r3", str(tmp_path),
        service_config={"journal_dir": str(tmp_path / "jrn")},
    )
    assert factory._config["service"]["journal_dir"] == str(
        tmp_path / "jrn" / "r3"
    )


def test_respawn_latency_fields_in_rpc_report():
    from benchmarks.serve_load import rpc_report
    from kindel_tpu.obs.metrics import fleet_metrics

    # the histogram exists on the fleet family (observed by the
    # process factory's spawn timer)
    assert fleet_metrics().respawn_seconds is not None
    after = {
        "kindel_rpc_call_seconds": {"p50": 0.01, "p99": 0.02},
        "kindel_fleet_respawn_seconds": {"p50": 1.5, "p99": 3.0},
    }
    report = rpc_report({}, after)
    assert report["respawn_p50_ms"] == 1500.0
    assert report["respawn_p99_ms"] == 3000.0


def test_parse_replica_addrs_and_static_fleet_guards():
    from kindel_tpu.fleet import parse_replica_addrs, static_fleet

    assert parse_replica_addrs("a:1, b:2,") == [("a", 1), ("b", 2)]
    assert parse_replica_addrs(["10.0.0.1:8801"]) == [("10.0.0.1", 8801)]
    with pytest.raises(ValueError):
        parse_replica_addrs("no-port")
    with pytest.raises(ValueError):
        parse_replica_addrs("")
    with pytest.raises(ValueError):
        static_fleet("a:1,b:2", min_replicas=1, max_replicas=3)


def test_static_fleet_serves_and_fails_over():
    """The multi-host groundwork satellite: a FleetService over two
    PRE-SPAWNED remote replicas (stub services behind real HTTP + the
    real RPC adapter — the wire without the device). Killing one
    backend fails requests over to the survivor; a slot restart
    re-dials the SAME address (spawn/respawn disabled)."""
    from types import SimpleNamespace

    from kindel_tpu.fleet.rpc import RpcServerAdapter
    from kindel_tpu.fleet.service import static_fleet
    from kindel_tpu.io.fasta import Sequence
    from kindel_tpu.serve.metrics import MetricsRegistry, ServeHTTPServer

    class _Stub:
        def __init__(self, name):
            self.name = name
            self.applied = 0

        def request(self, payload, deadline_s=None,
                    idempotency_key=None, **opts):
            self.applied += 1
            return SimpleNamespace(
                consensuses=[Sequence("ref_cns", "ACGTACGT")]
            )

        def healthz(self):
            return {"status": "ok", "queue_depth": 0, "watermark": 64,
                    "est_wait_s": 0.0}

        def drain(self, handback=False):
            return []

    stubs = [_Stub("a"), _Stub("b")]
    servers = [
        ServeHTTPServer(
            MetricsRegistry(), health_fn=s.healthz,
            post_routes=RpcServerAdapter(s).post_routes(),
        ).start()
        for s in stubs
    ]
    try:
        addrs = ",".join(f"{srv.host}:{srv.port}" for srv in servers)
        fleet = static_fleet(
            addrs, supervise=False, probe_interval_s=10.0,
        ).start()
        try:
            res = fleet.request(b"payload-one", timeout=30)
            assert [s.sequence for s in res.consensuses] == ["ACGTACGT"]
            assert sum(s.applied for s in stubs) == 1
            # roster slots re-dial their OWN address on restart —
            # never spawn
            rep0 = fleet.replica("r0")
            host_before = rep0.service._host, rep0.service._port
            rep0.restart()
            assert (rep0.service._host, rep0.service._port) == host_before
            # kill one backend server: the router fails the ticket
            # over to the survivor (RpcTransportError is a
            # replica-level failure)
            servers[0].stop()
            for _ in range(4):
                res = fleet.request(b"payload-two", timeout=30)
                assert res.consensuses[0].sequence == "ACGTACGT"
        finally:
            fleet.stop(drain=False)
    finally:
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — already stopped above
                pass


# ---------------------------------------------------------- the flagship


def test_flagship_double_sigkill_plus_poison_quarantine(
    tmp_path, monkeypatch
):
    """The flagship chaos run (DESIGN.md §24): 3 replica processes with
    per-slot journals under wire faults; one replica is SIGKILLed twice
    mid-load (its respawns finish their own orphans via journal
    replay), and one poison request — scoped by a match= crash fault to
    its payload digest — crash-loops its replica until the quarantine
    ladder takes it out after exactly K blamed crashes. Every
    non-poison request settles exactly once with FASTA sha256 equal to
    the single-replica in-process reference, and every slot's journal
    drains to zero live entries."""
    from benchmarks.serve_load import _synth_sam, run_load

    K = 2
    # single-replica in-process reference: the byte-identity anchor
    reference = run_load(clients=2, requests_per_client=3)
    assert reference["errors"] == 0
    assert reference["fasta_distinct"] == 1

    poison = _synth_sam(tmp_path / "poison.sam", seed=99).read_bytes()
    digest16 = hashlib.sha256(poison).hexdigest()[:16]
    jdir = tmp_path / "journal"

    # children inherit the env: the crash fires ONLY on flushes whose
    # member keys carry the poison digest (procreplica children
    # activate KINDEL_TPU_FAULTS at boot)
    monkeypatch.setenv(
        "KINDEL_TPU_FAULTS",
        f"serve.flush:crash:times=20:match={digest16}",
    )
    # parent-side wire faults on the submission path (the idempotency
    # machinery's test vehicle), activated in-process
    plan = rfaults.activate(FaultPlan.parse(
        "seed=11,rpc.call:drop_response:times=2:after=1,"
        "rpc.call:slow:times=2:delay=0.02"
    ))
    before = _snap()
    chaos_state: dict = {}

    def chaos(svc):
        victim = svc.replica("r0")

        def converged(min_generation=0):
            return (
                victim.generation >= min_generation
                and {r.state for r in svc.roster()} == {"ok"}
            )

        def wait_converged(what, min_generation=0):
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if converged(min_generation):
                    return
                time.sleep(0.05)
            raise AssertionError(
                f"fleet never converged after {what}: "
                f"{[(r.replica_id, r.state, r.generation) for r in svc.roster()]}"
            )

        # mid-load SIGKILL #1 and #2 of the same slot: convergence is
        # the slot's RESPAWN (generation bump), not just probe calm —
        # right after a SIGKILL every state still reads "ok"
        time.sleep(0.15)
        gen0 = victim.generation
        svc.kill_replica("r0")
        wait_converged("first SIGKILL", min_generation=gen0 + 1)
        svc.kill_replica("r0")
        wait_converged("second SIGKILL", min_generation=gen0 + 2)
        chaos_state["victim_generations"] = victim.generation - gen0

        # the poison request, submitted straight at r2's wire: its
        # flush crashes the child; the respawn replays it (suspect →
        # isolated), crashes again, and the THIRD life quarantines it
        poison_rep = svc.replica("r2")
        r2_gen0 = poison_rep.generation
        fut = poison_rep.service.submit(poison)
        try:
            fut.result(timeout=60)
            chaos_state["poison_outcome"] = "served"
        except Exception as e:  # noqa: BLE001 — the expected path
            chaos_state["poison_outcome"] = type(e).__name__
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if dr.scan(jdir / "r2").quarantined:
                break
            time.sleep(0.25)
        # capture the blame ledger NOW: once everything settles, the
        # retired-entry GC is entitled to unlink the history segments
        post = dr.scan(jdir / "r2")
        chaos_state["quarantined"] = sorted(post.quarantined)
        chaos_state["poison_blame"] = {
            k: v for k, v in post.blame.items()
            if k.startswith(digest16)
        }
        wait_converged("poison quarantine")
        chaos_state["r2_generations"] = (
            svc.replica("r2").generation - r2_gen0
        )

    report = run_load(
        clients=3, requests_per_client=3, procs=3,
        probe_interval_s=0.02, chaos=chaos,
        service_config={
            "journal_dir": str(jdir), "quarantine_after": K,
        },
    )
    after = _snap()

    # exactly once: every non-poison request resolved, none errored,
    # byte-identical to the single-replica in-process reference
    assert "chaos_errors" not in report, report.get("chaos_errors")
    assert report["errors"] == 0
    assert report["completed"] == report["requests"] == 9
    assert report["fasta_distinct"] == 1
    assert report["fasta_sha256"] == reference["fasta_sha256"]

    # the poison request failed typed at the caller (its replica died
    # under it / rejected it post-quarantine) — never served
    assert chaos_state["poison_outcome"] != "served"

    # quarantined after exactly K blamed crashes, on the replica it
    # crashed: the journal names the digest and the blame count
    poison_digest = dj.payload_digest(poison)
    assert chaos_state["quarantined"] == [poison_digest]
    assert chaos_state["poison_blame"], "poison key never blamed"
    assert all(
        v == K for v in chaos_state["poison_blame"].values()
    ), chaos_state["poison_blame"]

    # both SIGKILLs were detected and the slot respawned twice; the
    # poison crash-looped r2 through two more generations (the exact
    # respawn COUNTER can race the final fleet stop, so generations —
    # which the quarantine itself proves — are the hard pin)
    assert chaos_state["victim_generations"] == 2
    assert chaos_state["r2_generations"] >= 2
    assert _delta(before, after, "kindel_fleet_evictions_total") >= 3
    assert _delta(before, after, "kindel_fleet_respawns_total") >= 3
    # respawn latency is now a tracked number
    assert report["rpc"]["respawn_p99_ms"] > 0
    # the parent-side wire plan fired as written
    assert plan.fired[("rpc.call", "drop_response")] == 2

    # zero journal entries leaked: after drain, every slot's journal
    # scans to zero live entries
    for slot in ("r0", "r1", "r2"):
        leftover = dr.scan(jdir / slot)
        assert not leftover.entries, (
            slot, list(leftover.entries)
        )
