"""Progress observability (VERDICT r4 item 5): long runs must show
opt-in stderr progress — the reference's tqdm-bars equivalent
(/root/reference/kindel/kindel.py:40,390)."""

import subprocess
import sys

import pytest


def test_consensus_contig_progress(data_root, capsys, monkeypatch):
    from kindel_tpu.workloads import bam_to_consensus

    monkeypatch.setenv("KINDEL_TPU_PROGRESS", "1")
    bam_to_consensus(data_root / "data_minimap2" / "1.1.multi.bam")
    err = capsys.readouterr().err
    assert "building consensus" in err
    assert "3/3 contigs" in err


def test_streamed_chunk_progress(data_root, capsys, monkeypatch):
    from kindel_tpu.io.stream import stream_alignment

    monkeypatch.setenv("KINDEL_TPU_PROGRESS", "1")
    n = sum(
        1 for _ in stream_alignment(
            data_root / "data_bwa_mem" / "1.1.sub_test.bam",
            chunk_bytes=1 << 20,
        )
    )
    err = capsys.readouterr().err
    assert n > 1  # multi-chunk, or the test is vacuous
    assert "streaming 1.1.sub_test.bam" in err
    assert f"{n} chunks" in err
    assert "reads)" in err


def test_cohort_progress(data_root, capsys, monkeypatch):
    from kindel_tpu.batch import stream_bam_to_results

    monkeypatch.setenv("KINDEL_TPU_PROGRESS", "1")
    paths = [data_root / "data_bwa_mem" / "1.1.sub_test.bam"] * 3
    list(stream_bam_to_results(paths, chunk_size=2))
    err = capsys.readouterr().err
    assert "cohort 3/3 samples" in err


def test_progress_off_by_default_noninteractive(data_root, capsys,
                                                monkeypatch):
    """No KINDEL_TPU_PROGRESS and a non-TTY stderr → silent."""
    from kindel_tpu.workloads import bam_to_consensus

    monkeypatch.delenv("KINDEL_TPU_PROGRESS", raising=False)
    bam_to_consensus(data_root / "data_minimap2" / "1.1.multi.bam")
    assert "building consensus" not in capsys.readouterr().err


@pytest.mark.parametrize(
    "argv",
    [
        ["--progress", "consensus"],  # root position
        ["consensus", "--progress"],  # subcommand position
    ],
)
def test_cli_progress_flag(data_root, argv):
    """--progress on the real CLI process shows progress on stderr,
    accepted before or after the subcommand."""
    proc = subprocess.run(
        [sys.executable, "-m", "kindel_tpu", *argv,
         str(data_root / "data_minimap2" / "1.1.multi.bam")],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0
    assert "building consensus" in proc.stderr
