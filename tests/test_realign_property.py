"""Generative property for the realign/CDR engine.

Randomized divergent-segment geometries, two regimes:

- **intersecting**: the soft-clip extension spans from the two flanks
  overlap in reference coordinates — the reference implementation's own
  pairing regime. Default (reference-exact) realign must recover the
  novel segment.
- **gapped**: the spans are disjoint (the removed reference span is
  wider than both clip extensions combined) but the clip CONTENTS still
  overlap by >= GAP_PAIR_MIN_OVERLAP inside the novel segment — the
  reference's disabled-gp120 class. Default realign must leave the
  uncovered middle uncalled, and `cdr_gap` must close it.

This generalizes the fixed geometries of tests/test_gp120_cdr.py and
tests/distfixture.py to randomized widths/lengths/overlaps.
"""

from __future__ import annotations

import tempfile
from pathlib import Path

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from kindel_tpu.workloads import bam_to_consensus

_B = "ACGT"
READ = 48  # aligned flank length of the anchored reads


def _rand_seq(rng, n):
    return "".join(_B[i] for i in rng.integers(0, 4, size=n))


def _divergent_sam(rng, L, s, W, novel, cl, cr):
    """Sample genome ref[:s] + novel + ref[s+W:]; left-anchored reads end
    at s carrying novel[:cl] as a soft clip, right-anchored reads start
    at e=s+W carrying novel[-cr:]; background tiling covers the flanks."""
    e = s + W
    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:dv1\tLN:{L}".encode()]
    left_flank = _rand_seq(rng, READ)
    right_flank = _rand_seq(rng, READ)
    k = 0

    def read(pos1, cigar, seq):
        nonlocal k
        lines.append(
            f"r{k}\t0\tdv1\t{pos1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*".encode()
        )
        k += 1

    for _ in range(20):
        read(s - READ + 1, f"{READ}M{cl}S", left_flank + novel[:cl])
        read(e + 1, f"{cr}S{READ}M", novel[len(novel) - cr:] + right_flank)
    for _ in range(30):  # flank coverage away from the junction
        a = int(rng.integers(0, max(s - READ - 8, 1)))
        read(a + 1, "40M", _rand_seq(rng, 40))
        b = int(rng.integers(e + READ + 8, L - 48))
        read(b + 1, "40M", _rand_seq(rng, 40))
    return b"\n".join(lines) + b"\n"


@st.composite
def geometries(draw):
    nl = draw(st.integers(20, 60))          # novel segment length
    gapped = draw(st.booleans())
    if gapped:
        # clip contents overlap >= 16 inside novel, spans disjoint
        total = draw(st.integers(nl + 16, 2 * nl))
        W = draw(st.integers(total + 4, total + 300))
    else:
        # spans intersect AND contents overlap >= 7
        W = draw(st.integers(8, 2 * nl - 8))
        # overlap floor 12 (not the merge gate's 7): two ~100 bp clip
        # consensuses share a spurious 7-mer with probability ~0.5, and a
        # chance LCS tie can splice at the wrong junction on correct code
        total = draw(
            st.integers(max(W + 2, nl + 12), 2 * nl)
        )
    cl = draw(st.integers(max(total - nl, 1), min(nl, total - 1)))
    cr = total - cl
    return nl, W, cl, cr, gapped


@settings(max_examples=15, deadline=None)
@given(geometries(), st.integers(0, 10 ** 6))
def test_divergent_segment_recovery(geo, seed):
    nl, W, cl, cr, gapped = geo
    rng = np.random.default_rng(seed)
    L = W + 700
    s = 300
    novel = _rand_seq(rng, nl)
    blob = _divergent_sam(rng, L, s, W, novel, cl, cr)
    with tempfile.NamedTemporaryFile(suffix=".sam", delete=False) as fh:
        fh.write(blob)
        p = Path(fh.name)
    try:
        plain = bam_to_consensus(p, realign=True, min_overlap=7)
        seq_plain = plain.consensuses[0].sequence.upper()
        if not gapped:
            assert novel in seq_plain, (
                "intersecting-span geometry not recovered by "
                f"reference-exact pairing: nl={nl} W={W} cl={cl} cr={cr}"
            )
        else:
            # middle is uncovered and unmergeable without gap pairing
            assert novel not in seq_plain
            gap_res = bam_to_consensus(
                p, realign=True, min_overlap=7, cdr_gap=600
            )
            seq_gap = gap_res.consensuses[0].sequence.upper()
            assert novel in seq_gap, (
                f"gap pairing failed: nl={nl} W={W} cl={cl} cr={cr} "
                f"(content overlap {cl + cr - nl})"
            )
    finally:
        p.unlink()
