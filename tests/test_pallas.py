"""MXU histogram kernel (kindel_tpu/ops/pallas_count.py) vs numpy oracle.

Runs the pallas interpreter on the CPU test backend; the same kernel code
compiles for TPU (exercised by bench/TPU runs).
"""

import numpy as np
import pytest

from kindel_tpu.ops import count_events_pallas


def _oracle(pos, base, L, n_ch=5):
    out = np.zeros((L, n_ch), np.int32)
    np.add.at(out, (pos, base), 1)
    return out


@pytest.mark.parametrize("L", [1, 100, 512, 1000, 4097])
def test_count_matches_oracle(L):
    rng = np.random.default_rng(L)
    E = 5000
    pos = rng.integers(0, L, E)
    base = rng.integers(0, 5, E)
    got = count_events_pallas(pos, base, L, interpret=True)
    np.testing.assert_array_equal(got, _oracle(pos, base, L))


def test_count_empty():
    got = count_events_pallas(
        np.empty(0, np.int64), np.empty(0, np.int64), 300, interpret=True
    )
    np.testing.assert_array_equal(got, np.zeros((300, 5), np.int32))


def test_count_heavy_duplicates():
    # all events on one position — exercises accumulation across chunks
    E = 3000
    pos = np.full(E, 7)
    base = np.tile(np.arange(5), 600)
    got = count_events_pallas(pos, base, 64, interpret=True)
    expect = np.zeros((64, 5), np.int32)
    expect[7] = 600
    np.testing.assert_array_equal(got, expect)


def test_pallas_backend_consensus_matches_numpy(data_root):
    from kindel_tpu.workloads import bam_to_consensus

    bam = str(data_root / "data_bwa_mem" / "1.1.sub_test.bam")
    np_res = bam_to_consensus(bam, backend="numpy")
    pl_res = bam_to_consensus(bam, backend="pallas")
    assert [r.sequence for r in np_res.consensuses] == [
        r.sequence for r in pl_res.consensuses
    ]
    assert np_res.refs_reports == pl_res.refs_reports


def test_pallas_backend_realign_matches_numpy(data_root):
    from kindel_tpu.workloads import bam_to_consensus

    bam = str(data_root / "data_bwa_mem" / "1.1.sub_test.bam")
    np_res = bam_to_consensus(bam, backend="numpy", realign=True)
    pl_res = bam_to_consensus(bam, backend="pallas", realign=True)
    assert [r.sequence for r in np_res.consensuses] == [
        r.sequence for r in pl_res.consensuses
    ]


def test_count_real_events(data_root):
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment
    from kindel_tpu.pileup import build_pileup

    bam = data_root / "data_bwa_mem" / "1.1.sub_test.bam"
    ev = extract_events(load_alignment(str(bam)))
    rid = ev.present_ref_ids[0]
    sel = ev.match_rid == rid
    L = int(ev.ref_lens[rid])
    got = count_events_pallas(
        ev.match_pos[sel], ev.match_base[sel], L, interpret=True
    )
    expect = build_pileup(ev, rid).weights
    np.testing.assert_array_equal(got, expect)
