"""kindel_tpu.tune: store roundtrip, resolution-order precedence, the
budget-bounded slab search, and env hygiene (the search must never
mutate process state — the failure mode the old in-bench search had)."""

import json
import os

import pytest

from kindel_tpu import tune


@pytest.fixture
def store(tmp_path, monkeypatch):
    """Isolated tune store + no ambient knob pins."""
    path = tmp_path / "tune.json"
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(path))
    for var in ("KINDEL_TPU_SLABS", "KINDEL_TPU_STREAM_CHUNK_MB",
                "KINDEL_TPU_COHORT_BUDGET_MB"):
        monkeypatch.delenv(var, raising=False)
    return path


# ----------------------------------------------------------------- store


def test_store_roundtrip(store):
    key = tune.store_key("cpu", 10_000_000)
    assert tune.lookup(key) is None
    assert tune.record(key, {"n_slabs": 8, "timings_s": {"4": 0.5}})
    entry = tune.lookup(key)
    assert entry["n_slabs"] == 8
    assert entry["timings_s"] == {"4": 0.5}
    assert "recorded_at" in entry
    doc = json.loads(store.read_text())
    assert doc["version"] == tune.STORE_VERSION
    # merge, not clobber: a second record keeps the key's other fields
    assert tune.record(key, {"n_slabs": 16})
    entry = tune.lookup(key)
    assert entry["n_slabs"] == 16 and entry["timings_s"] == {"4": 0.5}


def test_store_key_mismatch_falls_back_to_default(store):
    # a winner measured at bacterial scale must not leak into an
    # amplicon-scale run (different contig bucket -> different key)
    tune.record(tune.store_key("cpu", 10_000_000), {"n_slabs": 8})
    n, src = tune.resolve_slabs(backend="cpu", max_contig=50_000)
    assert (n, src) == (tune.CPU_SLAB_DEFAULT, "default")
    # and the matching scale hits
    n, src = tune.resolve_slabs(backend="cpu", max_contig=10_000_000)
    assert (n, src) == (8, "cache")


def test_corrupt_or_foreign_store_is_empty(store):
    store.write_text("{not json")
    assert tune.load_store() == {}
    store.write_text(json.dumps({"version": 999, "entries": {"k": {}}}))
    assert tune.load_store() == {}
    # a bad store must not break recording either
    assert tune.record("k", {"n_slabs": 4})
    assert tune.lookup("k")["n_slabs"] == 4


def test_store_disabled(store, monkeypatch):
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", "off")
    assert tune.store_path() is None
    assert tune.record("k", {"n_slabs": 4}) is False
    assert tune.lookup("k") is None


# ------------------------------------------------------------ resolution


def test_resolution_precedence_arg_env_store_default(store, monkeypatch):
    key = tune.store_key("cpu", 10_000_000)
    tune.record(key, {"n_slabs": 7})
    monkeypatch.setenv("KINDEL_TPU_SLABS", "3")
    # explicit arg beats the env pin
    assert tune.resolve_slabs(
        explicit=5, backend="cpu", max_contig=10_000_000
    ) == (5, "explicit")
    # env pin beats the store
    assert tune.resolve_slabs(
        backend="cpu", max_contig=10_000_000
    ) == (3, "env")
    # store beats the default
    monkeypatch.delenv("KINDEL_TPU_SLABS")
    assert tune.resolve_slabs(
        backend="cpu", max_contig=10_000_000
    ) == (7, "cache")
    # nothing left: backend default
    store.unlink()
    assert tune.resolve_slabs(
        backend="cpu", max_contig=10_000_000
    ) == (tune.CPU_SLAB_DEFAULT, "default")
    assert tune.resolve_slabs(
        backend="tpu", max_contig=10_000_000
    ) == (tune.ACCEL_SLAB_DEFAULT, "default")


def test_malformed_env_pin_means_default_not_stale_cache(store, monkeypatch):
    # a malformed pin is explicit operator intent to override — it must
    # fall to the DEFAULT, never to a store entry the operator meant to
    # shadow (matches the historical bench/call_jax behavior)
    tune.record(tune.store_key("cpu", 10_000_000), {"n_slabs": 7})
    monkeypatch.setenv("KINDEL_TPU_SLABS", "not-a-number")
    assert tune.resolve_slabs(
        backend="cpu", max_contig=10_000_000
    ) == (tune.CPU_SLAB_DEFAULT, "default")


def test_stream_chunk_precedence(store, monkeypatch):
    assert tune.resolve_stream_chunk_mb(32) == (32.0, "explicit")
    # 0 anywhere means "never stream"
    assert tune.resolve_stream_chunk_mb(0) == (None, "explicit")
    monkeypatch.setenv("KINDEL_TPU_STREAM_CHUNK_MB", "16")
    assert tune.resolve_stream_chunk_mb() == (16.0, "env")
    assert tune.resolve_stream_chunk_mb(32) == (32.0, "explicit")
    monkeypatch.delenv("KINDEL_TPU_STREAM_CHUNK_MB")
    tune.record("stream|" + tune.host_fingerprint(), {"stream_chunk_mb": 8})
    assert tune.resolve_stream_chunk_mb() == (8.0, "cache")


def test_cohort_budget_precedence(store, monkeypatch):
    assert tune.resolve_cohort_budget_mb() == (
        tune.COHORT_BUDGET_MB_DEFAULT, "default",
    )
    monkeypatch.setenv("KINDEL_TPU_COHORT_BUDGET_MB", "128")
    assert tune.resolve_cohort_budget_mb() == (128, "env")
    assert tune.resolve_cohort_budget_mb(64) == (64, "explicit")


def test_resolve_bundles_all_knobs_with_sources(store, monkeypatch):
    monkeypatch.setenv("KINDEL_TPU_SLABS", "6")
    cfg = tune.resolve(backend="cpu", max_contig=10_000_000)
    assert cfg.n_slabs == 6
    assert dict(cfg.sources)["n_slabs"] == "env"
    assert dict(cfg.sources)["cohort_budget_mb"] == "default"
    # explicit TuningConfig fields win over the env pin
    cfg = tune.resolve(
        explicit=tune.TuningConfig(n_slabs=2), backend="cpu",
    )
    assert cfg.n_slabs == 2 and dict(cfg.sources)["n_slabs"] == "explicit"


def test_default_slab_constants_are_the_single_copy():
    # the 16/4 pair previously copy-pasted between bench.py and
    # call_jax.py lives here and only here
    assert tune.default_slabs("cpu") == tune.CPU_SLAB_DEFAULT == 16
    assert tune.default_slabs("tpu") == tune.ACCEL_SLAB_DEFAULT == 4
    from pathlib import Path

    call_jax_src = (
        Path(__file__).resolve().parent.parent
        / "kindel_tpu" / "call_jax.py"
    ).read_text()
    assert 'os.environ.get("KINDEL_TPU_SLABS"' not in call_jax_src


# ---------------------------------------------------------------- search


def test_search_grid_then_doubling_expansion():
    times = {1: 0.5, 4: 0.3, 16: 0.2, 32: 0.15, 64: 0.4}
    calls = []

    def measure(s):
        calls.append(s)
        return times[s]

    chosen, timings = tune.search_slabs(measure, clamp=93, budget_s=100)
    # grid 1/4/16, then 16 is the top config and still the winner -> 32,
    # then 32 wins -> 64, then 64 loses -> stop
    assert calls == [1, 4, 16, 32, 64]
    assert chosen == 32
    assert timings == times


def test_search_clamp_dedups_grid():
    calls = []
    chosen, _ = tune.search_slabs(
        lambda s: (calls.append(s), 0.1)[1], clamp=2, budget_s=100
    )
    # clamp 2 collapses 4 and 16 onto 2 — measured once, not three times
    assert calls == [1, 2]


def test_search_trivial_clamp_measures_nothing():
    chosen, timings = tune.search_slabs(
        lambda s: 1 / 0, clamp=1, budget_s=100
    )
    assert chosen == 1 and timings == {}


def test_search_budget_bounds_the_sweep():
    clock_now = [0.0]

    def clock():
        return clock_now[0]

    def measure(s):
        clock_now[0] += 10.0  # every probe costs 10 "seconds"
        return {1: 0.5, 4: 0.3, 16: 0.2}[s]

    chosen, timings = tune.search_slabs(
        measure, clamp=93, budget_s=15.0, clock=clock
    )
    # the second probe lands at t=20 > budget: pick from what we have,
    # no expansion past the grid
    assert set(timings) == {1, 4}
    assert chosen == 4


def test_search_mutates_no_env_even_on_probe_crash(store, monkeypatch):
    # the old in-bench search pinned KINDEL_TPU_SLABS per probe and left
    # it mutated when a probe raised; the library search takes the slab
    # count as an explicit argument — no env write anywhere
    monkeypatch.setenv("KINDEL_TPU_SLABS", "9")
    before = dict(os.environ)

    def measure(s):
        if s == 4:
            raise RuntimeError("probe crashed")
        return 0.5

    with pytest.raises(RuntimeError):
        tune.search_slabs(measure, clamp=93, budget_s=100)
    assert dict(os.environ) == before


def test_env_pin_restores_on_exception(monkeypatch):
    monkeypatch.delenv("KINDEL_TPU_SLABS", raising=False)
    with pytest.raises(RuntimeError):
        with tune.env_pin("KINDEL_TPU_SLABS", 4):
            assert os.environ["KINDEL_TPU_SLABS"] == "4"
            raise RuntimeError("boom")
    assert "KINDEL_TPU_SLABS" not in os.environ
    monkeypatch.setenv("KINDEL_TPU_SLABS", "2")
    with pytest.raises(RuntimeError):
        with tune.env_pin("KINDEL_TPU_SLABS", 8):
            assert os.environ["KINDEL_TPU_SLABS"] == "8"
            raise RuntimeError("boom")
    assert os.environ["KINDEL_TPU_SLABS"] == "2"


# ------------------------------------------------- integration touchpoints


def test_call_consensus_fused_explicit_tuning_pin(store):
    """An explicit TuningConfig beats everything — and n_slabs=1 forces
    the single fused kernel, byte-identical to the pipelined default."""
    jax = pytest.importorskip("jax")  # noqa: F841
    import numpy as np

    from kindel_tpu.call_jax import call_consensus_fused
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment_bytes

    rng = np.random.default_rng(0)
    lines = ["@HD\tVN:1.6", "@SQ\tSN:tref\tLN:400"]
    for i in range(20):
        pos = int(rng.integers(0, 340))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=60))
        lines.append(f"r{i}\t0\ttref\t{pos + 1}\t60\t60M\t*\t0\t0\t{seq}\t*")
    ev = extract_events(
        load_alignment_bytes(("\n".join(lines) + "\n").encode())
    )
    rid = ev.present_ref_ids[0]
    res1, d1, D1 = call_consensus_fused(
        ev, rid, build_changes=False,
        tuning=tune.TuningConfig(n_slabs=1),
    )
    res2, d2, D2 = call_consensus_fused(ev, rid, build_changes=False)
    assert res1.sequence == res2.sequence
    assert (d1, D1) == (d2, D2)


def test_stream_chunk_env_pin_resolves_for_workloads(store, monkeypatch,
                                                     tmp_path):
    """workloads._resolve_stream_chunk honors TuningConfig > env."""
    from kindel_tpu.tune import TuningConfig
    from kindel_tpu.workloads import _resolve_stream_chunk

    bam = tmp_path / "x.sam"
    bam.write_text("@HD\tVN:1.6\n")
    monkeypatch.setenv("KINDEL_TPU_STREAM_CHUNK_MB", "16")
    assert _resolve_stream_chunk(str(bam), None) == 16.0
    assert _resolve_stream_chunk(
        str(bam), None, tuning=TuningConfig(stream_chunk_mb=4)
    ) == 4.0
    assert _resolve_stream_chunk(str(bam), 2.0) == 2.0
    monkeypatch.delenv("KINDEL_TPU_STREAM_CHUNK_MB")
    assert _resolve_stream_chunk(str(bam), None) is None  # small file
