"""Parallel ingest (kindel_tpu.io.inflate) — determinism, bounds, faults.

The contract under test: the pipelined parallel inflater is an invisible
optimization. For EVERY worker count the decompressed byte stream, the
ReadBatch chunk sequence, the consensus FASTA, the truncation error (and
its offset / chunk attribution), and the io.read_chunk fault replay are
byte-identical to the serial path — only the wall clock may differ.
"""

from __future__ import annotations

import gzip
import io as _io
import struct
import zlib
from pathlib import Path

import numpy as np
import pytest

from kindel_tpu.io import bgzf, load_alignment
from kindel_tpu.io.errors import TruncatedInputError
from kindel_tpu.io.inflate import (
    DEFAULT_PREFETCH_BYTES,
    ParallelInflater,
    shared_pool,
)
from kindel_tpu.io.stream import stream_alignment
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience.faults import FaultPlan
from kindel_tpu.streaming import streamed_consensus

WORKER_COUNTS = (1, 2, 8)

import os

_DATA_ROOT = Path(
    os.environ.get("KINDEL_TPU_TEST_DATA", "/root/reference/tests")
)


def require_data(*rel) -> Path:
    path = _DATA_ROOT.joinpath(*rel)
    if not path.exists():
        pytest.skip(f"golden corpus not available: {path}")
    return path


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    rfaults.deactivate()


# --------------------------------------------------------------- builders


def bgzf_member(raw: bytes) -> bytes:
    """One conforming BGZF member (18-byte header with BC subfield,
    raw-deflate payload, CRC/ISIZE trailer)."""
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    payload = co.compress(raw) + co.flush()
    bsize = len(payload) + 26
    header = (
        b"\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff"
        + struct.pack("<H", 6) + b"BC" + struct.pack("<H", 2)
        + struct.pack("<H", bsize - 1)
    )
    return header + payload + struct.pack(
        "<II", zlib.crc32(raw), len(raw) & 0xFFFFFFFF
    )


def bgzf_compress(raw: bytes, member_bytes: int = 8 << 10) -> bytes:
    """raw → many-member BGZF blob (small members, so even a small test
    file exercises the pool's submission/reassembly machinery)."""
    out = [
        bgzf_member(raw[i: i + member_bytes])
        for i in range(0, len(raw), member_bytes)
    ]
    out.append(bgzf.BGZF_EOF)
    return b"".join(out)


def synth_bam_raw(ref_len: int = 20_000, n_reads: int = 600,
                  read_len: int = 100, seed: int = 0) -> bytes:
    """Uncompressed BAM bytes: one reference, n_reads simple 100M reads
    at random positions (the bench synthesizer's shape, miniaturized)."""
    rng = np.random.default_rng(seed)
    name = b"SYNTH1\x00"
    header_text = f"@SQ\tSN:SYNTH1\tLN:{ref_len}\n".encode()
    out = [
        b"BAM\x01" + struct.pack("<i", len(header_text)) + header_text
        + struct.pack("<i", 1)
        + struct.pack("<i", len(name)) + name + struct.pack("<i", ref_len)
    ]
    code = np.array([1, 2, 4, 8], dtype=np.uint8)
    for _ in range(n_reads):
        pos = int(rng.integers(0, ref_len - read_len))
        nib = code[rng.integers(0, 4, size=read_len)]
        packed = bytearray()
        for i in range(0, read_len, 2):
            hi = int(nib[i]) << 4
            lo = int(nib[i + 1]) if i + 1 < read_len else 0
            packed.append(hi | lo)
        rname = b"r\x00"
        cigar = struct.pack("<I", (read_len << 4) | 0)
        body = struct.pack(
            "<iiBBHHHiiii", 0, pos, len(rname), 60, 0, 1, 0,
            read_len, -1, -1, 0,
        )
        body += rname + cigar + bytes(packed) + b"\xff" * read_len
        out.append(struct.pack("<i", len(body)) + body)
    return b"".join(out)


@pytest.fixture(scope="module")
def synth_bam(tmp_path_factory) -> Path:
    raw = synth_bam_raw()
    path = tmp_path_factory.mktemp("ingest") / "synth.bam"
    path.write_bytes(bgzf_compress(raw))
    return path


def batch_tuples(batches):
    """Hashable per-read projection of a ReadBatch sequence, chunk
    structure included (chunk boundaries must not move with the worker
    count)."""
    out = []
    for b in batches:
        reads = []
        for i in range(b.n_reads):
            reads.append((
                int(b.ref_id[i]), int(b.pos[i]), int(b.flag[i]),
                b.seq[b.seq_off[i]: b.seq_off[i + 1]].tobytes(),
                tuple(b.cig_len[b.cig_off[i]: b.cig_off[i + 1]]),
            ))
        out.append(tuple(reads))
    return out


# ----------------------------------------------------------- determinism


def test_stream_bytes_identical_across_workers(synth_bam):
    blob = synth_bam.read_bytes()
    want = gzip.decompress(blob)
    for w in WORKER_COUNTS:
        got = b"".join(ParallelInflater(w).stream(_io.BytesIO(blob)))
        assert got == want, f"workers={w}"


def test_slurp_decompress_identical_across_workers(synth_bam):
    blob = synth_bam.read_bytes()
    want = gzip.decompress(blob)
    for w in WORKER_COUNTS:
        assert bgzf.decompress(blob, workers=w) == want, f"workers={w}"


def test_chunk_sequence_identical_across_workers(synth_bam):
    """Identical ReadBatch CHUNKS, not just identical totals: the
    parallel inflater must not move a chunk boundary."""
    want = batch_tuples(stream_alignment(synth_bam, 16 << 10,
                                         ingest_workers=1))
    assert len(want) > 3  # the file genuinely chunks
    for w in WORKER_COUNTS[1:]:
        got = batch_tuples(stream_alignment(synth_bam, 16 << 10,
                                            ingest_workers=w))
        assert got == want, f"workers={w}"


def test_streamed_consensus_fasta_identical_across_workers(synth_bam):
    results = {}
    for w in WORKER_COUNTS:
        res = streamed_consensus(
            synth_bam, backend="numpy", chunk_bytes=16 << 10,
            ingest_workers=w,
        )
        results[w] = [(s.name, s.sequence) for s in res.consensuses]
    assert results[1] == results[2] == results[8]
    assert results[1][0][1]  # non-empty sequence


def test_slurp_matches_load_alignment(synth_bam):
    """The eager loader (native or python, whatever is active) and the
    parallel slurp agree on the decoded reads."""
    eager = load_alignment(synth_bam)
    batches = list(stream_alignment(synth_bam, 1 << 30, ingest_workers=4))
    assert sum(b.n_reads for b in batches) == eager.n_reads


def test_generic_gzip_members_interleave(synth_bam):
    """A generic (no-BSIZE) gzip member mid-stream drains the pool and
    inflates serially — output identical, any worker count."""
    raw = gzip.decompress(synth_bam.read_bytes())
    third = len(raw) // 3
    mix = (
        bgzf_compress(raw[:third])[: -len(bgzf.BGZF_EOF)]
        + gzip.compress(raw[third: 2 * third])
        + bgzf_compress(raw[2 * third:])
    )
    for w in (1, 4):
        assert bgzf.decompress(mix, workers=w) == raw
        assert b"".join(ParallelInflater(w).stream(_io.BytesIO(mix))) == raw


@pytest.mark.parametrize(
    "rel",
    [
        ("data_bwa_mem", "1.1.sub_test.bam"),
        ("data_minimap2", "1.1.multi.bam"),
    ],
)
def test_refsuite_chunks_identical_across_workers(rel):
    """Real-corpus pin of the determinism contract: identical ReadBatch
    chunk sequence for every worker count on the refsuite BAMs."""
    path = require_data(*rel)
    want = batch_tuples(stream_alignment(path, 64 << 10, ingest_workers=1))
    for w in WORKER_COUNTS[1:]:
        got = batch_tuples(stream_alignment(path, 64 << 10,
                                            ingest_workers=w))
        assert got == want, f"workers={w}"


@pytest.mark.parametrize(
    "rel",
    [
        ("data_bwa_mem", "1.1.sub_test.bam"),
        ("data_minimap2", "1.1.multi.bam"),
    ],
)
def test_refsuite_fasta_identical_across_workers(rel):
    path = require_data(*rel)
    results = {}
    for w in WORKER_COUNTS:
        res = streamed_consensus(
            path, backend="numpy", chunk_bytes=64 << 10, ingest_workers=w
        )
        results[w] = [(s.name, s.sequence) for s in res.consensuses]
    assert results[1] == results[2] == results[8]


# --------------------------------------------------------- failure parity


def test_truncation_same_attribution_across_workers(synth_bam, tmp_path):
    """Mid-member truncation raises the SAME TruncatedInputError —
    message, path, chunk index — under the pool as serially."""
    blob = synth_bam.read_bytes()
    cut = tmp_path / "cut.bam"
    cut.write_bytes(blob[: int(len(blob) * 0.6)])
    seen = {}
    for w in WORKER_COUNTS:
        with pytest.raises(TruncatedInputError) as exc:
            for _ in stream_alignment(cut, 16 << 10, ingest_workers=w):
                pass
        seen[w] = (str(exc.value), exc.value.chunk_index,
                   str(exc.value.path))
    assert seen[1] == seen[2] == seen[8]
    assert seen[1][2] == str(cut)


def test_corrupt_member_same_error_across_workers():
    """A corrupt deflate payload surfaces the same wrapped ValueError
    (offset included) whatever the worker count, and an EARLIER member's
    error always wins over a later scan error."""
    good = bgzf_member(b"A" * 2000)
    bad = bgzf_member(b"B" * 2000)
    # corrupt the second member's payload with bytes no deflate stream
    # can start with after the stored header
    bad = bad[:18] + b"\xff\x00\xff\x00\xff\x00" + bad[24:]
    blob = good + bad + good + b"\x1f\x8b"  # trailing garbage header too
    errs = []
    for w in (1, 8):
        with pytest.raises(ValueError) as exc:
            bgzf.decompress(blob, workers=w)
        errs.append(str(exc.value))
    assert errs[0] == errs[1]
    assert f"offset {len(good)}" in errs[0]


def test_read_chunk_fault_replay_deterministic(synth_bam):
    """The PR-4 chaos contract: an io.read_chunk truncate fault fires on
    the same chunk with the same downstream attribution whatever the
    worker count — and replays identically run to run."""
    outcomes = []
    for w in (1, 8, 8):
        plan = rfaults.activate(
            FaultPlan.parse("seed=3,io.read_chunk:truncate:after=1")
        )
        try:
            # dropping a chunk's tail half mid-stream surfaces as a
            # ValueError: either typed truncation or a corrupt-record
            # scan — both deterministic, and identical across workers
            with pytest.raises(ValueError) as exc:
                for _ in stream_alignment(synth_bam, 16 << 10,
                                          ingest_workers=w):
                    pass
            outcomes.append((
                dict(plan.fired), plan.hits("io.read_chunk"),
                type(exc.value).__name__,
                getattr(exc.value, "chunk_index", None), str(exc.value),
            ))
        finally:
            rfaults.deactivate()
    assert outcomes[0] == outcomes[1] == outcomes[2]
    assert outcomes[0][0] == {("io.read_chunk", "truncate"): 1}


# ------------------------------------------------------- bounds and knobs


def test_inflight_window_stays_bounded(synth_bam):
    """The reassembly queue respects max_inflight_bytes (+ at most one
    member's estimate of slack) — the O(chunk) RSS bound's load-bearing
    half."""
    blob = synth_bam.read_bytes()

    class Spy(ParallelInflater):
        max_seen = 0

        def _submit(self, *a, **kw):
            super()._submit(*a, **kw)
            self.max_seen = max(self.max_seen, self._inflight)

    spy = Spy(workers=4, max_inflight_bytes=1 << 16)
    out = b"".join(spy.stream(_io.BytesIO(blob)))
    assert out == gzip.decompress(blob)
    assert spy.max_seen > 0
    assert spy.max_seen <= (1 << 16) + (16 << 10)


def test_shared_pool_is_shared_and_grows(monkeypatch):
    from kindel_tpu.io import inflate

    monkeypatch.setattr(inflate, "_POOL", None)
    monkeypatch.setattr(inflate, "_POOL_WORKERS", 0)
    p2 = shared_pool(2)
    assert shared_pool(2) is p2
    assert shared_pool(1) is p2  # never shrinks
    p4 = shared_pool(4)
    assert p4 is not p2
    assert shared_pool(3) is p4
    assert inflate.pool_workers() == 4


def test_resolve_ingest_workers_precedence(tmp_path, monkeypatch):
    from kindel_tpu import tune

    store = tmp_path / "tune.json"
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", str(store))
    monkeypatch.delenv("KINDEL_TPU_INGEST_WORKERS", raising=False)

    # default (host-derived, >= 1)
    n, src = tune.resolve_ingest_workers()
    assert n >= 1 and src == "default"
    # store beats default
    assert tune.record(tune.ingest_store_key(), {"ingest_workers": 3})
    assert tune.resolve_ingest_workers() == (3, "cache")
    # env pin beats store
    monkeypatch.setenv("KINDEL_TPU_INGEST_WORKERS", "5")
    assert tune.resolve_ingest_workers() == (5, "env")
    # explicit beats env
    assert tune.resolve_ingest_workers(2) == (2, "explicit")
    # malformed pin falls back to the default, never the store
    monkeypatch.setenv("KINDEL_TPU_INGEST_WORKERS", "banana")
    n, src = tune.resolve_ingest_workers()
    assert src == "default"
    # prefetch knob: env pin then default
    monkeypatch.setenv("KINDEL_TPU_INGEST_PREFETCH_MB", "2.5")
    assert tune.resolve_ingest_prefetch_mb() == (2.5, "env")
    monkeypatch.delenv("KINDEL_TPU_INGEST_PREFETCH_MB")
    v, src = tune.resolve_ingest_prefetch_mb()
    assert v == tune.INGEST_PREFETCH_MB_DEFAULT and src == "default"
    assert DEFAULT_PREFETCH_BYTES == tune.INGEST_PREFETCH_MB_DEFAULT << 20


def test_tuning_config_threads_ingest_workers(synth_bam, monkeypatch):
    """TuningConfig(ingest_workers=) reaches the inflater: the resolved
    worker gauge reflects the pinned count after a streamed run."""
    from kindel_tpu.obs.metrics import default_registry
    from kindel_tpu.tune import TuningConfig

    res = streamed_consensus(
        synth_bam, backend="numpy", chunk_bytes=16 << 10,
        tuning=TuningConfig(ingest_workers=2),
    )
    assert res.consensuses
    snap = default_registry().snapshot()
    assert snap.get("kindel_ingest_pool_workers") == 2


def test_search_ingest_workers_budget_and_pick():
    from kindel_tpu import tune

    walls = {1: 4.0, 2: 2.5, 4: 1.9, 8: 2.2}
    probed = []

    def measure(w):
        probed.append(w)
        return walls[w]

    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    chosen, timings = tune.search_ingest_workers(
        measure, max_workers=8, budget_s=100.0, clock=clock
    )
    assert probed == [1, 2, 4, 8]
    assert chosen == 4 and timings == walls
    # 1-core host: no search at all
    assert tune.search_ingest_workers(measure, max_workers=1) == (1, {})


def test_ingest_metrics_accumulate(synth_bam):
    from kindel_tpu.obs.metrics import default_registry

    from kindel_tpu.events import extract_events

    # earlier raises-tests keep suspended stream generators alive via
    # captured tracebacks; their close-time stats flush must not land
    # inside this test's measurement window
    import gc

    gc.collect()
    before = default_registry().snapshot()
    for batch in stream_alignment(synth_bam, 16 << 10, ingest_workers=2):
        extract_events(batch)
    after = default_registry().snapshot()

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    raw_len = len(gzip.decompress(synth_bam.read_bytes()))
    assert delta("kindel_ingest_members_total") >= raw_len // (8 << 10)
    assert delta("kindel_ingest_bytes_out_total") == raw_len
    assert delta("kindel_ingest_bytes_in_total") > 0
    assert delta("kindel_ingest_inflate_seconds_total") > 0
    assert delta("kindel_ingest_expand_seconds_total") > 0


# -------------------------------------------------------- sniffing fixes


class Trickle:
    """A pipe-like fh: the FIRST read returns a single byte (the
    short-first-read misrouting reproduction), later reads behave."""

    def __init__(self, data: bytes):
        self.data = data
        self.first = True

    def read(self, n: int) -> bytes:
        take = 1 if self.first else n
        self.first = False
        out = self.data[:take]
        self.data = self.data[take:]
        return out


def test_short_first_read_still_detects_gzip(synth_bam):
    """A 1-byte first read must not send a gzip stream down the
    plain-text path (io/stream satellite fix)."""
    blob = synth_bam.read_bytes()
    want = gzip.decompress(blob)
    for w in (1, 4):
        got = b"".join(ParallelInflater(w).stream(Trickle(blob)))
        assert got == want


def test_short_first_read_plain_passthrough():
    data = b"@HD\tVN:1.6\nplain text, not gzip\n"
    got = b"".join(ParallelInflater(2).stream(Trickle(data)))
    assert got == data


def test_single_byte_stream_is_plain():
    assert b"".join(ParallelInflater(2).stream(Trickle(b"\x1f"))) == b"\x1f"
    assert b"".join(ParallelInflater(2).stream(_io.BytesIO(b""))) == b""
