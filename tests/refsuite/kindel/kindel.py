"""The reference's `kindel.kindel` module surface, re-exported from
kindel_tpu. Everything the reference test suite imports directly
(/root/reference/tests/test_kindel.py:4,18-19,26-53,92-111,329-338):
`parse_bam`, `consensus`, `merge_by_lcs`, `cdrp_consensuses`,
`bam_to_consensus`, `weights`, `features`."""

from kindel_tpu.call import consensus  # noqa: F401
from kindel_tpu.compat import alignment, parse_bam  # noqa: F401
from kindel_tpu.realign import (  # noqa: F401
    Region,
    cdrp_consensuses,
    merge_by_lcs,
)
from kindel_tpu.workloads import (  # noqa: F401
    bam_to_consensus,
    features,
    weights,
)
