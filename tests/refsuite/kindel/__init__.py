"""Drop-in `kindel` package alias backed by kindel_tpu (SURVEY §7 step 7).

Placed on PYTHONPATH when running the reference's own test suite so that
`from kindel import kindel` resolves to the compat surface of this
framework (/root/reference/tests/test_kindel.py:4).
"""
