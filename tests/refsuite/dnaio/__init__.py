"""Minimal read-only stand-in for the `dnaio` package (not installed in
this environment) — just enough for the reference test suite, which only
does `with dnaio.open(path, mode="r") as reader` over FASTA files and
reads `.name` / `.sequence` off the records
(/root/reference/tests/test_kindel.py:117-123 and siblings)."""

from kindel_tpu.io.fasta import Sequence, read_fasta  # noqa: F401


class _Reader:
    def __init__(self, path):
        self._records = read_fasta(path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __iter__(self):
        return iter(self._records)


def open(path, mode="r"):  # noqa: A001 - dnaio's public name
    if "r" not in mode:
        raise NotImplementedError("refsuite dnaio shim is read-only")
    return _Reader(path)
