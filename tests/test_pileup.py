"""Curated pileup assertions — the reference's hand-verified counts
("Curated in Tablet / Samtools depth",
/root/reference/tests/test_kindel.py:68-89) asserted against the dense
accumulator tensors, pinning accumulator semantics independent of the CLI."""

import pytest

from kindel_tpu.events import extract_events
from kindel_tpu.io import load_alignment
from kindel_tpu.pileup import build_pileups

A, T, G, C, N = range(5)


@pytest.fixture(scope="module")
def bwa_pileup(data_root):
    ev = extract_events(load_alignment(data_root / "data_bwa_mem" / "1.1.sub_test.bam"))
    return next(iter(build_pileups(ev).values()))


@pytest.fixture(scope="module")
def ext_pileup(data_root):
    ev = extract_events(load_alignment(data_root / "data_ext" / "3.issue23.bc75.sam"))
    return next(iter(build_pileups(ev).values()))


def test_ref_identity(bwa_pileup):
    assert bwa_pileup.ref_id == "ENA|EU155341|EU155341.2"
    assert bwa_pileup.ref_len == 9306
    assert bwa_pileup.weights.shape == (9306, 5)


def test_known_weights(bwa_pileup, ext_pileup):
    assert bwa_pileup.weights[0, A] == 22
    assert bwa_pileup.weights[23, A] == 57
    assert ext_pileup.weights[68, G] == 1
    assert ext_pileup.weights[2368, T] == 13


def test_known_deletions(ext_pileup):
    for pos, count in [(399, 14), (402, 14), (411, 15),
                       (1048, 14), (1049, 14), (1050, 14)]:
        assert ext_pileup.deletions[pos] == count


def test_known_clips(bwa_pileup, ext_pileup):
    assert ext_pileup.clip_ends[1748] == 12
    assert bwa_pileup.clip_starts[525] == 16
    assert bwa_pileup.clip_starts[1437] == 84


def test_known_insertions(ext_pileup):
    # insertion strings are registered at the following reference position
    # (reference kindel.py:55-58; asserted with the same +1 the reference's
    # own tests use, tests/test_kindel.py:88-89)
    assert ext_pileup.ins.totals[452 + 1] == 14
    assert ext_pileup.ins.totals[456 + 1] == 14


def test_compat_parse_bam(data_root):
    """The reference-shaped compat API returns identical dict views."""
    from kindel_tpu.compat import parse_bam

    aln = list(parse_bam(data_root / "data_bwa_mem" / "1.1.sub_test.bam").values())[0]
    assert aln.ref_id == "ENA|EU155341|EU155341.2"
    assert len(aln.weights) == 9306
    assert aln.weights[0]["A"] == 22
    assert aln.weights[23]["A"] == 57
    assert aln.clip_starts[525] == 16


def test_refskip_advances_reference():
    """CIGAR N (spliced ref-skip) advances the reference coordinate and
    emits nothing — conscious divergence from the reference, which has no
    N branch and silently corrupts all later positions of the read
    (SURVEY.md §2.1). Pinned on both the vectorized fast path and the
    sequential exact path."""
    from collections import Counter

    import numpy as np

    from kindel_tpu.events import _exact_read_events
    from kindel_tpu.io.sam import parse_sam_bytes

    sam = (
        b"@HD\tVN:1.6\n"
        b"@SQ\tSN:ref1\tLN:300\n"
        b"r1\t0\tref1\t11\t60\t5M100N5M\t*\t0\t0\tAAAAACCCCC\t*\n"
    )
    batch = parse_sam_bytes(sam)
    ev = extract_events(batch)
    p = next(iter(build_pileups(ev).values()))

    assert all(p.weights[pos, A] == 1 for pos in range(10, 15))
    # the spliced-out span and the positions the reference would
    # (wrongly) hit stay empty
    assert p.weights[15:115].sum() == 0
    assert all(p.weights[pos, C] == 1 for pos in range(115, 120))
    assert p.deletions.sum() == 0

    # exact path agrees with the fast path
    out = {
        "match": ([], [], []),
        "del": ([], []),
        "cs": ([], []),
        "ce": ([], []),
        "csw": ([], [], []),
        "cew": ([], [], []),
    }
    _exact_read_events(out, Counter(), batch, 0)
    exact_pos = np.concatenate([np.asarray(x) for x in out["match"][1]])
    assert sorted(exact_pos) == sorted(ev.match_pos.tolist())
