"""Batched data-parallel consensus vs the single-file paths."""

import numpy as np

from kindel_tpu.batch import batch_bam_to_consensus
from kindel_tpu.workloads import bam_to_consensus


def test_batch_matches_single(data_root):
    paths = [
        data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam" for i in (1, 2, 3)
    ] + [data_root / "data_minimap2" / "1.1.multi.bam"]
    batch_out = batch_bam_to_consensus(paths)
    for path in paths:
        singles = bam_to_consensus(path).consensuses
        batched = batch_out[path]
        assert [s.name for s in singles] == [b.name for b in batched]
        for s, b in zip(singles, batched):
            assert s.sequence == b.sequence, path


def test_batch_empty():
    assert batch_bam_to_consensus([]) == {}


def test_stream_matches_batch(data_root):
    from kindel_tpu.batch import stream_bam_to_consensus

    paths = [
        data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam"
        for i in (1, 2, 3, 4)
    ]
    # chunk_size=2 → two device programs, exercising the overlap machinery
    streamed = list(stream_bam_to_consensus(paths, chunk_size=2))
    assert [p for p, _ in streamed] == paths  # input order preserved
    whole = batch_bam_to_consensus(paths)
    for p, records in streamed:
        assert [(r.name, r.sequence) for r in records] == [
            (r.name, r.sequence) for r in whole[p]
        ]


def test_stream_single_worker_no_deadlock(data_root):
    # regression: the prefetch wrapper must not share the decode pool, or
    # num_workers=1 deadlocks (wrapper blocks on tasks behind itself)
    from kindel_tpu.batch import stream_bam_to_consensus

    paths = [data_root / "data_bwa_mem" / "1.1.sub_test.bam"]
    out = list(stream_bam_to_consensus(paths, num_workers=1))
    assert len(out) == 1 and out[0][1]


def test_batch_cli_stem_collision(data_root, tmp_path):
    from kindel_tpu.cli import main

    src = data_root / "data_bwa_mem" / "1.1.sub_test.bam"
    a = tmp_path / "runA" / "s.bam"
    b = tmp_path / "runB" / "s.bam"
    for dst in (a, b):
        dst.parent.mkdir()
        dst.write_bytes(src.read_bytes())
    out_dir = tmp_path / "out"
    assert main(["batch", "-o", str(out_dir), str(a), str(b)]) == 0
    assert (out_dir / "s.fa").exists() and (out_dir / "s-2.fa").exists()


def test_batch_cli_resume(data_root, tmp_path, capsys):
    from kindel_tpu.cli import main
    from kindel_tpu.io.fasta import read_fasta
    from kindel_tpu.workloads import bam_to_consensus

    bams = [
        str(data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam")
        for i in (1, 2)
    ]
    out_dir = str(tmp_path / "cohort")
    assert main(["batch", "-o", out_dir, *bams]) == 0
    got = read_fasta(tmp_path / "cohort" / "1.1.sub_test.fa")
    expect = bam_to_consensus(bams[0]).consensuses
    assert [(r.name, r.sequence) for r in got] == [
        (r.name, r.sequence) for r in expect
    ]

    # resume: both outputs exist → nothing reprocessed
    capsys.readouterr()
    assert main(["batch", "-o", out_dir, "--resume", *bams]) == 0
    err = capsys.readouterr().err
    assert "skipping 2" in err
    assert "wrote 0" in err


def test_stream_yields_finished_chunks_before_decode_failure(
    data_root, tmp_path
):
    """A corrupt file in chunk k must not discard chunk k-1's finished
    results: the stream yields them first, then raises."""
    import pytest

    from kindel_tpu.batch import stream_bam_to_consensus

    good = str(data_root / "data_bwa_mem" / "1.1.sub_test.bam")
    bad = tmp_path / "corrupt.bam"
    bad.write_bytes(b"not a bam at all")

    got = []
    with pytest.raises(Exception):
        for path, recs in stream_bam_to_consensus(
            [good, str(bad)], chunk_size=1
        ):
            got.append((path, recs))
    assert [p for p, _ in got] == [good]
    assert got[0][1], "good sample's consensus records were lost"


def test_batch_dispatch_shards_rows():
    """Under a multi-device mesh the cohort rows must actually lay out
    across the dp axis (guards the sharded dispatch from silently
    regressing to device 0)."""
    import jax

    from kindel_tpu.batch import _dp_sharding

    sharding, dp = _dp_sharding(6)
    if len(jax.devices()) <= 1:
        assert sharding is None and dp == 1
    else:
        assert dp == min(len(jax.devices()), 6)
        spec = sharding(2).spec
        assert spec[0] == "dp"


def test_batch_uneven_cohort_pads_dummy_rows(data_root):
    """More units than devices and not a dp multiple: rows are padded with
    empty dummy units that must not perturb real samples."""
    import jax

    if len(jax.devices()) <= 1:
        import pytest

        pytest.skip("needs a multi-device mesh")
    # 6 bwa refs + 3 multi-BAM contigs = 9 units over 8 devices → B=16
    paths = [
        data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam"
        for i in (1, 2, 3, 4, 5, 6)
    ] + [data_root / "data_minimap2" / "1.1.multi.bam"]
    batch_out = batch_bam_to_consensus(paths)
    for path in paths:
        singles = bam_to_consensus(path).consensuses
        assert [s.sequence for s in singles] == [
            b.sequence for b in batch_out[path]
        ], path


def test_batch_full_parity_with_consensus(data_root):
    """The cohort contract (VERDICT r1 item 5): a batch run of one file
    must equal a `consensus` run of that file exactly — sequences,
    change lists, and report text — realign included."""
    from kindel_tpu.batch import batch_bam_to_results

    for realign in (False, True):
        for rel in (
            ("data_bwa_mem", "1.1.sub_test.bam"),
            ("data_minimap2", "1.1.multi.bam"),
        ):
            path = data_root.joinpath(*rel)
            single = bam_to_consensus(path, realign=realign)
            batch = batch_bam_to_results([path], realign=realign)[path]
            assert [s.name for s in single.consensuses] == [
                b.name for b in batch.consensuses
            ]
            assert [s.sequence for s in single.consensuses] == [
                b.sequence for b in batch.consensuses
            ]
            assert batch.refs_changes == single.refs_changes
            assert batch.refs_reports == single.refs_reports, (rel, realign)


def test_batch_realign_multi_sample(data_root):
    """Realign across a cohort: every sample's patched consensus equals
    its single-file realign run."""
    from kindel_tpu.batch import batch_bam_to_results

    paths = [
        data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam"
        for i in (1, 2, 3, 4, 5, 6)
    ]
    out = batch_bam_to_results(
        paths, realign=True, build_reports=False, build_changes=False
    )
    for p in paths:
        single = bam_to_consensus(p, realign=True).consensuses
        assert [s.sequence for s in single] == [
            b.sequence for b in out[p].consensuses
        ]


def test_stream_results_reports(data_root, tmp_path):
    """stream_bam_to_results carries reports; batch CLI --reports writes
    them next to the .fa."""
    from kindel_tpu.batch import stream_bam_to_results
    from kindel_tpu.cli import main

    path = data_root / "data_bwa_mem" / "2.1.sub_test.bam"
    want = bam_to_consensus(path, realign=True, min_overlap=7)
    got = dict(
        stream_bam_to_results(
            [path], realign=True, min_overlap=7, build_reports=True
        )
    )[path]
    assert got.refs_reports == want.refs_reports

    rc = main([
        "batch", str(path), "-o", str(tmp_path), "-r", "--reports",
    ])
    assert rc == 0
    rep = tmp_path / "2.1.sub_test.report.txt"
    assert rep.exists()
    assert rep.read_text() == "\n".join(want.refs_reports.values())


def _mixed_scale_cohort(tmp_path, n_small=3, n_big=2):
    """SAM cohort mixing amplicon-scale and multi-megabase references —
    the shape that OOMs an unbudgeeted cohort-max-padded dispatch."""
    import numpy as np

    rng = np.random.default_rng(3)
    paths = []
    sizes = [400] * n_small + [1_500_000] * n_big
    for si, L in enumerate(sizes):
        lines = ["@HD\tVN:1.6", f"@SQ\tSN:ref{si}\tLN:{L}"]
        for i in range(24):
            pos = int(rng.integers(0, L - 60))
            seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=60))
            cigar = "30M2D28M2S" if i % 3 else "60M"
            lines.append(
                f"r{i}\t0\tref{si}\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*"
            )
        p = tmp_path / f"s{si}.sam"
        p.write_text("\n".join(lines) + "\n")
        paths.append(p)
    return paths


def test_cohort_budget_groups_split_and_match(tmp_path, monkeypatch):
    """VERDICT r4 item 2: mixed-scale cohorts must split into
    footprint-budgeted groups with group-local padding (the amplicon rows
    never pad to the megabase length), and the grouped output must be
    byte-identical to the unbudgeted single-group dispatch."""
    from kindel_tpu.batch import (
        BatchOptions,
        _budget_groups,
        _load_units,
    )
    from concurrent.futures import ThreadPoolExecutor

    paths = _mixed_scale_cohort(tmp_path)
    opts = BatchOptions(realign=True)
    with ThreadPoolExecutor(max_workers=4) as pool:
        units = _load_units(paths, pool, opts)

    # 160 MB budget: one 1.5 Mb realign row pads to 2 MiB and costs
    # ~190 MB of dense channels, so the two big samples cannot share a
    # group — assert the structural properties, not magic group counts
    monkeypatch.setenv("KINDEL_TPU_COHORT_BUDGET_MB", "160")
    from kindel_tpu.batch import _bucket, _row_bytes

    groups = _budget_groups(units, opts)
    assert len(groups) > 1, "mixed cohort must split under a small budget"
    for g in groups:
        lb = max(_bucket(units[i].L, 1024) for i in g)
        assert len(g) * _row_bytes(lb, opts.realign) <= 160 << 20 or len(g) == 1
    # group-local padding: the small-sample group's padded L stays small
    small_groups = [
        g for g in groups if all(units[i].L <= 1024 for i in g)
    ]
    assert small_groups, "amplicon rows should group together"

    # byte-identity: grouped (tiny budget) == single group (huge budget)
    import kindel_tpu.batch as B

    monkeypatch.setenv("KINDEL_TPU_COHORT_BUDGET_MB", "160")
    split = B.batch_bam_to_results(paths, realign=True, build_reports=True)
    monkeypatch.setenv("KINDEL_TPU_COHORT_BUDGET_MB", "100000")
    whole = B.batch_bam_to_results(paths, realign=True, build_reports=True)
    for p in paths:
        assert [s.sequence for s in split[p].consensuses] == [
            s.sequence for s in whole[p].consensuses
        ]
        assert split[p].refs_reports == whole[p].refs_reports
    # and equals the single-file oracle
    for p in paths:
        single = bam_to_consensus(p, realign=True)
        assert [s.sequence for s in split[p].consensuses] == [
            s.sequence for s in single.consensuses
        ]


def test_row_bytes_estimate_vs_live_buffers(data_root):
    """VERDICT r4 weak 5: the cohort footprint budget's per-row estimate
    (_row_bytes) was analytical only — nothing checked it against what
    XLA actually keeps alive. Asserts bounds on the ONE shared
    measurement (benchmarks.budget_probe.measure_cohort_budget, which
    the relay watcher also banks on real HBM): the retained tensors must
    fit within the estimate (+25% slack for wire/meta outputs) and the
    estimate must not be so inflated that groups under-pack."""
    from benchmarks.budget_probe import measure_cohort_budget

    paths = [
        data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam" for i in (1, 2, 3)
    ]
    rec = measure_cohort_budget(paths)
    actual, est = rec["actual_bytes"], rec["estimate_bytes"]
    assert 0 < actual <= est * 1.25, rec
    assert actual >= est * 0.3, (
        f"estimate {est} is >3x the observed live bytes {actual}: "
        "groups would under-pack"
    )
