"""Batched data-parallel consensus vs the single-file paths."""

import numpy as np

from kindel_tpu.batch import batch_bam_to_consensus
from kindel_tpu.workloads import bam_to_consensus


def test_batch_matches_single(data_root):
    paths = [
        data_root / "data_bwa_mem" / f"{i}.1.sub_test.bam" for i in (1, 2, 3)
    ] + [data_root / "data_minimap2" / "1.1.multi.bam"]
    batch_out = batch_bam_to_consensus(paths)
    for path in paths:
        singles = bam_to_consensus(path).consensuses
        batched = batch_out[path]
        assert [s.name for s in singles] == [b.name for b in batched]
        for s, b in zip(singles, batched):
            assert s.sequence == b.sequence, path


def test_batch_empty():
    assert batch_bam_to_consensus([]) == {}
