"""kindel_tpu.paged — continuous superbatching: persistent paged pileup
with per-segment admit/retire.

Covers the pool/ledger layer (admission, free-list reuse, panel-cache
refcounts + LRU reclaim), the admission wait-hint jitter contract, the
assembled serve path (byte-identity vs lanes incl. realign and the
pool-full pending queue), straggler isolation under injected
serve.flush stalls, drain semantics, traffic-histogram geometry
derivation, and the flagship: randomized mixed-shape + realign traffic
through `--batch-mode paged` across a supervised fleet with a replica
kill + drain and active faults — FASTA sha256 identical to
single-replica lanes, every admitted future settled exactly once, and
at most one kernel compile per page geometry.
"""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

from kindel_tpu.batch import BatchOptions
from kindel_tpu.obs import runtime as obs_runtime
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.paged import PAGE_SLOTS, PagePool, PagedBatcher, PagedFlush
from kindel_tpu.paged import batcher as paged_batcher_mod
from kindel_tpu.paged.state import panel_key
from kindel_tpu.ragged import classify_units, parse_classes
from kindel_tpu.ragged import pack as rpack
from kindel_tpu.resilience import FaultPlan
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.serve import ConsensusClient, ConsensusService
from kindel_tpu.serve.queue import ServeRequest
from kindel_tpu.serve.worker import decode_request
from kindel_tpu.tune import TuningConfig
from kindel_tpu.workloads import bam_to_consensus

from tests.test_serve import make_sam

CLASSES = parse_classes("small:32x2048,medium:16x8192")


def _decode(payload, **opt_kwargs):
    return decode_request(
        ServeRequest(payload=payload, opts=BatchOptions(**opt_kwargs))
    )


def _mixed_sams(tmp_path, n, seed_base=0, l_lo=260, l_hi=5200):
    rng = np.random.default_rng(seed_base)
    return [
        make_sam(
            tmp_path / f"mix{i}.sam", ref=f"pref{i}",
            L=int(rng.integers(l_lo, l_hi)),
            n_reads=int(rng.integers(10, 45)), seed=seed_base * 100 + i,
        )
        for i in range(n)
    ]


def _counter(name: str) -> float:
    snap = default_registry().snapshot()
    return sum(
        float(v) for k, v in snap.items()
        if (k == name or k.startswith(name + "{"))
        and not isinstance(v, dict)
    )


# ------------------------------------------------------------ pool / ledger


def _unit(tmp_path, name, L=400, seed=0, **opt_kwargs):
    sam = make_sam(tmp_path / f"{name}.sam", ref=name, L=L, seed=seed)
    (u,) = _decode(str(sam), **opt_kwargs)
    return u


def test_pool_admit_retire_reuses_pages(tmp_path):
    pool = PagePool(CLASSES[0], clock=time.monotonic)
    u1 = _unit(tmp_path, "a", L=700, seed=1)
    u2 = _unit(tmp_path, "b", L=500, seed=2)
    s1 = pool.admit_unit(u1, rpack.consumption([u1]))
    s2 = pool.admit_unit(u2, rpack.consumption([u2]))
    assert s1 is not None and s2 is not None
    assert s1.slot_start % PAGE_SLOTS == 0
    assert s2.page0 >= s1.page0 + s1.n_pages  # disjoint page runs
    used = pool.pages_in_use
    # s1 retires (non-panel free path exercised via panel=None override)
    s1.panel = None
    pool.release(s1)
    assert pool.pages_in_use == used - s1.n_pages
    # freed run is reusable: a same-size unit lands back at page 0
    u3 = _unit(tmp_path, "c", L=700, seed=3)
    s3 = pool.admit_unit(u3, rpack.consumption([u3]))
    assert s3.page0 == s1.page0


def test_pool_panel_cache_refcount_and_lru_reclaim(tmp_path):
    pool = PagePool(CLASSES[0], clock=time.monotonic)
    sam = make_sam(tmp_path / "amp.sam", ref="amp", L=600, seed=5)
    (u1,) = _decode(str(sam))
    (u2,) = _decode(str(sam))  # identical payload, fresh unit objects
    assert panel_key(u1) == panel_key(u2)
    s1 = pool.admit_unit(u1, rpack.consumption([u1]))
    hit = pool.panel_hit(u2)
    assert hit is s1 and s1.refs == 2
    pool.release(s1)
    pool.release(s1)
    # zero refs + panel key: parked reclaimable, STILL resident
    assert s1.seg_id in pool.segments
    assert s1.seg_id in pool.reclaimable
    # a re-hit revives it with no new pages
    used = pool.pages_in_use
    again = pool.panel_hit(u2)
    assert again is s1 and pool.pages_in_use == used
    pool.release(s1)
    # admission pressure reclaims the parked segment LRU
    big = _unit(tmp_path, "big", L=1900, seed=6)
    while pool.admit_unit(big, rpack.consumption([big])) is not None:
        big = _unit(tmp_path, f"big{pool.n_resident}", L=1900, seed=6)
    assert s1.seg_id not in pool.segments, "LRU reclaim never fired"


def test_admission_wait_hint_uses_jittered_retry_after(monkeypatch):
    """The pool-full wait hint must route through the PR 8 ±25% jitter
    rule (queue.jittered_retry_after) — pinned by substitution, not by
    sampling statistics."""
    from kindel_tpu.paged import admit as paged_admit

    calls = []

    def fake_jitter(base, *, frac=0.25, floor=0.05, rng=None):
        calls.append((base, floor))
        return 0.123

    monkeypatch.setattr(
        paged_admit, "jittered_retry_after", fake_jitter
    )
    hint = paged_admit.wait_hint_s(0.05)
    assert hint == 0.123
    assert calls == [(0.05, 0.002)]
    # and the batcher consults exactly that helper
    mb = PagedBatcher(CLASSES, max_wait_s=0.07)
    monkeypatch.setattr(
        paged_batcher_mod, "wait_hint_s", lambda mw: calls.append(mw) or 0.2
    )
    assert mb._wait_hint_s() == 0.2
    assert calls[-1] == 0.07


def test_batcher_seals_tick_and_take_ready_degrades(tmp_path):
    sam = make_sam(tmp_path / "one.sam", seed=21)
    mb = PagedBatcher(CLASSES, max_wait_s=0.05)
    req = ServeRequest(payload=str(sam), opts=BatchOptions())
    mb.add(req, _decode(str(sam)))
    flush = mb.poll(timeout=5.0)
    assert isinstance(flush, PagedFlush)
    assert [r for r, _ in flush.entries] == [req]
    assert mb.take_ready(flush, limit=8) == []
    # the tick's launch reads the resident pool
    arrays, table, row_of = mb.snapshot_for_launch(flush)
    assert table.n_segments == 1
    mb.retire_flush(flush)


def test_oversize_falls_back_to_lanes(tmp_path):
    before = _counter("kindel_ragged_fallback_total")
    huge = make_sam(tmp_path / "huge.sam", ref="huge", L=9000, seed=3)
    mb = PagedBatcher(CLASSES, max_wait_s=30.0)
    mb.add(ServeRequest(payload=str(huge), opts=BatchOptions()),
           _decode(str(huge)))
    flushes = mb.flush_all()
    assert len(flushes) == 1 and not isinstance(flushes[0], PagedFlush)
    assert _counter("kindel_ragged_fallback_total") == before + 1


# ------------------------------------------------- serve path, end to end


def _serve_all(sams, mode, *, lane_coalesce=2, ragged_classes=None,
               **svc_kwargs):
    results = [None] * len(sams)
    errors: list = []
    with ConsensusService(
        tuning=TuningConfig(batch_mode=mode, lane_coalesce=lane_coalesce,
                            ragged_classes=ragged_classes),
        max_wait_s=0.15, decode_workers=4, **svc_kwargs,
    ) as svc:
        client = ConsensusClient(svc)

        def one(i):
            try:
                results[i] = client.fasta(str(sams[i]), timeout=300)
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [
            threading.Thread(target=one, args=(i,))
            for i in range(len(sams))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        health = svc.healthz()
    assert not errors, errors
    return results, health


def test_paged_equals_lanes_byte_identical_incl_realign(tmp_path):
    sams = _mixed_sams(tmp_path, 8, seed_base=5)
    lanes, _h = _serve_all(sams, "lanes")
    paged, health = _serve_all(sams, "paged")
    assert paged == lanes, "paged FASTA diverged from the lanes path"
    assert health["batch_mode"] == "paged"
    assert health["paged"], "healthz carries no pool residency"
    lanes_r, _ = _serve_all(sams[:4], "lanes", realign=True)
    paged_r, _ = _serve_all(sams[:4], "paged", realign=True)
    assert paged_r == lanes_r, "realign paged diverged from lanes"


def test_pool_full_pending_is_served_and_counted(tmp_path):
    sams = [
        make_sam(tmp_path / f"p{i}.sam", ref=f"pp{i}", L=900,
                 n_reads=20, seed=60 + i)
        for i in range(8)
    ]
    waits0 = _counter("kindel_paged_admission_waits_total")
    paged, _h = _serve_all(
        sams, "paged", ragged_classes="only:2x2048",
    )
    assert _counter("kindel_paged_admission_waits_total") > waits0, (
        "pool never filled — the pending path was not exercised"
    )
    lanes, _h = _serve_all(sams, "lanes")
    assert paged == lanes


def test_panel_cache_dedupes_identical_payloads(tmp_path):
    payload = make_sam(
        tmp_path / "amp.sam", ref="amp", L=900, n_reads=30, seed=7
    ).read_bytes()
    hits0 = _counter("kindel_paged_panel_hits_total")
    results = [None] * 10
    errors: list = []
    with ConsensusService(
        tuning=TuningConfig(batch_mode="paged"), max_wait_s=0.03,
    ) as svc:
        client = ConsensusClient(svc)

        def one(i):
            try:
                results[i] = client.fasta(payload, timeout=300)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(10)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    assert len(set(results)) == 1
    assert _counter("kindel_paged_panel_hits_total") > hits0, (
        "identical amplicon payloads shared no panel state"
    )
    retire = default_registry().snapshot().get(
        "kindel_paged_retire_seconds", {}
    )
    assert retire.get("count", 0) > 0, "no segment retire latency observed"


def test_straggler_isolation_under_flush_stall(tmp_path):
    """One stalled/large tick must not delay retirement or settlement
    of completed co-resident segments: the straggler stalls 0.8s in its
    own executor slot while later ticks launch, settle, and retire
    around it (latency bound pinned)."""
    big = make_sam(tmp_path / "big.sam", ref="big", L=5000, n_reads=45,
                   seed=1)
    smalls = [
        make_sam(tmp_path / f"s{i}.sam", ref=f"ss{i}", L=350,
                 n_reads=15, seed=10 + i)
        for i in range(5)
    ]
    lat: dict = {}
    errors: list = []
    with ConsensusService(
        tuning=TuningConfig(batch_mode="paged"), max_wait_s=0.02,
        decode_workers=4,
    ) as svc:
        client = ConsensusClient(svc)
        # warm both page-class kernels: the measured phase must see the
        # straggler, not a cold compile
        client.fasta(str(big), timeout=300)
        client.fasta(str(smalls[0]), timeout=300)
        plan = rfaults.activate(
            FaultPlan.parse("serve.flush:stall:times=1:delay=0.8")
        )
        try:
            def one(name, payload):
                t0 = time.perf_counter()
                try:
                    client.fasta(str(payload), timeout=300)
                    lat[name] = time.perf_counter() - t0
                except Exception as e:  # noqa: BLE001
                    errors.append((name, repr(e)))

            tb = threading.Thread(target=one, args=("big", big))
            tb.start()
            time.sleep(0.3)  # the straggler tick is launched + stalled
            threads = [
                threading.Thread(target=one, args=(f"s{i}", p))
                for i, p in enumerate(smalls)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            tb.join()
        finally:
            rfaults.deactivate()
    assert not errors, errors
    worst_small = max(v for k, v in lat.items() if k != "big")
    assert lat["big"] >= 0.7, "the stall missed the straggler tick"
    assert worst_small < 0.5, (
        f"completed co-resident segments waited on the straggler "
        f"({worst_small:.3f}s)"
    )
    assert plan.fired == {("serve.flush", "stall"): 1}


def test_drain_serves_fresh_and_pending(tmp_path):
    """Drain with a full pool: fresh ticks launch, never-admitted
    pending requests seal into classic flushes — every admitted future
    settles with real bytes."""
    sams = [
        make_sam(tmp_path / f"d{i}.sam", ref=f"dd{i}", L=900,
                 n_reads=20, seed=80 + i)
        for i in range(8)
    ]
    svc = ConsensusService(
        tuning=TuningConfig(
            batch_mode="paged", ragged_classes="only:2x2048"
        ),
        max_wait_s=5.0,  # ticks/pending sit until drain seals them
    ).start()
    futs = [svc.submit(str(p)) for p in sams]
    time.sleep(0.3)  # decodes land in the batcher
    svc.drain()
    results = [f.result(timeout=300) for f in futs]
    assert all(r.consensuses for r in results)


# ----------------------------------------------------- geometry from traffic


def test_derive_page_classes_from_histogram():
    from kindel_tpu import tune

    assert tune.derive_page_classes({}) is None
    hist = {1024: 80, 2048: 15, 16384: 5}
    spec = tune.derive_page_classes(hist)
    classes = parse_classes(spec)
    assert classes[0].length == 1024  # p50 of the observed strides
    assert classes[-1].length == 16384  # the max bucket
    assert all(4 <= c.rows <= 64 for c in classes)
    # derived spec leads the sweep candidates
    cands = tune.ragged_class_candidates(hist)
    assert cands[0] == spec and len(cands) > 1
    # empty histogram → static ladder unchanged
    assert tune.ragged_class_candidates({}) == tune.RAGGED_CLASS_CANDIDATES


def test_traffic_histogram_persists_and_retunes(tmp_path, monkeypatch):
    from kindel_tpu import tune

    monkeypatch.setenv(
        "KINDEL_TPU_TUNE_CACHE", str(tmp_path / "tune.json")
    )
    assert tune.record_traffic_histogram({2048: 10, 8192: 2})
    assert tune.record_traffic_histogram({2048: 5})
    assert tune.load_traffic_histogram() == {2048: 15, 8192: 2}
    # online retune: a batcher fed uniform small traffic re-derives its
    # geometry from the observed histogram and persists the winner
    mb = PagedBatcher(CLASSES, max_wait_s=30.0, retune_every=8)
    mb._hist = {1024: 200}
    mb._admissions = mb.retune_every - 1
    mb._record_traffic_locked = lambda units: setattr(
        mb, "_admissions", mb._admissions + 1
    )

    class _U:
        L = 200
    # drive the retune path directly (locked hook)
    with mb._cond:
        mb._record_traffic_locked([_U()])
        mb._maybe_retune_locked(time.monotonic())
    assert mb.classes[0].length == 1024
    entry = tune.lookup(tune.ragged_store_key())
    assert entry and entry.get("source") == "traffic"
    assert parse_classes(entry["classes"])


def test_batch_mode_paged_resolution(monkeypatch):
    from kindel_tpu import tune

    monkeypatch.setenv("KINDEL_TPU_BATCH_MODE", "paged")
    assert tune.resolve_batch_mode() == ("paged", "env")
    assert tune.resolve_batch_mode("paged") == ("paged", "explicit")


# ---------------------------------------------------------- the flagship


def test_paged_fleet_chaos_mixed_realign_exactly_once(tmp_path):
    """The flagship: randomized mixed-shape + realign traffic through
    `--batch-mode paged` against a 3-replica supervised fleet with
    decode workers, coalescing, an active fault plan, a replica KILL
    and a DRAIN mid-load. The FASTA of every payload is byte-identical
    to a single-replica lanes run, every admitted future settles
    exactly once, and the run compiles at most one segment kernel per
    (page geometry, wire variant)."""
    from kindel_tpu.fleet import FleetService

    sams = _mixed_sams(tmp_path, 9, seed_base=31)
    opts = [
        {"realign": True} if i % 3 == 0 else {} for i in range(len(sams))
    ]
    # single-replica lanes reference
    reference, _h = _serve_all(sams, "lanes")
    ref_realign, _h = _serve_all(
        [s for i, s in enumerate(sams) if opts[i]], "lanes", realign=True
    )
    want = list(reference)
    it = iter(ref_realign)
    for i in range(len(sams)):
        if opts[i]:
            want[i] = next(it)

    cache_before = obs_runtime.jit_cache_sizes().get(
        "ragged_call_kernel", 0
    )
    plan = rfaults.activate(
        FaultPlan.parse("seed=5,serve.flush:error:times=2:after=1")
    )
    results = [None] * len(sams)
    errors: list = []
    try:
        svc = FleetService(
            replicas=3, probe_interval_s=0.02, max_wait_s=0.05,
            decode_workers=4,
            tuning=TuningConfig(batch_mode="paged", lane_coalesce=2),
        ).start()
        try:
            from kindel_tpu.io.fasta import format_fasta

            barrier = threading.Barrier(len(sams) + 1)

            def one(i):
                barrier.wait()
                try:
                    res = svc.request(
                        str(sams[i]), timeout=300, **opts[i]
                    )
                    results[i] = format_fasta(res.consensuses)
                except Exception as e:  # noqa: BLE001
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=one, args=(i,))
                for i in range(len(sams))
            ]
            for t in threads:
                t.start()
            barrier.wait()
            time.sleep(0.15)
            svc.kill_replica("r1")
            time.sleep(0.25)
            svc.drain("r2")
            for t in threads:
                t.join()
        finally:
            svc.stop()
    finally:
        rfaults.deactivate()
    cache_after = obs_runtime.jit_cache_sizes().get(
        "ragged_call_kernel", 0
    )
    assert not errors, errors
    # every admitted future settled exactly once, with the right bytes
    assert results == want, "paged fleet FASTA diverged from lanes"
    assert plan.fired == {("serve.flush", "error"): 2}
    # ≤ 1 compile per (page geometry, wire variant): 2 geometries × the
    # fast + realign variants
    geometries = len({
        classify_units(_decode(str(p)), CLASSES) for p in sams
    })
    assert cache_after - cache_before <= 2 * max(geometries, 1), (
        "more segment-kernel compiles than page geometries × variants",
        cache_after - cache_before,
    )
