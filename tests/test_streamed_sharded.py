"""Streamed ingest × position-sharded product path (VERDICT r2 item 2).

Contract: chunked decode reduced into position-sharded device state
(kindel_tpu.parallel.stream_product) must reproduce the numpy oracle
byte-for-byte — sequences, changes, reports — on the 8-device virtual
mesh, with and without realign, across chunk boundaries, multi-contig
inputs, and text SAMs. Also pins that bam_to_consensus auto-routes
large files through this path now that the round-2 stand-down
(stream XOR shard) is deleted.
"""

import os
from pathlib import Path

import jax
import pytest

from kindel_tpu.streaming import streamed_consensus
from kindel_tpu.workloads import bam_to_consensus

_DATA_ROOT = Path(
    os.environ.get("KINDEL_TPU_TEST_DATA", "/root/reference/tests")
)

TINY_CHUNK = 64 << 10


def require_data(*rel) -> Path:
    path = _DATA_ROOT.joinpath(*rel)
    if not path.exists():
        pytest.skip(f"golden corpus not available: {path}")
    return path


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the multi-device virtual mesh"
)


def _assert_identical(got, ref):
    assert [c.sequence for c in got.consensuses] == [
        c.sequence for c in ref.consensuses
    ]
    assert got.refs_changes == ref.refs_changes
    assert got.refs_reports == ref.refs_reports


@pytest.mark.parametrize("realign", [False, True])
@pytest.mark.parametrize(
    "rel",
    [
        ("data_bwa_mem", "1.1.sub_test.bam"),
        ("data_minimap2", "1.1.multi.bam"),
        ("data_ext", "1.issue23.debug.sam"),
    ],
    ids=["bwa", "multi-contig", "text-sam"],
)
def test_streamed_sharded_identity(rel, realign):
    bam = require_data(*rel)
    ref = bam_to_consensus(bam, realign=realign, backend="numpy",
                           min_overlap=7)
    got = streamed_consensus(bam, realign=realign, backend="jax",
                             min_overlap=7, chunk_bytes=TINY_CHUNK)
    _assert_identical(got, ref)


def test_streamed_sharded_chunk_boundary_invariance():
    """Reduction is additive: any chunking yields identical output."""
    bam = require_data("data_bwa_mem", "1.1.sub_test.bam")
    a = streamed_consensus(bam, backend="jax", chunk_bytes=16 << 10)
    b = streamed_consensus(bam, backend="jax", chunk_bytes=1 << 20)
    _assert_identical(a, b)


def test_auto_stream_routes_through_mesh(monkeypatch, tmp_path):
    """With >1 device visible, a file past the stream threshold streams
    AND shards (the round-2 stand-down traded one for the other)."""
    import kindel_tpu.parallel.stream_product as sp

    bam = require_data("data_bwa_mem", "1.1.sub_test.bam")
    monkeypatch.setenv("KINDEL_TPU_STREAM_THRESHOLD_MB", "0.01")

    seen = {}
    orig = sp.ShardedStreamAccumulator.add_batch

    def spy(self, batch):
        seen["n_shards"] = self.n
        return orig(self, batch)

    monkeypatch.setattr(sp.ShardedStreamAccumulator, "add_batch", spy)
    ref = bam_to_consensus(bam, backend="numpy")
    got = bam_to_consensus(bam, backend="jax")
    assert seen.get("n_shards", 0) > 1, "sharded stream path never engaged"
    _assert_identical(got, ref)


def test_single_device_jax_stream_branch(monkeypatch):
    """KINDEL_TPU_FORCE_FUSED pins the single-device jax streamed branch
    (StreamAccumulator device path + counts_call_kernel), which the
    sharded routing would otherwise shadow on the virtual mesh."""
    bam = require_data("data_bwa_mem", "1.1.sub_test.bam")
    monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", "1")
    ref = bam_to_consensus(bam, backend="numpy")
    got = streamed_consensus(bam, backend="jax", chunk_bytes=TINY_CHUNK)
    _assert_identical(got, ref)


def test_explicit_chunk_still_shards():
    bam = require_data("data_bwa_mem", "1.1.sub_test.bam")
    ref = bam_to_consensus(bam, backend="numpy", realign=True,
                           min_overlap=7)
    got = bam_to_consensus(bam, backend="jax", realign=True, min_overlap=7,
                           stream_chunk_mb=0.0625)
    _assert_identical(got, ref)


def test_pad_safe_block_guard():
    """PAD_POS flat-scatter wraparound guard: int32(2^30·5) wraps to a
    positive in-range index for blocks past 2^30/5 positions, so the
    guard must reject them (review r3 finding)."""
    from kindel_tpu.pileup_jax import MAX_PAD_SAFE_BLOCK, check_pad_safe_block

    check_pad_safe_block(MAX_PAD_SAFE_BLOCK)  # at the limit: fine
    with pytest.raises(ValueError, match="PAD_POS"):
        check_pad_safe_block(MAX_PAD_SAFE_BLOCK + 1)
    # the wrap itself: the sentinel's two's-complement flat index must be
    # out of range for every legal block size
    from kindel_tpu.events import N_CHANNELS
    from kindel_tpu.pileup_jax import PAD_POS

    wrapped = int(PAD_POS) * N_CHANNELS & 0xFFFFFFFF
    if wrapped >= 2**31:
        wrapped -= 2**32
    assert wrapped < 0 or wrapped >= MAX_PAD_SAFE_BLOCK * N_CHANNELS


@pytest.mark.parametrize("workload", ["weights", "features", "variants"])
def test_stats_workloads_sharded_identity(workload, monkeypatch):
    """weights/features/variants with backend=jax on the mesh reduce the
    per-base channels position-sharded (VERDICT r2 missing item 5) and
    must produce exactly the numpy tables — eager and streamed."""
    import pandas as pd

    from kindel_tpu import workloads

    monkeypatch.delenv("KINDEL_TPU_FORCE_FUSED", raising=False)
    bam = require_data("data_minimap2", "1.1.multi.bam")
    fn = getattr(workloads, workload)
    ref = fn(bam, backend="numpy")
    eager = fn(bam, backend="jax")
    pd.testing.assert_frame_equal(eager, ref, check_dtype=False,
                                  check_categorical=False)

    monkeypatch.setenv("KINDEL_TPU_STREAM_CHUNK_MB", "0.0625")
    streamed = fn(bam, backend="jax")
    pd.testing.assert_frame_equal(streamed, ref, check_dtype=False,
                                  check_categorical=False)
