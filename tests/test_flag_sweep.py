"""Corpus-wide sweep for the beyond-the-reference realign flags.

VERDICT r4 item 5 asked for proof the default-off flags are surgical on
real data. Running every BAM/SAM the reference ships (bwa, minimap2,
segemehl, ext, bacterial) through each flag and both composed
established, and this test now pins:

- `--cdr-gap 600` changes NOTHING, corpus-wide: the >=16 bp merge gate
  (realign.py GAP_PAIR_MIN_OVERLAP) rejects every candidate gap pair on
  every real file — dozens of "No overlap found" warnings on the
  bacterial genome, zero sequence changes. Byte-identity is asserted for
  all files.
- `--fix-clip-artifacts` fires on exactly FOUR corpus files — the
  designed case (data_ext/3.issue23.bc75.sam, whose fixed output equals
  the reference's own curated expectation, tests/test_issue23.py) plus
  three where the same two artifact classes occur naturally
  (bwa 5.1, segemehl 4.1, bact.tiny) — and every firing strictly
  REMOVES 1-3 duplicate/phantom bases (the fixed sequence is a
  subsequence of the default one). It can never add or substitute: both
  repairs (zero-floor insertion suppression, forward clip-extension
  flank dedup) only drop bases, which this test asserts corpus-wide.
  This is the same artifact the reference's reverse scan already
  compensates (kindel.py:257-261 lag handling); the flag makes the
  forward scan symmetric, so firing on other aligners' ambiguous clip
  boundaries is the feature working, not collateral.
- composed, `--cdr-gap` adds nothing on top of `--fix-clip-artifacts`
  anywhere.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from conftest import DATA_ROOT
from kindel_tpu.workloads import bam_to_consensus

#: every aligner corpus the reference ships (SURVEY §4)
CORPUS = sorted(
    p
    for pattern in (
        "data_bwa_mem/*.bam",
        "data_minimap2/*.bam",
        "data_minimap2_bact/*.bam",
        "data_segemehl/*.bam",
        "data_ext/*.sam",
    )
    for p in DATA_ROOT.glob(pattern)
)

#: (corpus dir, file name) -> bases removed by --fix-clip-artifacts;
#: every other corpus file must be byte-identical under the flag
FIX_REMOVALS = {
    ("data_ext", "3.issue23.bc75.sam"): 1,
    ("data_bwa_mem", "5.1.sub_test.bam"): 1,
    ("data_segemehl", "4.1.sub_test.bam"): 2,
    ("data_minimap2_bact", "bact.tiny.bam"): 3,
}

pytestmark = pytest.mark.skipif(
    not CORPUS, reason="golden corpus not available"
)


def _seqs(res):
    return [c.sequence for c in res.consensuses]


def _is_subseq(small: str, big: str) -> bool:
    it = iter(big)
    return all(c in it for c in small)


@pytest.mark.parametrize(
    "path", CORPUS, ids=lambda p: f"{p.parent.name}/{p.name}"
)
def test_flag_sweep_surgical(path: Path):
    base = bam_to_consensus(path, realign=True, min_overlap=7)
    gap = bam_to_consensus(path, realign=True, min_overlap=7, cdr_gap=600)
    fix = bam_to_consensus(
        path, realign=True, min_overlap=7, fix_clip_artifacts=True
    )
    both = bam_to_consensus(
        path, realign=True, min_overlap=7, cdr_gap=600,
        fix_clip_artifacts=True,
    )
    assert _seqs(gap) == _seqs(base), "--cdr-gap changed a real corpus file"
    assert _seqs(both) == _seqs(fix), "--cdr-gap interacted with the fix"
    expected_removed = FIX_REMOVALS.get((path.parent.name, path.name))
    if expected_removed is None:
        assert _seqs(fix) == _seqs(base), (
            "--fix-clip-artifacts fired on an unexpected corpus file"
        )
    else:
        b_all, f_all = "".join(_seqs(base)), "".join(_seqs(fix))
        assert len(b_all) - len(f_all) == expected_removed
        # the fix may only DROP duplicate/phantom bases, never add or
        # substitute: the fixed consensus is a subsequence of the default
        assert _is_subseq(f_all, b_all)
